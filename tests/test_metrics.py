"""MFU accounting unit tests (SURVEY.md hard part #5; VERDICT r1 item 8:
the device table must warn, not silently assume v5e) + the shared
percentile/ring-buffer aggregation serve latency metrics ride on."""

import importlib
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.metrics import Ring, percentiles

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast

# the package re-exports an `mfu` *function*; grab the module itself
mfu_mod = importlib.import_module("solvingpapers_tpu.metrics.mfu")


class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


@pytest.mark.parametrize("kind,peak", [
    ("TPU v4", 275e12),
    ("TPU v5 lite", 197e12),
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v6e", 918e12),
])
def test_chip_peak_flops_known_kinds(kind, peak):
    assert mfu_mod.chip_peak_flops(_FakeDevice(kind)) == peak


def test_chip_peak_flops_unknown_kind_warns_once():
    # unknown chips return the NaN sentinel (a silently-assumed v5e peak
    # mis-scaled every MFU number); consumers gate on math.isfinite
    import math

    mfu_mod._warned_kinds.clear()
    with pytest.warns(UserWarning, match="unrecognized device_kind"):
        assert math.isnan(mfu_mod.chip_peak_flops(_FakeDevice("TPU v9x")))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second call must not warn again
        assert math.isnan(mfu_mod.chip_peak_flops(_FakeDevice("TPU v9x")))


def test_transformer_flops_per_token():
    # 6N + 12 L D S training; 2N + 4 L D S inference
    assert mfu_mod.transformer_flops_per_token(100, 2, 8, 16) == 600 + 12 * 2 * 8 * 16
    assert mfu_mod.transformer_flops_per_token(
        100, 2, 8, 16, training=False) == 200 + 4 * 2 * 8 * 16


def test_active_param_count_discounts_routed_experts():
    params = {
        "layer_0": {"moe": {
            "w1": jnp.zeros((8, 4, 6)), "w2": jnp.zeros((8, 4, 6)),
            "w3": jnp.zeros((8, 6, 4)),
            "gate": {"kernel": jnp.zeros((4, 8))},
        }},
        "head": {"kernel": jnp.zeros((4, 10))},
    }
    total = 3 * 8 * 24 + 32 + 40
    active = mfu_mod.active_param_count(params, top_experts=2, n_experts=8)
    assert active == total - (3 * 8 * 24 - 3 * 8 * 24 * 2 // 8)
    # without MoE info: plain total
    assert mfu_mod.active_param_count(params) == total


def test_percentiles_keys_and_values():
    vals = list(range(1, 101))  # 1..100
    out = percentiles(vals)
    assert set(out) == {"p50", "p95", "p99"}
    assert out["p50"] == pytest.approx(50.5)
    assert out["p95"] == pytest.approx(np.percentile(vals, 95))
    # non-integer quantile keeps its fractional label
    assert set(percentiles(vals, qs=(99.9,))) == {"p99.9"}
    assert percentiles([]) == {}


def test_ring_bounds_memory_and_tracks_recent():
    ring = Ring(capacity=4)
    for v in [1.0, 2.0, 3.0]:
        ring.add(v)
    assert len(ring) == 3
    assert ring.mean() == pytest.approx(2.0)
    for v in [4.0, 5.0, 6.0]:  # wraps: live window is now {3,4,5,6}
        ring.add(v)
    assert len(ring) == 4
    assert ring.total_added == 6
    assert sorted(ring.values().tolist()) == [3.0, 4.0, 5.0, 6.0]
    assert ring.percentiles(qs=(50,))["p50"] == pytest.approx(4.5)


def test_ring_empty_and_invalid_capacity():
    ring = Ring(capacity=8)
    assert len(ring) == 0
    assert ring.percentiles() == {}
    assert np.isnan(ring.mean())
    with pytest.raises(ValueError, match="capacity"):
        Ring(capacity=0)


def test_parity_regression_check():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "parity_suite",
        pathlib.Path(__file__).parent.parent / "tools" / "parity_suite.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    history = [{"workloads": {
        "gpt_shakespeare": {"steps": 1000, "val_loss": 1.90},
        "vit_mnist": {"steps": 1200, "val_accuracy": 0.97},
    }}]
    ok = {"workloads": {
        "gpt_shakespeare": {"steps": 1000, "val_loss": 1.91},  # within tol
        "vit_mnist": {"steps": 1200, "val_accuracy": 0.975},
    }}
    assert mod.check_regressions(history, ok) == []
    bad = {"workloads": {
        "gpt_shakespeare": {"steps": 1000, "val_loss": 2.10},
        "vit_mnist": {"steps": 1200, "val_accuracy": 0.91},
    }}
    flags = mod.check_regressions(history, bad)
    assert len(flags) == 2, flags
    # different step counts must not be compared
    other = {"workloads": {
        "gpt_shakespeare": {"steps": 125, "val_loss": 3.0},
    }}
    assert mod.check_regressions(history, other) == []


# --------------------------------------------- log-bucketed histograms


def test_histogram_single_observation_is_exact():
    from solvingpapers_tpu.metrics import LogHistogram

    h = LogHistogram()
    h.add(0.25)
    assert len(h) == 1
    assert h.mean() == pytest.approx(0.25)
    # min/max clamping makes a single-bucket population exact — the
    # property that lets histogram percentiles keep the Ring's key
    # semantics for sparse data
    assert h.percentiles() == {"p50": 0.25, "p95": 0.25, "p99": 0.25}


def test_histogram_quantile_error_bounded_by_bucket_width():
    """Property: the quantile estimate lands in the same bucket as the
    exact nearest-rank sample, so its error is at most that bucket's
    width (the claim the log-bucket layout is sized around)."""
    import math

    from solvingpapers_tpu.metrics import LogHistogram

    rng = np.random.default_rng(7)
    vals = rng.lognormal(mean=-2.0, sigma=1.8, size=4000)
    h = LogHistogram()
    for v in vals:
        h.add(v)
    s = np.sort(vals)
    for q in (0.01, 0.25, 0.5, 0.9, 0.95, 0.99, 0.999):
        exact = s[max(1, math.ceil(q * s.size)) - 1]
        est = h.quantile(q)
        i = h._index(exact)
        lo = 0.0 if i == 0 else h.lo * 10.0 ** ((i - 1) / h._scale)
        hi = h.lo if i == 0 else h.edge(i - 1)
        width = hi - lo
        assert abs(est - exact) <= width + 1e-12, (q, est, exact, width)
    # mean and count are exact, not bucket-resolution
    assert h.mean() == pytest.approx(float(s.mean()))
    assert len(h) == s.size


def test_histogram_merge_is_exact():
    """merge-of-shards == shard-of-merged: identical bucket counts,
    count, min/max (the per-replica aggregation enabler)."""
    from solvingpapers_tpu.metrics import LogHistogram

    rng = np.random.default_rng(3)
    vals = rng.lognormal(-1.0, 2.0, 2003)
    whole = LogHistogram()
    shards = [LogHistogram() for _ in range(5)]
    for i, v in enumerate(vals):
        whole.add(v)
        shards[i % 5].add(v)
    merged = LogHistogram.merge(shards)
    assert (merged.counts == whole.counts).all()
    assert merged.count == whole.count
    assert merged.min == whole.min and merged.max == whole.max
    assert merged.sum == pytest.approx(whole.sum, rel=1e-12)
    for q in (0.5, 0.95, 0.99):
        assert merged.quantile(q) == whole.quantile(q)
    # layout mismatch must refuse, not silently mis-bucket
    with pytest.raises(ValueError, match="layout"):
        whole.merge_from(LogHistogram(lo=1e-3))


def test_histogram_overflow_underflow_counted_and_clamped():
    from solvingpapers_tpu.metrics import LogHistogram

    h = LogHistogram(lo=1e-2, hi=1e2, buckets_per_decade=4)
    h.add(1e-5)   # underflow
    h.add(1e5)    # overflow
    h.add(1.0)
    assert len(h) == 3
    assert h.counts[0] == 1 and h.counts[-1] == 1
    # quantiles clamp to observed extremes, never invent an edge value
    assert h.quantile(0.0) == pytest.approx(1e-5)
    assert h.quantile(1.0) == pytest.approx(1e5)
    with pytest.raises(ValueError, match="lo"):
        LogHistogram(lo=0.0)
    with pytest.raises(ValueError, match="buckets_per_decade"):
        LogHistogram(buckets_per_decade=0)


def test_prometheus_renders_native_histogram(tmp_path):
    """LogHistogram values become _bucket{le=...}/_sum/_count series on
    BOTH exposition paths (render backs the textfile sink and the live
    /metrics endpoint): cumulative counts, every edge emitted (aligned
    le sets across replicas), +Inf bucket == count."""
    from solvingpapers_tpu.metrics import LogHistogram, PrometheusTextWriter

    h = LogHistogram(lo=0.01, hi=10.0, buckets_per_decade=2)
    for v in (0.02, 0.3, 0.3, 5.0):
        h.add(v)
    text = PrometheusTextWriter.render(
        3, {"serve/ttft_s": h, "serve/ttft_s_mean": h.mean()})
    lines = text.splitlines()
    assert "# TYPE serve_ttft_s histogram" in lines
    assert "# TYPE serve_ttft_s_mean gauge" in lines
    buckets = [ln for ln in lines if ln.startswith("serve_ttft_s_bucket{")]
    # 3 decades x 2 buckets + underflow + +Inf
    assert len(buckets) == 8
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert counts[-1] == 4
    assert buckets[-1].startswith('serve_ttft_s_bucket{le="+Inf"}')
    assert "serve_ttft_s_count 4" in lines
    (sum_line,) = [ln for ln in lines if ln.startswith("serve_ttft_s_sum ")]
    assert float(sum_line.split(" ")[1]) == pytest.approx(5.62)
    # atomic-write path renders identically
    path = str(tmp_path / "h.prom")
    PrometheusTextWriter(path).write(3, {"serve/ttft_s": h})
    assert "serve_ttft_s_count 4" in open(path).read()


def test_prometheus_histogram_wins_derived_name_collisions():
    from solvingpapers_tpu.metrics import LogHistogram, PrometheusTextWriter

    h = LogHistogram(lo=0.01, hi=10.0, buckets_per_decade=2)
    h.add(0.5)
    text = PrometheusTextWriter.render(
        0, {"x": h, "x_count": 99.0})
    # the histogram's _count series wins; no duplicate series emitted
    value_lines = [ln for ln in text.splitlines()
                   if ln.startswith("x_count")]
    assert value_lines == ["x_count 1"]


def test_histogram_nway_merge_associative_and_commutative():
    """Fleet-merge algebra: merging replicas' shards is associative
    and commutative — the merged /metrics series cannot depend on
    replica order or on whether shards were pre-combined."""
    import itertools

    from solvingpapers_tpu.metrics import LogHistogram

    rng = np.random.default_rng(11)
    shards = []
    for i in range(4):
        h = LogHistogram()
        for v in rng.lognormal(-1.0 + 0.4 * i, 1.5, 300 + 50 * i):
            h.add(v)
        shards.append(h)

    def eq(a, b):
        return ((a.counts == b.counts).all() and a.count == b.count
                and a.min == b.min and a.max == b.max
                and a.sum == pytest.approx(b.sum, rel=1e-9))

    flat = LogHistogram.merge(shards)
    # associativity: ((0+1)+2)+3 == 0+((1+2)+3) == flat N-way
    left = LogHistogram.merge(
        [LogHistogram.merge(shards[:2]), shards[2], shards[3]])
    right = LogHistogram.merge(
        [shards[0], LogHistogram.merge(
            [shards[1], LogHistogram.merge(shards[2:])])])
    assert eq(left, flat) and eq(right, flat)
    # commutativity: every permutation of the shards merges identically
    for perm in itertools.permutations(shards):
        assert eq(LogHistogram.merge(list(perm)), flat)
    # the inputs are untouched (merge copies; a scrape must not
    # mutate the live per-replica histograms it aggregates)
    assert sum(s.count for s in shards) == flat.count


def test_histogram_merge_while_recording_never_tears():
    """The fleet /metrics race: merging a LIVE histogram (a serving
    thread mid-`add`) must never tear — every merged snapshot satisfies
    bucket-total == count, and the quiescent merge is exact."""
    import threading

    from solvingpapers_tpu.metrics import LogHistogram

    src = LogHistogram()
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            src.add(1e-3 * (1 + i % 997))
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(300):
            m = LogHistogram.merge([src])
            assert int(m.counts.sum()) == m.count
    finally:
        stop.set()
        t.join()
    m = LogHistogram.merge([src])
    assert int(m.counts.sum()) == m.count == len(src)
    assert m.sum == pytest.approx(src.sum)


def test_prometheus_render_constant_labels():
    """`labels=` stamps a constant label set on every series — gauges,
    histogram buckets (joined with `le`), _sum/_count and the
    `last_step` rider — with sanitized names and escaped values."""
    from solvingpapers_tpu.metrics import LogHistogram, PrometheusTextWriter

    h = LogHistogram(lo=0.01, hi=10.0, buckets_per_decade=2)
    h.add(0.3)
    text = PrometheusTextWriter.render(
        7, {"serve/ttft_s": h, "serve/qps": 2.0},
        labels={"replica": "r0", "mo del": 'a"b\nc\\d'})
    lines = text.splitlines()
    ls = '{replica="r0",mo_del="a\\"b\\nc\\\\d"}'
    assert f"serve_qps{ls} 2.0" in lines
    assert f"last_step{ls} 7" in lines
    assert "# TYPE serve_ttft_s histogram" in lines
    buckets = [ln for ln in lines
               if ln.startswith("serve_ttft_s_bucket{")]
    assert buckets and all(
        ln.startswith('serve_ttft_s_bucket{replica="r0",'
                      'mo_del="a\\"b\\nc\\\\d",le="')
        for ln in buckets)
    assert f"serve_ttft_s_count{ls} 1" in lines
    # unlabeled render is byte-stable vs the pre-label contract
    assert PrometheusTextWriter.render(7, {"a": 1.0}) == (
        "# TYPE a gauge\na 1.0\n"
        "# TYPE last_step gauge\nlast_step 7\n")


def test_prometheus_render_sets_fleet_contract():
    """The fleet /metrics shape: ONE `# TYPE` per metric name across
    all label sets, per-set `last_step{labels}` riders, (name, labels)
    dedupe with last write winning, and a histogram in any set claiming
    its derived names across ALL sets."""
    from solvingpapers_tpu.metrics import LogHistogram, PrometheusTextWriter

    h0 = LogHistogram(lo=0.01, hi=10.0, buckets_per_decade=2)
    h1 = LogHistogram(lo=0.01, hi=10.0, buckets_per_decade=2)
    for v in (0.02, 0.3):
        h0.add(v)
    h1.add(5.0)
    merged = LogHistogram.merge([h0, h1])
    text = PrometheusTextWriter.render_sets([
        (9, None, {"serve/ttft_s": merged, "fleet/replicas": 2.0}),
        # the gauge colliding with the histogram's _count is dropped
        (9, {"replica": "r0"}, {"serve/ttft_s": h0, "serve/qps": 1.0,
                                "serve/ttft_s_count": 99.0}),
        (4, {"replica": "r1"}, {"serve/ttft_s": h1, "serve/qps": 3.0}),
    ])
    lines = text.splitlines()
    for name in ("serve_ttft_s", "serve_qps", "last_step"):
        assert sum(ln.startswith(f"# TYPE {name} ")
                   for ln in lines) == 1, name
    assert 'serve_qps{replica="r0"} 1.0' in lines
    assert 'serve_qps{replica="r1"} 3.0' in lines
    assert "fleet_replicas 2.0" in lines
    assert "last_step 9" in lines
    assert 'last_step{replica="r0"} 9' in lines
    assert 'last_step{replica="r1"} 4' in lines
    # merged _count == sum of the labeled _counts (scrape aggregation)
    assert "serve_ttft_s_count 3" in lines
    assert 'serve_ttft_s_count{replica="r0"} 2' in lines
    assert 'serve_ttft_s_count{replica="r1"} 1' in lines
    assert not any(ln.startswith("serve_ttft_s_count{replica=\"r0\"} 99")
                   for ln in lines)
    # dedupe pointwise on (name, labels): the last write wins
    text2 = PrometheusTextWriter.render_sets([
        (1, {"replica": "r0"}, {"x": 1.0}),
        (2, {"replica": "r0"}, {"x": 5.0}),
    ])
    xs = [ln for ln in text2.splitlines() if ln.startswith('x{')]
    assert xs == ['x{replica="r0"} 5.0']


# ----------------------------------------------------- writer robustness


def test_jsonl_writer_context_manager_flushes_and_fsyncs(tmp_path):
    import json

    from solvingpapers_tpu.metrics import JSONLWriter

    path = str(tmp_path / "m.jsonl")
    with JSONLWriter(path) as w:
        w.write(1, {"loss": 2.0})
        w.write(2, {"loss": 1.5})
    recs = [json.loads(line) for line in open(path)]
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[1]["loss"] == 1.5
    # double close (context exit then explicit) must be a no-op
    w.close()


def test_multiwriter_close_survives_raising_writer(tmp_path):
    from solvingpapers_tpu.metrics import JSONLWriter, MetricsWriter, MultiWriter

    class Boom(MetricsWriter):
        def write(self, step, metrics):
            pass

        def close(self):
            raise RuntimeError("socket died")

    tail = JSONLWriter(str(tmp_path / "tail.jsonl"))
    multi = MultiWriter(Boom(), tail)
    multi.write(1, {"x": 1.0})
    # the raising writer must not stop the sweep: the JSONL still closes
    # (flush + fsync) and the first error still propagates
    with pytest.raises(RuntimeError, match="socket died"):
        multi.close()
    assert tail.f.closed
