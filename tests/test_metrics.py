"""MFU accounting unit tests (SURVEY.md hard part #5; VERDICT r1 item 8:
the device table must warn, not silently assume v5e) + the shared
percentile/ring-buffer aggregation serve latency metrics ride on."""

import importlib
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.metrics import Ring, percentiles

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast

# the package re-exports an `mfu` *function*; grab the module itself
mfu_mod = importlib.import_module("solvingpapers_tpu.metrics.mfu")


class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


@pytest.mark.parametrize("kind,peak", [
    ("TPU v4", 275e12),
    ("TPU v5 lite", 197e12),
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v6e", 918e12),
])
def test_chip_peak_flops_known_kinds(kind, peak):
    assert mfu_mod.chip_peak_flops(_FakeDevice(kind)) == peak


def test_chip_peak_flops_unknown_kind_warns_once():
    # unknown chips return the NaN sentinel (a silently-assumed v5e peak
    # mis-scaled every MFU number); consumers gate on math.isfinite
    import math

    mfu_mod._warned_kinds.clear()
    with pytest.warns(UserWarning, match="unrecognized device_kind"):
        assert math.isnan(mfu_mod.chip_peak_flops(_FakeDevice("TPU v9x")))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # second call must not warn again
        assert math.isnan(mfu_mod.chip_peak_flops(_FakeDevice("TPU v9x")))


def test_transformer_flops_per_token():
    # 6N + 12 L D S training; 2N + 4 L D S inference
    assert mfu_mod.transformer_flops_per_token(100, 2, 8, 16) == 600 + 12 * 2 * 8 * 16
    assert mfu_mod.transformer_flops_per_token(
        100, 2, 8, 16, training=False) == 200 + 4 * 2 * 8 * 16


def test_active_param_count_discounts_routed_experts():
    params = {
        "layer_0": {"moe": {
            "w1": jnp.zeros((8, 4, 6)), "w2": jnp.zeros((8, 4, 6)),
            "w3": jnp.zeros((8, 6, 4)),
            "gate": {"kernel": jnp.zeros((4, 8))},
        }},
        "head": {"kernel": jnp.zeros((4, 10))},
    }
    total = 3 * 8 * 24 + 32 + 40
    active = mfu_mod.active_param_count(params, top_experts=2, n_experts=8)
    assert active == total - (3 * 8 * 24 - 3 * 8 * 24 * 2 // 8)
    # without MoE info: plain total
    assert mfu_mod.active_param_count(params) == total


def test_percentiles_keys_and_values():
    vals = list(range(1, 101))  # 1..100
    out = percentiles(vals)
    assert set(out) == {"p50", "p95", "p99"}
    assert out["p50"] == pytest.approx(50.5)
    assert out["p95"] == pytest.approx(np.percentile(vals, 95))
    # non-integer quantile keeps its fractional label
    assert set(percentiles(vals, qs=(99.9,))) == {"p99.9"}
    assert percentiles([]) == {}


def test_ring_bounds_memory_and_tracks_recent():
    ring = Ring(capacity=4)
    for v in [1.0, 2.0, 3.0]:
        ring.add(v)
    assert len(ring) == 3
    assert ring.mean() == pytest.approx(2.0)
    for v in [4.0, 5.0, 6.0]:  # wraps: live window is now {3,4,5,6}
        ring.add(v)
    assert len(ring) == 4
    assert ring.total_added == 6
    assert sorted(ring.values().tolist()) == [3.0, 4.0, 5.0, 6.0]
    assert ring.percentiles(qs=(50,))["p50"] == pytest.approx(4.5)


def test_ring_empty_and_invalid_capacity():
    ring = Ring(capacity=8)
    assert len(ring) == 0
    assert ring.percentiles() == {}
    assert np.isnan(ring.mean())
    with pytest.raises(ValueError, match="capacity"):
        Ring(capacity=0)


def test_parity_regression_check():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "parity_suite",
        pathlib.Path(__file__).parent.parent / "tools" / "parity_suite.py",
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    history = [{"workloads": {
        "gpt_shakespeare": {"steps": 1000, "val_loss": 1.90},
        "vit_mnist": {"steps": 1200, "val_accuracy": 0.97},
    }}]
    ok = {"workloads": {
        "gpt_shakespeare": {"steps": 1000, "val_loss": 1.91},  # within tol
        "vit_mnist": {"steps": 1200, "val_accuracy": 0.975},
    }}
    assert mod.check_regressions(history, ok) == []
    bad = {"workloads": {
        "gpt_shakespeare": {"steps": 1000, "val_loss": 2.10},
        "vit_mnist": {"steps": 1200, "val_accuracy": 0.91},
    }}
    flags = mod.check_regressions(history, bad)
    assert len(flags) == 2, flags
    # different step counts must not be compared
    other = {"workloads": {
        "gpt_shakespeare": {"steps": 125, "val_loss": 3.0},
    }}
    assert mod.check_regressions(history, other) == []


# ----------------------------------------------------- writer robustness


def test_jsonl_writer_context_manager_flushes_and_fsyncs(tmp_path):
    import json

    from solvingpapers_tpu.metrics import JSONLWriter

    path = str(tmp_path / "m.jsonl")
    with JSONLWriter(path) as w:
        w.write(1, {"loss": 2.0})
        w.write(2, {"loss": 1.5})
    recs = [json.loads(line) for line in open(path)]
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[1]["loss"] == 1.5
    # double close (context exit then explicit) must be a no-op
    w.close()


def test_multiwriter_close_survives_raising_writer(tmp_path):
    from solvingpapers_tpu.metrics import JSONLWriter, MetricsWriter, MultiWriter

    class Boom(MetricsWriter):
        def write(self, step, metrics):
            pass

        def close(self):
            raise RuntimeError("socket died")

    tail = JSONLWriter(str(tmp_path / "tail.jsonl"))
    multi = MultiWriter(Boom(), tail)
    multi.write(1, {"x": 1.0})
    # the raising writer must not stop the sweep: the JSONL still closes
    # (flush + fsync) and the first error still propagates
    with pytest.raises(RuntimeError, match="socket died"):
        multi.close()
    assert tail.f.closed
