"""In-kernel flash-attention dropout — REAL TPU ONLY.

interpret-mode pltpu.prng_random_bits is a zero stub (every mask would be
all-keep, silently scaling probs by 1/(1-rate)), so these tests require the
hardware PRNG:

    SPTPU_TEST_PLATFORM=axon python -m pytest tests/test_flash_dropout_tpu.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.kernels import flash_attention

if jax.devices()[0].platform not in ("tpu",) and "TPU" not in str(
    getattr(jax.devices()[0], "device_kind", "")
):
    pytest.skip("requires a real TPU (in-kernel PRNG)", allow_module_level=True)


def make_qkv(key, b, sq, skv, n, n_kv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, n, d), dtype)
    k = jax.random.normal(kk, (b, skv, n_kv, d), dtype)
    v = jax.random.normal(kv, (b, skv, n_kv, d), dtype)
    return q, k, v


class TestInKernelDropout:
    """In-kernel attention-prob dropout: deterministic in seed, unbiased,
    and gradient-consistent (the backward kernels must regenerate the exact
    forward masks from (seed, block id) despite different loop orders)."""

    def setup_method(self):
        self.q, self.k, self.v = make_qkv(jax.random.key(7), 1, 256, 256, 2, 2, 32)

    def flash(self, rate, seed, q=None):
        return flash_attention(
            self.q if q is None else q, self.k, self.v, causal=True,
            dropout_rate=rate, dropout_seed=seed,
        )

    def test_deterministic_in_seed(self):
        a = self.flash(0.3, 5)
        b = self.flash(0.3, 5)
        c = self.flash(0.3, 6)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_unbiased_and_zero_rate_matches_dense(self):
        # TPU f32 matmuls pass through the MXU at bf16-level precision, so
        # hardware comparisons use bf16 tolerances (exact f32 equality is
        # covered by the interpret-mode suite)
        base = ops.dot_product_attention(self.q, self.k, self.v, causal=True)
        np.testing.assert_allclose(
            np.asarray(self.flash(0.0, 0)), np.asarray(base), rtol=3e-2, atol=3e-2
        )
        # mean over many seeds approaches the no-dropout output (unbiased):
        # single-seed mean |diff| is ~0.08; averaging n seeds shrinks it by
        # ~1/sqrt(n). Assert the mean absolute deviation, not the max (the
        # max over 16k elements is dominated by sampling noise).
        acc = np.zeros_like(np.asarray(base))
        n = 24
        for s in range(n):
            acc += np.asarray(self.flash(0.25, 100 + s))
        mad = np.abs(acc / n - np.asarray(base)).mean()
        assert mad < 0.05, mad

    def test_dv_mask_consistency_via_linearity(self):
        """out is exactly linear in v: out(v+U) - out(v) = P_dropped @ U with
        a fixed seed. Then <dOut, W> must equal <U, grad_v sum(out*W)> — an
        identity that only holds if the dk/dv backward kernel regenerates
        the forward's exact dropout mask (no finite-difference noise)."""
        key = jax.random.key(3)
        w = jax.random.normal(key, self.q.shape)
        u = jax.random.normal(jax.random.fold_in(key, 1), self.v.shape)

        def loss(v):
            return jnp.sum(
                flash_attention(self.q, self.k, v, causal=True,
                                dropout_rate=0.3, dropout_seed=11) * w
            )

        gv = jax.grad(loss)(self.v)
        lhs = float(loss(self.v + u) - loss(self.v))
        rhs = float(jnp.sum(u * gv))
        # bf16-MXU rounding noise; exact-mask grad equality is covered by
        # test_grads_match_dense_replica_with_extracted_mask
        np.testing.assert_allclose(lhs, rhs, rtol=2e-2)

    def test_grads_match_dense_replica_with_extracted_mask(self):
        """Strongest dropout-grad check: extract the kernel's actual keep
        mask (PRNG bits are reproducible across kernels — verified
        empirically), rebuild the identical dropped-attention function in
        dense JAX, and compare autodiff grads. Catches any fwd/bwd mask or
        formula inconsistency without finite-difference noise (fd at bf16
        MXU precision is unreliable: input quantization swamps eps-scale
        perturbations)."""
        from jax.experimental import pallas as pl

        from solvingpapers_tpu.kernels.flash_attention import _dropout_keep

        S, D, rate, seed = 256, 32, 0.3, 11
        bq = bk = 128  # 2x2 blocks exercises the uid indexing across blocks

        def mask_kernel(o_ref):
            for j in range(S // bq):
                for kb in range(S // bk):
                    uid = j * (S // bk) + kb  # _uid(i=0, j, kb)
                    keep = _dropout_keep((bq, bk), seed, uid, rate)
                    o_ref[j * bq:(j + 1) * bq, kb * bk:(kb + 1) * bk] = (
                        keep.astype(jnp.float32)
                    )

        keep = (
            jnp.asarray(
                pl.pallas_call(
                    mask_kernel,
                    out_shape=jax.ShapeDtypeStruct((S, S), jnp.float32),
                )()
            )
            > 0
        )
        assert 0.6 < float(keep.mean()) < 0.8  # actually dropping

        q, k, v = make_qkv(jax.random.key(5), 1, S, S, 1, 1, D)

        def dense(q, k, v):
            qq = q[0, :, 0, :] * D**-0.5
            s = qq @ k[0, :, 0, :].T
            s = jnp.where(jnp.tril(jnp.ones((S, S), bool)), s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            return (jnp.where(keep, p / (1 - rate), 0.0) @ v[0, :, 0, :])[
                None, :, None, :
            ]

        def flash(q, k, v):
            return flash_attention(
                q, k, v, causal=True, dropout_rate=rate, dropout_seed=seed,
                block_q=bq, block_k=bk,
            )

        fwd_err = float(jnp.max(jnp.abs(flash(q, k, v) - dense(q, k, v))))
        assert fwd_err < 2e-2, fwd_err
        gf = jax.grad(lambda *a: jnp.sum(flash(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(lambda *a: jnp.sum(dense(*a) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            rel = float(jnp.max(jnp.abs(a - b)) / jnp.max(jnp.abs(b)))
            assert rel < 2e-2, rel

    def test_trains_with_dropout(self):
        """End-to-end: GPT with use_flash + in-kernel dropout must train."""
        import numpy as onp

        from solvingpapers_tpu.data.batches import lm_batch_iterator
        from solvingpapers_tpu.models.gpt import GPT, GPTConfig
        from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

        cfg = GPTConfig(vocab_size=64, block_size=128, dim=64, n_layers=2,
                        n_heads=2, dropout=0.1, dtype="bfloat16",
                        use_flash=True)
        tcfg = TrainConfig(steps=0, batch_size=16, log_every=10**9,
                           eval_every=0,
                           optimizer=OptimizerConfig(max_lr=3e-3,
                                                     total_steps=40))
        tr = Trainer(GPT(cfg), tcfg)
        toks = onp.random.default_rng(0).integers(0, 20, size=100_000)
        it = lm_batch_iterator(toks, 16, 128, seed=0)
        b0 = next(it)
        state = tr.init_state(b0)
        tr._build_steps()
        state, m = tr._train_step(state, b0)
        first = float(jax.device_get(m["train_loss"]))
        for _ in range(40):
            state, m = tr._train_step(state, next(it))
        last = float(jax.device_get(m["train_loss"]))
        assert last < first - 0.5, (first, last)


class TestRingFlashDropout:
    """CP dropout (VERDICT r2 item 6): the ring-flash path with in-kernel
    dropout, validated as far as one real chip allows — a 1-member ring is
    the same custom-VJP code path (per-chunk seed salting, masked merges,
    backward mask regeneration); multi-member decorrelation is structural
    (_chunk_seed strides distinct (owner, chunk) pairs apart in seed space).
    """

    def _ring(self, q, k, v, rate, seed):
        from jax.sharding import Mesh

        from solvingpapers_tpu.sharding.ring_attention import (
            ring_flash_attention_local,
        )

        mesh = Mesh(np.array(jax.devices()[:1]), ("context",))
        fn = lambda q, k, v: ring_flash_attention_local(  # noqa: E731
            q, k, v, "context", causal=True, dropout_rate=rate,
            dropout_seed=seed,
        )
        from jax.sharding import PartitionSpec as P

        return jax.shard_map(
            fn, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False,
        )(q, k, v)

    def setup_method(self):
        kq, kk, kv = jax.random.split(jax.random.key(9), 3)
        self.q = jax.random.normal(kq, (1, 256, 2, 32))
        self.k = jax.random.normal(kk, (1, 256, 2, 32))
        self.v = jax.random.normal(kv, (1, 256, 2, 32))

    def test_one_member_ring_matches_plain_flash_dropout(self):
        """_chunk_seed(s, 0, 0, 1) == s, so the 1-ring must equal the plain
        kernel with the same seed bit-for-bit — pins the seed plumbing."""
        ring = self._ring(self.q, self.k, self.v, 0.3, 5)
        plain = flash_attention(self.q, self.k, self.v, causal=True,
                                dropout_rate=0.3, dropout_seed=5)
        np.testing.assert_array_equal(np.asarray(ring), np.asarray(plain))

    def test_ring_dropout_grad_linearity(self):
        """out is linear in v at fixed seed; <loss(v+u)-loss(v)> must equal
        <u, grad_v loss> through the ring's custom VJP — holds only if the
        backward ring regenerates the forward's exact per-chunk masks."""
        key = jax.random.key(4)
        w = jax.random.normal(key, self.q.shape)
        u = jax.random.normal(jax.random.fold_in(key, 1), self.v.shape)

        def loss(v):
            return jnp.sum(self._ring(self.q, self.k, v, 0.3, 11) * w)

        gv = jax.grad(loss)(self.v)
        lhs = float(loss(self.v + u) - loss(self.v))
        rhs = float(jnp.sum(u * gv))
        np.testing.assert_allclose(lhs, rhs, rtol=2e-2)

    def test_chunk_seeds_decorrelate(self):
        """Distinct (owner, chunk) pairs map to seeds the kernel treats as
        independent streams: the kernel output for consecutive pair seeds
        must differ (the multi-member ring's mask independence)."""
        from solvingpapers_tpu.sharding.ring_attention import _chunk_seed

        base = jnp.asarray([7], jnp.int32)
        seeds = [
            int(_chunk_seed(base, jnp.int32(o), jnp.int32(c), 4)[0])
            for o in range(2) for c in range(2)
        ]
        assert len(set(seeds)) == 4  # all pairs distinct
        outs = [
            np.asarray(flash_attention(self.q, self.k, self.v, causal=True,
                                       dropout_rate=0.3, dropout_seed=s))
            for s in seeds[:2]
        ]
        assert not np.allclose(outs[0], outs[1])


class TestUlyssesFlashDropout:
    """Ulysses CP dropout on TPU, validated as far as one real chip allows:
    a 1-member axis runs the same code path (in-kernel seed from make_rng's
    per-member stream through the all_to_all wrapper); multi-member mask
    independence is structural (the engine folds the rng per 'context'
    member, and within a member the kernel's per-(bn, block) uid salts
    heads apart) and is exercised on the CPU mesh by
    tests/test_engine_cp.py::test_cp_ulysses_dropout_trains_deterministically.
    """

    def _ulysses(self, q, k, v, rate, seed):
        from jax.sharding import Mesh, PartitionSpec as P

        from solvingpapers_tpu.sharding.ring_attention import (
            ulysses_attention_local,
        )

        mesh = Mesh(np.array(jax.devices()[:1]), ("context",))
        core = lambda q, k, v: flash_attention(  # noqa: E731
            q, k, v, causal=True, dropout_rate=rate, dropout_seed=seed,
        )
        fn = lambda q, k, v: ulysses_attention_local(  # noqa: E731
            q, k, v, "context", core
        )
        return jax.shard_map(
            fn, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False,
        )(q, k, v)

    def setup_method(self):
        kq, kk, kv = jax.random.split(jax.random.key(13), 3)
        self.q = jax.random.normal(kq, (1, 256, 2, 32))
        self.k = jax.random.normal(kk, (1, 256, 2, 32))
        self.v = jax.random.normal(kv, (1, 256, 2, 32))

    def test_one_member_matches_plain_flash_dropout(self):
        """A 1-member axis is an identity all_to_all: the wrapped core must
        equal the plain kernel bit-for-bit at the same seed."""
        out = self._ulysses(self.q, self.k, self.v, 0.3, 5)
        plain = flash_attention(self.q, self.k, self.v, causal=True,
                                dropout_rate=0.3, dropout_seed=5)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(plain))

    def test_dropout_grad_linearity_through_all_to_all(self):
        """out is linear in v at fixed seed; the identity
        <loss(v+u)-loss(v)> == <u, grad_v loss> holds only if the backward
        regenerates the forward's masks through the all_to_all transpose."""
        key = jax.random.key(6)
        w = jax.random.normal(key, self.q.shape)
        u = jax.random.normal(jax.random.fold_in(key, 1), self.v.shape)

        def loss(v):
            return jnp.sum(self._ulysses(self.q, self.k, v, 0.3, 11) * w)

        gv = jax.grad(loss)(self.v)
        lhs = float(loss(self.v + u) - loss(self.v))
        rhs = float(jnp.sum(u * gv))
        np.testing.assert_allclose(lhs, rhs, rtol=2e-2)
