"""ServeMetrics.snapshot() key-surface tests + the Prometheus textfile
sink that scrapes it.

The snapshot's flat key set is now API three consumers depend on: the
MetricsWriter sinks, the flight recorder's anomaly dumps
(metrics/trace.py embeds a snapshot per dump), and the
PrometheusTextWriter (sanitized names must stay stable or dashboards
break). These tests lock the presence/absence rules: finish-reason keys
appear per observed reason, prefix gauges appear iff lookups occurred,
and rate keys are absent (not NaN/inf) at zero elapsed time.
"""

import os
import types

import pytest

from solvingpapers_tpu.metrics.writer import PrometheusTextWriter
from solvingpapers_tpu.serve.metrics import ServeMetrics

pytestmark = pytest.mark.fast


def _req(submit=0.0, prompt_len=4, reason=None):
    return types.SimpleNamespace(
        submit_time=submit, prompt=list(range(prompt_len)),
        finish_reason=reason,
    )


def _base_keys():
    return {
        "serve/tokens_out", "serve/tokens_prefilled",
        "serve/requests_finished", "serve/requests_rejected", "serve/steps",
    }


def test_snapshot_empty_has_only_counters():
    snap = ServeMetrics().snapshot()
    assert set(snap) == _base_keys()
    assert all(v == 0.0 for v in snap.values())


def test_rate_keys_absent_at_zero_elapsed():
    """One instant of activity: elapsed == 0, so tokens/requests-per-sec
    must be ABSENT — a 0-division inf/NaN would poison every sink."""
    m = ServeMetrics()
    m.record_first_token(_req(), now=1.0, prefilled=4)
    snap = m.snapshot()
    assert "serve/tokens_per_sec" not in snap
    assert "serve/requests_per_sec" not in snap
    # latency rings observed -> their summaries ARE present
    assert snap["serve/ttft_s_mean"] == pytest.approx(1.0)
    # a second observation later opens the window and the rates appear
    m.record_tokens(_req(), n=2, span_s=0.5, now=2.0)
    snap = m.snapshot()
    assert snap["serve/tokens_per_sec"] == pytest.approx(3 / 1.0)
    assert snap["serve/requests_per_sec"] == 0.0


def test_finish_reason_keys_per_observed_reason():
    m = ServeMetrics()
    for reason in ("eos", "eos", "timeout", None):
        m.record_finish(_req(reason=reason), now=1.0)
    snap = m.snapshot()
    assert snap["serve/finish_eos"] == 2.0
    assert snap["serve/finish_timeout"] == 1.0
    assert snap["serve/finish_unknown"] == 1.0
    assert "serve/finish_cancelled" not in snap  # never observed
    assert snap["serve/requests_finished"] == 4.0


def test_prefix_gauges_present_iff_lookups_occurred():
    m = ServeMetrics()
    assert not any(k.startswith("serve/prefix") for k in m.snapshot())
    # a MISS still counts as a lookup -> the whole gauge family appears
    m.record_prefix_lookup(0)
    snap = m.snapshot()
    assert snap["serve/prefix_lookups"] == 1.0
    assert snap["serve/prefix_hits"] == 0.0
    assert snap["serve/prefix_hit_rate"] == 0.0
    m.record_prefix_lookup(32)
    m.record_prefix_state(bytes_held=1024, evictions=2)
    snap = m.snapshot()
    assert snap["serve/prefix_hit_rate"] == 0.5
    assert snap["serve/prefix_cached_tokens"] == 32.0
    assert snap["serve/tokens_prefilled_saved"] == 32.0
    assert snap["serve/prefix_evictions"] == 2.0
    assert snap["serve/prefix_hbm_bytes"] == 1024.0


def test_latency_summaries_present_iff_observed():
    m = ServeMetrics()
    m.record_admit(_req(submit=0.0), now=0.25)
    snap = m.snapshot()
    assert snap["serve/queue_wait_s_mean"] == pytest.approx(0.25)
    assert snap["serve/queue_wait_s_p99"] == pytest.approx(0.25)
    assert "serve/itl_s_mean" not in snap  # no tokens streamed yet
    assert "serve/ttft_s_mean" not in snap
    assert "serve/e2e_s_mean" not in snap  # nothing finished yet
    m.record_finish(_req(submit=0.0, reason="eos"), now=0.75)
    snap = m.snapshot()
    assert snap["serve/e2e_s_mean"] == pytest.approx(0.75)
    assert snap["serve/e2e_s_p99"] == pytest.approx(0.75)


def test_prom_snapshot_carries_histograms_iff_observed():
    """prom_snapshot() = snapshot() + the LogHistogram objects under the
    base latency names — what the Prometheus paths render as native
    _bucket/_sum/_count series; flat sinks keep the float surface."""
    from solvingpapers_tpu.metrics.hist import LogHistogram

    m = ServeMetrics()
    assert not any(isinstance(v, LogHistogram)
                   for v in m.prom_snapshot().values())
    m.record_admit(_req(submit=0.0), now=0.25)
    snap = m.prom_snapshot()
    assert isinstance(snap["serve/queue_wait_s"], LogHistogram)
    assert "serve/ttft_s" not in snap  # unobserved stays absent
    # the float summary rides alongside, under its own names
    assert snap["serve/queue_wait_s_mean"] == pytest.approx(0.25)
    # the whole mixed set renders as valid exposition text
    text = PrometheusTextWriter.render(1, snap)
    assert 'serve_queue_wait_s_bucket{le="+Inf"} 1' in text
    assert "serve_queue_wait_s_count 1" in text
    # emit() routes histograms only to sinks that declare support
    class Flat:
        accepts_histograms = False

        def write(self, step, metrics):
            self.seen = metrics

    flat = Flat()
    m.emit(flat)
    assert not any(isinstance(v, LogHistogram) for v in flat.seen.values())


def test_slo_gauges_present_iff_configured():
    """slo/* + serve/goodput_* appear exactly when the engine has
    ServeConfig.slo_targets (gauge provider, same mechanism as the
    paged/spec/observatory families) and account per-class attainment,
    burn and goodput."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.serve import SamplingParams, ServeConfig, ServeEngine
    from solvingpapers_tpu.serve.slo import DEFAULT_SLO_TARGETS

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    plain = ServeEngine(model, params, ServeConfig(n_slots=2, max_len=32))
    assert not any(k.startswith("slo/") or "goodput" in k
                   for k in plain.metrics.snapshot())
    # an slo tag without a tracker must refuse, not silently untrack
    with pytest.raises(ValueError, match="slo_targets"):
        plain.submit(np.arange(4, dtype=np.int32),
                     params=SamplingParams(slo="interactive"))
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        slo_targets=DEFAULT_SLO_TARGETS,
    ))
    with pytest.raises(ValueError, match="unknown SLO class"):
        eng.submit(np.arange(4, dtype=np.int32),
                   params=SamplingParams(slo="platinum"))
    snap = eng.metrics.snapshot()
    for cls in DEFAULT_SLO_TARGETS:
        assert snap[f"slo/{cls}_finished"] == 0.0
        assert f"slo/{cls}_attainment" not in snap  # no invented values
    assert snap["serve/goodput_tokens"] == 0.0
    hs = [
        eng.submit(np.arange(4 + i, dtype=np.int32), max_new_tokens=6,
                   params=SamplingParams(slo="interactive"))
        for i in range(2)
    ]
    hs.append(eng.submit(np.arange(8, dtype=np.int32), max_new_tokens=6))
    eng.run()
    assert all(h.done for h in hs)
    end = eng.metrics.snapshot()
    assert end["slo/interactive_finished"] == 2.0
    assert end["slo/standard_finished"] == 1.0  # untagged -> standard
    assert 0.0 <= end["slo/interactive_attainment"] <= 1.0
    assert end["slo/interactive_burn_rate"] >= 0.0
    assert end["serve/goodput_tokens"] <= end["serve/tokens_out"]
    if end["serve/goodput_tokens"]:
        assert end["serve/goodput_tokens_per_s"] > 0
    # the per-request verdict rides the handle for the debug timeline
    assert hs[0].slo_result is not None
    assert hs[0].slo_result["class"] == "interactive"
    assert set(hs[0].slo_result) >= {"attained", "violated", "latencies"}
    # /statusz carries the build identity + slo section
    doc = eng.statusz()
    assert doc["build"]["jax"] and doc["build"]["uptime_s"] >= 0
    assert doc["build"]["version"]
    assert set(doc["slo"]["classes"]) == set(DEFAULT_SLO_TARGETS)
    assert doc["slo"]["classes"]["interactive"]["finished"] == 2
    for k in end:
        if k.startswith("slo/"):
            assert PrometheusTextWriter.sanitize(k).startswith("slo_")


def test_slo_tracker_accounting_rules():
    """Unit rules: timeout counts as a violation, cancelled is excluded
    entirely, a request that never reached a configured target's phase
    is violated (no observation != attained), burn = windowed violation
    rate / error budget."""
    import types as _t

    from solvingpapers_tpu.serve.slo import SloTracker

    tr = SloTracker({"standard": {"ttft_s": 1.0, "e2e_s": 5.0,
                                  "objective": 0.9}}, burn_window=4)

    def req(reason, submit=0.0, first=None, finish=None, tokens=3,
            slo=None):
        return _t.SimpleNamespace(
            finish_reason=reason, submit_time=submit,
            first_token_time=first, finish_time=finish,
            tokens=list(range(tokens)),
            params=_t.SimpleNamespace(slo=slo),
        )

    ok = tr.observe(req("eos", first=0.5, finish=2.0), now=2.0)
    assert ok["attained"] and tr.goodput_tokens == 3
    # timeout before first token: ttft configured but unobservable ->
    # violated, tokens excluded from goodput
    bad = tr.observe(req("timeout"), now=2.0)
    assert not bad["attained"] and "ttft_s" in bad["violated"]
    assert tr.goodput_tokens == 3
    # cancelled: excluded from numerator AND denominator
    assert tr.observe(req("cancelled"), now=1.0) is None
    g = tr.gauges(elapsed_s=2.0)
    assert g["slo/standard_finished"] == 2.0
    assert g["slo/standard_attainment"] == 0.5
    # window [True, False]: violation rate 0.5 / budget 0.1 = 5.0
    assert g["slo/standard_burn_rate"] == pytest.approx(5.0)
    assert g["serve/goodput_tokens_per_s"] == pytest.approx(1.5)
    assert tr.statusz()["excluded_finishes"] == 1
    # config validation fails loudly
    with pytest.raises(ValueError, match="standard"):
        SloTracker({"gold": {"ttft_s": 1.0}})
    with pytest.raises(ValueError, match="unknown keys"):
        SloTracker({"standard": {"ttft_ms": 1.0}})
    with pytest.raises(ValueError, match="objective"):
        SloTracker({"standard": {"ttft_s": 1.0, "objective": 1.5}})


def test_preemption_keys_present_iff_observed():
    """serve/preemptions + serve/recompute_tokens ride the snapshot only
    once a preemption happened — the paged pool's exhaustion path must
    not grow the lane pool's key surface."""
    m = ServeMetrics()
    assert "serve/preemptions" not in m.snapshot()
    m.record_preemption()
    m.record_recompute_tokens(24)
    snap = m.snapshot()
    assert snap["serve/preemptions"] == 1.0
    assert snap["serve/recompute_tokens"] == 24.0
    # recompute work counts as prefill compute too
    assert snap["serve/tokens_prefilled"] == 24.0


def test_fault_keys_present_iff_observed():
    """The fault-tolerance counter family (serve/fault_*,
    serve/watchdog_stalls, serve/shed_<class>, serve/degrade_transitions)
    rides the snapshot only once its event happened — a fault-free run's
    key surface is byte-identical to the pre-fault engine's."""
    m = ServeMetrics()
    base = m.snapshot()
    fault_prefixes = ("serve/fault", "serve/watchdog", "serve/shed_",
                      "serve/degrade")
    assert not [k for k in base if k.startswith(fault_prefixes)]
    m.record_fault_injected()
    m.record_quarantine()
    m.record_engine_retry()
    m.record_engine_unhealthy()
    m.record_watchdog_stall(1.25)
    m.record_recovery(0.5)
    m.record_degrade_transition()
    m.record_shed("batch")
    m.record_shed("batch")
    snap = m.snapshot()
    assert snap["serve/fault_injected"] == 1.0
    assert snap["serve/fault_quarantined"] == 1.0
    assert snap["serve/fault_retries"] == 1.0
    assert snap["serve/fault_unhealthy"] == 1.0
    assert snap["serve/watchdog_stalls"] == 1.0
    assert snap["serve/fault_recovery_s"] == 0.5
    assert snap["serve/degrade_transitions"] == 1.0
    assert snap["serve/shed_batch"] == 2.0
    # every key must survive the Prometheus name sanitizer
    PrometheusTextWriter.render(0, snap)


def test_page_gauges_present_iff_paged_engine():
    """serve/pages_* appear exactly when the engine runs the paged pool
    (the engine registers a gauge provider, same mechanism as the
    observatory) and report the live free/active split."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.serve import ServeConfig, ServeEngine

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    lane = ServeEngine(model, params, ServeConfig(n_slots=2, max_len=32))
    assert not any(k.startswith("serve/pages")
                   for k in lane.metrics.snapshot())
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8, paged=True,
        page_size=4,
    ))
    snap = eng.metrics.snapshot()
    budget = 2 * (32 // 4)
    assert snap["serve/pages_free"] == float(budget)
    assert snap["serve/pages_active"] == 0.0
    assert snap["serve/page_fragmentation"] == 0.0
    h = eng.submit(np.arange(9, dtype=np.int32), max_new_tokens=16)
    eng.step()  # prefill + one block: the stream is still mid-flight
    mid = eng.metrics.snapshot()
    assert mid["serve/pages_active"] > 0
    assert mid["serve/pages_free"] < budget
    assert 0.0 <= mid["serve/page_fragmentation"] < 1.0
    eng.run()
    assert h.done
    end = eng.metrics.snapshot()
    assert end["serve/pages_free"] == float(budget)
    # names survive the Prometheus grammar like every other serve/* key
    for k in ("serve/pages_free", "serve/pages_active",
              "serve/page_fragmentation"):
        assert PrometheusTextWriter.sanitize(k).startswith("serve_")


def test_spec_gauges_present_iff_speculation_enabled():
    """serve/spec_* appear exactly when the engine speculates (gauge
    provider registered iff ServeConfig.speculative) and track the
    acceptance accounting."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.serve import ServeConfig, ServeEngine

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    plain = ServeEngine(model, params, ServeConfig(n_slots=2, max_len=32))
    assert not any(k.startswith("serve/spec_")
                   for k in plain.metrics.snapshot())
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        speculative="ngram", spec_k=2, spec_rounds=2,
    ))
    snap = eng.metrics.snapshot()
    assert snap["serve/spec_acceptance_rate"] == 0.0
    assert snap["serve/spec_tokens_per_step"] == 0.0
    h = eng.submit(np.tile(np.asarray([3, 9], np.int32), 5),
                   max_new_tokens=12)
    eng.run()
    assert h.done
    end = eng.metrics.snapshot()
    assert end["serve/spec_tokens_per_step"] > 0
    assert 0.0 <= end["serve/spec_acceptance_rate"] <= 1.0
    assert end["serve/spec_drafts_rejected"] >= 0.0
    # the /statusz spec section mirrors the same accounting
    spec = eng.statusz()["spec"]
    assert spec["drafter"] == "ngram" and spec["steps"] > 0
    for k in ("serve/spec_acceptance_rate", "serve/spec_tokens_per_step",
              "serve/spec_drafts_rejected"):
        assert PrometheusTextWriter.sanitize(k).startswith("serve_")


def test_kv_quant_gauges_present_iff_quantized_pool():
    """serve/kv_bytes_per_token + serve/kv_quant_* appear exactly when
    the engine's pool is quantized (gauge provider registered iff
    ServeConfig.kv_quant), the exact-lane pair only with a sidecar
    configured, and the byte gauges decompose analytically."""
    import jax
    import jax.numpy as jnp

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.serve import ServeConfig, ServeEngine
    from solvingpapers_tpu.serve.kv_pool import quant_pool_bytes

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    plain = ServeEngine(model, params, ServeConfig(n_slots=2, max_len=32))
    assert not any(k.startswith(("serve/kv_bytes", "serve/kv_quant"))
                   for k in plain.metrics.snapshot())
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, kv_quant="int8", kv_quant_block=16,
    ))
    snap = eng.metrics.snapshot()
    pool_bytes, scale_bytes, _, base_bytes = quant_pool_bytes(
        eng.pool.caches)
    assert snap["serve/kv_bytes_per_token"] == pytest.approx(
        pool_bytes / (2 * 32))
    assert snap["serve/kv_quant_scale_bytes"] == float(scale_bytes)
    assert snap["serve/kv_quant_bytes_saved"] == float(
        base_bytes - pool_bytes)
    # no sidecar configured -> the exact-lane pair stays absent
    assert "serve/kv_quant_exact_lanes_free" not in snap
    ex = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, kv_quant="int8", kv_exact_lanes=2,
    ))
    esnap = ex.metrics.snapshot()
    assert esnap["serve/kv_quant_exact_lanes_free"] == 2.0
    assert esnap["serve/kv_quant_exact_active"] == 0.0
    for k in ("serve/kv_bytes_per_token", "serve/kv_quant_scale_bytes",
              "serve/kv_quant_bytes_saved",
              "serve/kv_quant_exact_lanes_free"):
        assert PrometheusTextWriter.sanitize(k).startswith("serve_")


# ------------------------------------- observatory gauges (mem/compile)


def test_observatory_gauges_absent_without_provider():
    """mem/* and compile/* keys exist IFF the compile & memory
    observatory registered its gauge providers — a bare ServeMetrics
    must never grow them."""
    m = ServeMetrics()
    m.record_first_token(_req(), now=1.0, prefilled=4)
    snap = m.snapshot()
    assert not any(k.startswith(("mem/", "compile/", "roofline/"))
                   for k in snap)


def test_gauge_providers_ride_every_snapshot():
    m = ServeMetrics()
    calls = {"n": 0}

    def provider():
        calls["n"] += 1
        return {"compile/compilations": 3.0, "mem/kv_pool_bytes": 4096.0,
                "roofline/decode_block_flops_per_s": 1e9}

    m.add_gauge_provider(provider)
    snap = m.snapshot()
    assert snap["compile/compilations"] == 3.0
    assert snap["mem/kv_pool_bytes"] == 4096.0
    assert snap["roofline/decode_block_flops_per_s"] == 1e9
    # resolved per snapshot (live gauges), and base keys survive the merge
    m.snapshot()
    assert calls["n"] == 2
    assert _base_keys() <= set(snap)


def test_real_observatory_gauge_key_surface():
    """The actual CompileRegistry/HBMLedger providers emit the documented
    key families, every value a float, every name Prometheus-sanitizable
    (the same contract the snapshot's serve/* keys honor)."""
    from solvingpapers_tpu.metrics.xla_obs import CompileRegistry, HBMLedger

    m = ServeMetrics()
    reg = CompileRegistry()
    ledger = HBMLedger(capacity_bytes=1 << 30)
    ledger.register("kv_pool", 4096)
    ledger.temp_fn = reg.max_temp_bytes
    m.add_gauge_provider(reg.gauges)
    m.add_gauge_provider(ledger.gauges)
    snap = m.snapshot()
    for key in ("compile/programs", "compile/compilations",
                "compile/recompiles", "compile/storms", "compile/time_s",
                "mem/kv_pool_bytes", "mem/live_bytes",
                "mem/program_temp_bytes", "mem/projected_peak_bytes",
                "mem/capacity_bytes", "mem/headroom_bytes"):
        assert key in snap, key
        assert isinstance(snap[key], float), key
    assert snap["mem/headroom_bytes"] == float((1 << 30) - 4096)
    # the whole surface must survive the Prometheus sink's name grammar
    import re

    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    for k in snap:
        assert name_re.match(PrometheusTextWriter.sanitize(k)), k


# ------------------------------------------------------- prometheus sink


def test_prometheus_sanitizes_the_snapshot_name_table(tmp_path):
    """Every snapshot key must sanitize to a valid Prometheus metric name
    ([a-zA-Z_:][a-zA-Z0-9_:]*) — including the fractional-percentile
    shape p99.9 — and the sink must expose the full serve table."""
    m = ServeMetrics()
    m.record_admit(_req(), now=0.5)
    m.record_first_token(_req(), now=1.0, prefilled=4)
    m.record_tokens(_req(), n=4, span_s=0.4, now=2.0)
    m.record_finish(_req(reason="eos"), now=2.0)
    m.record_prefix_lookup(16)
    snap = m.snapshot()
    path = str(tmp_path / "serve.prom")
    w = PrometheusTextWriter(path)
    w.write(7, snap)
    text = open(path).read()
    import re

    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    seen = set()
    for line in text.splitlines():
        if line.startswith("#"):
            assert line.split()[1] == "TYPE"
            continue
        name, value = line.split(" ", 1)
        assert name_re.match(name), name
        float(value)  # parseable
        seen.add(name)
    assert seen == {PrometheusTextWriter.sanitize(k) for k in snap} | {
        "last_step"
    }
    assert "serve_ttft_s_p99 " in text
    assert "serve_finish_eos 1.0" in text
    assert "last_step 7" in text
    # the fractional-percentile name shape stays legal
    assert PrometheusTextWriter.sanitize("serve/itl_s_p99.9") == \
        "serve_itl_s_p99_9"
    assert PrometheusTextWriter.sanitize("9lives") == "_9lives"


def test_prometheus_write_is_atomic_replace(tmp_path):
    path = str(tmp_path / "m.prom")
    w = PrometheusTextWriter(path, prefix="train_")
    w.write(1, {"loss": 1.5})
    w.write(2, {"loss": 1.25})  # replaces, never appends
    text = open(path).read()
    value_lines = [ln for ln in text.splitlines()
                   if ln.startswith("train_loss ")]
    assert value_lines == ["train_loss 1.25"]  # replaced, not appended
    assert "train_last_step 2" in text
    assert not os.path.exists(path + ".tmp")  # tmp file consumed by rename


def test_prometheus_dedupes_colliding_names(tmp_path):
    """Two keys that sanitize to one name, or a user metric named
    last_step, must not produce duplicate series — node_exporter's
    textfile collector rejects the WHOLE file on a duplicate."""
    path = str(tmp_path / "m.prom")
    w = PrometheusTextWriter(path)
    w.write(9, {"serve/ttft": 1.0, "serve.ttft": 2.0, "last_step": 5.0})
    lines = open(path).read().splitlines()
    names = [ln.split(" ", 1)[0] for ln in lines if not ln.startswith("#")]
    assert len(names) == len(set(names)), f"duplicate series: {names}"
    # last key wins the collision; the staleness rider yields to the
    # user's own last_step metric
    assert "serve_ttft 2.0" in lines
    assert "last_step 5.0" in lines


def test_prometheus_nonfinite_values(tmp_path):
    path = str(tmp_path / "m.prom")
    PrometheusTextWriter(path).write(
        0, {"a": float("inf"), "b": float("-inf"), "c": float("nan")}
    )
    text = open(path).read()
    assert "a +Inf" in text and "b -Inf" in text and "c NaN" in text
