"""Checkpoint round-trips (SURVEY.md §4 plan): full-state resume restores
identical training trajectories; params-only export round-trips; viz
artifacts render.
"""

import os

import jax
import numpy as np

from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.sharding import MeshConfig, create_mesh
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

TINY = GPTConfig(vocab_size=64, block_size=16, dim=16, n_layers=1, n_heads=2,
                 dropout=0.0)


def make_trainer(steps, ckdir=None, ckpt_every=0, total_steps=4, async_ckpt=True):
    # schedule horizon fixed at 4 so the interrupted and straight runs see
    # identical LR at every step
    mesh = create_mesh(MeshConfig(data=1), jax.devices()[:1])
    cfg = TrainConfig(
        steps=steps, batch_size=4, log_every=1000, eval_every=0,
        checkpoint_dir=ckdir, ckpt_every=ckpt_every,
        async_checkpointing=async_ckpt,
        optimizer=OptimizerConfig(max_lr=1e-3, warmup_steps=0,
                                  total_steps=total_steps),
    )
    return Trainer(GPT(TINY), cfg, mesh=mesh)


import pytest  # noqa: E402

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast


@pytest.mark.parametrize("async_ckpt", [True, False], ids=["async", "sync"])
def test_resume_matches_uninterrupted(tmp_path, async_ckpt):
    """Train 4 steps straight == train 2, resume from checkpoint, train 2 —
    for both the async (background write, donated step buffers still safe
    because Orbax finishes the D2H snapshot before save() returns) and the
    fully synchronous manager."""
    _, toks, _ = load_char_corpus(synthetic_chars=5_000)
    it_fn = lambda: lm_batch_iterator(toks, 4, TINY.block_size, seed=0)  # noqa: E731

    straight = make_trainer(4).fit(it_fn())

    ckdir = str(tmp_path / "ck")
    make_trainer(2, ckdir, ckpt_every=2, async_ckpt=async_ckpt).fit(it_fn())
    # resume: same deterministic batch stream; fit skips to start_step by
    # restoring, so feed the iterator from the same seed and let steps 0-1
    # be consumed by the restored start_step offset
    it = it_fn()
    for _ in range(2):
        next(it)  # the two batches already trained before preemption
    resumed = make_trainer(4, ckdir, ckpt_every=100, async_ckpt=async_ckpt).fit(it)

    for a, b in zip(jax.tree.leaves(straight.params), jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)
    assert int(resumed.step) == 4


def test_resume_realigns_scan_windows(tmp_path):
    """A checkpoint resume can land mid scan-window (TrainConfig.scan_steps):
    fit must single-step back to alignment, keep window ends on multiples
    of scan_steps, and track the uninterrupted run (scan-compiled and
    single-step programs fuse differently, so the two runs' different
    window mixes diverge at float-epsilon level — same tolerance as the
    sharded-equality tests)."""
    import dataclasses

    _, toks, _ = load_char_corpus(synthetic_chars=5_000)
    it_fn = lambda: lm_batch_iterator(toks, 4, TINY.block_size, seed=0)  # noqa: E731

    def scanify(t, steps):
        # sgd, not adam: the two runs mix scan-compiled and single-step
        # programs at different steps, and adam's normalizer amplifies the
        # resulting float-epsilon differences into lr-scale sign flips
        # (same reasoning as the PP equality tests)
        t.config = dataclasses.replace(
            t.config, scan_steps=4, steps=steps, log_every=1000,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-2,
                                      warmup_steps=0, total_steps=16),
        )
        t.tx, t.schedule = __import__(
            "solvingpapers_tpu.train.engine", fromlist=["make_optimizer"]
        ).make_optimizer(t.config.optimizer)
        return t

    straight = scanify(make_trainer(14, total_steps=16), 14).fit(it_fn())

    ckdir = str(tmp_path / "ck")
    # stop at 6 (not a multiple of 4; the forced final save records it):
    # the resume starts mid-window, must single-step to re-align, then run
    # the 8-12 window and the ragged 12-14 tail
    scanify(make_trainer(6, ckdir, ckpt_every=4, total_steps=16), 6).fit(it_fn())
    it = it_fn()
    for _ in range(6):
        next(it)
    resumed = scanify(
        make_trainer(14, ckdir, ckpt_every=100, total_steps=16), 14
    ).fit(it)

    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
    assert int(resumed.step) == 14


def test_async_save_overlaps_and_is_durable(tmp_path):
    """An async periodic save must return before the write is durable (the
    step loop keeps running) yet be fully restorable after close(). The
    overlap assertion is relative to a measured sync save of the SAME
    state, so a silent regression to blocking saves fails the test
    regardless of how fast the filesystem is."""
    import time

    from solvingpapers_tpu.checkpoint import CheckpointManager

    # ~128 MB: big enough that a full sync write is measurably slower than
    # an async dispatch on any filesystem
    big = {f"w{i}": jax.numpy.full((1024, 8192), float(i), jax.numpy.float32)
           for i in range(4)}

    sync_dir = str(tmp_path / "sync_ck")
    sync_mgr = CheckpointManager(sync_dir, save_every=1, async_saves=False)
    t0 = time.perf_counter()
    assert sync_mgr.maybe_save(1, big)
    sync_elapsed = time.perf_counter() - t0
    sync_mgr.close()

    ckdir = str(tmp_path / "async_ck")
    mgr = CheckpointManager(ckdir, save_every=1, async_saves=True)
    t0 = time.perf_counter()
    assert mgr.maybe_save(1, big)
    dispatch = time.perf_counter() - t0
    mgr.close()  # blocks until durable

    mgr2 = CheckpointManager(ckdir, save_every=1, async_saves=True)
    restored = mgr2.restore_latest(jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), big))
    assert restored is not None and restored[1] == 1
    np.testing.assert_array_equal(np.asarray(restored[0]["w2"]),
                                  np.asarray(big["w2"]))
    mgr2.close()
    assert dispatch < sync_elapsed * 0.5, (dispatch, sync_elapsed)


def test_params_export_roundtrip(tmp_path):
    from solvingpapers_tpu.checkpoint import export_params, load_params

    model = GPT(TINY)
    toks = jax.numpy.zeros((1, 8), jax.numpy.int32)
    params = model.init({"params": jax.random.key(0)}, toks)["params"]
    path = str(tmp_path / "export")
    export_params(path, jax.device_get(params))
    loaded = load_params(path)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_reconstruction_grid_renders(tmp_path):
    from solvingpapers_tpu.metrics.viz import save_reconstruction_grid, save_text_sample

    rng = np.random.default_rng(0)
    orig = rng.random((8, 784)).astype(np.float32)
    recon = rng.random((8, 28, 28, 1)).astype(np.float32)
    path = save_reconstruction_grid(orig, recon, str(tmp_path / "g.png"))
    assert os.path.getsize(path) > 1000

    tpath = save_text_sample("hello", str(tmp_path / "arts"), 500)
    assert tpath.endswith("generated_500.txt")
    assert open(tpath).read() == "hello"


def test_activation_curves_render(tmp_path):
    from solvingpapers_tpu.metrics.viz import save_activation_curves

    path = save_activation_curves(str(tmp_path / "act.png"))
    assert os.path.getsize(path) > 5000


def test_grad_accumulation_matches_big_batch():
    """optax.MultiSteps accumulation: 2 micro-steps of batch 4 must equal
    one step of batch 8 (the functional replacement for deepseekv3
    cell 54's accumulate-then-step inner loop)."""
    import jax.numpy as jnp

    from solvingpapers_tpu.data import load_char_corpus
    from solvingpapers_tpu.data.batches import lm_batch_iterator

    _, toks, _ = load_char_corpus(synthetic_chars=5_000)
    it = lm_batch_iterator(toks, 8, TINY.block_size, seed=0)
    big = next(it)
    micro1 = {k: v[:4] for k, v in big.items()}
    micro2 = {k: v[4:] for k, v in big.items()}

    mesh = create_mesh(MeshConfig(data=1), jax.devices()[:1])

    def make(accum):
        # sgd without clipping: the update is linear in the gradient, so
        # mean-of-micro-grads == big-batch grad exactly (adamw's g/|g|
        # first step amplifies float summation-order noise unboundedly)
        cfg = TrainConfig(
            steps=2, batch_size=8, log_every=1000, eval_every=0,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-2, warmup_steps=0,
                                      total_steps=4, accum_steps=accum,
                                      grad_clip=0.0, weight_decay=0.0),
        )
        return Trainer(GPT(TINY), cfg, mesh=mesh)

    t_big = make(1)
    s_big = t_big.init_state(big)
    t_big._build_steps()
    s_big, _ = t_big._train_step(s_big, big)

    t_acc = make(2)
    s_acc = t_acc.init_state(micro1)
    t_acc._build_steps()
    s_acc, _ = t_acc._train_step(s_acc, micro1)
    s_acc, _ = t_acc._train_step(s_acc, micro2)

    for a, b in zip(jax.tree.leaves(s_big.params), jax.tree.leaves(s_acc.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
