"""Grammar-constrained JSON decoding (serve/grammar.py + the engine's
allow-mask plumbing).

Contracts under test:
* the stepper's allowed set is NEVER empty before the document
  completes (for any legal token walk, under any budget the engine
  would admit) and is empty exactly at `done`;
* budget-aware closing: a constrained stream always completes a
  `json.loads`-parseable document within its token budget;
* EOS has no place mid-document — `submit` rejects grammar + eos_id;
* through the engine, constrained and unconstrained slots share the
  ONE compiled decode program (jit cache pinned) and constrained
  greedy streams are deterministic, on both pool layouts.
"""

import json
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import JsonStepper, ServeConfig, ServeEngine
from solvingpapers_tpu.serve.engine import _decode_program, _prefill_program
from solvingpapers_tpu.serve.grammar import encode_allow

# 64-char table covering the JSON alphabet (ids beyond stay letters)
ALPHABET = '{}[]":,-.0123456789 \nabcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOP\\'
TABLE = list(ALPHABET[:64])

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


# ------------------------------------------------------------- stepper unit


def test_ctor_rejects_vocab_without_braces():
    with pytest.raises(ValueError, match="cannot express"):
        JsonStepper(list("abc"))


def test_min_close_at_start_is_two():
    st = JsonStepper(TABLE)
    assert st.min_close == 2  # '{' '}'


def test_known_document_feeds_to_done():
    st = JsonStepper(TABLE)
    doc = '{"a": [1, 2.5e-3, true, null, "x\\n"], "b": {"": false}}'
    for ch in doc:
        st.feed(ch)
    assert st.done
    assert st.allowed() == []  # EOS territory: nothing legal after done


def test_illegal_char_raises():
    st = JsonStepper(TABLE)
    st.feed("{")
    with pytest.raises(ValueError, match="not legal"):
        st.feed(":")  # a colon cannot follow '{'


@pytest.mark.parametrize("seed", range(12))
def test_random_walk_mask_never_empty_and_parses(seed):
    """Any walk that always picks from `allowed(budget)` completes a
    valid document within the budget — the mask is never empty before
    `done`, and `done` arrives at or before budget exhaustion."""
    rng = random.Random(seed)
    st = JsonStepper(TABLE)
    budget = rng.randint(2, 80)
    out = []
    b = budget
    while not st.done:
        ids = st.allowed(b)
        assert ids, (seed, "".join(out), st.mode, b)
        tid = rng.choice(ids)
        st.advance(tid)
        out.append(TABLE[tid])
        b -= 1
        assert b >= 0, (seed, "".join(out))
    json.loads("".join(out))


def test_tight_budget_forces_minimal_document():
    st = JsonStepper(TABLE)
    ids = st.allowed(2)
    assert [TABLE[t] for t in ids] == ["{"]
    st.advance(ids[0])
    ids = st.allowed(1)
    assert [TABLE[t] for t in ids] == ["}"]
    st.advance(ids[0])
    assert st.done


def test_allowed_is_deterministic_and_closing_first():
    st = JsonStepper(TABLE)
    st.advance(TABLE.index("{"))
    a, b = st.allowed(50), st.allowed(50)
    assert a == b
    # most-closing-first ordering: '}' (completes the doc) leads, so a
    # sample_cap truncation can never strand the stream
    assert TABLE[a[0]] == "}"


def test_multichar_tokens_simulated_whole():
    table = ["{", "}", '"ab"', ":", "7", '"}', "}{"]
    st = JsonStepper(table)
    st.advance(0)  # {
    ids = st.allowed(10)
    # '}{' is illegal (document completes mid-token then continues)
    assert 6 not in ids
    assert 2 in ids  # a whole quoted key is one legal token
    st.advance(2)
    assert st.allowed(8) == [3]  # only ':' after a key
    st.advance(3)
    st.advance(4)
    st.advance(1)
    assert st.done


def test_string_budget_closes_before_exhaustion():
    """Inside a string with the budget running out, the mask narrows to
    the closing quote and then the container closers."""
    st = JsonStepper(TABLE)
    for ch in '{"k':
        st.feed(ch)
    # min_close: '"' + ':' + value + '}' = 4
    assert st.min_close == 4
    ids = st.allowed(4)
    assert [TABLE[t] for t in ids] == ['"']


def test_encode_allow_truncates_head():
    row = encode_allow([5, 9, 2], cap=2)
    assert row.tolist() == [5, 9]
    row = encode_allow([5], cap=4)
    assert row.tolist() == [5, -1, -1, -1]


# ------------------------------------------------------------ engine level


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_engine_json_mode_emits_valid_json(gpt_tiny, paged):
    """A constrained greedy stream through the engine parses, finishes
    "stop" at the complete document, and is deterministic — while an
    unconstrained request shares the same batch untouched."""
    model, params = gpt_tiny
    streams = []
    for _ in range(2):
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=4, max_len=64, decode_block=4, bucket=8,
            paged=paged, page_size=8 if paged else None,
        ))
        h = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=24,
                       grammar=JsonStepper(TABLE))
        plain = eng.submit(np.arange(3, dtype=np.int32), max_new_tokens=12)
        eng.run()
        text = "".join(TABLE[t] for t in h.tokens)
        json.loads(text)
        assert h.finish_reason == "stop"
        assert len(h.tokens) <= 24
        assert plain.finish_reason == "length" and len(plain.tokens) == 12
        streams.append(h.tokens)
    assert streams[0] == streams[1], "constrained greedy stream not pinned"


def test_engine_json_mode_compile_count_unchanged(gpt_tiny):
    """The allow-mask is a traced operand: admitting a constrained
    request compiles ZERO new programs beyond the plain engine's."""
    model, params = gpt_tiny
    cfg = ServeConfig(n_slots=2, max_len=64, decode_block=4, bucket=8)
    eng = ServeEngine(model, params, cfg)
    eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=8)
    eng.run()
    decode_progs = _decode_program._cache_size()
    prefill_progs = _prefill_program._cache_size()
    h = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=16,
                   grammar=JsonStepper(TABLE))
    eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=8)
    eng.run()
    json.loads("".join(TABLE[t] for t in h.tokens))
    assert _decode_program._cache_size() == decode_progs
    assert _prefill_program._cache_size() == prefill_progs


def test_submit_rejects_grammar_with_eos(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8))
    with pytest.raises(ValueError, match="complete document"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=16,
                   eos_id=3, grammar=JsonStepper(TABLE))


def test_submit_rejects_budget_below_min_close(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8))
    with pytest.raises(ValueError, match="shortest document"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=1,
                   grammar=JsonStepper(TABLE))


def test_engine_grammar_default_eos_ignored(gpt_tiny):
    """An engine-wide default eos_id must not leak into a grammar
    request (EOS only legal at a complete document)."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8, eos_id=0))
    h = eng.submit(np.arange(5, dtype=np.int32), max_new_tokens=24,
                   grammar=JsonStepper(TABLE))
    eng.run()
    assert h.eos_id is None
    assert h.finish_reason == "stop"
    json.loads("".join(TABLE[t] for t in h.tokens))


def test_engine_grammar_one_token_per_block_budget_exact(gpt_tiny):
    """A constrained slot advances one token per decode block; even so
    the budget-aware mask closes the document at or before
    max_new_tokens — never a truncated stream."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=8, bucket=8))
    h = eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=10,
                   grammar=JsonStepper(TABLE))
    eng.run()
    assert h.finish_reason == "stop"
    assert len(h.tokens) <= 10
    json.loads("".join(TABLE[t] for t in h.tokens))
