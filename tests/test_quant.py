"""Property tests for ops/quant.py — the int8 KV-cache primitive behind
`ServeConfig.kv_quant`.

The properties the serving pools lean on: round-trip error bounded by
half a scale step per block, block absmax mapping to +-127 exactly (the
requantization-stability anchor), all-zero blocks round-tripping
bit-exact (fresh pools hold zeros), and the sidecar scale shapes pinned
for both the lane layout (time-blocked lanes) and the page layout
(block == page_size, one scale row per physical page).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.ops.quant import (
    dequantize,
    dequantize_tree,
    quantize,
    quantize_tree,
    scale_shape,
)

pytestmark = pytest.mark.fast


def _lane_leaf(shape, seed=0, scale=3.0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(0.0, scale, size=shape), jnp.float32)


@pytest.mark.parametrize(
    "shape,block",
    [((2, 64, 4, 8), 16), ((3, 32, 12), 8), ((1, 48, 2, 16), 48)],
    ids=["kv-lane", "latent-lane", "kv-page"],
)
def test_roundtrip_error_bounded_by_half_scale(shape, block):
    """|x - deq(q)| <= scale/2 for every entry, against the entry's OWN
    block scale — the symmetric-absmax bound the quality gate rides on."""
    x = _lane_leaf(shape, seed=1)
    q, scale = quantize(x, block)
    assert q.dtype == jnp.int8
    deq = dequantize(q, scale, jnp.float32)
    err = np.abs(np.asarray(x) - np.asarray(deq))
    # broadcast each entry's block scale back over the leaf layout
    b, t = shape[0], shape[1]
    nb = t // block
    s = np.asarray(scale)
    if len(shape) == 4:
        s_full = np.broadcast_to(
            s[:, :, None, :, None], (b, nb, block, shape[2], shape[3])
        ).reshape(shape)
    else:
        s_full = np.broadcast_to(
            s[:, :, None, None], (b, nb, block, shape[2])
        ).reshape(shape)
    assert np.all(err <= s_full / 2 + 1e-6 * s_full + 1e-12)


def test_absmax_entries_map_to_pm127_exactly():
    """Each block's max-magnitude entry quantizes to exactly +-127 —
    which is also why requantizing a dequantized block with an unchanged
    absmax is a fixed point (127 * scale == absmax)."""
    x = _lane_leaf((2, 32, 2, 8), seed=2)
    block = 8
    q, scale = quantize(x, block)
    xs = np.asarray(x).reshape(2, 4, block, 2, 8)
    qs = np.asarray(q).reshape(2, 4, block, 2, 8)
    flat_x = np.abs(xs).transpose(0, 1, 3, 2, 4).reshape(2, 4, 2, -1)
    flat_q = np.abs(qs).transpose(0, 1, 3, 2, 4).reshape(2, 4, 2, -1)
    arg = np.argmax(flat_x, axis=-1)
    picked = np.take_along_axis(flat_q, arg[..., None], axis=-1)[..., 0]
    assert np.all(picked == 127)
    # and the scale is absmax / 127 for every (batch, block, head) row
    np.testing.assert_allclose(
        np.asarray(scale), flat_x.max(axis=-1) / 127.0, rtol=1e-6
    )


def test_requantize_of_dequantized_block_is_fixed_point():
    """quantize(dequantize(q, s)) == (q, s) when the block content is
    untouched — the property that lets the serving programs requantize
    only written windows without drifting their neighbours."""
    x = _lane_leaf((2, 64, 4, 8), seed=3)
    q, s = quantize(x, 16)
    deq = dequantize(q, s, jnp.float32)
    q2, s2 = quantize(deq, 16)
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    np.testing.assert_allclose(np.asarray(s), np.asarray(s2), rtol=1e-6)


def test_all_zero_blocks_roundtrip_bit_exact():
    """Zero pages (fresh pools, zero-padded lanes) must survive exactly:
    scale 0, q 0, dequant 0 — never a NaN from a 0/0."""
    x = jnp.zeros((2, 32, 3, 4), jnp.float32)
    q, scale = quantize(x, 16)
    assert np.all(np.asarray(q) == 0)
    assert np.all(np.asarray(scale) == 0.0)
    deq = dequantize(q, scale, jnp.float32)
    assert np.all(np.asarray(deq) == 0.0)
    assert np.all(np.isfinite(np.asarray(deq)))
    # mixed: one zero block next to a live one stays exact
    x = x.at[:, 16:].set(_lane_leaf((2, 16, 3, 4), seed=4))
    q, scale = quantize(x, 16)
    deq = dequantize(q, scale, jnp.float32)
    assert np.all(np.asarray(deq[:, :16]) == 0.0)


def test_scale_shapes_pinned_for_lane_and_page_layouts():
    """The sidecar shapes three pools depend on: lane KV leaves carry
    (S, T/block, H) scales, latent lanes (S, T/block), and page-pool
    leaves (pages, 1, H) / (pages, 1) — one scale row per physical page
    (block == page_size), which is what lets scales ride the page
    tables."""
    assert scale_shape((8, 128, 4, 32), 16) == (8, 8, 4)
    assert scale_shape((8, 128, 96), 16) == (8, 8)
    # page layout: batch dim IS the page id, time dim == page_size
    assert scale_shape((65, 16, 4, 32), 16) == (65, 1, 4)
    assert scale_shape((65, 16, 96), 16) == (65, 1)
    q, s = quantize(_lane_leaf((8, 128, 4, 32)), 16)
    assert s.shape == (8, 8, 4) and s.dtype == jnp.float32
    q, s = quantize(_lane_leaf((65, 16, 96)), 16)
    assert s.shape == (65, 1)
    with pytest.raises(ValueError):
        scale_shape((8, 100, 4, 32), 16)  # block must tile time
    with pytest.raises(ValueError):
        scale_shape((8, 100), 16)  # not a cache-leaf layout


def test_quantize_is_traceable_and_clip_symmetric():
    """Traced under jit (the pools quantize inside the serving
    programs), and the code space stays symmetric: -128 never appears."""
    x = _lane_leaf((2, 32, 2, 8), seed=5, scale=50.0)
    q, scale = jax.jit(lambda a: quantize(a, 8))(x)
    assert int(np.asarray(q).min()) >= -127
    deq = jax.jit(lambda a, b: dequantize(a, b, jnp.bfloat16))(q, scale)
    assert deq.dtype == jnp.bfloat16


def test_tree_helpers_preserve_structure():
    from solvingpapers_tpu.infer.cache import KVCache, LatentCache

    tree = [KVCache.init(2, 32, 2, 8, jnp.float32),
            LatentCache.init(2, 32, 24, jnp.float32)]
    tree = jax.tree_util.tree_map(
        lambda a: a + _lane_leaf(a.shape, seed=6), tree
    )
    q_tree, s_tree = quantize_tree(tree, 16)
    assert isinstance(q_tree[0], KVCache) and isinstance(s_tree[0], KVCache)
    assert q_tree[0].k.dtype == jnp.int8
    assert s_tree[0].k.shape == (2, 2, 2)
    assert s_tree[1].c.shape == (2, 2)
    deq = dequantize_tree(q_tree, s_tree, jnp.float32)
    err = jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))), tree, deq
    )
    for leaf_err, leaf in zip(jax.tree_util.tree_leaves(err),
                              jax.tree_util.tree_leaves(tree)):
        assert leaf_err <= float(jnp.max(jnp.abs(leaf))) / 127.0
