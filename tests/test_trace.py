"""Flight-recorder tracing tests (metrics/trace.py + engine wiring).

The contract: tracing is invisible when off (engine holds None, streams
token-exact either way), and when on the exported Chrome trace's
request-lifecycle spans PARTITION each request's wall time — queue +
prefill + decode == finish_time - submit_time — because the engine stamps
them from the same Request timestamps the latency metrics use. That
identity is what makes `cli trace-summary` a trustworthy post-mortem.
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.metrics.trace import (
    AnomalyMonitor,
    FlightRecorder,
    events_to_chrome,
    format_summary,
    load_chrome,
    summarize_trace,
)
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import ServeConfig, ServeEngine

pytestmark = pytest.mark.fast

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompts(n, seed=0, lo=4, hi=20):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, GPT_TINY.vocab_size,
                     size=int(rng.integers(lo, hi))).astype(np.int32)
        for _ in range(n)
    ]


# ---------------------------------------------------------------- recorder


def test_ring_is_bounded_and_keeps_newest():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.instant(f"e{i}", "t", "engine")
    assert len(rec) == 4
    assert rec.total_recorded == 10
    assert [e.name for e in rec.events()] == ["e6", "e7", "e8", "e9"]
    assert [e.name for e in rec.last(2)] == ["e8", "e9"]
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(capacity=0)


def test_span_records_duration_and_survives_exceptions():
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    rec = FlightRecorder(clock=clock)
    with rec.span("ok", "t", "train"):
        pass
    with pytest.raises(RuntimeError):
        with rec.span("boom", "t", "train"):
            raise RuntimeError("x")
    evs = rec.events()
    assert [e.name for e in evs] == ["ok", "boom"]
    assert all(e.ph == "X" and e.dur == 1.0 for e in evs)


def test_recorder_is_thread_safe():
    rec = FlightRecorder(capacity=10_000)

    def work(k):
        for i in range(500):
            rec.instant(f"t{k}", "t", "engine", i=i)

    threads = [threading.Thread(target=work, args=(k,)) for k in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert rec.total_recorded == 2000
    assert len(rec) == 2000


def test_chrome_export_structure(tmp_path):
    rec = FlightRecorder()
    rec.complete("queue", "request", "queue", ts=1.0, dur=0.5, req=7)
    rec.complete("prefill", "request", "slot1", ts=1.5, dur=0.25, req=7)
    rec.instant("finish", "request", "slot1", ts=2.0, req=7, reason="eos")
    rec.counter("queue_depth", "engine", "engine", ts=1.0, depth=3)
    path = rec.export_chrome(str(tmp_path / "t.json"))
    obj = json.load(open(path))
    evs = obj["traceEvents"]
    # thread-name metadata for every track, in display-sort order
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert names == {"engine", "queue", "slot1"}
    # timestamps are relative microseconds
    q = next(e for e in evs if e["ph"] == "X" and e["name"] == "queue")
    assert q["ts"] == 0.0 and q["dur"] == 0.5e6
    assert q["args"]["req"] == 7
    # one flow per request: start + finish (2 spans + 1 instant -> s, t, f)
    flows = [e for e in evs if e.get("cat") == "flow" and e.get("id") == 7]
    assert [f["ph"] for f in flows] == ["s", "t", "f"]
    # counters carry their values
    c = next(e for e in evs if e["ph"] == "C")
    assert c["args"] == {"depth": 3}
    assert load_chrome(path) == evs


# ------------------------------------------------------------- anomalies


def _mon(tmp_path, rec, **kw):
    return AnomalyMonitor(rec, str(tmp_path / "anom.jsonl"),
                          snapshot_fn=lambda: {"serve/steps": 1.0}, **kw)


def _dumps(tmp_path):
    p = tmp_path / "anom.jsonl"
    if not p.exists():
        return []
    return [json.loads(line) for line in p.read_text().splitlines()]


def test_anomaly_slow_step_uses_rolling_median(tmp_path):
    rec = FlightRecorder()
    rec.instant("ctx", "engine", "engine")
    mon = _mon(tmp_path, rec, min_steps=4, slow_step_factor=5.0)
    for _ in range(8):
        mon.observe_step(0.01)
    mon.observe_step(0.02)  # 2x: under the factor, no dump
    assert mon.dumps == 0
    mon.observe_step(0.2)  # 20x the median
    assert mon.dumps == 1
    (rec_d,) = _dumps(tmp_path)
    assert rec_d["kind"] == "slow_step"
    assert rec_d["detail"]["median_s"] == pytest.approx(0.01)
    assert rec_d["metrics"] == {"serve/steps": 1.0}
    assert [e["name"] for e in rec_d["events"]] == ["ctx"]


def test_anomaly_reject_burst_fires_once_per_burst(tmp_path):
    rec = FlightRecorder()
    mon = _mon(tmp_path, rec, reject_burst=3)
    for _ in range(5):  # one burst, even past the threshold
        mon.observe_reject()
    assert mon.dumps == 1
    mon.observe_accept()  # reset
    for _ in range(3):
        mon.observe_reject()
    assert mon.dumps == 2


def test_anomaly_finish_reasons_and_keep_newest_rotation(tmp_path):
    """Past max_dumps the file rotates KEEP-NEWEST (the old hard cap
    silently dropped every later incident — exactly the records a live
    post-mortem needs), warning once on the first rotation."""
    rec = FlightRecorder()
    mon = _mon(tmp_path, rec, max_dumps=3)
    mon.observe_finish("eos")
    mon.observe_finish("length")
    assert mon.dumps == 0
    mon.observe_finish("timeout")
    mon.observe_finish("cancelled")
    assert mon.dumps == 2
    mon.observe_finish("timeout")  # fills the file to the cap
    with pytest.warns(RuntimeWarning, match="rotating keep-newest"):
        for i in range(10):
            mon.dump("probe", i=i)
    assert mon.dumps == 13  # total ever taken keeps counting
    recs = _dumps(tmp_path)
    assert len(recs) == 3  # file stays bounded...
    # ...and holds the NEWEST records, oldest rotated out
    assert [r["detail"].get("i") for r in recs] == [7, 8, 9]
    # a second overflow must not warn again
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        mon.dump("probe", i=10)
    assert [r["detail"].get("i") for r in _dumps(tmp_path)] == [8, 9, 10]
    assert not (tmp_path / "anom.jsonl.tmp").exists()


# ------------------------------------------------------ engine integration


def test_engine_trace_off_is_absent(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(n_slots=2, max_len=64))
    assert eng.trace is None and eng._mon is None
    with pytest.raises(ValueError, match="needs trace=True"):
        ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=64, trace_dump_path="x.jsonl",
        ))


def test_traced_phases_partition_request_wall_time(gpt_tiny, tmp_path):
    """Acceptance: phase durations from the exported trace sum to within
    5% of each request's measured TTFT + decode wall time (they are exact
    up to export rounding — same clock readings)."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8, trace=True,
    ))
    prompts = _prompts(6, seed=3)
    handles = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    assert all(h.done for h in handles)
    path = eng.trace.export_chrome(str(tmp_path / "serve.json"))
    summary = summarize_trace(path)
    assert summary["n_requests"] == len(handles)
    assert summary["finish_reasons"] == {"length": len(handles)}
    by_id = {r["req"]: r for r in summary["requests"]}
    for h in handles:
        r = by_id[h.id]
        wall = h.finish_time - h.submit_time
        ttft = h.first_token_time - h.submit_time
        assert r["total_s"] == pytest.approx(wall, rel=0.05, abs=1e-5)
        assert (r["phases"]["queue"] + r["phases"]["prefill"]
                == pytest.approx(ttft, rel=0.05, abs=1e-5))
        assert r["slot"] == f"slot{h.slot}"
        assert r["tokens"] == len(h.tokens)
    # instrumentation exists alongside the lifecycle spans
    names = {e.name for e in eng.trace.events()}
    assert {"submit", "step", "prefill_program", "decode_block",
            "finish"} <= names
    # the step spans carry batch composition
    step_ev = next(e for e in eng.trace.events() if e.name == "step")
    assert {"prefills", "decode_slots", "transfers",
            "device_s"} <= set(step_ev.args)
    out = format_summary(summary, top=3)
    assert "slowest 3 requests" in out and "queue_s" in out


def test_traced_streams_match_untraced(gpt_tiny):
    """Tracing must be observationally invisible: same tokens either way."""
    model, params = gpt_tiny
    prompts = _prompts(4, seed=5)

    def run(trace):
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=64, decode_block=4, bucket=8, trace=trace,
        ))
        hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run()
        return [h.tokens for h in hs]

    assert run(True) == run(False)


def test_engine_anomaly_dump_on_queue_timeout(gpt_tiny, tmp_path):
    model, params = gpt_tiny
    dump = str(tmp_path / "anom.jsonl")
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8, trace=True,
        trace_dump_path=dump,
    ))
    blocker = eng.submit(_prompts(1, seed=6)[0], max_new_tokens=16)
    doomed = eng.submit(_prompts(1, seed=7)[0], max_new_tokens=16,
                        deadline_s=1e-6)
    eng.run()
    assert blocker.finish_reason == "length"
    assert doomed.finish_reason == "timeout"
    recs = [json.loads(line) for line in open(dump)]
    kinds = [r["kind"] for r in recs]
    assert "finish_timeout" in kinds
    rec = recs[kinds.index("finish_timeout")]
    assert rec["metrics"]["serve/finish_timeout"] == 1.0
    assert any(e.get("name") == "finish" for e in rec["events"])


def test_prefix_cache_and_scheduler_events(gpt_tiny, tmp_path):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=2, bucket=8, trace=True,
        prefix_cache=True, prefix_page=4,
    ))
    stem = _prompts(1, seed=8, lo=16, hi=17)[0]
    tails = _prompts(3, seed=9, lo=4, hi=5)
    handles = [eng.submit(np.concatenate([stem, t]), max_new_tokens=4)
               for t in tails]
    eng.run()
    assert all(h.done for h in handles)
    names = [e.name for e in eng.trace.events()]
    assert "prefix_lookup" in names
    assert "prefix_snapshot" in names
    # at least one hit-splice after the first request seeded the stem
    splices = [e for e in eng.trace.events() if e.name == "splice"]
    assert splices and all(e.args["matched"] > 0 for e in splices)
    lookups = [e for e in eng.trace.events() if e.name == "prefix_lookup"]
    assert len(lookups) == len(handles)
    assert sum(e.args["hit"] for e in lookups) >= 1


def test_idle_steps_are_not_traced_or_monitored(gpt_tiny, tmp_path):
    """An external loop polling step() while idle must not spam the ring
    or feed ~microsecond no-ops into the anomaly monitor's rolling
    median (which would flag the first REAL step as a slow-step
    anomaly and dump on every step after it)."""
    model, params = gpt_tiny
    dump = str(tmp_path / "anom.jsonl")
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8, trace=True,
        trace_dump_path=dump,
    ))
    for _ in range(32):  # idle polling, past the monitor's min_steps
        eng.step()
    assert not any(e.name == "step" for e in eng.trace.events())
    h = eng.submit(_prompts(1, seed=13)[0], max_new_tokens=8)
    eng.run()
    assert h.done
    assert eng._mon.dumps == 0, "real step flagged as anomaly after idling"
    steps = [e for e in eng.trace.events() if e.name == "step"]
    assert steps, "working steps must still be traced"


def test_summarize_tallies_rejects_separately(gpt_tiny):
    """Rejected submissions never held a lane: they must not appear as
    zero-phase request rows (indistinguishable from a served request the
    ring lost) but as a separate tally."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8, max_waiting=2,
        trace=True,
    ))
    prompts = _prompts(3, seed=14)
    handles = [eng.submit(p, max_new_tokens=4) for p in prompts]
    assert handles[2].state == "rejected"
    eng.run()
    summary = summarize_trace(eng.trace.to_chrome())
    assert summary["n_requests"] == 2
    assert summary["rejected"] == 1
    assert handles[2].id not in {r["req"] for r in summary["requests"]}
    assert "rejected submissions: 1" in format_summary(summary)


def test_summarize_handles_unadmitted_requests(gpt_tiny, tmp_path):
    """A request cancelled while waiting has only a queue phase; its
    total is still finish - submit."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8, trace=True,
    ))
    h0 = eng.submit(_prompts(1, seed=10)[0], max_new_tokens=8)
    h1 = eng.submit(_prompts(1, seed=11)[0], max_new_tokens=8)
    eng.cancel(h1)
    eng.run()
    assert h1.finish_reason == "cancelled"
    summary = summarize_trace(eng.trace.to_chrome())
    r1 = next(r for r in summary["requests"] if r["req"] == h1.id)
    assert set(r1["phases"]) == {"queue"}
    wall = h1.finish_time - h1.submit_time
    assert r1["total_s"] == pytest.approx(wall, rel=0.05, abs=1e-5)
    assert r1["finish_reason"] == "cancelled"
    assert h0.done


def test_events_to_chrome_empty():
    assert events_to_chrome([]) == {"traceEvents": [],
                                    "displayTimeUnit": "ms"}


def test_summarize_joins_http_phases(gpt_tiny):
    """HTTP front-door spans (cat "http", serve/api.py) join their
    request's engine lifecycle row: http_phases + e2e_s appear on rows
    that have them, the summary grows an `http` section, and a trace
    WITHOUT http spans keeps the key absent (PR-8-era traces summarize
    unchanged)."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8, trace=True,
    ))
    h = eng.submit(_prompts(1, seed=21)[0], max_new_tokens=6)
    eng.run()
    assert h.done
    no_http = summarize_trace(eng.trace.to_chrome())
    assert "http" not in no_http
    assert "http_phases" not in no_http["requests"][0]
    # synthesize the front door's contiguous spans around the engine's
    t0 = h.submit_time
    eng.trace.complete("accept", "http", "http", ts=t0 - 0.003,
                       dur=0.001, req=h.id, trace_id="rid-1")
    eng.trace.complete("parse", "http", "http", ts=t0 - 0.002,
                       dur=0.0015, req=h.id)
    eng.trace.complete("queue_handoff", "http", "http", ts=t0 - 0.0005,
                       dur=0.0005, req=h.id)
    eng.trace.complete("sse_drain", "http", "http", ts=h.finish_time,
                       dur=0.002, req=h.id, events=3)
    eng.trace.instant("disconnect", "http", "http", req=h.id)
    # an http span for an UNKNOWN request must not invent a timeline row
    eng.trace.complete("accept", "http", "http", ts=t0, dur=0.001,
                       req=99999)
    summary = summarize_trace(eng.trace.to_chrome())
    assert summary["n_requests"] == 1
    r = summary["requests"][0]
    assert r["http_phases"] == pytest.approx({
        "accept": 0.001, "parse": 0.0015, "queue_handoff": 0.0005,
        "sse_drain": 0.002,
    }, rel=1e-3)
    assert r["e2e_s"] == pytest.approx(r["total_s"] + 0.005, rel=1e-3)
    assert summary["http"]["disconnects"] == 1
    assert summary["http"]["phase_totals_s"]["accept"] == \
        pytest.approx(0.001, rel=1e-3)
    out = format_summary(summary)
    assert "http front door:" in out and "disconnects: 1" in out


# ------------------------------------------------------------------ fleet


def _fleet_sections():
    """Router + two replica recorders with a migrated request: the
    synthetic fleet the stitching/summary tests drive."""
    router = FlightRecorder()
    r0 = FlightRecorder()
    r1 = FlightRecorder()
    router.complete("route", "fleet", "router", ts=1.0, dur=0.002,
                    req=5, rid="rid-a", replica="r0", attempts=1,
                    scores=[{"replica": "r0"}])
    r0.instant("submit", "request", "queue", ts=1.002, req=5,
               prompt_len=4, rid="rid-a")
    r0.complete("queue", "request", "queue", ts=1.002, dur=0.001, req=5)
    r0.instant("finish", "request", "slot0", ts=1.01, req=5,
               reason="migrated")
    router.complete("migrate", "fleet", "router", ts=1.02, dur=0.004,
                    req=5, rid="rid-a", src="r0", dst="r1")
    router.complete("drain", "fleet", "router", ts=1.02, dur=0.005,
                    replica="r0", entries=1, migrated=1, errors=0)
    r1.instant("journal_adopt", "engine", "engine", ts=1.024,
               rid="rid-a", committed=3, done=False)
    r1.instant("finish", "request", "slot0", ts=1.05, req=6,
               reason="length")
    return [("router", router.events()), ("r0", r0.events()),
            ("r1", r1.events())]


def test_fleet_events_to_chrome_structure():
    from solvingpapers_tpu.metrics.trace import fleet_events_to_chrome

    obj = fleet_events_to_chrome(_fleet_sections())
    evs = obj["traceEvents"]
    # the manifest leads: declared sections survive an events-only
    # round trip (load_chrome) so partial exports stay detectable
    assert evs[0]["name"] == "fleet_manifest"
    assert evs[0]["args"]["sections"] == ["router", "r0", "r1"]
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e.get("ph") == "M" and e["name"] == "process_name"}
    assert pnames == {1: "router", 2: "r0", 3: "r1"}
    # timestamps are relative to the earliest event ACROSS sections
    route = next(e for e in evs if e.get("name") == "route")
    assert route["ts"] == 0.0 and route["pid"] == 1
    # the cross-section flow follows the rid through all three
    # processes (route -> submit -> migrate -> adopt)
    import zlib

    fid = zlib.crc32(b"rid-a")
    flows = sorted((e for e in evs if e.get("cat") == "fleet_flow"
                    and e.get("id") == fid), key=lambda e: e["ts"])
    assert {e["pid"] for e in flows} == {1, 2, 3}
    assert [f["ph"] for f in flows] == ["s", "t", "t", "f"]
    assert all(f["name"] == "req:rid-a" for f in flows)
    # duplicate section labels are refused, not silently shadowed
    with pytest.raises(ValueError, match="duplicate"):
        fleet_events_to_chrome([("r0", []), ("r0", [])])


def test_summarize_fleet_section_present_iff_fleet_events(gpt_tiny):
    """The `fleet` summary key exists exactly when the trace holds
    fleet events — a single-engine export keeps the key ABSENT (the
    same pinning as the PR-6 `mesh` section), so pre-fleet traces
    summarize byte-identically."""
    from solvingpapers_tpu.metrics.trace import fleet_events_to_chrome

    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8, trace=True,
    ))
    eng.submit(_prompts(1, seed=31)[0], max_new_tokens=4)
    eng.run()
    assert "fleet" not in summarize_trace(eng.trace.to_chrome())

    summary = summarize_trace(fleet_events_to_chrome(_fleet_sections()))
    fleet = summary["fleet"]
    assert fleet["sections"] == ["router", "r0", "r1"]
    assert fleet["routing"]["route"] == 1
    assert fleet["routing"]["migrations"] == 1
    assert fleet["migrations"] == [
        {"rid": "rid-a", "from": "r0", "to": "r1"}]
    assert fleet["requests_by_replica"] == {"r0": 1, "r1": 1}
    assert fleet["drain_wall_s"] == pytest.approx(0.005, rel=1e-3)
    out = format_summary(summary)
    assert "fleet:" in out and "r0 -> r1" in out


def test_partial_fleet_export_refused(tmp_path):
    """A stitched file whose manifest declares sections the event list
    is missing (a truncated/filtered export) must raise, not summarize
    a slice of the fleet as the whole."""
    import json as _json

    from solvingpapers_tpu.metrics.trace import fleet_events_to_chrome

    obj = fleet_events_to_chrome(_fleet_sections())
    partial = [e for e in obj["traceEvents"] if e.get("pid") != 3]
    with pytest.raises(ValueError, match="partial fleet export"):
        summarize_trace({"traceEvents": partial})
    # the cli surfaces the stitcher's own message with exit 2
    from solvingpapers_tpu.cli import main as cli_main

    p = tmp_path / "partial.json"
    p.write_text(_json.dumps({"traceEvents": partial}))
    assert cli_main(["trace-summary", str(p)]) == 2


def test_cli_trace_summary_fleet_flag_contract(gpt_tiny, tmp_path,
                                               capsys):
    """`trace-summary --fleet` exits 0 on a stitched export and 2 with
    a clear message on a single-engine trace; without the flag the
    single-engine trace keeps summarizing exactly as before."""
    import json as _json

    from solvingpapers_tpu.cli import main as cli_main
    from solvingpapers_tpu.metrics.trace import fleet_events_to_chrome

    stitched = tmp_path / "fleet.json"
    stitched.write_text(_json.dumps(
        fleet_events_to_chrome(_fleet_sections())))
    assert cli_main(["trace-summary", str(stitched), "--fleet"]) == 0
    assert "fleet:" in capsys.readouterr().out

    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8, trace=True,
    ))
    eng.submit(_prompts(1, seed=32)[0], max_new_tokens=4)
    eng.run()
    single = eng.trace.export_chrome(str(tmp_path / "single.json"))
    assert cli_main(["trace-summary", single, "--fleet"]) == 2
    assert "holds no fleet events" in capsys.readouterr().err
    assert cli_main(["trace-summary", single]) == 0


# ------------------------------------------------------------------- cli


def test_cli_trace_summary_roundtrip(gpt_tiny, tmp_path, capsys):
    from solvingpapers_tpu.cli import main as cli_main

    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8, trace=True,
    ))
    for p in _prompts(3, seed=12):
        eng.submit(p, max_new_tokens=6)
    eng.run()
    path = eng.trace.export_chrome(str(tmp_path / "t.json"))
    assert cli_main(["trace-summary", path, "--top", "2"]) == 0
    out = capsys.readouterr().out
    assert "requests: 3" in out and "slowest 2 requests" in out
    # missing file and traceless JSON fail loudly
    assert cli_main(["trace-summary", str(tmp_path / "nope.json")]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text('{"traceEvents": []}')
    assert cli_main(["trace-summary", str(empty)]) == 1


# ----------------------------------------------------------------- train


def test_train_trace_spans_and_goodput(tmp_path, capsys):
    from solvingpapers_tpu.train import Trainer
    from solvingpapers_tpu.train.engine import TrainConfig

    cfg = GPTConfig(vocab_size=32, block_size=16, dim=16, n_layers=1,
                    n_heads=2, dropout=0.0)
    model = GPT(cfg)

    def batches():
        rng = np.random.default_rng(0)
        while True:
            # batch divisible by the conftest's 8-virtual-device data mesh
            x = rng.integers(0, 32, size=(8, 16)).astype(np.int32)
            yield {"x": jnp.asarray(x), "y": jnp.asarray(x)}

    path = str(tmp_path / "train.json")
    tc = TrainConfig(steps=4, batch_size=8, log_every=2, eval_every=2,
                     eval_batches=1, trace_path=path)
    Trainer(model, tc).fit(batches(), eval_iter_fn=lambda: batches())
    evs = load_chrome(path)
    names = [e["name"] for e in evs if e.get("ph") in ("X", "i")]
    assert names.count("step") == 4
    assert "data_wait" in names and "eval" in names
    (gp,) = [e for e in evs if e.get("name") == "goodput"]
    assert 0 < gp["args"]["goodput"] <= 1
    assert gp["args"]["step_s"] <= gp["args"]["wall_s"]
    # the first (compile) step is tagged and excluded from goodput's
    # numerator — compile-dominated runs must read as LOW goodput
    steps = [e for e in evs if e.get("name") == "step"]
    assert [e["args"]["compiled"] for e in steps] == [1, 0, 0, 0]
    counted = sum(e["dur"] / 1e6 for e in steps if not e["args"]["compiled"])
    assert gp["args"]["step_s"] == pytest.approx(counted, rel=0.01)
    # trace-summary understands train traces too (its --help promises it)
    from solvingpapers_tpu.cli import main as cli_main

    assert cli_main(["trace-summary", path]) == 0
    out = capsys.readouterr().out
    assert "train trace" in out and "goodput" in out and "data_wait" in out
