"""Pipeline parallelism on a real model (VERDICT r1 item 3): GPTPipe's
GPipe schedule over the 'pipe' axis must match the sequential stage scan
(dense oracle) for forward, loss, and gradients, through the stock Trainer
and the CLI front door, composed with data parallelism.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.gpt_pipe import GPTPipe, GPTPipeConfig
from solvingpapers_tpu.sharding import MeshConfig, PP_RULES, create_mesh
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer


def _cfgs(pp: bool, mesh_cfg):
    model = GPTPipeConfig(
        vocab_size=64, block_size=32, dim=32, n_layers=4, n_heads=2,
        n_stages=4, n_microbatches=4, pipeline_parallel=pp,
    )
    # sgd, not adamw: adam's first-step update saturates at +-lr for every
    # element, so numerically-zero grads whose sign is reduction-order noise
    # would flip whole elements by 2*lr and the comparison would measure
    # noise, not the pipeline
    train = TrainConfig(
        steps=2, batch_size=8, log_every=1, eval_every=0,
        mesh=mesh_cfg, pipeline_parallel=pp,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )
    return model, train


def _batch(key, b=8, s=32, vocab=64):
    x = jax.random.randint(key, (b, s), 0, vocab)
    return {"x": x, "y": jnp.roll(x, -1, axis=1)}


def test_gpt_pipe_dense_equals_blockwise_loop():
    """The staged-dense path is literally the blocks applied in order: the
    oracle for everything else here."""
    cfg = GPTPipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                        n_heads=2, n_stages=2, n_microbatches=2)
    model = GPTPipe(cfg)
    toks = jax.random.randint(jax.random.key(0), (2, 32), 0, 64)
    params = model.init({"params": jax.random.key(1)}, toks)["params"]
    logits, _ = model.apply({"params": params}, toks)

    x = jnp.take(params["tok_emb"]["embedding"], toks, axis=0)
    x = x + jnp.take(params["pos_emb"], jnp.arange(32), axis=0)
    for st in range(cfg.n_stages):
        x = model._stage_fn(jax.tree.map(lambda a: a[st], params["stages"]), x)
    from solvingpapers_tpu.models.layers import LayerNorm

    x = LayerNorm().apply({"params": params["ln_f"]}, x)
    ref = x @ params["lm_head"]["kernel"]
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_pp_trainer_step_matches_dense_trainer(devices):
    """One Trainer._train_step under PP (data=2 x pipe=4, stage params
    sharded over 'pipe') == the dense single-device Trainer step."""
    batch = _batch(jax.random.key(0))

    d_model, d_train = _cfgs(False, MeshConfig(data=1))
    dense = Trainer(GPTPipe(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    p_model, p_train = _cfgs(True, MeshConfig(data=2, pipe=4))
    pp = Trainer(GPTPipe(p_model), p_train, rules=PP_RULES,
                 mesh=create_mesh(MeshConfig(data=2, pipe=4), devices))
    p_state = pp.init_state(batch)
    # the stage stack must actually live sharded over the pipe axis
    stage_leaf = jax.tree.leaves(p_state.params["stages"])[0]
    assert "pipe" in str(stage_leaf.sharding.spec)
    pp._build_steps()
    p_state, p_metrics = pp._train_step(p_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=1e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def _drop_cfgs(pp: bool, mesh_cfg, dropout=0.3, remat=False):
    model = GPTPipeConfig(
        vocab_size=64, block_size=32, dim=32, n_layers=4, n_heads=2,
        n_stages=4, n_microbatches=4, pipeline_parallel=pp,
        dropout=dropout, remat=remat,
    )
    train = TrainConfig(
        steps=2, batch_size=8, log_every=1, eval_every=0,
        mesh=mesh_cfg, pipeline_parallel=pp, seed=7,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )
    return model, train


def test_pp_dropout_step_deterministic_and_active(devices):
    """Dropout 0.3 trains under the GPipe schedule (VERDICT r3 missing #1):
    masks are a pure function of (key, stage, layer, microbatch), so the
    same TrainState produces bit-identical steps, while the deterministic
    eval loss differs from the train loss on the same batch (masks are
    actually applied)."""
    batch = _batch(jax.random.key(0))
    mesh_cfg = MeshConfig(data=2, pipe=4)

    def run():
        model, train = _drop_cfgs(True, mesh_cfg)
        t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        val = t._eval_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                float(jax.device_get(metrics["grad_norm"])),
                float(jax.device_get(val["val_loss"])))

    loss1, gn1, val1 = run()
    loss2, gn2, val2 = run()
    assert loss1 == loss2 and gn1 == gn2  # regenerable masks
    assert np.isfinite(loss1) and np.isfinite(gn1)
    # dropout active: the (post-step) deterministic loss is not the train
    # loss; a generous gap guard distinguishes mask-on from mask-off
    assert abs(val1 - loss1) > 1e-3


def test_pp_dropout_remat_grads_match(devices):
    """remat replays the stage_fn with the SAME per-(stage, microbatch)
    keys, so gradients under jax.checkpoint equal the no-remat gradients —
    the fwd/bwd mask-consistency property the regenerable-seed recipe
    guarantees."""
    batch = _batch(jax.random.key(3))
    mesh_cfg = MeshConfig(data=2, pipe=4)
    results = []
    for remat in (False, True):
        model, train = _drop_cfgs(True, mesh_cfg, remat=remat)
        t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        results.append((
            float(jax.device_get(metrics["train_loss"])),
            float(jax.device_get(metrics["grad_norm"])),
            jax.device_get(state.params),
        ))
    (l0, g0, p0), (l1, g1, p1) = results
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    np.testing.assert_allclose(g0, g1, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_interleaved_dropout_deterministic_and_active(devices):
    """Dropout under the interleaved (virtual-stage) schedule: the tick
    folds (global stage = j*P + d, microbatch) into the key, so repeated
    steps are bit-identical and masks are applied."""
    batch = _batch(jax.random.key(7))
    mesh_cfg = MeshConfig(data=2, pipe=2)

    def run():
        model = GPTPipeConfig(
            vocab_size=64, block_size=32, dim=32, n_layers=4, n_heads=2,
            n_stages=4, virtual_stages=2, n_microbatches=4,
            pipeline_parallel=True, dropout=0.3,
        )
        train = TrainConfig(
            steps=2, batch_size=8, log_every=1, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=True, seed=3,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                      total_steps=4, grad_clip=1.0),
        )
        t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices[:4]))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        val = t._eval_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                float(jax.device_get(val["val_loss"])))

    l1, v1 = run()
    l2, v2 = run()
    assert (l1, v1) == (l2, v2)
    assert np.isfinite(l1)
    assert abs(v1 - l1) > 1e-3  # masks applied


def test_pp_dropout_units_decorrelated():
    """With every microbatch given IDENTICAL content, per-(stage,
    microbatch) keys must still produce different masks — logits differ
    across microbatches (a per-batch mask would make them equal). Dense
    path (pipeline_parallel=False) shares the stage fold, so the property
    is tested on the schedule itself via the single-device shard_map."""
    cfg = GPTPipeConfig(
        vocab_size=64, block_size=16, dim=32, n_layers=2, n_heads=2,
        n_stages=2, n_microbatches=4, pipeline_parallel=True, dropout=0.5,
    )
    model = GPTPipe(cfg)
    row = jax.random.randint(jax.random.key(5), (1, 16), 0, 64)
    toks = jnp.tile(row, (8, 1))  # 4 microbatches x 2 identical rows
    params = model.init({"params": jax.random.key(6)}, toks)["params"]

    mesh = create_mesh(MeshConfig(pipe=2), jax.devices()[:2])
    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.sharding.pipeline import shard_map_compat

    def local(p, t):
        logits, _ = model.apply(
            {"params": p}, t, deterministic=False,
            rngs={"dropout": jax.random.key(9)},
        )
        return logits

    specs = jax.tree.map(
        lambda _: P(), params, is_leaf=lambda x: x is None
    )
    specs = dict(specs, stages=jax.tree.map(lambda _: P("pipe"),
                                            params["stages"]))
    run = jax.jit(shard_map_compat(
        local, mesh=mesh, in_specs=(specs, P()), out_specs=P(),
        check_vma=False,
    ))
    logits = run(params, toks)
    per_mb = np.asarray(logits).reshape(4, 2, 16, 64)
    # identical content everywhere: any equality across microbatches would
    # mean the mask ignored the schedule's per-(stage, microbatch) fold
    assert not np.allclose(per_mb[0, 0], per_mb[1, 0])
    assert not np.allclose(per_mb[1, 0], per_mb[2, 0])
    # and the whole schedule is a pure function of the key: rerun == run
    np.testing.assert_array_equal(np.asarray(run(params, toks)),
                                  np.asarray(logits))


def test_pp_grad_groups_match_single_flush(devices):
    """pp_grad_groups splits the batch into sequential pipeline flushes
    with accumulated grads (memory-bounded PP, VERDICT r3 missing #2):
    the step must equal the single-flush PP step up to fp reassociation.
    Group flushes use n_microbatches = pipe size, the memory-optimal
    setting the feature exists for."""
    batch = _batch(jax.random.key(2))
    mesh_cfg = MeshConfig(data=1, pipe=4)

    def run(groups, n_micro):
        model, train = _cfgs(True, mesh_cfg)
        model = dataclasses.replace(model, n_microbatches=n_micro)
        train = dataclasses.replace(train, pp_grad_groups=groups,
                                    mesh=mesh_cfg)
        t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices[:4]))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                jax.device_get(state.params))

    # single flush: all 8 rows as 8 microbatches; grouped: 2 flushes of 4
    l_full, p_full = run(1, 8)
    l_grp, p_grp = run(2, 4)
    np.testing.assert_allclose(l_grp, l_full, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_grp), jax.tree.leaves(p_full)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_pp_grad_groups_compose_with_interleaved(devices):
    """pp_grad_groups x virtual_stages (VERDICT r4 weak item): each group
    is an independent flush through the interleaved schedule, so the
    composition must equal the single-flush interleaved step — pinned here
    so the combo can't silently diverge."""
    batch = _batch(jax.random.key(2))
    mesh_cfg = MeshConfig(data=1, pipe=2)

    def run(groups, n_micro):
        model, train = _cfgs(True, mesh_cfg)
        model = dataclasses.replace(model, n_stages=4, virtual_stages=2,
                                    n_microbatches=n_micro)
        train = dataclasses.replace(train, pp_grad_groups=groups,
                                    mesh=mesh_cfg)
        t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices[:2]))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                jax.device_get(state.params))

    # single flush: 8 rows as 8 microbatches; grouped: 2 flushes of 4
    l_full, p_full = run(1, 8)
    l_grp, p_grp = run(2, 4)
    np.testing.assert_allclose(l_grp, l_full, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_grp), jax.tree.leaves(p_full)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_pp_1f1b_trainer_matches_gpipe(devices):
    """TrainConfig.pp_schedule='1f1b' routes the PP backward through the
    1F1B schedule (grads computed inside the shard_map, activation memory
    bounded by pipe depth) — two train steps must match the GPipe-schedule
    trainer's loss and params."""
    batch = _batch(jax.random.key(7))
    mesh_cfg = MeshConfig(data=2, pipe=4)

    def run(schedule):
        model, train = _cfgs(True, mesh_cfg)
        train = dataclasses.replace(train, pp_schedule=schedule)
        t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices))
        state = t.init_state(batch)
        t._build_steps()
        losses = []
        for _ in range(2):
            state, metrics = t._train_step(state, batch)
            losses.append(float(jax.device_get(metrics["train_loss"])))
        return losses, jax.device_get(state.params)

    l_ref, p_ref = run("gpipe")
    l_new, p_new = run("1f1b")
    # step 1 runs on IDENTICAL params: losses must agree to fp noise;
    # step 2 compounds the optimizer update over reassociated grads
    np.testing.assert_allclose(l_new[0], l_ref[0], rtol=1e-5)
    np.testing.assert_allclose(l_new[1], l_ref[1], rtol=1e-3)
    for a, b in zip(jax.tree.leaves(p_new), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_pp_1f1b_llama_trainer_matches_gpipe(devices):
    """The second staged family through TrainConfig.pp_schedule='1f1b':
    LlamaPipe (RoPE positions in the stage closure, RMSNorm head)."""
    from solvingpapers_tpu.models.llama3_pipe import LlamaPipe, LlamaPipeConfig

    batch = _batch(jax.random.key(9))
    mesh_cfg = MeshConfig(data=2, pipe=4)

    def run(schedule):
        model = LlamaPipeConfig(
            vocab_size=64, max_seq_len=32, dim=32, n_layers=4, n_heads=4,
            n_kv_heads=2, n_stages=4, n_microbatches=4,
            pipeline_parallel=True,
        )
        train = TrainConfig(
            steps=1, batch_size=8, log_every=1, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=True, pp_schedule=schedule,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-1,
                                      warmup_steps=0, total_steps=4,
                                      grad_clip=1.0),
        )
        t = Trainer(LlamaPipe(model), train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                jax.device_get(state.params))

    l_ref, p_ref = run("gpipe")
    l_new, p_new = run("1f1b")
    np.testing.assert_allclose(l_new, l_ref, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_new), jax.tree.leaves(p_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_pp_1f1b_rejects_unsupported_compositions(devices):
    model, train = _cfgs(True, MeshConfig(data=1, pipe=4))
    mesh = create_mesh(MeshConfig(data=1, pipe=4), devices[:4])
    batch = _batch(jax.random.key(1))

    # grad groups are redundant under 1F1B
    t = Trainer(GPTPipe(model),
                dataclasses.replace(train, pp_schedule="1f1b",
                                    pp_grad_groups=2),
                rules=PP_RULES, mesh=mesh)
    t.init_state(batch)
    with pytest.raises(NotImplementedError, match="pp_grad_groups"):
        t._build_steps()


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_pp_1f1b_dropout_deterministic_and_active(devices, family):
    """Dropout under the 1F1B schedule (per-(stage, microbatch)
    regenerable keys, identical in the backward recompute), for BOTH
    f1b families: identical TrainStates step bit-identically, losses are
    finite, and the step-1 train loss (computed on the identical init
    params) DIFFERS from the dropout=0 run's — masks demonstrably fire
    in the forward that produced the loss, so a silently-dead rng
    channel cannot pass."""
    batch = _batch(jax.random.key(0))
    mesh_cfg = MeshConfig(data=2, pipe=2)

    def build(dropout):
        if family == "gpt":
            model = GPTPipeConfig(
                vocab_size=64, block_size=32, dim=32, n_layers=4,
                n_heads=2, n_stages=2, n_microbatches=4,
                pipeline_parallel=True, dropout=dropout,
            )
            pipe_model = GPTPipe(model)
        else:
            from solvingpapers_tpu.models.llama3_pipe import (
                LlamaPipe, LlamaPipeConfig,
            )

            model = LlamaPipeConfig(
                vocab_size=64, max_seq_len=32, dim=32, n_layers=4,
                n_heads=4, n_kv_heads=2, n_stages=2, n_microbatches=4,
                pipeline_parallel=True, dropout=dropout,
            )
            pipe_model = LlamaPipe(model)
        train = TrainConfig(
            steps=1, batch_size=8, log_every=1, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=True, pp_schedule="1f1b",
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-1,
                                      warmup_steps=0, total_steps=4,
                                      grad_clip=1.0),
        )
        return pipe_model, train

    def run(dropout):
        pipe_model, train = build(dropout)
        t = Trainer(pipe_model, train, rules=PP_RULES,
                    mesh=create_mesh(mesh_cfg, devices[:4]))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                float(jax.device_get(metrics["grad_norm"])))

    l1, g1 = run(0.1)
    l2, g2 = run(0.1)
    assert l1 == l2 and g1 == g2  # regenerable keys -> bit-deterministic
    assert np.isfinite(l1) and np.isfinite(g1)
    # same init params (init is dropout-independent): a dropout-on step-1
    # loss equal to the dropout-off loss means the masks never fired
    l_off, _ = run(0.0)
    assert abs(l1 - l_off) > 1e-4, (l1, l_off)


def test_pp_trainer_rejects_stage_mesh_mismatch(devices):
    model, train = _cfgs(True, MeshConfig(data=1, pipe=2))
    model = dataclasses.replace(model, n_stages=4, n_layers=4)
    t = Trainer(GPTPipe(model), train, rules=PP_RULES,
                mesh=create_mesh(MeshConfig(data=1, pipe=2), devices[:2]))
    t.init_state(_batch(jax.random.key(1)))
    with pytest.raises(ValueError, match="must equal the mesh 'pipe'"):
        t._build_steps()


def test_pp_model_rejects_caches():
    cfg = GPTPipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                        n_heads=2, n_stages=2)
    model = GPTPipe(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init({"params": jax.random.key(0)}, toks)["params"]
    with pytest.raises(NotImplementedError, match="decode caches"):
        model.apply({"params": params}, toks, caches=[])


def test_pp_cli_front_door(devices, tmp_path):
    from solvingpapers_tpu import cli

    jsonl = tmp_path / "metrics.jsonl"
    rc = cli.main([
        "train", "--config", "gpt_pp_smoke", "--steps", "12",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    import json

    rows = [json.loads(l) for l in jsonl.read_text().splitlines()]
    train_rows = [r for r in rows if "train_loss" in r]
    assert train_rows and all(np.isfinite(r["train_loss"]) for r in train_rows)
    assert train_rows[-1]["train_loss"] < train_rows[0]["train_loss"] + 0.5
    assert any("val_loss" in r for r in rows)


def test_pp_export_to_dense_gpt_matches_and_decodes():
    """to_dense restacks stage params into the dense GPT layout: forward
    must be identical, and the dense model's cached decode works — the
    decode path for pipeline-trained weights."""
    cfg = GPTPipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                        n_heads=2, n_stages=2, n_microbatches=2)
    model = GPTPipe(cfg)
    toks = jax.random.randint(jax.random.key(5), (2, 16), 0, 64)
    params = model.init({"params": jax.random.key(6)}, toks)["params"]
    ref, _ = model.apply({"params": params}, toks)

    gpt, dense_params = model.to_dense(params)
    out, _ = gpt.apply({"params": dense_params}, toks, deterministic=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)

    from solvingpapers_tpu.infer import generate

    ids = generate(gpt, dense_params, toks[:1, :8], jax.random.key(7),
                   max_new_tokens=8)
    assert ids.shape == (1, 16)


@pytest.mark.parametrize("use_flash", [False, True], ids=["jnp", "flash"])
def test_cp_pp_trainer_step_matches_dense(devices, use_flash):
    """CP x PP (data=1 x context=2 x pipe=4): sequence sharded over
    'context' with the ring inside each stage, stages over 'pipe' — must
    equal the dense single-device staged scan."""
    batch = _batch(jax.random.key(7), b=4, s=32)

    d_model, d_train = _cfgs(False, MeshConfig(data=1))
    dense = Trainer(GPTPipe(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    mesh_cfg = MeshConfig(data=1, context=2, pipe=4)
    c_model, c_train = _cfgs(True, mesh_cfg)
    c_model = dataclasses.replace(c_model, context_parallel=True,
                                  use_flash=use_flash)
    c_train = dataclasses.replace(c_train, context_parallel=True)
    cp = Trainer(GPTPipe(c_model), c_train, rules=PP_RULES,
                 mesh=create_mesh(mesh_cfg, devices))
    c_state = cp.init_state(batch)
    assert "pipe" in str(jax.tree.leaves(c_state.params["stages"])[0].sharding.spec)
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_cp_pp_export_to_dense_decodes(devices):
    """A CP+PP-trained GPTPipe must export to a DENSE (non-CP) GPT that
    decodes outside shard_map."""
    cfg = GPTPipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                        n_heads=2, n_stages=2, n_microbatches=2,
                        pipeline_parallel=True, context_parallel=True)
    model = GPTPipe(cfg)
    mesh = create_mesh(MeshConfig(data=2, context=2, pipe=2), devices)
    from jax.sharding import PartitionSpec as P

    toks = jnp.zeros((2, 32), jnp.int32)
    params = jax.shard_map(
        lambda x: model.init({"params": jax.random.key(0)}, x)["params"],
        mesh=mesh, in_specs=P(("data",), "context"), out_specs=P(),
    )(toks)
    gpt, dense_params = model.to_dense(jax.device_get(params))
    assert not gpt.cfg.context_parallel
    from solvingpapers_tpu.infer import generate

    ids = generate(gpt, dense_params, toks[:1, :4], jax.random.key(1),
                   max_new_tokens=4)
    assert ids.shape == (1, 8)


@pytest.mark.parametrize("v", [2, 4], ids=["v2", "v4"])
def test_interleaved_schedule_matches_dense(devices, v):
    """Interleaved (virtual-stage) schedule: n_stages = pipe * v thin
    stages, microbatches looping the ring in groups of P — must equal the
    dense staged scan exactly (same function, smaller bubble)."""
    batch = _batch(jax.random.key(30), b=8)
    pipe = 2
    n_stages = pipe * v

    def cfgs(pp):
        model = GPTPipeConfig(
            vocab_size=64, block_size=32, dim=32, n_layers=n_stages,
            n_heads=2, n_stages=n_stages, n_microbatches=4,
            virtual_stages=v if pp else v,  # same config, schedule differs
            pipeline_parallel=pp,
        )
        train = TrainConfig(
            steps=2, batch_size=8, log_every=1, eval_every=0,
            mesh=MeshConfig(data=2, pipe=pipe), pipeline_parallel=pp,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                      total_steps=4, grad_clip=1.0),
        )
        return model, train

    d_model, d_train = cfgs(False)
    d_train = dataclasses.replace(d_train, mesh=MeshConfig(data=1))
    dense = Trainer(GPTPipe(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    p_model, p_train = cfgs(True)
    pp = Trainer(GPTPipe(p_model), p_train, rules=PP_RULES,
                 mesh=create_mesh(MeshConfig(data=2, pipe=pipe), devices[:4]))
    p_state = pp.init_state(batch)
    pp._build_steps()
    p_state, p_metrics = pp._train_step(p_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=1e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_interleaved_to_dense_roundtrip():
    """Permuted storage (device-major rows) must restack to the dense GPT
    in GLOBAL stage order."""
    cfg = GPTPipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=8,
                        n_heads=2, n_stages=8, virtual_stages=4,
                        n_microbatches=2)
    model = GPTPipe(cfg)
    toks = jax.random.randint(jax.random.key(31), (2, 16), 0, 64)
    params = model.init({"params": jax.random.key(32)}, toks)["params"]
    ref, _ = model.apply({"params": params}, toks)  # dense oracle, global order
    gpt, dense_params = model.to_dense(params)
    out, _ = gpt.apply({"params": dense_params}, toks, deterministic=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
