"""Quantized KV serving (`ServeConfig.kv_quant`) acceptance tests.

The contract: int8 cache storage is a POOL property, invisible to the
model and to every engine behavior except output numerics — and those
are gated by measurement (the bench's greedy-agreement rate), not
exactness, EXCEPT for `kv_exact` traffic, which must stay byte-identical
to the unquantized engine while sharing its compiled programs with
quantized slots. Byte accounting is pinned analytically: the claim the
whole feature exists for is `int8 + scales ~= half the bf16 bytes`, and
the ledger must say so exactly.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.ops.quant import scale_shape
from solvingpapers_tpu.serve import ServeConfig, ServeEngine
from solvingpapers_tpu.serve.kv_pool import (
    KVSlotPool,
    PagedKVPool,
    QuantSegment,
    quant_pool_bytes,
)
from solvingpapers_tpu.serve.sampling import SamplingParams

GPT_TINY = GPTConfig(vocab_size=64, block_size=96, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompts(n, seed=0, lo=5, hi=20):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, GPT_TINY.vocab_size,
                     size=int(rng.integers(lo, hi))).astype(np.int32)
        for _ in range(n)
    ]


BASE = dict(n_slots=3, max_len=64, decode_block=4, bucket=16)


def _run(model, params, scfg, prompts, max_new=10, params_for=None):
    eng = ServeEngine(model, params, scfg)
    handles = [
        eng.submit(p, max_new_tokens=max_new,
                   params=params_for(i) if params_for else None)
        for i, p in enumerate(prompts)
    ]
    eng.run()
    assert all(h.done for h in handles)
    return eng, [h.tokens for h in handles]


def _agreement(ref, got):
    total = sum(len(r) for r in ref)
    same = sum(int(a == b) for r, g in zip(ref, got) for a, b in zip(r, g))
    return same / total


# ------------------------------------------------------------- quality


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_quant_greedy_streams_track_full_precision(gpt_tiny, paged):
    """int8 storage under greedy decode: high token agreement with the
    full-precision pool (the bench gates >= 0.99 on the trained corpus
    model; the random-init tiny model here is the harsher case)."""
    model, params = gpt_tiny
    prompts = _prompts(6, seed=1)
    _, ref = _run(model, params, ServeConfig(**BASE), prompts)
    extra = dict(paged=True, page_size=16) if paged else {}
    _, got = _run(model, params,
                  ServeConfig(**BASE, kv_quant="int8", **extra), prompts)
    assert _agreement(ref, got) >= 0.95


def test_kv_exact_streams_byte_identical_in_mixed_batch(gpt_tiny):
    """The escape hatch: kv_exact rows of a MIXED exact/quantized batch
    are byte-identical to the unquantized engine, quantized rows to the
    all-quantized engine — one engine, both service levels."""
    model, params = gpt_tiny
    prompts = _prompts(6, seed=2)
    _, ref = _run(model, params, ServeConfig(**BASE), prompts)
    _, quant = _run(model, params, ServeConfig(**BASE, kv_quant="int8"),
                    prompts)
    _, mixed = _run(
        model, params,
        ServeConfig(**BASE, kv_quant="int8", kv_exact_lanes=2), prompts,
        params_for=lambda i: SamplingParams(kv_exact=(i % 2 == 0)),
    )
    for i in range(len(prompts)):
        if i % 2 == 0:
            assert mixed[i] == ref[i], f"exact row {i} diverged"
        else:
            assert mixed[i] == quant[i], f"quantized row {i} diverged"


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_kv_exact_byte_identical_on_both_pools(gpt_tiny, paged):
    model, params = gpt_tiny
    prompts = _prompts(4, seed=3)
    extra = dict(paged=True, page_size=16) if paged else {}
    _, ref = _run(model, params, ServeConfig(**BASE, **extra), prompts)
    _, got = _run(
        model, params,
        ServeConfig(**BASE, kv_quant="int8", kv_exact_lanes=3, **extra),
        prompts, params_for=lambda i: SamplingParams(kv_exact=True),
    )
    assert got == ref


# ------------------------------------------------- prefix cache + spec


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_quant_prefix_cache_streams_exact_vs_cache_off(gpt_tiny, paged):
    """Quantized prefix reuse: cached int8 pages/segments splice back
    bitwise (full blocks of real tokens quantize identically for the
    producer and a re-prefilling consumer), so quantized greedy streams
    are token-exact cache on vs off."""
    model, params = gpt_tiny
    rng = np.random.default_rng(4)
    stem = rng.integers(0, 64, size=32).astype(np.int32)
    prompts = [
        np.concatenate([stem, rng.integers(0, 64, size=5).astype(np.int32)])
        for _ in range(6)
    ]
    extra = dict(paged=True, page_size=16) if paged else {}
    qcfg = ServeConfig(**BASE, kv_quant="int8", **extra)
    _, off = _run(model, params, qcfg, prompts, max_new=6)
    eng, on = _run(
        model, params,
        dataclasses.replace(qcfg, prefix_cache=True, prefix_page=16),
        prompts, max_new=6,
    )
    assert on == off
    assert eng.metrics.snapshot()["serve/prefix_hit_rate"] > 0.5


def test_kv_exact_bypasses_quantized_prefix_cache(gpt_tiny):
    """A kv_exact request must neither consume nor feed the quantized
    radix tree (a spliced int8 prefix would break its byte-exactness)."""
    model, params = gpt_tiny
    rng = np.random.default_rng(5)
    stem = rng.integers(0, 64, size=32).astype(np.int32)
    prompts = [
        np.concatenate([stem, rng.integers(0, 64, size=4).astype(np.int32)])
        for _ in range(4)
    ]
    _, ref = _run(model, params, ServeConfig(**BASE), prompts, max_new=6)
    eng, got = _run(
        model, params,
        ServeConfig(**BASE, kv_quant="int8", kv_exact_lanes=3,
                    prefix_cache=True, prefix_page=16),
        prompts, max_new=6,
        params_for=lambda i: SamplingParams(kv_exact=True),
    )
    assert got == ref
    snap = eng.metrics.snapshot()
    # exact admissions never touched the tree: no lookups recorded
    assert "serve/prefix_lookups" not in snap
    assert eng.prefix_cache.n_nodes == 0


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_quant_speculative_ngram_matches_plain_quant(gpt_tiny, paged):
    """Speculation is lossless RELATIVE TO ITS ENGINE's sampler+storage:
    spec-on quantized greedy streams equal spec-off quantized ones."""
    model, params = gpt_tiny
    prompts = _prompts(4, seed=6, lo=8, hi=20)
    extra = dict(paged=True, page_size=16) if paged else {}
    qcfg = ServeConfig(**BASE, kv_quant="int8", **extra)
    _, plain = _run(model, params, qcfg, prompts)
    _, spec = _run(
        model, params,
        dataclasses.replace(qcfg, speculative="ngram", spec_k=3,
                            spec_rounds=2),
        prompts,
    )
    assert spec == plain


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_deepseekv3_latent_lanes_quantize(paged):
    """The flagship's MLA LatentCache quantizes the same way: 3-D
    (B, T, C) leaves take one absmax scale per (slot, time-block) —
    ops.quant's per-block-scalar granularity for latents — and serve
    through both pools with high agreement and byte-exact kv_exact."""
    import dataclasses as dc

    from solvingpapers_tpu.models.deepseekv3 import (
        DeepSeekV3,
        DeepSeekV3Config,
    )

    cfg = DeepSeekV3Config(
        vocab_size=64, block_size=64, dim=32, n_layers=2, n_heads=4,
        latent_dim=8, rope_dim=8, n_experts=4, top_experts=2,
        dropout=0.0, attn_dropout=0.0,
    )
    model = DeepSeekV3(cfg)
    prompts = _prompts(3, seed=4, lo=5, hi=14)
    variables = model.init({"params": jax.random.key(3)},
                           jnp.asarray(prompts[0])[None, :])
    params = variables["params"]
    extra = {"moe_state": variables["moe_state"]}
    base = dict(n_slots=2, max_len=32, decode_block=2, bucket=8)
    pool = dict(paged=True, page_size=16) if paged else {}

    def run(scfg, params_for=None):
        eng = ServeEngine(model, params, scfg, extra_variables=extra)
        hs = [eng.submit(p, max_new_tokens=6,
                         params=params_for(i) if params_for else None)
              for i, p in enumerate(prompts)]
        eng.run()
        return [h.tokens for h in hs]

    ref = run(ServeConfig(**base, **pool))
    got = run(ServeConfig(**base, kv_quant="int8", kv_quant_block=16,
                          **pool))
    assert _agreement(ref, got) >= 0.95
    exact = run(
        ServeConfig(**base, kv_quant="int8", kv_quant_block=16,
                    kv_exact_lanes=2, **pool),
        params_for=lambda i: SamplingParams(kv_exact=True),
    )
    assert exact == ref


# ------------------------------------------------------ byte accounting


def test_quant_pool_bytes_pinned_analytically(gpt_tiny):
    """Ledger honesty: the quantized pools' nbytes decompose EXACTLY
    into int8 payload + f32 scale rows (+ exact sidecar) computed from
    shapes alone, and land under 0.6x of the same pool unquantized."""
    model, params = gpt_tiny
    cfg = GPT_TINY
    head_dim = cfg.dim // cfg.n_heads
    n_slots, max_len, qb = 4, 64, 16

    plain = KVSlotPool(model, n_slots, max_len)
    pool = KVSlotPool(model, n_slots, max_len, quant="int8",
                      quant_block=qb, exact_lanes=2)
    # per layer: k and v leaves (S, T, H, D) int8 + (S, T/qb, H) f32
    leaf_elems = n_slots * max_len * cfg.n_heads * head_dim
    scale_elems = np.prod(
        scale_shape((n_slots, max_len, cfg.n_heads, head_dim), qb))
    expect_q = 2 * cfg.n_layers * leaf_elems
    expect_s = 2 * cfg.n_layers * scale_elems * 4
    base_itemsize = jnp.zeros((), GPT_TINY.compute_dtype).dtype.itemsize
    expect_exact = 2 * cfg.n_layers * (3 * max_len * cfg.n_heads
                                       * head_dim) * base_itemsize
    got_pool, got_s, got_e, got_base = quant_pool_bytes(pool.caches)
    assert got_pool == expect_q + expect_s
    assert got_s == expect_s
    assert got_e == expect_exact
    assert got_base == expect_q * base_itemsize
    assert pool.nbytes == got_pool + got_e
    assert got_base == plain.nbytes
    # the capacity claim, pinned at the ledger: payload+scales <= 0.6x
    assert got_pool <= 0.6 * plain.nbytes

    pplain = PagedKVPool(model, n_slots, max_len, 16)
    ppool = PagedKVPool(model, n_slots, max_len, 16, quant="int8")
    qp, sp, ep, basep = quant_pool_bytes(ppool.phys)
    n_pages = pplain.n_pages
    assert qp == (2 * cfg.n_layers * n_pages * 16 * cfg.n_heads * head_dim
                  + 2 * cfg.n_layers * n_pages * cfg.n_heads * 4)
    assert ep == 0 and basep == pplain.nbytes
    assert ppool.nbytes == qp
    assert ppool.page_nbytes == qp // n_pages
    assert qp <= 0.6 * pplain.nbytes


def test_quant_gauges_and_statusz(gpt_tiny):
    model, params = gpt_tiny
    eng, _ = _run(model, params,
                  ServeConfig(**BASE, kv_quant="int8", kv_exact_lanes=1),
                  _prompts(2, seed=7))
    snap = eng.metrics.snapshot()
    pool_bytes, scale_bytes, exact_bytes, base_bytes = \
        quant_pool_bytes(eng.pool.caches)
    assert snap["serve/kv_bytes_per_token"] == pytest.approx(
        pool_bytes / (BASE["n_slots"] * BASE["max_len"]))
    assert snap["serve/kv_quant_scale_bytes"] == scale_bytes
    assert snap["serve/kv_quant_bytes_saved"] == base_bytes - pool_bytes
    assert snap["serve/kv_quant_exact_lanes_free"] == 1.0
    doc = eng.statusz()
    kq = doc["kv_quant"]
    assert kq["mode"] == "int8"
    assert kq["quant_bytes"] == pool_bytes
    assert kq["baseline_bytes"] == base_bytes
    assert kq["bytes_ratio"] == pytest.approx(pool_bytes / base_bytes,
                                              abs=1e-4)
    assert kq["exact_lanes_free"] == 1


# ------------------------------------------------- programs + lifecycle


def test_mixed_batch_shares_compiled_programs(gpt_tiny):
    """kv_exact rides the packed control rows: a mixed exact/quantized
    batch adds ZERO compiled prefill/decode programs over an
    all-quantized engine (the jit-cache pin of the one-engine claim)."""
    from solvingpapers_tpu.serve.engine import (
        _decode_program,
        _prefill_program,
    )

    model, params = gpt_tiny
    prompts = _prompts(4, seed=8, lo=8, hi=9)  # one prefill bucket
    qcfg = ServeConfig(**BASE, kv_quant="int8", kv_exact_lanes=2)
    _run(model, params, qcfg, prompts)
    decode_progs = _decode_program._cache_size()
    prefill_progs = _prefill_program._cache_size()
    _run(model, params, qcfg, prompts,
         params_for=lambda i: SamplingParams(kv_exact=(i % 2 == 0)))
    assert _decode_program._cache_size() == decode_progs
    assert _prefill_program._cache_size() == prefill_progs


def test_exact_lane_exhaustion_serializes_and_frees(gpt_tiny):
    """More kv_exact requests than sidecar lanes: the admission gate
    serializes them (requeue, never a crash), every stream finishes,
    and the lane free-list drains back to full."""
    model, params = gpt_tiny
    prompts = _prompts(5, seed=9)
    eng, got = _run(
        model, params,
        ServeConfig(**BASE, kv_quant="int8", kv_exact_lanes=1), prompts,
        params_for=lambda i: SamplingParams(kv_exact=True),
    )
    _, ref = _run(model, params, ServeConfig(**BASE), prompts)
    assert got == ref
    assert eng._exact_free == [1]
    assert not any(eng._eidx)


def test_exact_lanes_release_on_cancel(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params,
                      ServeConfig(**BASE, kv_quant="int8",
                                  kv_exact_lanes=1))
    req = eng.submit(_prompts(1, seed=10)[0], max_new_tokens=32,
                     params=SamplingParams(kv_exact=True))
    eng.step()
    assert len(eng._exact_free) == 0
    eng.cancel(req)
    eng.step()
    assert req.finish_reason == "cancelled"
    assert eng._exact_free == [1]


# ------------------------------------------------------------ validation


def test_config_validation(gpt_tiny):
    model, params = gpt_tiny
    with pytest.raises(ValueError, match="kv_quant must be"):
        ServeEngine(model, params, ServeConfig(**BASE, kv_quant="fp8"))
    with pytest.raises(ValueError, match="kv_exact_lanes"):
        ServeEngine(model, params, ServeConfig(**BASE, kv_exact_lanes=2))
    for bad_block in (0, -16):  # -16 divides 64, so the modulo can't catch it
        with pytest.raises(ValueError, match="kv_quant_block must be"):
            ServeEngine(model, params,
                        ServeConfig(**BASE, kv_quant="int8",
                                    kv_quant_block=bad_block))
    with pytest.raises(ValueError, match="not a multiple of the quant"):
        ServeEngine(model, params,
                    ServeConfig(**{**BASE, "max_len": 60},
                                kv_quant="int8"))
    with pytest.raises(ValueError, match="prefix_page"):
        ServeEngine(model, params,
                    ServeConfig(**BASE, kv_quant="int8", prefix_cache=True,
                                prefix_page=24, kv_quant_block=16))
    eng = ServeEngine(model, params, ServeConfig(**BASE, kv_quant="int8"))
    with pytest.raises(ValueError, match="kv_exact requests need"):
        eng.submit(np.arange(4, dtype=np.int32),
                   params=SamplingParams(kv_exact=True))
    # kv_exact on an UNQUANTIZED engine is a documented no-op
    eng2 = ServeEngine(model, params, ServeConfig(**BASE))
    req = eng2.submit(np.arange(4, dtype=np.int32), max_new_tokens=2,
                      params=SamplingParams(kv_exact=True))
    eng2.run()
    assert req.done


def test_quant_pool_rejects_plain_segment_splice(gpt_tiny):
    model, params = gpt_tiny
    plain = KVSlotPool(model, 2, 64)
    pool = KVSlotPool(model, 2, 64, quant="int8", quant_block=16)
    seg = plain.extract_prefix(0, 0, 16)
    with pytest.raises(TypeError, match="QuantSegment"):
        pool.splice_prefix(0, seg)
    qseg = pool.extract_prefix(0, 0, 16)
    assert isinstance(qseg, QuantSegment)
    with pytest.raises(ValueError, match="not aligned"):
        pool.splice_prefix(0, qseg, offset=8)


def test_written_stores_preserve_committed_codes_bf16():
    """Committed positions below the write frontier keep their int8
    codes BYTE-exact when their block/page is rewritten and the write
    leaves the block absmax unchanged — even on bf16 pools, where the
    lane view the programs write from is a lossy cast of the stored
    values. The store helpers merge unwritten positions from their own
    f32-dequantized codes (quantize(dequantize(q, s)) is only a fixed
    point in f32: a bf16 round trip perturbs the absmax and walks
    codes), so repeated decode steps cannot random-walk committed
    entries on any compute dtype. (When a write DOES raise the block
    absmax, committed codes legitimately re-encode against the new
    scale — values stay within scale/2; that is block quantization.)"""
    from solvingpapers_tpu.ops.quant import dequantize, quantize
    from solvingpapers_tpu.serve.kv_pool import (
        QuantStore,
        quant_scatter_written_pages,
        quant_store_written,
    )

    rng = np.random.default_rng(7)
    # awkward magnitudes: bf16's 8-bit mantissa perturbs dequantized
    # values enough to flip codes on a lane-view round trip
    x = jnp.asarray(
        rng.uniform(-93.7, 93.7, size=(2, 32, 2, 8)), jnp.float32
    )
    # plant each frontier block's absmax at a COMMITTED position so the
    # write below cannot change the scale
    x = x.at[0, 9].set(93.7).at[1, 17].set(93.7)
    block = 8
    q0, s0 = quantize(x, block)

    # --- lane pool: rewrite the blocks around each slot's frontier
    store = QuantStore(q=q0, scale=s0, exact=None, block=block,
                       dtype=jnp.bfloat16)
    lanes = dequantize(q0, s0, jnp.bfloat16)  # what the program gathers
    pos0 = jnp.array([12, 20], jnp.int32)
    span = 4
    new = jnp.asarray(rng.uniform(-50, 50, size=(2, span, 2, 8)),
                      jnp.bfloat16)
    for s in range(2):
        lanes = jax.lax.dynamic_update_slice(
            lanes, new[s:s + 1], (s, int(pos0[s]), 0, 0))
    out = quant_store_written(store, lanes, pos0,
                              span, jnp.zeros((2,), jnp.int32))
    for s in range(2):
        lo = int(pos0[s])
        np.testing.assert_array_equal(
            np.asarray(out.q[s, :lo]), np.asarray(q0[s, :lo]),
            err_msg=f"slot {s}: committed codes below pos0 drifted",
        )
    # sanity: the written span actually took the new values' codes
    assert not np.array_equal(
        np.asarray(out.q[0, 12:16]), np.asarray(q0[0, 12:16]))
    # and a second identical store is idempotent
    out2 = quant_store_written(out, lanes, pos0,
                               span, jnp.zeros((2,), jnp.int32))
    np.testing.assert_array_equal(np.asarray(out2.q), np.asarray(out.q))

    # --- paged pool: same contract through the lo/hi window merge
    page = 8
    px = jnp.asarray(
        rng.uniform(-93.7, 93.7, size=(5, page, 2, 8)), jnp.float32
    )
    px = px.at[2, 1].set(93.7)  # committed absmax in the frontier page
    pq0, ps0 = quantize(px, page)
    pstore = QuantStore(q=pq0, scale=ps0, exact=None, block=page,
                        dtype=jnp.bfloat16)
    table = jnp.array([[1, 2, 3]], jnp.int32)  # 1 slot, 3 logical pages
    gathered = dequantize(pq0, ps0, jnp.bfloat16)[
        jnp.array([1, 2, 3])].reshape(1, 3 * page, 2, 8)
    pos = jnp.array([12], jnp.int32)  # mid page 1: logical 8..15
    lanes = jax.lax.dynamic_update_slice(
        gathered, new[:1], (0, 12, 0, 0))
    pout = quant_scatter_written_pages(pstore, lanes, table, pos,
                                       lo=pos, hi=pos + span)
    np.testing.assert_array_equal(
        np.asarray(pout.q[2, :4]), np.asarray(pq0[2, :4]),
        err_msg="committed codes below the page write window drifted",
    )
    assert not np.array_equal(
        np.asarray(pout.q[2, 4:]), np.asarray(pq0[2, 4:]))
    # untargeted physical pages are untouched entirely
    np.testing.assert_array_equal(np.asarray(pout.q[1]),
                                  np.asarray(pq0[1]))
    np.testing.assert_array_equal(np.asarray(pout.q[3]),
                                  np.asarray(pq0[3]))


def test_spec_writeback_excludes_rejected_draft_tail():
    """The speculative write-back bounds the requantized window by the
    device-committed end on EVERY compute dtype: a rejected draft's
    outlier activation past `hi` must neither enter the codes nor
    inflate the block/page absmax scale that committed tokens share
    (that coarsening would be locked in even after the garbage is
    overwritten)."""
    from solvingpapers_tpu.ops.quant import dequantize, quantize
    from solvingpapers_tpu.serve.kv_pool import (
        QuantStore,
        quant_scatter_window_pages,
        quant_store_written,
    )

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.uniform(-1.0, 1.0, size=(1, 16, 2, 4)),
                    jnp.float32)
    # pin each block's absmax OUTSIDE the written window so the commit
    # cannot change the scale — code equality at the tail is then exact
    x = x.at[:, 7].set(1.0).at[:, 15].set(1.0)
    block = 8
    q0, s0 = quantize(x, block)
    store = QuantStore(q=q0, scale=s0, exact=None, block=block,
                       dtype=jnp.float32)
    lanes = dequantize(q0, s0, jnp.float32)
    # commit 2 tokens at [4, 6); plant a rejected-draft OUTLIER at 6
    pos0 = jnp.array([4], jnp.int32)
    committed = jnp.asarray(rng.uniform(-1, 1, size=(1, 2, 2, 4)),
                            jnp.float32)
    lanes = lanes.at[:, 4:6].set(committed)
    lanes = lanes.at[:, 6].set(1000.0)
    out = quant_store_written(store, lanes, pos0, 4,
                              jnp.zeros((1,), jnp.int32),
                              hi=pos0 + 2, tail_garbage=True)
    # the outlier never reached the codes or the scale
    assert float(out.scale.max()) < 1000.0 / 127.0
    np.testing.assert_array_equal(np.asarray(out.q[0, 6]),
                                  np.asarray(q0[0, 6]))
    # committed values survive at fine-scale precision
    got = dequantize(out.q, out.scale, jnp.float32)[0, 4:6]
    np.testing.assert_allclose(np.asarray(got), np.asarray(committed[0]),
                               atol=float(out.scale.max()) / 2 + 1e-6)

    # paged path: same contract through quant_scatter_window_pages
    px = jnp.asarray(rng.uniform(-1.0, 1.0, size=(3, 8, 2, 4)),
                     jnp.float32)
    px = px.at[:, 7].set(1.0)
    pq0, ps0 = quantize(px, 8)
    pstore = QuantStore(q=pq0, scale=ps0, exact=None, block=8,
                        dtype=jnp.float32)
    table = jnp.array([[1, 2]], jnp.int32)
    glanes = dequantize(pq0, ps0, jnp.float32)[
        jnp.array([1, 2])].reshape(1, 16, 2, 4)
    glanes = glanes.at[:, 4:6].set(committed)
    glanes = glanes.at[:, 6].set(1000.0)
    pout = quant_scatter_window_pages(pstore, glanes, table,
                                      jnp.array([4], jnp.int32),
                                      jnp.array([5], jnp.int32), 4)
    assert float(pout.scale.max()) < 1000.0 / 127.0
    np.testing.assert_array_equal(np.asarray(pout.q[1, 6]),
                                  np.asarray(pq0[1, 6]))


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_prefill_pads_never_reach_quant_codes(gpt_tiny, paged):
    """The prefill write sites pass the real-token end (`hi`) through to
    `quant_store_lane` / `quant_scatter_lane_pages`: prompts right-pad
    to the bucket, the model computes KV for the pad positions, and
    quantizing those activations into the tail block/page would inflate
    its absmax scale and permanently coarsen the last committed prompt
    tokens' codes (the scale/2 bound degrades with the scale). Pads must
    land as ZERO codes instead — zeros can never widen a scale — and
    the later decode rewrites of the shared block re-encode them from
    those zero codes, so the tail past the decode frontier stays zero
    for the stream's whole life."""
    model, params = gpt_tiny
    scfg = ServeConfig(n_slots=1, max_len=64, decode_block=4, bucket=16,
                       kv_quant="int8", kv_quant_block=16, paged=paged,
                       page_size=16 if paged else None)
    eng = ServeEngine(model, params, scfg)
    prompt = np.arange(1, 11, dtype=np.int32)  # length 10 -> padded 16
    h = eng.submit(prompt, max_new_tokens=2)
    pid = None
    while not h.done:
        eng.step()
        if paged and pid is None and eng.pool.table[0, 0] != 0:
            pid = int(eng.pool.table[0, 0])  # before release resets it
    store = eng.pool.phys if paged else eng.pool.caches
    row = pid if paged else 0
    # positions [14, 16) were only ever written by the prefill (decode
    # block 4 writes at most [10, 14)): real pads, zeroed under `hi`
    for qleaf in jax.tree_util.tree_leaves(store.q):
        assert not np.any(np.asarray(qleaf[row, 14:16])), \
            "right-padding activations reached the quantized codes"
