"""LLaMA3 model tests: forward shape, GQA head accounting, cached decode
equivalence (which the reference's generate fails — LLaMA-jax.ipynb cell 14
never passes the cache), loss-goes-down smoke training, sgd parity option.
"""

import jax
import jax.numpy as jnp
import numpy as np

from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator
from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

TINY = LlamaConfig(
    vocab_size=64, max_seq_len=32, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
    dropout=0.0,
)


def test_forward_shape_and_param_structure():
    model = Llama(TINY)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = model.init({"params": jax.random.key(0)}, toks)["params"]
    logits, caches = model.apply({"params": params}, toks)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert caches is None
    # GQA: kv projection is n_kv_heads wide, q is n_heads wide
    attn = params["block_0"]["attn"]
    head_dim = TINY.dim // TINY.n_heads
    assert attn["q"]["kernel"].shape == (TINY.dim, TINY.n_heads * head_dim)
    assert attn["kv"]["kernel"].shape == (TINY.dim, 2 * TINY.n_kv_heads * head_dim)


def test_cached_decode_equals_full_forward():
    model = Llama(TINY)
    rng = jax.random.key(1)
    prompt = jax.random.randint(rng, (2, 6), 0, TINY.vocab_size)
    params = model.init({"params": rng}, prompt)["params"]

    out = generate(model, params, prompt, rng, max_new_tokens=8)
    toks = prompt
    for _ in range(8):
        logits, _ = model.apply({"params": params}, toks, deterministic=True)
        toks = jnp.concatenate([toks, jnp.argmax(logits[:, -1], -1)[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(toks))


def test_cp_decode_matches_dense_generate(devices):
    """KV-cache decode under context parallelism (CPKVCache, ring prefill,
    distributed-softmax steps) must emit the dense generate's exact greedy
    tokens."""
    import dataclasses

    from solvingpapers_tpu.infer import generate_cp
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    cfg = dataclasses.replace(TINY, max_seq_len=64)
    model = Llama(cfg)
    prompt = jax.random.randint(jax.random.key(2), (2, 32), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.key(0)}, prompt)["params"]
    ref = generate(model, params, prompt, jax.random.key(1), max_new_tokens=12)

    cp_model = Llama(dataclasses.replace(cfg, context_parallel=True))
    mesh = create_mesh(MeshConfig(data=1, context=4), jax.devices()[:4])
    out = generate_cp(cp_model, params, prompt, jax.random.key(1), mesh,
                      max_new_tokens=12)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_loss_decreases_with_sgd():
    """Reference parity: llama3 trains with hand-rolled SGD (cell 29)."""
    _, train_toks, _ = load_char_corpus(synthetic_chars=20_000)
    cfg = TrainConfig(
        steps=40, batch_size=8, log_every=100, eval_every=0,
        optimizer=OptimizerConfig(name="sgd", max_lr=0.5, warmup_steps=0,
                                  total_steps=40, grad_clip=1.0,
                                  weight_decay=0.0),
    )
    trainer = Trainer(Llama(TINY), cfg)
    it = lm_batch_iterator(train_toks, 8, TINY.max_seq_len, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    state, m0 = trainer._train_step(state, b0)
    first = float(m0["train_loss"])
    for _ in range(cfg.steps):
        state, m = trainer._train_step(state, next(it))
    assert float(m["train_loss"]) < first - 0.3


def test_sharded_train_matches_single_device(devices):
    from solvingpapers_tpu.sharding import MeshConfig, batch_sharding, create_mesh

    _, train_toks, _ = load_char_corpus(synthetic_chars=10_000)
    opt = OptimizerConfig(max_lr=1e-3, warmup_steps=0, total_steps=10)

    def run(mesh_config, devs):
        mesh = create_mesh(mesh_config, devs)
        cfg = TrainConfig(steps=2, batch_size=8, log_every=100, eval_every=0,
                          optimizer=opt)
        trainer = Trainer(Llama(TINY), cfg, mesh=mesh)
        it = lm_batch_iterator(train_toks, 8, TINY.max_seq_len, seed=3,
                               sharding=batch_sharding(mesh))
        b0 = next(it)
        state = trainer.init_state(b0)
        trainer._build_steps()
        losses = []
        for batch in [b0, next(it)]:
            state, m = trainer._train_step(state, batch)
            losses.append(float(m["train_loss"]))
        return losses

    single = run(MeshConfig(data=1, fsdp=1, model=1), devices[:1])
    sharded = run(MeshConfig(data=2, fsdp=2, model=2), devices)
    np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=2e-5)


def test_remat_matches_noremat():
    """remat=True must be numerically identical (it only trades recompute
    for memory) for both forward and gradients."""
    import dataclasses

    base = dataclasses.replace(TINY, remat=False)
    rmt = dataclasses.replace(TINY, remat=True)
    toks = jax.random.randint(jax.random.key(5), (2, 16), 0, TINY.vocab_size)
    params = Llama(base).init({"params": jax.random.key(0)}, toks)["params"]

    def loss(cfg, params):
        logits, _ = Llama(cfg).apply({"params": params}, toks)
        return jnp.sum(logits.astype(jnp.float32) ** 2)

    l0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(rmt, p))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
