"""Replay observatory tests (serve/replay.py + ServeEngine.replay_submit
+ journal.read_entries + the HTTP replay surface in serve/api.py).

Contracts under test. Exactness: an identical-config replay of a
journaled greedy + seeded-stochastic workload is byte-exact on BOTH
pool layouts and scores agreement 1.0 (teacher-forced cuts pin the
recorded seed chains via the committed-prefix path). Grading: a lossy
int8-kv candidate produces a structurally complete report whose
divergences carry first-divergence offsets — never a crash. Screening:
unreplayable entries (grammar, stop strings without a detokenizer,
kv_exact without lanes, tokenless, still-live) land as ``skipped`` with
reasons, never divergences. Snapshot loading: a torn final line is
tolerated; mid-file corruption raises; a journal rotating under a
concurrent reader never tears a record, and the brief ENOENT window a
non-POSIX rename can expose is absorbed by one retry. Zero cost when
unused: a replay-less engine compiles the same program set whether or
not replay traffic ran on a twin, and its metrics carry no replay/*
keys. HTTP: POST /v1/replay runs bounded in the background and GET
/v1/replay/<id> serves progress then the report; the replay/* gauges
appear on the LIVE engine's /metrics only after a run finishes.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.serve import (
    ApiServer,
    Journal,
    JournalError,
    ReplayHarness,
    ServeConfig,
    ServeEngine,
    read_entries,
)
from solvingpapers_tpu.serve.replay import (
    apply_overrides,
    report_gauges,
    sanitize_config,
)
from solvingpapers_tpu.serve.sampling import SamplingParams


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32,
                          n_layers=2, n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = _gpt_tiny()
    return _MODEL


def _prompts(n, seed=0, size=8):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=size).astype(np.int32)
            for _ in range(n)]


def _cfg(**kw):
    base = dict(n_slots=3, max_len=32, decode_block=4, bucket=8,
                max_prefills_per_step=3)
    base.update(kw)
    return ServeConfig(**base)


def _params_for(i):
    """Greedy + seeded stochastic cycle: every stream byte-replayable."""
    if i % 3 == 1:
        return SamplingParams(temperature=0.8, top_p=0.9, seed=100 + i)
    if i % 3 == 2:
        return SamplingParams(temperature=1.3, top_k=8, seed=200 + i)
    return None


def _record(path, n=6, max_new=8, cfg=None, params_for=_params_for):
    """Serve a small workload through a journaled engine and return the
    (closed) engine's handles — the recorded reference streams."""
    model, params = _model()
    eng = ServeEngine(model, params,
                      cfg or _cfg(journal_path=path))
    hs = [eng.submit(p, max_new_tokens=max_new,
                     params=params_for(i) if params_for else None)
          for i, p in enumerate(_prompts(n))]
    eng.run()
    eng.journal.sync()
    eng.close()
    return hs


# ------------------------------------------------------------ exactness


@pytest.mark.parametrize("candidate_kw", [{}, {"paged": True,
                                               "page_size": 8}])
def test_identical_config_replay_byte_exact(tmp_path, candidate_kw):
    path = str(tmp_path / "j.jsonl")
    cfg = _cfg(journal_path=path, **candidate_kw)
    _record(path, cfg=cfg)
    model, params = _model()
    h = ReplayHarness(model, params)
    entries = h.load(path)
    report = h.run(entries, _cfg(**candidate_kw), cut_stride=4)
    assert report["streams_total"] == 6
    assert report["streams_compared"] == 6  # greedy + seeded, all
    assert report["byte_exact_rate"] == 1.0, report["diverged"]
    assert report["agreement_rate"] == 1.0
    assert report["agreement_rate_greedy"] == 1.0
    assert report["agreement_rate_seeded"] == 1.0
    assert report["cut_positions"] > 0
    assert not report["skipped"]
    kinds = {r["kind"] for r in report["streams"]}
    assert kinds == {"greedy", "seeded"}
    assert report["replay_metrics"]["tokens_per_sec"] > 0


def test_quant_candidate_graded_never_crashes(tmp_path):
    path = str(tmp_path / "j.jsonl")
    _record(path)
    model, params = _model()
    h = ReplayHarness(model, params)
    report = h.run(h.load(path),
                   _cfg(kv_quant="int8", kv_quant_block=8),
                   cut_stride=4)
    # lossy storage: byte exactness MAY break (that is the canary's
    # point) but the report stays structurally complete and graded
    assert report["streams_compared"] == 6
    assert 0.0 <= report["byte_exact_rate"] <= 1.0
    assert 0.0 <= report["agreement_rate"] <= 1.0
    # per-kind split: the greedy score is the gated one, the seeded
    # score discloses seed-chain sensitivity to the lossy candidate
    assert 0.0 <= report["agreement_rate_greedy"] <= 1.0
    assert 0.0 <= report["agreement_rate_seeded"] <= 1.0
    for d in report["diverged"]:
        assert 0 <= d["first_divergence"] <= d["recorded_tokens"]
    if report["diverged"]:
        assert report["first_divergence_p50"] is not None


def test_baseline_deltas_and_max_requests(tmp_path):
    path = str(tmp_path / "j.jsonl")
    _record(path)
    model, params = _model()
    h = ReplayHarness(model, params)
    report = h.run(h.load(path), _cfg(decode_block=8),
                   baseline=_cfg(), cut_stride=0, max_requests=3)
    assert report["streams_total"] == 3
    assert report["agreement_rate"] is None  # cut pass disabled
    assert "baseline_metrics" in report and "deltas" in report
    assert any(k.endswith("_delta_pct") for k in report["deltas"])


# ------------------------------------------------------------ screening


def test_unreplayable_entries_become_skips(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    j.append_submit("gram", [1, 2], 4, None, {}, 0.0, grammar=True)
    j.append_commit("gram", [5])
    j.append_finish("gram", "length", {})
    j.append_submit("stop", [1, 2], 4, None,
                    {"temperature": 0.0, "stop": ["xy"]}, 0.1)
    j.append_commit("stop", [5])
    j.append_finish("stop", "stop", {})
    j.append_submit("kvx", [1, 2], 4, None,
                    {"temperature": 0.0, "kv_exact": True}, 0.2)
    j.append_commit("kvx", [5])
    j.append_finish("kvx", "length", {})
    j.append_submit("none", [1, 2], 4, None, {}, 0.3)
    j.append_finish("none", "length", {})
    j.append_submit("live", [1, 2], 4, None, {}, 0.4)
    j.append_commit("live", [5])
    j.append_submit("ok", [1, 2], 4, None, {}, 0.5)
    j.append_commit("ok", [5, 6])
    j.append_finish("ok", "length", {})
    j.sync()
    j.close()
    model, params = _model()
    h = ReplayHarness(model, params)  # no detokenize
    report = h.run(h.load(path), _cfg(kv_quant="int8", kv_quant_block=8),
                   cut_stride=0)
    reasons = {s["rid"]: s["reason"] for s in report["skipped"]}
    assert "grammar" in reasons["gram"]
    assert "detokenize" in reasons["stop"]
    assert "kv_exact" in reasons["kvx"]
    assert "no committed tokens" in reasons["none"]
    assert "still live" in reasons["live"]
    # skips are NEVER divergences; the one clean entry still replays
    assert report["streams_replayed"] == 1
    assert report["streams_compared"] == 1
    assert all(s["rid"] != "ok" for s in report["skipped"])


def test_empty_corpus_report_shape(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    j.append_submit("live", [1, 2], 4, None, {}, 0.0)
    j.sync()
    j.close()
    model, params = _model()
    h = ReplayHarness(model, params)
    report = h.run(h.load(path), _cfg())
    assert report["streams_replayed"] == 0
    assert report["byte_exact_rate"] is None
    assert report["agreement_rate"] is None
    # gauges omit the None aggregates rather than zero-filling
    g = report_gauges(report)
    assert "replay/byte_exact_rate" not in g
    assert g["replay/streams_compared"] == 0.0


# -------------------------------------------------------- snapshot load


def test_torn_final_line_does_not_abort_replay(tmp_path):
    path = str(tmp_path / "j.jsonl")
    _record(path, n=3)
    with open(path, "a") as f:
        f.write('{"kind":"commit","rid":"x","tok')  # crash-torn tail
    entries = read_entries(path)
    assert len(entries) == 3
    model, params = _model()
    h = ReplayHarness(model, params)
    report = h.run(entries, _cfg(), cut_stride=0)
    assert report["byte_exact_rate"] == 1.0


def test_midfile_corruption_raises(tmp_path):
    path = str(tmp_path / "j.jsonl")
    _record(path, n=3)
    lines = open(path).read().splitlines()
    lines[1] = '{"kind": "comm'
    open(path, "w").write("\n".join(lines) + "\n")
    with pytest.raises(JournalError):
        read_entries(path)


def test_rotation_under_concurrent_reader(tmp_path):
    """A journal compacting (atomic tmp+rename swap) while a reader
    loops `read_entries` on the same path: every snapshot parses —
    whole pre-rotation file or whole post-rotation file, never a
    hybrid, never a torn record, never JournalError."""
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, rotate_finished=4)
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            try:
                for e in read_entries(path):
                    assert e.rid.startswith("r")
            except (JournalError, FileNotFoundError) as exc:
                errors.append(exc)

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i in range(200):  # 200 finishes / rotate_finished=4 -> ~50
            rid = f"r{i}"     # rotations under the reader's feet
            j.append_submit(rid, [1, 2, 3], 8, None, {}, float(i))
            j.append_commit(rid, [4, 5])
            j.append_finish(rid, "length", {})
            j.sync()
    finally:
        stop.set()
        t.join()
        j.close()
    assert j.rotations > 10
    assert not errors, errors[0]


def test_enoent_during_swap_retried_once(tmp_path, monkeypatch):
    """Non-POSIX rename semantics can expose a brief window where the
    path resolves to nothing mid-swap; read_entries absorbs exactly
    one, and still raises when the file is genuinely gone."""
    path = str(tmp_path / "j.jsonl")
    _record(path, n=3)
    real_open = open
    fails = {"n": 1}

    def flaky_open(p, *a, **kw):
        if p == path and fails["n"] > 0:
            fails["n"] -= 1
            raise FileNotFoundError(p)
        return real_open(p, *a, **kw)

    monkeypatch.setattr("builtins.open", flaky_open)
    assert len(read_entries(path, retry_delay_s=0.0)) == 3
    with pytest.raises(FileNotFoundError):
        read_entries(str(tmp_path / "gone.jsonl"), retry_delay_s=0.0)


# ------------------------------------------------- config plumbing


def test_apply_overrides_and_sanitize():
    cfg = _cfg()
    out = apply_overrides(cfg, {"kv_quant": "int8", "decode_block": "8",
                                "paged": "true"})
    assert out.kv_quant == "int8" and out.decode_block == 8 and out.paged
    with pytest.raises(ValueError, match="unknown ServeConfig field"):
        apply_overrides(cfg, {"decode_blocks": 8})
    s = sanitize_config(_cfg(journal_path="x.jsonl", api_port=0,
                             max_waiting=4), n_requests=100)
    assert s.journal_path is None and s.api_port is None
    assert s.max_waiting == 101


def test_replay_submit_contract(tmp_path):
    model, params = _model()
    jeng = ServeEngine(model, params,
                       _cfg(journal_path=str(tmp_path / "j.jsonl")))
    with pytest.raises(ValueError, match="journal-off"):
        jeng.replay_submit(np.arange(4, dtype=np.int32))
    jeng.close()
    eng = ServeEngine(model, params, _cfg())
    with pytest.raises(ValueError, match="budget"):
        eng.replay_submit(np.arange(4, dtype=np.int32),
                          max_new_tokens=2, committed=[1, 2])
    # recorded max_tokens must not shadow the explicit replay budget
    h = eng.replay_submit(np.arange(4, dtype=np.int32), max_new_tokens=3,
                          params=SamplingParams(max_tokens=1))
    eng.run()
    assert len(h.tokens) == 3
    eng.close()


# ------------------------------------------------- zero cost when unused


def test_replayless_engine_program_set_and_metrics_pinned():
    model, params = _model()
    cfg = _cfg(xla_obs=True)
    plain = ServeEngine(model, params, cfg)
    for i, p in enumerate(_prompts(4)):
        plain.submit(p, max_new_tokens=6, params=_params_for(i))
    plain.run()
    plain_programs = set(plain.registry.snapshot()["programs"])
    snap = plain.metrics.snapshot()
    assert not any(k.startswith("replay/") for k in snap)
    plain.close()

    replay = ServeEngine(model, params, cfg)
    hs = [replay.replay_submit(p, max_new_tokens=6,
                               params=_params_for(i))
          for i, p in enumerate(_prompts(4))]
    # a teacher-forced cut through the committed-prefix resume path
    replay.replay_submit(_prompts(1)[0], max_new_tokens=5,
                         committed=[int(t) for t in hs[0].tokens[:4]])
    replay.run()
    assert set(replay.registry.snapshot()["programs"]) <= plain_programs
    replay.close()


# ----------------------------------------------------------------- http


@pytest.fixture(scope="module")
def replay_server(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("rj") / "j.jsonl")
    model, params = _model()
    eng = ServeEngine(model, params,
                      _cfg(journal_path=path, api_port=0))
    for i, p in enumerate(_prompts(4)):
        eng.submit(p, max_new_tokens=6, params=_params_for(i))
    eng.run()
    eng.journal.sync()
    srv = ApiServer(eng, model_name="gpt-tiny")
    yield srv, eng, path
    srv.close()


def _http(srv, path, body=None, method=None):
    req = urllib.request.Request(
        srv.url(path),
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"},
        method=method or ("POST" if body is not None else "GET"),
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_replay_endpoint(replay_server):
    srv, eng, _path = replay_server
    code, doc = _http(srv, "/v1/replay", {"config_overrides": {"nope": 1}})
    assert code == 400 and "nope" in doc["error"]["message"]
    code, doc = _http(srv, "/v1/replay/absent0000", method="GET")
    assert code == 404 and doc["error"]["code"] == "replay_not_found"

    code, doc = _http(srv, "/v1/replay", {"cut_stride": 4})
    assert code == 202, doc
    rid = doc["id"]
    deadline = 120.0
    import time as _t
    t0 = _t.monotonic()
    while _t.monotonic() - t0 < deadline:
        code, doc = _http(srv, f"/v1/replay/{rid}", method="GET")
        assert code == 200
        if doc["state"] != "running":
            break
        _t.sleep(0.05)
    assert doc["state"] == "finished", doc.get("error")
    rep = doc["report"]
    assert rep["byte_exact_rate"] == 1.0, rep["diverged"]
    assert rep["agreement_rate"] == 1.0
    assert doc["progress"]["done"] == doc["progress"]["total"]
    # the finished run's gauges ride the LIVE engine's metrics through
    # the front door's provider (present only now that a run finished)
    snap = eng.metrics.snapshot()
    assert snap["replay/byte_exact_rate"] == 1.0
    assert snap["replay/streams_compared"] == 4.0


def test_http_replay_single_flight(replay_server):
    srv, _eng, _path = replay_server
    with srv._replay_lock:
        srv._replay_active = True
    try:
        code, doc = _http(srv, "/v1/replay", {})
        assert code == 409
        assert doc["error"]["code"] == "replay_in_flight"
    finally:
        with srv._replay_lock:
            srv._replay_active = False


def test_report_gauge_contract(tmp_path):
    assert report_gauges(None) == {}
    path = str(tmp_path / "j.jsonl")
    _record(path, n=3)
    model, params = _model()
    h = ReplayHarness(model, params)
    g = report_gauges(h.run(h.load(path), _cfg(), cut_stride=4))
    assert g["replay/byte_exact_rate"] == 1.0
    assert g["replay/agreement_rate"] == 1.0
    assert g["replay/streams_compared"] == 3.0
    assert g["replay/wall_s"] > 0
    assert "replay/first_divergence_p50" not in g  # no divergences
