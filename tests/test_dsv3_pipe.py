"""Pipeline-parallel flagship (VERDICT r2 item 4): DSV3Pipe's GPipe
schedule over 'pipe' must match the sequential stage scan (dense oracle)
for forward/loss/grads AND the aux-free routing-bias updates (the MoE
state must stay shard-invariant across the pipe axis), through the stock
Trainer; plus PP x FSDP (ZeRO-gathered non-stage params) and export to the
dense DeepSeekV3 for decode.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.deepseekv3_pipe import DSV3Pipe, DSV3PipeConfig
from solvingpapers_tpu.sharding import MeshConfig, PP_RULES, create_mesh
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn


def _cfgs(pp: bool, mesh_cfg, **model_over):
    kw = dict(n_stages=2, n_microbatches=2)
    kw.update(model_over)
    model = DSV3PipeConfig(
        vocab_size=64, block_size=32, dim=32, n_layers=4, n_heads=4,
        latent_dim=8, rope_dim=8, n_experts=4, top_experts=2,
        pipeline_parallel=pp, **kw,
    )
    train = TrainConfig(
        steps=2, batch_size=8, log_every=1, eval_every=0,
        mesh=mesh_cfg, pipeline_parallel=pp,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )
    return model, train


def _batch(key, b=8, s=32, vocab=64):
    x = jax.random.randint(key, (b, s), 0, vocab)
    return {"x": x, "y": jnp.roll(x, -1, axis=1)}


def _run(model_cfg, train_cfg, mesh_cfg, devs, batch, steps=2):
    mesh = create_mesh(mesh_cfg, devs)
    tr = Trainer(DSV3Pipe(model_cfg), train_cfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn, rules=PP_RULES, mesh=mesh)
    state = tr.init_state(batch)
    tr._build_steps()
    metrics = None
    for _ in range(steps):
        state, metrics = tr._train_step(state, batch)
    return state, metrics


def test_dsv3_pipe_dense_matches_dense_deepseekv3():
    """The staged dense oracle must equal the real DeepSeekV3 forward with
    restacked params — the blocks are literally the same modules."""
    cfg = DSV3PipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                         n_heads=4, latent_dim=8, rope_dim=8, n_experts=4,
                         top_experts=2, n_stages=2)
    model = DSV3Pipe(cfg)
    toks = jax.random.randint(jax.random.key(0), (2, 32), 0, 64)
    variables = model.init({"params": jax.random.key(1)}, toks)
    logits, _ = model.apply(variables, toks)

    dense, dparams, dstate = model.to_dense(
        variables["params"], variables["moe_state"]
    )
    ref, _ = dense.apply({"params": dparams, "moe_state": dstate}, toks,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "mesh_cfg",
    [MeshConfig(data=2, pipe=4), MeshConfig(data=2, fsdp=2, pipe=2)],
    ids=["dp2_pp4", "dp2_fsdp2_pp2"],
)
def test_dsv3_pp_trainer_matches_dense(devices, mesh_cfg):
    """Two PP Trainer steps == two dense single-device steps: loss, params
    AND the stacked routing bias (shard-invariant across 'pipe')."""
    batch = _batch(jax.random.key(0))
    n_stages = dict(zip(("data", "fsdp", "model", "expert", "context", "pipe"),
                        mesh_cfg.resolve(8)))["pipe"]

    d_model, d_train = _cfgs(False, MeshConfig(data=1), n_stages=n_stages)
    d_state, d_metrics = _run(d_model, d_train, MeshConfig(data=1),
                              jax.devices()[:1], batch)

    p_model, p_train = _cfgs(True, mesh_cfg, n_stages=n_stages)
    p_state, p_metrics = _run(p_model, p_train, mesh_cfg, devices, batch)

    stage_leaf = jax.tree.leaves(p_state.params["stages"])[0]
    assert "pipe" in str(stage_leaf.sharding.spec)

    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    # MoE observability flows under PP
    assert "train_moe_load_entropy" in p_metrics
    # routing bias: identical update to the dense oracle
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_dsv3_pp_flash_runs(devices):
    """use_flash staging (check_vma off) still steps and updates state."""
    mesh_cfg = MeshConfig(data=2, pipe=4)
    p_model, p_train = _cfgs(True, mesh_cfg, n_stages=4, use_flash=True)
    batch = _batch(jax.random.key(3))
    state, metrics = _run(p_model, p_train, mesh_cfg, jax.devices()[:8], batch)
    assert np.isfinite(float(jax.device_get(metrics["train_loss"])))
    bias = jax.tree.leaves(jax.device_get(state.model_state))[0]
    assert np.isfinite(np.asarray(bias)).all()


def test_dsv3_pp_dropout_trains_deterministically(devices):
    """The reference flagship recipe (dropout 0.1, deepseekv3.ipynb cell 4)
    under PP: masks are pure functions of (key, stage, layer, microbatch),
    so identical TrainStates step bit-identically, losses are finite, and
    the deterministic eval loss differs from the train loss (masks are
    actually applied). Closes VERDICT r3 missing #1."""
    batch = _batch(jax.random.key(0))
    mesh_cfg = MeshConfig(data=2, pipe=2)

    def run():
        model, train = _cfgs(True, mesh_cfg, dropout=0.1, attn_dropout=0.1)
        mesh = create_mesh(mesh_cfg, devices[:4])
        tr = Trainer(DSV3Pipe(model), train, loss_fn=dsv3_loss_fn,
                     init_fn=dsv3_init_fn, rules=PP_RULES, mesh=mesh)
        state = tr.init_state(batch)
        tr._build_steps()
        state, metrics = tr._train_step(state, batch)
        val = tr._eval_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                float(jax.device_get(metrics["grad_norm"])),
                float(jax.device_get(val["val_loss"])))

    l1, g1, v1 = run()
    l2, g2, v2 = run()
    assert l1 == l2 and g1 == g2 and v1 == v2
    assert np.isfinite(l1) and np.isfinite(g1)
    assert abs(v1 - l1) > 1e-3  # dropout-on train loss != deterministic loss


def test_dsv3_pipe_export_decodes():
    """PP-trained weights export to the dense DeepSeekV3 and decode
    (cached decode == full-prefix recompute with the same weights)."""
    cfg = DSV3PipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                         n_heads=4, latent_dim=8, rope_dim=8, n_experts=4,
                         top_experts=2, n_stages=2)
    model = DSV3Pipe(cfg)
    toks = jax.random.randint(jax.random.key(5), (2, 16), 0, 64)
    variables = model.init({"params": jax.random.key(6)}, toks)
    dense, dparams, dstate = model.to_dense(
        variables["params"], variables["moe_state"]
    )

    from solvingpapers_tpu.infer import generate

    prompt = toks[:1, :8]
    out = generate(dense, dparams, prompt, jax.random.key(7),
                   max_new_tokens=6, extra_variables={"moe_state": dstate})
    ref = prompt
    for _ in range(6):
        logits, _ = dense.apply({"params": dparams, "moe_state": dstate},
                                ref, deterministic=True)
        ref = jnp.concatenate([ref, jnp.argmax(logits[:, -1], -1)[:, None]],
                              axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_dsv3_pipe_rejects_caches_and_headless_mtp():
    cfg = DSV3PipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=2,
                         n_heads=2, latent_dim=8, n_experts=2, top_experts=1,
                         n_stages=2)
    model = DSV3Pipe(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.key(0)}, toks)
    with pytest.raises(NotImplementedError, match="decode caches"):
        model.apply(variables, toks, caches=[])
    with pytest.raises(ValueError, match="mtp_heads"):
        model.apply(variables, toks, return_mtp=True)


def test_dsv3_pp_mtp_trainer_matches_dense(devices):
    """MTP under pipeline parallelism: the schedule's output is
    psum-broadcast, so the MTP heads run replicated after the staged stack
    — the PP step (loss, params, routing state incl. the MTP layer's own
    bias) must equal the dense-oracle step."""
    batch = _batch(jax.random.key(5))
    mesh_cfg = MeshConfig(data=2, pipe=2)

    d_model, d_train = _cfgs(False, MeshConfig(data=1), mtp_heads=1)
    d_state, d_metrics = _run(
        d_model, d_train, MeshConfig(data=1), devices[:1], batch
    )

    p_model, p_train = _cfgs(True, mesh_cfg, mtp_heads=1)
    p_state, p_metrics = _run(p_model, p_train, mesh_cfg, devices[:4], batch)

    for key in ("train_loss", "train_mtp_loss"):
        np.testing.assert_allclose(
            float(jax.device_get(p_metrics[key])),
            float(jax.device_get(d_metrics[key])), rtol=2e-5,
        )
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_dsv3_pipe_mtp_export_matches_dense_family():
    """to_dense with MTP heads: the restacked params/state under the dense
    family must reproduce the staged dense-oracle's (logits, mtp_logits)."""
    cfg = DSV3PipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                         n_heads=4, latent_dim=8, rope_dim=8, n_experts=4,
                         top_experts=2, n_stages=2, mtp_heads=2)
    model = DSV3Pipe(cfg)
    toks = jax.random.randint(jax.random.key(0), (2, 32), 0, 64)
    variables = model.init({"params": jax.random.key(1)}, toks)
    (logits, mtp_logits), _ = model.apply(variables, toks, return_mtp=True)

    dense, dparams, dstate = model.to_dense(
        variables["params"], variables["moe_state"]
    )
    (ref, ref_mtp), _ = dense.apply(
        {"params": dparams, "moe_state": dstate}, toks,
        deterministic=True, return_mtp=True,
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(mtp_logits), np.asarray(ref_mtp),
                               rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------- llama3 staging


def test_llama_pipe_pp_matches_dense(devices):
    """LlamaPipe (GQA+RoPE+SwiGLU staged via the shared builder): PP
    Trainer steps == dense single-device steps."""
    from solvingpapers_tpu.models.llama3_pipe import LlamaPipe, LlamaPipeConfig

    def cfgs(pp, mesh_cfg):
        model = LlamaPipeConfig(
            vocab_size=64, max_seq_len=32, dim=32, n_layers=4, n_heads=4,
            n_kv_heads=2, n_stages=4, n_microbatches=2, pipeline_parallel=pp,
        )
        train = TrainConfig(
            steps=2, batch_size=8, log_every=1, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=pp,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-1,
                                      warmup_steps=0, total_steps=4,
                                      grad_clip=1.0),
        )
        return model, train

    batch = _batch(jax.random.key(11))
    d_model, d_train = cfgs(False, MeshConfig(data=1))
    dense = Trainer(LlamaPipe(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    mesh_cfg = MeshConfig(data=2, pipe=4)
    p_model, p_train = cfgs(True, mesh_cfg)
    pp = Trainer(LlamaPipe(p_model), p_train, rules=PP_RULES,
                 mesh=create_mesh(mesh_cfg, devices))
    p_state = pp.init_state(batch)
    pp._build_steps()
    p_state, p_metrics = pp._train_step(p_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_dsv3_pp_interleaved_trainer_matches_dense(devices):
    """Interleaved schedule for the FLAGSHIP (VERDICT r4 ask 3): 4 thin
    stages as virtual_stages=2 over pipe=2 — loss, params AND the MoE
    routing bias riding the schedule's per-virtual-slice aux stack must
    equal the dense oracle."""
    batch = _batch(jax.random.key(0))
    over = dict(n_stages=4, virtual_stages=2, n_microbatches=4)

    d_model, d_train = _cfgs(False, MeshConfig(data=1), **over)
    d_state, d_metrics = _run(d_model, d_train, MeshConfig(data=1),
                              jax.devices()[:1], batch)

    mesh_cfg = MeshConfig(data=2, pipe=2)
    p_model, p_train = _cfgs(True, mesh_cfg, **over)
    p_state, p_metrics = _run(p_model, p_train, mesh_cfg, devices[:4], batch)

    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    assert "train_moe_load_entropy" in p_metrics
    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_moe_load_entropy"])),
        float(jax.device_get(d_metrics["train_moe_load_entropy"])),
        rtol=1e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_dsv3_pp_1f1b_trainer_matches_gpipe(devices):
    """The FLAGSHIP through TrainConfig.pp_schedule='1f1b': MoE routing
    loads ride the schedule's aux channel and the aux-free bias update
    recombines exactly like the GPipe path — loss, params AND moe_state
    must match the GPipe-schedule trainer."""
    batch = _batch(jax.random.key(3))
    mesh_cfg = MeshConfig(data=2, pipe=2)

    def run(schedule):
        model, train = _cfgs(True, mesh_cfg, n_stages=2, n_microbatches=4)
        train = dataclasses.replace(train, steps=1, pp_schedule=schedule)
        state, metrics = _run(model, train, mesh_cfg, devices[:4], batch,
                              steps=1)
        return (float(jax.device_get(metrics["train_loss"])),
                jax.device_get(state.params),
                jax.device_get(state.model_state))

    l_ref, p_ref, ms_ref = run("gpipe")
    l_new, p_new, ms_new = run("1f1b")
    np.testing.assert_allclose(l_new, l_ref, rtol=1e-5)
    # tree.map verifies STRUCTURE too (a dropped passthrough state key
    # must fail, not silently truncate a leaf zip)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        ),
        ms_new, ms_ref,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4
        ),
        p_new, p_ref,
    )


def test_dsv3_pipe_interleaved_to_dense_roundtrip():
    """Interleaved storage layout (row d*v + j = global stage j*P + d):
    the dense oracle and to_dense export must agree with the GPipe-layout
    family given the same global stages."""
    cfg = DSV3PipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=4,
                         n_heads=4, latent_dim=8, rope_dim=8, n_experts=4,
                         top_experts=2, n_stages=4, virtual_stages=2,
                         n_microbatches=2)
    model = DSV3Pipe(cfg)
    toks = jax.random.randint(jax.random.key(0), (2, 32), 0, 64)
    variables = model.init({"params": jax.random.key(1)}, toks)
    logits, _ = model.apply(variables, toks)
    dense, dparams, dstate = model.to_dense(
        variables["params"], variables["moe_state"]
    )
    ref, _ = dense.apply({"params": dparams, "moe_state": dstate}, toks,
                         deterministic=True)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_llama_pipe_interleaved_matches_dense(devices):
    """LlamaPipe interleaved schedule (virtual_stages=2 over pipe=2)
    == dense oracle."""
    from solvingpapers_tpu.models.llama3_pipe import LlamaPipe, LlamaPipeConfig

    def cfgs(pp, mesh_cfg):
        model = LlamaPipeConfig(
            vocab_size=64, max_seq_len=32, dim=32, n_layers=4, n_heads=4,
            n_kv_heads=2, n_stages=4, virtual_stages=2, n_microbatches=4,
            pipeline_parallel=pp,
        )
        train = TrainConfig(
            steps=1, batch_size=8, log_every=1, eval_every=0,
            mesh=mesh_cfg, pipeline_parallel=pp,
            optimizer=OptimizerConfig(name="sgd", max_lr=1e-1,
                                      warmup_steps=0, total_steps=4,
                                      grad_clip=1.0),
        )
        return model, train

    batch = _batch(jax.random.key(11))
    d_model, d_train = cfgs(False, MeshConfig(data=1))
    dense = Trainer(LlamaPipe(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    mesh_cfg = MeshConfig(data=2, pipe=2)
    p_model, p_train = cfgs(True, mesh_cfg)
    pp = Trainer(LlamaPipe(p_model), p_train, rules=PP_RULES,
                 mesh=create_mesh(mesh_cfg, devices[:4]))
    p_state = pp.init_state(batch)
    pp._build_steps()
    p_state, p_metrics = pp._train_step(p_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(p_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(p_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_llama_pipe_export_decodes():
    from solvingpapers_tpu.infer import generate
    from solvingpapers_tpu.models.llama3_pipe import LlamaPipe, LlamaPipeConfig

    cfg = LlamaPipeConfig(vocab_size=64, max_seq_len=32, dim=32, n_layers=4,
                          n_heads=4, n_kv_heads=2, n_stages=2)
    model = LlamaPipe(cfg)
    toks = jax.random.randint(jax.random.key(12), (2, 16), 0, 64)
    params = model.init({"params": jax.random.key(13)}, toks)["params"]
    ref, _ = model.apply({"params": params}, toks)

    llama, dense_params = model.to_dense(params)
    out, _ = llama.apply({"params": dense_params}, toks, deterministic=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    ids = generate(llama, dense_params, toks[:1, :8], jax.random.key(14),
                   max_new_tokens=6)
    assert ids.shape == (1, 14)


def test_pp_remat_matches_noremat(devices):
    """remat=True (jax.checkpoint per block inside the stage_fn) must be
    numerically identical — it only trades recompute for the GPipe scan's
    per-tick activation memory."""
    batch = _batch(jax.random.key(20))
    mesh_cfg = MeshConfig(data=2, pipe=4)
    outs = []
    for remat in (False, True):
        m, t = _cfgs(True, mesh_cfg, n_stages=4, remat=remat)
        state, metrics = _run(m, t, mesh_cfg, devices, batch)
        outs.append((float(jax.device_get(metrics["train_loss"])),
                     jax.device_get(state.params)))
    np.testing.assert_allclose(outs[0][0], outs[1][0], rtol=1e-6)
    for a, b in zip(jax.tree.leaves(outs[0][1]), jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_dsv3_cp_pp_trainer_matches_dense(devices):
    """CP x PP for the FLAGSHIP (data=1 x context=2 x pipe=4): sequence
    sharded over 'context' with the MLA latent ring inside each stage,
    stages over 'pipe', routing state invariant over BOTH — must equal the
    dense single-device staged scan (loss, params, moe_state)."""
    import dataclasses as dc

    batch = _batch(jax.random.key(21), b=4, s=32)

    d_model, d_train = _cfgs(False, MeshConfig(data=1), n_stages=4)
    d_state, d_metrics = _run(d_model, d_train, MeshConfig(data=1),
                              jax.devices()[:1], batch,
                              )

    mesh_cfg = MeshConfig(data=1, context=2, pipe=4)
    c_model, c_train = _cfgs(True, mesh_cfg, n_stages=4)
    c_model = dc.replace(c_model, context_parallel=True)
    c_train = dc.replace(c_train, context_parallel=True, batch_size=4)
    c_state, c_metrics = _run(c_model, c_train, mesh_cfg, devices, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)
