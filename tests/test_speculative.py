"""MTP self-speculative decoding (infer/speculative.py): greedy output
must be IDENTICAL to plain generate — speculation changes only how many
forwards it takes. Verified on untrained params (drafts mostly reject:
the all-reject path must still be exact) and the acceptance bookkeeping.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.infer import generate, generate_speculative
from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config

TINY = DeepSeekV3Config(
    vocab_size=64, block_size=128, dim=32, n_layers=2, n_heads=2,
    latent_dim=8, rope_dim=8, pe_scale=0.02, n_experts=4, top_experts=2,
    dropout=0.0, attn_dropout=0.0, mtp_heads=1,
)


def _setup(seed=0, prompt_len=9):
    model = DeepSeekV3(TINY)
    prompt = jax.random.randint(
        jax.random.key(seed), (1, prompt_len), 0, TINY.vocab_size
    )
    variables = model.init({"params": jax.random.key(seed + 1)}, prompt,
                           return_mtp=True)
    extra = {"moe_state": variables["moe_state"]}
    return model, variables["params"], prompt, extra


@pytest.mark.parametrize("new", [5, 16])
def test_speculative_equals_plain_greedy(new):
    model, params, prompt, extra = _setup(prompt_len=9)
    plain = generate(model, params, prompt, jax.random.key(9),
                     max_new_tokens=new, sampler=ops.sample_greedy,
                     extra_variables=extra, max_len=prompt.shape[1] + new + 2)
    spec, stats = generate_speculative(
        model, params, prompt, max_new_tokens=new, extra_variables=extra,
    )
    np.testing.assert_array_equal(np.asarray(spec[:, : prompt.shape[1] + new]),
                                  np.asarray(plain))
    f = int(stats["forwards"])
    a = int(stats["accepted"])
    # bookkeeping: each forward commits 1 + accepted tokens, first token
    # comes from prefill; the loop may overshoot by one accepted token
    assert f + a + 1 in (new, new + 1), (f, a)
    assert 0 <= a <= f


@pytest.mark.slow
def test_speculative_accepts_on_predictable_stream():
    """A prompt the model continues deterministically after a short
    training burst should accept drafts (>0) — the speedup mechanism is
    live, not just the fallback path. Marked slow (training-fit-backed):
    tier-1 keeps draft-verify token equality at every level (the
    equality tests here, the CLI regression, the serving matrix in
    tests/test_spec.py), and live-acceptance is gated by CI's
    serve-bench speculative smoke (acceptance fields + exactness on a
    trained model)."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    model = DeepSeekV3(TINY)
    # a trivially periodic corpus: the model memorizes it fast, so the MTP
    # head's 2-ahead predictions line up with the main model's argmax
    toks = np.tile(np.arange(8), 4000)
    tcfg = TrainConfig(
        steps=150, batch_size=8, log_every=1000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10,
                                  total_steps=150),
    )
    trainer = Trainer(model, tcfg, loss_fn=dsv3_loss_fn, init_fn=dsv3_init_fn)
    state = trainer.fit(lm_batch_iterator(toks, 8, 32, seed=0))
    params = jax.device_get(state.params)
    extra = {"moe_state": jax.device_get(state.model_state)["moe_state"]}

    prompt = jnp.asarray(np.tile(np.arange(8), 2)[None, :], jnp.int32)
    new = 24
    plain = generate(model, params, prompt, jax.random.key(0),
                     max_new_tokens=new, sampler=ops.sample_greedy,
                     extra_variables=extra, max_len=prompt.shape[1] + new + 2)
    spec, stats = generate_speculative(
        model, params, prompt, max_new_tokens=new, extra_variables=extra,
    )
    np.testing.assert_array_equal(np.asarray(spec[:, : prompt.shape[1] + new]),
                                  np.asarray(plain))
    assert int(stats["accepted"]) > 0, dict(stats)
    assert int(stats["forwards"]) < new  # strictly fewer forwards


TINY2 = dc.replace(TINY, mtp_heads=2)


@pytest.mark.parametrize("new", [5, 16])
def test_speculative_2draft_equals_plain_greedy(new):
    """Chained 2-head drafts: greedy output identical to plain generate,
    even when untrained drafts mostly reject."""
    model = DeepSeekV3(TINY2)
    prompt = jax.random.randint(jax.random.key(0), (1, 9), 0, TINY2.vocab_size)
    variables = model.init({"params": jax.random.key(1)}, prompt,
                           return_mtp=True)
    extra = {"moe_state": variables["moe_state"]}
    params = variables["params"]
    plain = generate(model, params, prompt, jax.random.key(9),
                     max_new_tokens=new, sampler=ops.sample_greedy,
                     extra_variables=extra, max_len=prompt.shape[1] + new + 3)
    spec, stats = generate_speculative(
        model, params, prompt, max_new_tokens=new, extra_variables=extra,
        n_drafts=2,
    )
    np.testing.assert_array_equal(np.asarray(spec[:, : prompt.shape[1] + new]),
                                  np.asarray(plain))
    f, a = int(stats["forwards"]), int(stats["accepted"])
    # each forward commits 1 + (accepted this iter); overshoot <= 2
    assert new <= f + a + 1 <= new + 2, (f, a)
    assert 0 <= a <= 2 * f


@pytest.mark.slow
def test_speculative_2draft_beats_single_on_predictable_stream():
    """On a memorized periodic stream the chained drafts must push
    tokens/forward ABOVE the single-draft cap of 2. Marked slow (a
    training fit feeds a PERFORMANCE acceptance): 2-draft token
    equality stays tier-1 (`test_speculative_2draft_equals_plain_greedy`
    + the full-context edge), and the live-speedup contract is gated by
    CI's serve-bench speculative smoke."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    model = DeepSeekV3(TINY2)
    toks = np.tile(np.arange(8), 4000)
    tcfg = TrainConfig(
        steps=150, batch_size=8, log_every=1000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10,
                                  total_steps=150),
    )
    trainer = Trainer(model, tcfg, loss_fn=dsv3_loss_fn, init_fn=dsv3_init_fn)
    state = trainer.fit(lm_batch_iterator(toks, 8, 32, seed=0))
    params = jax.device_get(state.params)
    extra = {"moe_state": jax.device_get(state.model_state)["moe_state"]}

    prompt = jnp.asarray(np.tile(np.arange(8), 2)[None, :], jnp.int32)
    new = 24
    plain = generate(model, params, prompt, jax.random.key(0),
                     max_new_tokens=new, sampler=ops.sample_greedy,
                     extra_variables=extra, max_len=prompt.shape[1] + new + 3)
    spec, stats = generate_speculative(
        model, params, prompt, max_new_tokens=new, extra_variables=extra,
        n_drafts=2,
    )
    np.testing.assert_array_equal(np.asarray(spec[:, : prompt.shape[1] + new]),
                                  np.asarray(plain))
    f, a = int(stats["forwards"]), int(stats["accepted"])
    tpf = 1 + a / f
    assert tpf > 2.0, dict(stats)  # beyond the single-draft cap


def test_speculative_2draft_full_context_edge():
    """Full-context decode (s0 + new + n_drafts - 1 == block_size): the
    cache must NOT clamp the final 3-token chunk's write (a clamped
    dynamic_update_slice would shift the write one slot left and corrupt a
    committed token's latent — code-review r5 finding)."""
    cfg = dc.replace(TINY2, block_size=48)
    model = DeepSeekV3(cfg)
    s0 = 16
    new = cfg.block_size - s0 - 1  # 31: exactly at the position limit
    prompt = jax.random.randint(jax.random.key(2), (1, s0), 0, cfg.vocab_size)
    variables = model.init({"params": jax.random.key(1)}, prompt,
                           return_mtp=True)
    extra = {"moe_state": variables["moe_state"]}
    params = variables["params"]
    plain = generate(model, params, prompt, jax.random.key(9),
                     max_new_tokens=new, sampler=ops.sample_greedy,
                     extra_variables=extra)
    spec, _ = generate_speculative(model, params, prompt, max_new_tokens=new,
                                   extra_variables=extra, n_drafts=2)
    np.testing.assert_array_equal(np.asarray(spec[:, : s0 + new]),
                                  np.asarray(plain[:, : s0 + new]))
    # one past the limit must raise, not silently clamp
    with pytest.raises(ValueError, match="max positions"):
        generate_speculative(model, params, prompt, max_new_tokens=new + 1,
                             extra_variables=extra, n_drafts=2)


def test_cli_sample_speculative_matches_plain_greedy(tmp_path, capsys):
    """`cli sample --speculative` (the user-facing wiring of
    infer/speculative.py) prints EXACTLY the text of `--greedy` — the
    CLI-level token-equality regression for the MTP path, pinned end to
    end through config registry + tokenizer + restore plumbing."""
    from solvingpapers_tpu.cli import main as cli_main
    from solvingpapers_tpu.configs import register
    from solvingpapers_tpu.configs.registry import (
        OptimizerConfig,
        RunConfig,
        TrainConfig,
    )

    @register("dsv3_mtp_clitest")
    def _cfg() -> RunConfig:
        return RunConfig(
            name="dsv3_mtp_clitest",
            model_family="deepseekv3",
            model=TINY,  # the f32 tiny config the equality tests use
            train=TrainConfig(
                steps=1, batch_size=2, log_every=1, eval_every=0,
                optimizer=OptimizerConfig(max_lr=1e-3, total_steps=1),
            ),
            data={"kind": "char", "path": None, "block_size": 32},
            notes="test-only tiny MTP config",
        )

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("abcdefgh " * 400)
    common = ["sample", "--config", "dsv3_mtp_clitest",
              "--data-path", str(corpus), "--prompt", "abcab",
              "--max-new-tokens", "16", "--seed", "3"]
    base = common + ["--greedy"]
    assert cli_main(base) == 0
    plain = capsys.readouterr().out
    assert cli_main(base + ["--speculative"]) == 0
    cap = capsys.readouterr()
    assert cap.out == plain, "--speculative changed the greedy text"
    # bad invocations exit with a message, never a traceback
    assert cli_main(common + ["--speculative"]) == 1  # demands --greedy
    assert cli_main(base + ["--speculative", "--spec-drafts", "2"]) == 1


def test_speculative_rejects_bad_inputs():
    model, params, prompt, extra = _setup()
    with pytest.raises(ValueError, match="batch 1"):
        generate_speculative(model, params, jnp.tile(prompt, (2, 1)),
                             max_new_tokens=4, extra_variables=extra)
    no_mtp = DeepSeekV3(dc.replace(TINY, mtp_heads=0))
    with pytest.raises(ValueError, match="mtp_heads"):
        generate_speculative(no_mtp, params, prompt, max_new_tokens=4,
                             extra_variables=extra)
