"""Context-parallel training through the stock Trainer and the CLI front
door (VERDICT r1 item 2): the shard_map-composed CP train step must equal
the dense single-device Trainer step, and `cli train --config
llama3_long_smoke` must run end-to-end on the virtual 8-device mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig
from solvingpapers_tpu.sharding import MeshConfig, create_mesh
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer


def _make_batch(key, batch, seq, vocab):
    x = jax.random.randint(key, (batch, seq), 0, vocab)
    return {"x": x, "y": jnp.roll(x, -1, axis=1)}


def _tiny_cfgs(context_parallel, mesh_cfg, impl="ring"):
    # ulysses all_to_all needs kv heads divisible by the context axis (4)
    heads, kv = (8, 4) if impl == "ulysses" else (4, 2)
    model = LlamaConfig(
        vocab_size=64, max_seq_len=64, dim=32, n_layers=2, n_heads=heads,
        n_kv_heads=kv, dropout=0.0, context_parallel=context_parallel,
        context_impl=impl,
    )
    train = TrainConfig(
        steps=2, batch_size=4, log_every=1, eval_every=0,
        mesh=mesh_cfg, context_parallel=context_parallel,
        optimizer=OptimizerConfig(max_lr=1e-2, warmup_steps=0, total_steps=4,
                                  grad_clip=1.0),
    )
    return model, train


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_trainer_step_matches_dense_trainer(devices, impl):
    """One Trainer._train_step under CP (data=2 x context=4, shard_map ring
    or Ulysses inside) == the dense single-device Trainer step: same loss,
    same updated params."""
    batch = _make_batch(jax.random.key(0), 4, 64, 64)

    d_model, d_train = _tiny_cfgs(False, MeshConfig(data=1), impl)
    dense = Trainer(Llama(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    c_model, c_train = _tiny_cfgs(True, MeshConfig(data=2, context=4), impl)
    cp = Trainer(Llama(c_model), c_train,
                 mesh=create_mesh(MeshConfig(data=2, context=4), devices))
    c_state = cp.init_state(batch)
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_perplexity"])),
        float(jax.device_get(d_metrics["train_perplexity"])), rtol=1e-5,
    )
    # atol covers Adam's epsilon amplifying all_to_all reduction-order noise
    # on near-zero grads (observed max 8e-5 on 1/2720 elements for ulysses)
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_cp_eval_matches_dense(devices):
    batch = _make_batch(jax.random.key(1), 4, 64, 64)
    d_model, d_train = _tiny_cfgs(False, MeshConfig(data=1))
    dense = Trainer(Llama(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    d_val = dense.evaluate(d_state, iter([batch]))

    c_model, c_train = _tiny_cfgs(True, MeshConfig(data=2, context=4))
    cp = Trainer(Llama(c_model), c_train,
                 mesh=create_mesh(MeshConfig(data=2, context=4), devices))
    c_state = cp.init_state(batch)
    c_val = cp.evaluate(c_state, iter([batch]))
    np.testing.assert_allclose(c_val["val_loss"], d_val["val_loss"], rtol=1e-5)


def test_cp_ulysses_dropout_trains_deterministically(devices):
    """Dropout under Ulysses CP (VERDICT r3 missing #4): after the
    all_to_all each member computes full attention for its own head group,
    so per-member rng folds (the engine's 'context' fold) give every
    (head, block) an independent mask. Dense core on CPU; same TrainState
    -> bit-identical steps; eval (deterministic) loss differs from the
    dropout-on train loss."""
    batch = _make_batch(jax.random.key(1), 4, 64, 64)
    mesh_cfg = MeshConfig(data=2, context=4)

    def run():
        model, train = _tiny_cfgs(True, mesh_cfg, "ulysses")
        model = dataclasses.replace(model, dropout=0.2)
        t = Trainer(Llama(model), train,
                    mesh=create_mesh(mesh_cfg, devices))
        state = t.init_state(batch)
        t._build_steps()
        state, metrics = t._train_step(state, batch)
        val = t._eval_step(state, batch)
        return (float(jax.device_get(metrics["train_loss"])),
                float(jax.device_get(metrics["grad_norm"])),
                float(jax.device_get(val["val_loss"])))

    l1, g1, v1 = run()
    l2, g2, v2 = run()
    assert (l1, g1, v1) == (l2, g2, v2)
    assert np.isfinite(l1) and np.isfinite(g1)
    assert abs(v1 - l1) > 1e-3


def test_cp_rejects_model_tp_axes(devices):
    model, train = _tiny_cfgs(True, MeshConfig(data=1, model=2, context=4))
    t = Trainer(Llama(model), train,
                mesh=create_mesh(MeshConfig(data=1, model=2, context=4), devices))
    batch = _make_batch(jax.random.key(2), 4, 64, 64)
    t.init_state(batch)
    with pytest.raises(NotImplementedError, match="does not compose"):
        t._build_steps()


def test_cp_cli_front_door(devices, tmp_path, capsys):
    """`cli train --config llama3_long_smoke` runs the CP Trainer end to
    end (VERDICT: 'a config that refuses to train is started, not done')."""
    from solvingpapers_tpu import cli

    jsonl = tmp_path / "metrics.jsonl"
    rc = cli.main([
        "train", "--config", "llama3_long_smoke", "--steps", "12",
        "--jsonl", str(jsonl),
    ])
    assert rc == 0
    import json

    rows = [json.loads(l) for l in jsonl.read_text().splitlines()]
    train_rows = [r for r in rows if "train_loss" in r]
    assert train_rows, rows
    assert all(np.isfinite(r["train_loss"]) for r in train_rows)
    # the CP smoke must actually learn a little on the synthetic corpus
    assert train_rows[-1]["train_loss"] < train_rows[0]["train_loss"] + 0.5
    assert any("val_loss" in r for r in rows)


def test_cp_fsdp_trainer_step_matches_dense(devices):
    """CP composed with FSDP (data=2 x fsdp=2 x context=2): params stored
    sharded over 'fsdp' (ZeRO layout), all-gathered inside the shard_map
    step, grads reduce-scattered — must equal the dense single-device step."""
    batch = _make_batch(jax.random.key(3), 4, 64, 64)

    d_model, d_train = _tiny_cfgs(False, MeshConfig(data=1))
    dense = Trainer(Llama(d_model), d_train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    mesh_cfg = MeshConfig(data=2, fsdp=2, context=2)
    c_model, c_train = _tiny_cfgs(True, mesh_cfg)
    cp = Trainer(Llama(c_model), c_train,
                 mesh=create_mesh(mesh_cfg, devices))
    c_state = cp.init_state(batch)
    # at least one param must actually be stored sharded over fsdp
    fsdp_sharded = [
        l for l in jax.tree.leaves(c_state.params)
        if "fsdp" in str(l.sharding.spec)
    ]
    assert fsdp_sharded, "no param stored sharded over the fsdp axis"
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=1e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("family", ["gpt", "gemma"])
def test_cp_extends_across_zoo(devices, family):
    """CP is zoo-wide (long-context is first-class): GPT (learned positions)
    and Gemma (grouped MQA + RoPE) train under the CP Trainer and match
    their dense single-device step."""
    if family == "gpt":
        from solvingpapers_tpu.models.gpt import GPT as Model, GPTConfig as Cfg

        kw = dict(vocab_size=64, block_size=64, dim=32, n_layers=2,
                  n_heads=4, dropout=0.0)
    else:
        from solvingpapers_tpu.models.gemma import Gemma as Model, GemmaConfig as Cfg

        kw = dict(vocab_size=64, max_seq_len=64, dim=32, n_layers=2,
                  n_heads=4, n_kv_heads=2, dropout=0.0)
    batch = _make_batch(jax.random.key(4), 4, 64, 64)
    train = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )

    dense = Trainer(Model(Cfg(**kw)), train,
                    mesh=create_mesh(MeshConfig(data=1), devices[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    import dataclasses as dc

    c_train = dc.replace(train, context_parallel=True,
                         mesh=MeshConfig(data=2, context=4))
    cp = Trainer(Model(Cfg(**kw, context_parallel=True)), c_train,
                 mesh=create_mesh(MeshConfig(data=2, context=4), devices))
    c_state = cp.init_state(batch)
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=1e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_gpt_cp_rejects_positions_past_table(devices):
    """A CP GPT whose GLOBAL sequence exceeds the learned position table
    must fail at trace time, not silently clamp every late token to the
    last table row."""
    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=1,
                    n_heads=2, dropout=0.0, context_parallel=True)
    model = GPT(cfg)
    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    toks = jnp.zeros((2, 128), jnp.int32)  # global 128 > block_size 64
    with pytest.raises(ValueError, match="exceeds max positions"):
        jax.shard_map(
            lambda x: model.init({"params": jax.random.key(0)}, x),
            mesh=mesh, in_specs=P(("data",), "context"), out_specs=P(),
        )(toks)
