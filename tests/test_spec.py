"""Speculative decoding subsystem (solvingpapers_tpu/serve/spec.py +
engine wiring).

The contract under test: speculation changes how many forwards a stream
takes, NEVER its content or distribution —

* greedy streams with speculation enabled are byte-identical to spec-off
  serving and to one-shot `generate`, for every decoder family, on both
  pool layouts, including across paged-pool preemption/recompute;
* stochastic slots use rejection sampling against `fused_sample`'s
  truncated distributions: the committed-token marginal matches the
  plain sampler's empirical distribution (fixed-seed statistical test),
  and a seeded stream replays identically run-to-run;
* mixed spec/non-spec batches (greedy + stochastic + grammar) share ONE
  compiled speculative decode program (jit-cache pinned);
* the scheduler's anti-starvation clock counts DELIVERED tokens, so a
  high-acceptance slot cannot starve the wait budget.
"""

import dataclasses as dc
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.serve import SamplingParams, ServeConfig, ServeEngine
from solvingpapers_tpu.serve.engine import _spec_decode_program
from solvingpapers_tpu.serve.sampling import PackedSampling, fused_sample
from solvingpapers_tpu.serve.scheduler import FIFOScheduler, Request
from solvingpapers_tpu.serve.spec import (
    SpecController,
    ngram_drafts,
    spec_verify,
)


# builders are deterministic (fixed init keys) and everything downstream
# treats params as read-only, so each family's model/params build once
# per session — engine pools copy out of init_caches, never into params
@functools.lru_cache(maxsize=None)
def _gpt():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params, None, 64


@functools.lru_cache(maxsize=None)
def _llama3():
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    model = Llama(LlamaConfig(vocab_size=64, max_seq_len=64, dim=32,
                              n_layers=2, n_heads=4, n_kv_heads=2,
                              dropout=0.0))
    params = model.init({"params": jax.random.key(1)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params, None, 64


@functools.lru_cache(maxsize=None)
def _gemma():
    from solvingpapers_tpu.models.gemma import Gemma, GemmaConfig

    model = Gemma(GemmaConfig(vocab_size=64, max_seq_len=64, dim=32,
                              n_layers=2, n_heads=4, n_kv_heads=2,
                              dropout=0.0))
    params = model.init({"params": jax.random.key(2)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params, None, 64


@functools.lru_cache(maxsize=None)
def _dsv3(mtp_heads=0):
    from solvingpapers_tpu.models.deepseekv3 import (
        DeepSeekV3,
        DeepSeekV3Config,
    )

    # 1 layer / 2 experts: the smallest config that still exercises the
    # family's serving particulars (latent-cache lanes, moe_state extra
    # variables, MTP heads) — dsv3 traces dominate this module's compile
    # bill, and the spec contract is model-size-independent
    model = DeepSeekV3(DeepSeekV3Config(
        vocab_size=64, block_size=96, dim=32, n_layers=1, n_heads=2,
        latent_dim=8, rope_dim=8, pe_scale=0.02, n_experts=2,
        top_experts=2, dropout=0.0, attn_dropout=0.0, mtp_heads=mtp_heads,
    ))
    variables = model.init(
        {"params": jax.random.key(3)}, jnp.zeros((1, 8), jnp.int32),
        **({"return_mtp": True} if mtp_heads else {}),
    )
    extra = {"moe_state": variables["moe_state"]}
    return model, variables["params"], extra, 64


_FAMILIES = {"gpt": _gpt, "llama3": _llama3, "gemma": _gemma,
             "deepseekv3": _dsv3}


def _prompts(n, seed=0, lo=5, hi=16, vocab=64):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


#: memoized one-shot `generate` references. The _FAMILIES builders are
#: deterministic (fixed init keys), so two tests asking for the same
#: (family, prompt, length) reference — e.g. the lane and paged arms of
#: the exactness matrix — would recompute an identical stream; passing
#: `cache_key=family` skips the duplicate generate compile + run, which
#: is most of this module's tier-1 cost.
_REF_CACHE: dict = {}


def _ref(model, params, extra, prompt, new, cache_key=None):
    if cache_key is not None:
        k = (cache_key, np.asarray(prompt, np.int32).tobytes(), new)
        if k in _REF_CACHE:
            return _REF_CACHE[k]
    out = generate(model, params, jnp.asarray(prompt)[None, :],
                   jax.random.key(0), max_new_tokens=new,
                   extra_variables=extra)
    toks = np.asarray(out[0, len(prompt):]).tolist()
    if cache_key is not None:
        _REF_CACHE[k] = toks
    return toks


# ------------------------------------------------------ greedy exactness


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_spec_greedy_streams_exact(family, paged):
    """Greedy spec-on streams == spec-off streams == one-shot generate,
    for all four families on both pools — speculation must be invisible
    in the tokens (including the all-reject path: untrained models
    rarely accept, which is the hard case for the commit bookkeeping)."""
    model, params, extra, vocab = _FAMILIES[family]()
    prompts = _prompts(4, seed=4, vocab=vocab)

    def run(spec):
        # spec_rounds=2 == the controller's probe length, so probe and
        # full blocks share ONE compiled program per arm (the probe!=full
        # two-program path is covered once, by the S=2/max_len=64
        # cluster below)
        kw = dict(speculative="ngram", spec_k=4, spec_rounds=2) if spec \
            else {}
        if paged:
            kw.update(paged=True, page_size=8)
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=48, decode_block=4, bucket=8, **kw,
        ), extra_variables=extra)
        hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
        eng.run()
        return eng, hs

    eng_on, on = run(True)
    # one-shot generate IS the canonical reference (spec-off serving ==
    # generate is pinned by tests/test_serve.py); compiling a second
    # spec-off engine per family x pool would double this matrix's cost,
    # so the direct spec-off comparison runs once, on the cheapest combo
    if family == "gpt" and not paged:
        _, off = run(False)
        for i in range(len(prompts)):
            assert on[i].tokens == off[i].tokens, "spec-on != spec-off"
    for i, p in enumerate(prompts):
        ref = _ref(model, params, extra, p, 10, cache_key=family)
        assert on[i].tokens == ref, (
            f"{family}/{'paged' if paged else 'lane'} spec-on diverged: "
            f"{on[i].tokens} != {ref}"
        )
    snap = eng_on.metrics.snapshot()
    assert "serve/spec_acceptance_rate" in snap
    assert snap["serve/spec_tokens_per_step"] > 0


def test_spec_greedy_exact_across_paged_preemption():
    """A page budget too small for the offered load forces
    preempt-and-recompute mid-stream; with speculation on, resumed
    streams must still be byte-exact (the resume prefill + the spec
    block's accepted-window scatter compose losslessly)."""
    model, params, extra, vocab = _gpt()
    prompts = _prompts(4, seed=9, lo=8, hi=12, vocab=vocab)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=4, max_len=48, decode_block=4, bucket=8,
        paged=True, page_size=8, page_budget=12,
        speculative="ngram", spec_k=4, spec_rounds=2,
    ))
    hs = [eng.submit(p, max_new_tokens=16) for p in prompts]
    eng.run()
    assert all(h.done for h in hs)
    for p, h in zip(prompts, hs):
        assert h.tokens == _ref(model, params, None, p, 16)
    assert eng.metrics.preemptions > 0, (
        "workload never preempted — shrink page_budget so the test "
        "exercises recompute under speculation"
    )


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_spec_composes_with_prefix_cache(paged):
    """Speculation + the radix prefix cache (splice on the lane pool,
    zero-copy page sharing on the paged pool): shared-stem greedy
    streams stay byte-exact vs a cache-off spec-off engine, and the
    cache still hits."""
    model, params, _, vocab = _gpt()
    rng = np.random.default_rng(17)
    stem = rng.integers(0, vocab, size=16).astype(np.int32)
    prompts = [np.concatenate([stem, rng.integers(0, vocab, size=6)
                               .astype(np.int32)]) for _ in range(4)]
    kw = dict(paged=True, page_size=8) if paged else {}
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=48, decode_block=4, bucket=8,
        prefix_cache=True, prefix_page=8,
        speculative="ngram", spec_k=4, spec_rounds=2, **kw,
    ))
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    for p, h in zip(prompts, hs):
        assert h.tokens == _ref(model, params, None, p, 10,
                                cache_key="gpt-prefix")
    assert eng.metrics.prefix_hits > 0, "stems never hit the cache"


def test_spec_eos_mid_chunk_truncates_exactly():
    """An EOS committed mid-chunk ends the stream at the EOS (kept),
    discarding the chunk's overshoot — same contract as the plain
    block's mid-block EOS."""
    model, params, _, vocab = _gpt()
    prompt = _prompts(1, seed=11, lo=8, hi=9)[0]
    ref = _ref(model, params, None, prompt, 16)
    eos = ref[3]
    assert eos not in ref[:3]
    # n_slots=2/max_len=64 on purpose: the same program shapes as the
    # seeded/adversarial/compile-count/grammar tests below, so this
    # module compiles the cluster's spec program once
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
        speculative="ngram", spec_k=4, spec_rounds=4,
    ))
    h = eng.submit(prompt, max_new_tokens=16, eos_id=eos)
    eng.run()
    assert h.finish_reason == "eos"
    assert h.tokens == ref[:4] and h.tokens[-1] == eos


# ------------------------------------------------------------ MTP drafter


@pytest.mark.parametrize(
    "heads",
    [1, pytest.param(2, marks=pytest.mark.slow)],
)
def test_spec_mtp_greedy_exact(heads):
    """The MTP drafter (deepseekv3 heads, lane pool): greedy streams
    byte-identical to generate even when untrained drafts mostly
    reject, for 1 and 2 chained heads. The 2-head arm is slow-marked
    (a second trace of the whole MTP spec program for the wider chunk):
    tier-1 keeps 1-head serving exactness here plus 2-draft chain
    equality at the function level
    (tests/test_speculative.py::test_speculative_2draft_equals_plain_greedy
    and the full-context edge)."""
    model, params, extra, vocab = _dsv3(mtp_heads=heads)
    prompts = _prompts(2, seed=6, vocab=vocab)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=48, decode_block=4, bucket=8,
        speculative="mtp", spec_rounds=2,
    ), extra_variables=extra)
    hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run()
    for p, h in zip(prompts, hs):
        assert h.tokens == _ref(model, params, extra, p, 8)
    assert eng.metrics.spec_steps > 0


@pytest.mark.slow
def test_spec_mtp_accepts_on_predictable_stream():
    """On a memorized periodic corpus the MTP drafter must accept (the
    speedup mechanism is live, not just the all-reject fallback) while
    streams stay exact — the serving twin of
    tests/test_speculative.py's acceptance test. Marked slow (a 150-step
    training fit): tier-1 already gates MTP exactness (the untrained
    all-reject path above), and trained-draft acceptance is gated by
    CI's serve-bench speculative smoke; the function-level twin
    (tests/test_speculative.py) is slow-marked for the same reason."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.deepseekv3 import (
        DeepSeekV3,
        DeepSeekV3Config,
    )
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    cfg = DeepSeekV3Config(
        vocab_size=64, block_size=128, dim=32, n_layers=2, n_heads=2,
        latent_dim=8, rope_dim=8, pe_scale=0.02, n_experts=4,
        top_experts=2, dropout=0.0, attn_dropout=0.0, mtp_heads=1,
    )
    model = DeepSeekV3(cfg)
    toks = np.tile(np.arange(8), 4000)
    tcfg = TrainConfig(
        steps=150, batch_size=8, log_every=1000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10,
                                  total_steps=150),
    )
    trainer = Trainer(model, tcfg, loss_fn=dsv3_loss_fn,
                      init_fn=dsv3_init_fn)
    state = trainer.fit(lm_batch_iterator(toks, 8, 32, seed=0))
    params = jax.device_get(state.params)
    extra = {"moe_state": jax.device_get(state.model_state)["moe_state"]}
    prompts = [np.tile(np.arange(8), 2).astype(np.int32),
               np.tile(np.arange(8), 2)[3:].astype(np.int32)]
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
        speculative="mtp", spec_rounds=4,
    ), extra_variables=extra)
    hs = [eng.submit(p, max_new_tokens=20) for p in prompts]
    eng.run()
    for p, h in zip(prompts, hs):
        assert h.tokens == _ref(model, params, extra, p, 20)
    assert eng.metrics.spec_accepted > 0, "trained drafts never accepted"


def test_spec_config_validation():
    model, params, _, _ = _gpt()
    with pytest.raises(ValueError, match="spec_rounds"):
        ServeEngine(model, params, ServeConfig(max_len=48, spec_rounds=4))
    with pytest.raises(ValueError, match="speculative must be one of"):
        ServeEngine(model, params, ServeConfig(max_len=48,
                                               speculative="oracle"))
    with pytest.raises(ValueError, match="mtp_heads == 0"):
        ServeEngine(model, params, ServeConfig(max_len=48,
                                               speculative="mtp"))
    dmodel, dparams, dextra, _ = _dsv3(mtp_heads=1)
    with pytest.raises(ValueError, match="lane pool"):
        ServeEngine(dmodel, dparams, ServeConfig(
            speculative="mtp", paged=True, page_size=16, max_len=48,
        ), extra_variables=dextra)
    with pytest.raises(ValueError, match="prefix"):
        ServeEngine(dmodel, dparams, ServeConfig(
            max_len=48, speculative="mtp", prefix_cache=True,
        ), extra_variables=dextra)
    with pytest.raises(ValueError, match="spec_k"):
        ServeEngine(model, params, ServeConfig(max_len=48,
                                               speculative="ngram",
                                               spec_k=0))


# ----------------------------------------------------- stochastic slots


def test_spec_seeded_streams_reproducible_and_greedy_in_mix_exact():
    """A seeded stochastic request replays the same stream across two
    spec-on engines (the rng chain folds only (seed, committed index)),
    and a greedy request sharing those batches stays exact vs spec-off."""
    model, params, _, vocab = _gpt()
    prompts = _prompts(3, seed=7, vocab=vocab)

    def run(spec):
        kw = dict(speculative="ngram", spec_k=4, spec_rounds=4) if spec \
            else {}
        # 2 slots for 3 requests: the third queues behind the first
        # free slot, which also exercises the chain's independence from
        # slot assignment/interleaving (and shares the module's S=2
        # compiled-program cluster)
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=64, decode_block=4, bucket=8, **kw))
        hs = [
            eng.submit(prompts[0], max_new_tokens=10),
            eng.submit(prompts[1], max_new_tokens=10,
                       params=SamplingParams(temperature=1.2, top_p=0.9,
                                             seed=7)),
            eng.submit(prompts[2], max_new_tokens=10,
                       params=SamplingParams(temperature=0.8, top_k=8,
                                             seed=11, logprobs=True)),
        ]
        eng.run()
        return hs

    a, b, off = run(True), run(True), run(False)
    assert a[0].tokens == off[0].tokens == _ref(model, params, None,
                                                prompts[0], 10)
    assert a[1].tokens == b[1].tokens
    assert a[2].tokens == b[2].tokens
    assert len(a[2].logprobs) == len(a[2].tokens)
    assert all(np.isfinite(lp) and lp <= 0 for lp in a[2].logprobs)


@pytest.mark.parametrize("draft_kind", ["likely", "unlikely", "mixed"])
def test_spec_verify_matches_plain_sampler_distribution(draft_kind):
    """Fixed-seed statistical test: the committed token at the FIRST
    chunk position (a verify-or-resample position) must be distributed
    exactly like `fused_sample`'s draw from the same truncated
    distribution, whatever the draft was — the lossless rejection
    sampling claim, measured empirically (total variation under the
    sampling-noise floor)."""
    vocab, cap, n = 32, 16, 4000
    logits = jax.random.normal(jax.random.key(1), (1, vocab)) * 2.0
    packed = PackedSampling(
        temperature=jnp.asarray([0.9]), top_p=jnp.asarray([0.85]),
        min_p=jnp.asarray([0.02]), top_k=jnp.asarray([12]),
        need_lp=jnp.asarray([0]),
    )
    keysets = jax.random.split(jax.random.key(2), n)
    ref = jax.vmap(
        lambda kk: fused_sample(logits, packed, kk[None], cap=cap)[0][0]
    )(keysets)
    ref_hist = np.bincount(np.asarray(ref), minlength=vocab) / n

    order = np.asarray(jnp.argsort(-logits[0]))
    draft = {"likely": int(order[0]), "unlikely": int(order[-1]),
             "mixed": int(order[3])}[draft_kind]
    big_l = 3
    lg = jnp.broadcast_to(logits[0], (1, big_l, vocab))
    drafts = jnp.asarray([[draft, draft]], jnp.int32)
    avail = jnp.asarray([2], jnp.int32)

    def one(kk):
        keys = jax.vmap(
            lambda i: jax.random.fold_in(kk, i)
        )(jnp.arange(big_l))[None, :]
        out, _, _ = spec_verify(lg, drafts, avail, packed, keys, cap=cap)
        return out[0, 0]

    toks = jax.vmap(one)(jax.random.split(jax.random.key(3), n))
    hist = np.bincount(np.asarray(toks), minlength=vocab) / n
    tv = 0.5 * float(np.abs(hist - ref_hist).sum())
    assert tv < 0.05, (
        f"rejection-sampled marginal diverged from the plain sampler "
        f"(draft={draft_kind}, TV={tv:.4f})"
    )


def test_spec_verify_greedy_rows_are_argmax():
    """Greedy rows commit row argmaxes and accept only exact matches —
    the committed matrix IS the greedy continuation."""
    vocab, cap = 32, 16
    lg = jax.random.normal(jax.random.key(5), (2, 4, vocab))
    am = np.asarray(jnp.argmax(lg, -1))
    drafts = jnp.asarray(
        [[int(am[0, 0]), int(am[0, 1]), 0],
         [int(am[1, 0]) + 1, 0, 0]], jnp.int32) % vocab
    avail = jnp.asarray([3, 3], jnp.int32)
    packed = PackedSampling(
        temperature=jnp.zeros(2), top_p=jnp.ones(2), min_p=jnp.zeros(2),
        top_k=jnp.zeros(2, jnp.int32), need_lp=jnp.zeros(2, jnp.int32),
    )
    keys = jnp.stack([jax.random.split(jax.random.key(6), 4)] * 2)
    out, commits, _ = spec_verify(lg, drafts, avail, packed, keys, cap=cap)
    np.testing.assert_array_equal(np.asarray(out), am)
    # slot 0 accepted drafts 0,1 (exact argmaxes), rejected draft 2
    # unless it happened to be the argmax too
    expect0 = 3 + (int(am[0, 2]) == 0)
    assert int(commits[0]) == min(expect0, 4)
    # slot 1's first draft is wrong by construction: exactly 1 commit
    assert int(commits[1]) == 1


# ------------------------------------------------------ drafter + control


def test_ngram_drafts_lookup():
    """The device lookup proposes the continuation of the most recent
    earlier occurrence of the longest matching tail n-gram."""
    hist = jnp.asarray([5, 1, 2, 9, 9, 1, 2, 7, 3, 1, 2, 0, 0, 0, 0, 0],
                       jnp.int32)
    # live length 11: tail bigram (1, 2) last recurred at index 5 -> the
    # continuation is hist[7:] = [7, 3, ...]
    drafts, avail = ngram_drafts(hist, jnp.int32(11), k=3, nmax=3)
    assert int(avail) == 3
    np.testing.assert_array_equal(np.asarray(drafts), [7, 3, 1])
    # nothing recurs: no proposal
    fresh = jnp.asarray([1, 2, 3, 4, 5, 6, 7, 8], jnp.int32)
    _, avail = ngram_drafts(fresh, jnp.int32(8), k=3, nmax=3)
    assert int(avail) == 0
    # short history never proposes (nothing earlier to match)
    _, avail = ngram_drafts(fresh, jnp.int32(1), k=3, nmax=3)
    assert int(avail) == 0


def test_spec_controller_backoff_and_probe():
    """The three-state controller: cold start probes, zero acceptance
    holds (plain blocks) with EXPONENTIAL backoff between cheap probes,
    recovered acceptance promotes to full speculation."""
    ctl = SpecController(min_rate=1.0, probe_every=4, decay=0.0)
    assert ctl.decide() == "probe"  # cold start measures cheaply
    ctl.observe(accepted=0, rounds=8)  # 0/round < 1.0 -> hold 4
    assert [ctl.decide() for _ in range(4)] == ["off"] * 4
    assert ctl.decide() == "probe"
    ctl.observe(accepted=0, rounds=2)  # failed probe -> hold DOUBLES
    assert [ctl.decide() for _ in range(8)] == ["off"] * 8
    assert ctl.decide() == "probe"
    ctl.observe(accepted=16, rounds=2)  # 8/round: recovered
    assert ctl.decide() == "full"
    ctl.observe(accepted=12, rounds=6)  # still healthy
    assert ctl.decide() == "full"
    stats = ctl.stats()
    assert stats["fallback_steps"] == 12
    assert stats["probes"] == 3
    assert stats["mode"] == "full"
    # a healthy recovery reset the backoff: the next failure holds 4
    ctl.observe(accepted=0, rounds=6)  # decay=0 -> EMA drops instantly
    assert sum(1 for _ in range(20) if ctl.decide() == "off") == 4


def test_spec_adversarial_traffic_falls_back():
    """High-temperature random streams defeat the n-gram drafter; the
    engine must settle onto the plain block program (fallback steps
    dominate) instead of paying the chunk width every step — and the
    streams still finish correctly."""
    model, params, _, vocab = _gpt()
    prompts = _prompts(6, seed=13, vocab=vocab)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
        speculative="ngram", spec_k=4, spec_rounds=4,
        spec_min_rate=0.5, spec_probe_every=4,
    ))
    hs = [eng.submit(p, max_new_tokens=24,
                     params=SamplingParams(temperature=2.0, seed=100 + i))
          for i, p in enumerate(prompts)]
    eng.run()
    assert all(h.done for h in hs)
    stats = eng.statusz()["spec"]
    assert stats["fallback_steps"] > 0, (
        "adversarial traffic never triggered the controller's fallback"
    )


def test_spec_compile_count_one_program_for_mixed_batches():
    """Greedy + stochastic + draft-less slots in one batch add ZERO
    compiled speculative decode programs over an all-greedy run — draft
    length and every sampling knob are traced operands."""
    model, params, _, vocab = _gpt()
    prompts = _prompts(4, seed=5, lo=4, hi=8, vocab=vocab)
    cfg = ServeConfig(n_slots=2, max_len=64, decode_block=4, bucket=8,
                      speculative="ngram", spec_k=4, spec_rounds=4)

    eng = ServeEngine(model, params, cfg)
    for p in prompts:
        eng.submit(p, max_new_tokens=6)
    eng.run()
    progs = _spec_decode_program._cache_size()
    assert progs >= 1

    eng = ServeEngine(model, params, cfg)
    mixes = (None,
             SamplingParams(temperature=1.3, top_p=0.8, seed=1),
             SamplingParams(temperature=0.7, top_k=5),
             SamplingParams(temperature=1.0, min_p=0.1, seed=2,
                            logprobs=True))
    for p, sp in zip(prompts, mixes):
        eng.submit(p, max_new_tokens=6, params=sp)
    eng.run()
    assert _spec_decode_program._cache_size() == progs


def test_spec_grammar_slot_stays_constrained():
    """A grammar-constrained request inside a speculative engine decodes
    draft-free (one committed token per step) and still produces a
    complete, parseable JSON document."""
    import json

    from solvingpapers_tpu.serve.grammar import JsonStepper

    model, params, _, vocab = _gpt()
    table = list(
        '{}[]":,-.0123456789 \nabcdefghijklmnopqrstuvwxyz'
        "ABCDEFGHIJKLMNOP\\"
    )[:vocab]
    stepper = JsonStepper(table)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
        speculative="ngram", spec_k=4, spec_rounds=4,
    ), detokenize=lambda ids: "".join(table[i] or "" for i in ids))
    g = eng.submit(_prompts(1, seed=21)[0], max_new_tokens=40,
                   grammar=stepper)
    plain = eng.submit(_prompts(1, seed=22)[0], max_new_tokens=10)
    eng.run()
    assert g.finish_reason == "stop"
    text = "".join(table[t] or "" for t in g.tokens)
    json.loads(text)
    assert plain.tokens == _ref(model, params, None,
                                _prompts(1, seed=22)[0], 10)


# --------------------------------------------------- scheduler fairness


def _req(n=4):
    return Request(prompt=np.arange(n, dtype=np.int32), max_new_tokens=4,
                   eos_id=None)


def test_scheduler_tick_weight_normalizes_wait_to_delivered_tokens():
    """The anti-starvation budget is a DELIVERED-TOKEN quantum: a
    speculative engine passing weight = delivered/block must trip the
    override after the same delivered work as a plain engine ticking 1
    per block — high acceptance cannot stretch the head's wait."""
    # plain engine: 1.0/step; budget trips after max_wait_steps blocks
    plain = FIFOScheduler(decode_priority=True, max_prefills_per_step=1,
                          max_wait_steps=4)
    plain.submit(_req())
    for _ in range(5):
        plain.tick()
    assert len(plain.pick(n_free=2, n_active=4)) == 1  # budget fired

    # spec engine at 3x acceptance: each step delivers 3 blocks' worth;
    # the same delivered-token quantum is 2 steps, not 5
    spec = FIFOScheduler(decode_priority=True, max_prefills_per_step=1,
                         max_wait_steps=4)
    spec.submit(_req())
    for _ in range(2):
        spec.tick(weight=3.0)
    assert spec.queue[0].waited_steps == pytest.approx(6.0)
    assert len(spec.pick(n_free=2, n_active=4)) == 1  # same quantum

    # WITHOUT the weight (the regression): 2 high-acceptance steps =
    # 6 blocks of delivered work, yet the head would still be waiting
    legacy = FIFOScheduler(decode_priority=True, max_prefills_per_step=1,
                           max_wait_steps=4)
    legacy.submit(_req())
    for _ in range(2):
        legacy.tick()  # the old 1-per-iteration clock
    head = legacy.queue[0]
    assert head.waited_steps <= legacy.max_wait_steps  # still starved

    # sub-1 weights clamp: a purge-only step cannot age slower than 1
    clamp = FIFOScheduler(max_wait_steps=4)
    clamp.submit(_req())
    clamp.tick(weight=0.25)
    assert clamp.queue[0].waited_steps == pytest.approx(1.0)


def test_engine_spec_step_passes_delivered_weight():
    """End-to-end: with speculation accepting, the engine's tick weight
    exceeds 1 (waiting requests age faster than one unit per step)."""
    model, params, _, vocab = _gpt()
    # a repetitive prompt the untrained model continues repetitively —
    # the lookup accepts, so one step delivers more than a block
    prompt = np.tile(np.asarray([3, 9], np.int32), 8)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=2, bucket=8,
        speculative="ngram", spec_k=4, spec_rounds=2,
    ))
    h1 = eng.submit(prompt, max_new_tokens=24)
    waiter = eng.submit(_prompts(1, seed=30)[0], max_new_tokens=4)
    eng.step()  # admit h1 (prefill only)
    eng.step()  # first spec block
    if eng.metrics.spec_accepted > 0:
        assert waiter.waited_steps > 2.0, (
            "delivered-token weight never aged the waiting request "
            f"faster than the step clock (waited={waiter.waited_steps})"
        )
    eng.run()
    assert h1.done and waiter.done
    assert h1.tokens == _ref(model, params, None, prompt, 24)
