"""Fault-tolerance tests (serve/faults.py + the engine's supervised
step boundary in serve/engine.py).

Four contracts under test. Blast-radius isolation: with a seeded fault
plan poisoning K of N concurrent streams (both pools, speculation on
and off), the N-K untouched streams must be TOKEN-EXACT vs a fault-free
run, the poisoned streams finish "error", and `assert_no_leaks` passes
after drain. Systemic recovery: synthetic XlaRuntimeError/OOM trigger
bounded pool-rebuild retries — streams resume by recompute token-exact
— and persistent failure drains to `unhealthy` (/healthz 503) with a
backoff-gated recovery that serves a fresh request token-exactly.
Liveness: injected stalls fire the watchdog, and `ServeEngine.close` /
`force_drain` return within their bound with everything reclaimed.
None-pattern: with `fault_plan=None` the compiled-program inventory is
byte-for-byte the plain engine's (the compile registry proves no scrub
or extra program exists) and streams are untouched — the always-traced
finite-logits guard is a numeric no-op on finite logits.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_no_leaks
from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.serve import (
    DegradationLadder,
    FaultPlan,
    FaultSpec,
    ServeConfig,
    ServeEngine,
)
from solvingpapers_tpu.serve.faults import InjectedFault, classify_failure


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32,
                          n_layers=2, n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = _gpt_tiny()
    return _MODEL


def _ref(model, params, prompt, max_new):
    out = generate(model, params, jnp.asarray(prompt)[None, :],
                   jax.random.key(0), max_new_tokens=max_new)
    return np.asarray(out[0, len(prompt):]).tolist()


def _prompts(n, seed=0, size=8):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=size).astype(np.int32)
            for _ in range(n)]


def _cfg(**kw):
    base = dict(n_slots=3, max_len=32, decode_block=4, bucket=8,
                max_prefills_per_step=3)
    base.update(kw)
    return ServeConfig(**base)


# ------------------------------------------------------------- plan units


def test_fault_plan_is_deterministic_and_validates():
    specs = [
        dict(site="decode", kind="nan", visit=3, slot=1),
        dict(site="prefill", kind="oom", visit=0, count=2),
    ]
    a, b = FaultPlan(specs), FaultPlan(specs)
    fired_a = [tuple(s.kind for s in a.poke("decode")) for _ in range(5)]
    fired_b = [tuple(s.kind for s in b.poke("decode")) for _ in range(5)]
    assert fired_a == fired_b == [(), (), (), ("nan",), ()]
    # count=2 fires at consecutive visits
    assert [len(a.poke("prefill")) for _ in range(3)] == [1, 1, 0]
    # from_config on a live plan resets its counters (bench arms reuse
    # one config object across engines)
    fresh = FaultPlan.from_config(a)
    assert fresh.fired == 0 and fresh.poke("prefill")[0].kind == "oom"
    assert FaultPlan.from_config(None) is None
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec(site="nowhere", kind="nan", visit=0)
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec(site="decode", kind="meteor", visit=0)
    with pytest.raises(ValueError, match="stall_s"):
        FaultSpec(site="decode", kind="stall", visit=0)
    with pytest.raises(ValueError, match="sse_write"):
        FaultSpec(site="decode", kind="socket_reset", visit=0)
    with pytest.raises(ValueError, match="poison"):
        FaultSpec(site="scatter", kind="nan", visit=0)


def test_classify_failure_taxonomy():
    assert classify_failure(InjectedFault("oom", "decode")) == "systemic"
    assert classify_failure(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory")) == "systemic"

    class XlaRuntimeError(RuntimeError):
        pass

    assert classify_failure(XlaRuntimeError("boom")) == "systemic"
    assert classify_failure(KeyError("host bug")) == "host"


def test_ladder_hysteresis_and_shed_order():
    lad = DegradationLadder(up_steps=2, down_steps=3)
    assert lad.observe(True) is None          # 1 pressured step: hold
    assert lad.observe(True) == 1             # 2nd: escalate one rung
    assert lad.shed_classes() == ()
    for expect in (2, 3, 4):
        assert lad.observe(True) is None
        assert lad.observe(True) == expect
    assert lad.rung == 4 and lad.shed_classes() == ("batch", "standard")
    assert lad.observe(True) is None          # capped at max rung
    # de-escalation needs down_steps CONSECUTIVE clear evaluations,
    # and a pressured step resets the clear counter (hysteresis)
    assert lad.observe(False) is None
    assert lad.observe(True) is None
    assert [lad.observe(False) for _ in range(3)] == [None, None, 3]
    assert lad.shed_classes() == ("batch",)   # reverse re-arm order
    for expect in (2, 1, 0):
        assert [lad.observe(False) for _ in range(3)][-1] == expect
    assert lad.rung == 0


# ------------------------------------------------- blast-radius isolation


@pytest.mark.parametrize("kind", ["nan", "inf"])
@pytest.mark.parametrize("paged", [False, True])
def test_quarantine_isolates_poisoned_slot(paged, kind):
    """K=1 of N=3 streams poisoned at a decode visit: the poisoned
    stream finishes "error", the other two are token-exact vs the
    fault-free reference, and the drained pool leaks nothing."""
    model, params = _model()
    prompts = _prompts(3, seed=1)
    plan = [dict(site="decode", kind=kind, visit=1, slot=1)]
    kw = dict(paged=True, page_size=4) if paged else {}
    eng = ServeEngine(model, params, _cfg(fault_plan=plan, **kw))
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    errs = [h for h in hs if h.finish_reason == "error"]
    assert len(errs) == 1, [h.finish_reason for h in hs]
    for h, p in zip(hs, prompts):
        if h is not errs[0]:
            assert h.tokens == _ref(model, params, p, 10), \
                "an untouched stream diverged — blast radius leaked"
    snap = eng.metrics.snapshot()
    assert snap["serve/fault_quarantined"] == 1.0
    assert snap["serve/finish_error"] == 1.0
    assert_no_leaks(eng)


@pytest.mark.parametrize("paged", [False, True])
def test_quarantine_isolates_with_speculation(paged):
    model, params = _model()
    prompts = _prompts(3, seed=2)
    plan = [dict(site="decode", kind="nan", visit=1, slot=2)]
    kw = dict(paged=True, page_size=4) if paged else {}
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, speculative="ngram", spec_k=2, spec_rounds=2,
        **kw,
    ))
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    errs = [h for h in hs if h.finish_reason == "error"]
    assert len(errs) == 1
    for h, p in zip(hs, prompts):
        if h is not errs[0]:
            assert h.tokens == _ref(model, params, p, 10)
    assert_no_leaks(eng)


@pytest.mark.parametrize("paged", [False, True])
def test_quarantine_on_quantized_pool_scrubs_scales(paged):
    """Quantized pools: a quarantine must scrub int8 codes AND scale
    rows (a NaN absmax scale would dequantize the whole block to NaN
    for the slot's next occupant), and the exact-lane free list must
    survive the drain."""
    model, params = _model()
    prompts = _prompts(3, seed=21)
    plan = [dict(site="decode", kind="nan", visit=1, slot=0)]
    kw = dict(paged=True, page_size=4) if paged else {}
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, kv_quant="int8", kv_quant_block=4,
        kv_exact_lanes=1, **kw))
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    assert sum(h.finish_reason == "error" for h in hs) == 1
    # a fresh stream through the scrubbed slot must be clean (int8
    # agreement with the exact reference is gated elsewhere; here the
    # contract is finite, deterministic output)
    h = eng.submit(prompts[0], max_new_tokens=10)
    eng.run()
    assert h.finish_reason == "length" and len(h.tokens) == 10
    assert_no_leaks(eng)


def test_prefill_poison_quarantines_at_admission():
    model, params = _model()
    prompts = _prompts(2, seed=3)
    plan = [dict(site="prefill", kind="nan", visit=0)]
    eng = ServeEngine(model, params, _cfg(fault_plan=plan))
    hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run()
    assert hs[0].finish_reason == "error" and hs[0].tokens == []
    assert hs[1].tokens == _ref(model, params, prompts[1], 6)
    assert_no_leaks(eng)


def test_scrubbed_lane_cannot_poison_next_occupant():
    """The quarantine scrub contract: after a NaN quarantine, a fresh
    request admitted into the SAME slot must stream token-exactly —
    0 * NaN is NaN, so an unscrubbed lane would contaminate it through
    the masked attention tail."""
    model, params = _model()
    p0, p1 = _prompts(2, seed=4)
    plan = [dict(site="decode", kind="nan", visit=0, slot=0)]
    eng = ServeEngine(model, params, _cfg(n_slots=1,
                                          max_prefills_per_step=1,
                                          fault_plan=plan))
    h0 = eng.submit(p0, max_new_tokens=10)
    eng.run()
    assert h0.finish_reason == "error"
    h1 = eng.submit(p1, max_new_tokens=10)
    eng.run()
    assert h1.tokens == _ref(model, params, p1, 10), \
        "poison leaked into the quarantined slot's next occupant"
    assert_no_leaks(eng)


# ----------------------------------------------------- systemic recovery


@pytest.mark.parametrize("paged", [False, True])
def test_systemic_failure_rebuilds_and_resumes_exactly(paged):
    model, params = _model()
    prompts = _prompts(3, seed=5)
    plan = [dict(site="decode", kind="xla_error", visit=2)]
    kw = dict(paged=True, page_size=4) if paged else {}
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, fault_retry_backoff_s=0.001, **kw))
    hs = [eng.submit(p, max_new_tokens=10) for p in prompts]
    eng.run()
    for h, p in zip(hs, prompts):
        assert h.tokens == _ref(model, params, p, 10), \
            "rebuild-and-recompute broke a stream"
    snap = eng.metrics.snapshot()
    assert snap["serve/fault_retries"] == 1.0
    assert "serve/fault_recovery_s" in snap
    assert eng.health == "healthy"
    assert_no_leaks(eng)


def test_mid_admission_failure_loses_no_picked_request():
    """Regression: `pick` pops a whole admission batch; a fault raised
    mid-batch (the injected prefill OOM) must requeue the not-yet-
    admitted tail, not leak it out of the queue forever."""
    model, params = _model()
    prompts = _prompts(3, seed=6)
    plan = [dict(site="prefill", kind="oom", visit=0)]
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, fault_retry_backoff_s=0.001))
    hs = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run()
    assert all(h.done for h in hs), [h.state for h in hs]
    for h, p in zip(hs, prompts):
        assert h.tokens == _ref(model, params, p, 6)
    assert_no_leaks(eng)


def test_persistent_failure_drains_unhealthy_then_recovers():
    model, params = _model()
    p0 = _prompts(1, seed=7)[0]
    # exactly one unhealthy episode: max_retries=1 consumes 2 visits
    plan = [dict(site="decode", kind="xla_error", visit=0, count=2)]
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, fault_max_retries=1,
        fault_retry_backoff_s=0.001, fault_recover_backoff_s=0.5,
    ))
    h0 = eng.submit(p0, max_new_tokens=10)
    eng.run()
    assert eng.health == "unhealthy"
    assert h0.finish_reason == "error", "unhealthy drain must fail fast"
    # inside the backoff window: submissions reject with the reason
    hr = eng.submit(p0, max_new_tokens=10)
    assert hr.state == "rejected" and hr.reject_reason == "unhealthy"
    time.sleep(0.55)
    h1 = eng.submit(p0, max_new_tokens=10)
    assert h1.state == "waiting"
    eng.run()
    assert eng.health == "healthy"
    assert h1.tokens == _ref(model, params, p0, 10), \
        "recovered engine lost token-exactness"
    snap = eng.metrics.snapshot()
    assert snap["serve/fault_unhealthy"] == 1.0
    assert_no_leaks(eng)


def test_traced_unhealthy_drain_of_mid_admission_request():
    """Regression: a request whose PREFILL keeps failing has no first
    token when the unhealthy drain force-finishes it — with tracing on,
    _finish must close its lifecycle with a zero-width prefill phase
    instead of subtracting None (which killed the engine loop the
    boundary exists to protect)."""
    model, params = _model()
    p0 = _prompts(1, seed=20)[0]
    plan = [dict(site="prefill", kind="oom", visit=0, count=10)]
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, fault_max_retries=1,
        fault_retry_backoff_s=0.001, fault_recover_backoff_s=0.5,
        trace=True,
    ))
    h = eng.submit(p0, max_new_tokens=8)
    eng.run()
    assert eng.health == "unhealthy" and h.finish_reason == "error"
    names = {e.name for e in eng.trace.events()}
    assert {"queue", "prefill", "decode", "unhealthy"} <= names, names
    assert_no_leaks(eng)


def test_healthz_flips_503_while_unhealthy_and_back():
    import urllib.error
    import urllib.request

    model, params = _model()
    p0 = _prompts(1, seed=8)[0]
    plan = [dict(site="decode", kind="xla_error", visit=0, count=2)]
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, fault_max_retries=1,
        fault_retry_backoff_s=0.001, fault_recover_backoff_s=0.5,
        status_port=0,
    ))
    try:
        url = eng.status.url("/healthz")
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200 and r.read() == b"ok\n"
        eng.submit(p0, max_new_tokens=10)
        eng.run()
        assert eng.health == "unhealthy"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=30)
        assert ei.value.code == 503
        assert ei.value.read() == b"unhealthy\n"
        doc_health = eng.statusz()["health"]
        assert doc_health["state"] == "unhealthy"
        assert doc_health["unhealthy_episodes"] == 1
        # past the backoff /healthz flips back to 200 on its own
        # (readiness — a load balancer that dropped the replica on 503
        # must be able to see it recover without routing traffic first)
        time.sleep(0.55)
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200, \
                "healthz stayed 503 past the recovery backoff"
        h = eng.submit(p0, max_new_tokens=10)
        eng.run()
        assert h.tokens == _ref(model, params, p0, 10)
        with urllib.request.urlopen(url, timeout=30) as r:
            assert r.status == 200, "recovered engine must answer 200"
    finally:
        eng.close()


# ------------------------------------------------------ liveness bounds


def test_watchdog_flags_stalled_step():
    model, params = _model()
    p0 = _prompts(1, seed=9)[0]
    plan = [dict(site="decode", kind="stall", visit=1, stall_s=0.08)]
    eng = ServeEngine(model, params, _cfg(
        fault_plan=plan, fault_step_deadline_s=0.04))
    h = eng.submit(p0, max_new_tokens=10)
    eng.run()
    snap = eng.metrics.snapshot()
    assert snap["serve/watchdog_stalls"] == 1.0
    assert h.tokens == _ref(model, params, p0, 10), \
        "a stall must delay, never corrupt"
    assert eng.statusz()["health"]["watchdog_stalls"] == 1


def test_bounded_close_force_cancels_wedged_streams():
    """The SIGTERM contract: close(drain_s) must return promptly even
    when every step stalls — leftover streams force-cancel host-side
    and the pool drains leak-free."""
    model, params = _model()
    p0 = _prompts(1, seed=10)[0]
    plan = [dict(site="decode", kind="stall", visit=0, stall_s=0.2,
                 count=1000)]
    eng = ServeEngine(model, params, _cfg(fault_plan=plan))
    h = eng.submit(p0, max_new_tokens=20)
    eng.step()  # admitted and mid-stream
    t0 = time.monotonic()
    eng.close(drain_s=0.25)
    took = time.monotonic() - t0
    assert h.done and h.finish_reason == "cancelled"
    # bound: the drain window plus at most ONE stalled step's overrun
    assert took < 2.0, f"close took {took:.2f}s — not bounded"
    assert_no_leaks(eng)


# ---------------------------------------------------------- None-pattern


def test_disabled_fault_plane_compiles_no_extra_programs():
    """fault_plan=None keeps the compiled inventory byte-for-byte the
    plain engine's: the registry (which records EVERY program the
    engine runs) shows exactly prefill + decode — no scrub, no fault
    branch — and the always-on finite guard never perturbs streams."""
    model, params = _model()
    prompts = _prompts(2, seed=11)
    eng = ServeEngine(model, params, _cfg(xla_obs=True))
    hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run()
    names = set(eng.registry.snapshot()["programs"])
    assert names == {"prefill_program", "decode_block"}, names
    for h, p in zip(hs, prompts):
        assert h.tokens == _ref(model, params, p, 8)
    assert eng.health == "healthy"
    # fault keys absent from a fault-free snapshot (key-surface contract)
    snap = eng.metrics.snapshot()
    assert not [k for k in snap if "fault" in k or "watchdog" in k], \
        "fault gauges leaked into a fault-free run's key surface"


# --------------------------------------------------- degradation ladder


def _burn_engine(model, params, **kw):
    """An engine whose SLO targets are impossible on this hardware —
    every finish violates, so the burn-rate pressure signal is
    guaranteed to fire without timing games."""
    targets = {
        "interactive": {"ttft_s": 1e-9, "objective": 0.99},
        "standard": {"ttft_s": 1e-9, "objective": 0.99},
        "batch": {"ttft_s": 1e-9, "objective": 0.9},
    }
    return ServeEngine(model, params, _cfg(
        slo_targets=targets, degrade=True, degrade_up_steps=1,
        degrade_down_steps=4, **kw))


def test_ladder_escalates_on_burn_and_sheds_by_class():
    model, params = _model()
    prompts = _prompts(8, seed=12)
    eng = _burn_engine(model, params)
    from solvingpapers_tpu.serve.sampling import SamplingParams

    for p in prompts[:4]:
        eng.submit(p, max_new_tokens=4)
    eng.run()
    # violations filled the burn window; up_steps=1 climbs one rung per
    # evaluation — idle steps keep evaluating while the window still
    # shows the burn, so drive a few to reach the shedding rungs
    for _ in range(4):
        eng.step()
    assert eng.degradation_rung >= 3, eng.degradation_rung
    assert eng.health == "degraded"
    # batch is shed first; interactive is never shed by the ladder
    hb = eng.submit(prompts[4], max_new_tokens=4,
                    params=SamplingParams(slo="batch"))
    assert hb.state == "rejected" and hb.reject_reason == "shed:batch"
    hi = eng.submit(prompts[5], max_new_tokens=4,
                    params=SamplingParams(slo="interactive"))
    assert hi.state == "waiting"
    eng.run()
    assert hi.done
    snap = eng.metrics.snapshot()
    assert snap["serve/shed_batch"] >= 1.0
    assert snap["serve/degradation_rung"] >= 3.0
    assert snap["serve/degrade_transitions"] >= 3.0
    lad = eng.statusz()["health"]["ladder"]
    assert lad["rung"] == eng.degradation_rung
    assert "batch" in lad["shedding"]
    assert_no_leaks(eng)


def test_ladder_deescalates_in_reverse_with_hysteresis():
    model, params = _model()
    prompts = _prompts(2, seed=13)
    eng = _burn_engine(model, params)
    for p in prompts:
        eng.submit(p, max_new_tokens=4)
    eng.run()
    rung0 = eng.degradation_rung
    assert rung0 >= 1
    # clear the pressure: rebuild the burn window with attained
    # finishes by relaxing the targets in place (the tracker object is
    # live state — tests may retune it)
    for spec in eng._slo.targets.values():
        spec["ttft_s"] = 1e9
    for st in eng._slo._stats.values():
        st["window"].clear()
    p_new = _prompts(1, seed=14)[0]
    h = eng.submit(p_new, max_new_tokens=20)
    eng.run()
    assert h.done
    assert eng.degradation_rung < rung0, \
        "ladder never de-escalated after the pressure cleared"
    assert_no_leaks(eng)


def test_ladder_holds_speculation_at_rung_two():
    from solvingpapers_tpu.serve.spec import SpecController

    ctl = SpecController(min_rate=1.0, probe_every=4)
    assert ctl.decide() == "probe"
    ctl.hold(3)
    assert [ctl.decide() for _ in range(3)] == ["off"] * 3
    assert ctl.decide() == "probe"  # hold expired; adaptive state intact
    assert ctl.ema is None and ctl.fallback_steps == 3
