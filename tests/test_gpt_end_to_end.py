"""End-to-end slice tests: GPT char-LM training, cached decode, sharding.

The SURVEY.md §4 contract: loss-goes-down smoke training, cache-equivalence
(decode with cache == full-prefix forward — which the reference fails),
and sharded-vs-single-device numerical equality on the virtual 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator
from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.sharding import MeshConfig, create_mesh, batch_sharding
from solvingpapers_tpu.train import Trainer, TrainConfig, OptimizerConfig

TINY = GPTConfig(vocab_size=64, block_size=32, dim=32, n_layers=2, n_heads=2, dropout=0.0)


def tiny_corpus():
    tok, train, val = load_char_corpus(synthetic_chars=20_000)
    assert tok.vocab_size <= TINY.vocab_size
    return tok, train, val


def test_gpt_loss_decreases():
    _, train_toks, _ = tiny_corpus()
    cfg = TrainConfig(
        steps=30,
        batch_size=8,
        log_every=100,
        eval_every=0,
        optimizer=OptimizerConfig(max_lr=1e-2, warmup_steps=5, total_steps=30),
    )
    trainer = Trainer(GPT(TINY), cfg)
    it = lm_batch_iterator(train_toks, 8, TINY.block_size, seed=0)
    first_batch = next(it)
    state = trainer.init_state(first_batch)
    trainer._build_steps()
    state, m0 = trainer._train_step(state, first_batch)
    losses = [float(m0["train_loss"])]
    for _ in range(cfg.steps):
        state, m = trainer._train_step(state, next(it))
        losses.append(float(m["train_loss"]))
    assert losses[-1] < losses[0] - 0.5, losses[:3] + losses[-3:]


def test_scan_steps_window_equals_sequential_steps():
    """TrainConfig.scan_steps runs K steps per dispatch via lax.scan; the
    result must be bit-identical to K sequential _train_step calls (same
    per-step rng fold on state.step)."""
    _, train_toks, _ = tiny_corpus()
    K = 4

    def make(scan_steps):
        cfg = TrainConfig(
            steps=0, batch_size=8, log_every=100, eval_every=0,
            scan_steps=scan_steps,
            optimizer=OptimizerConfig(max_lr=1e-2, warmup_steps=5, total_steps=30),
        )
        t = Trainer(GPT(TINY), cfg)
        it = lm_batch_iterator(train_toks, 8, TINY.block_size, seed=0)
        s = t.init_state(next(it))
        t._build_steps()
        return t, s

    it = lm_batch_iterator(train_toks, 8, TINY.block_size, seed=0)
    next(it)  # consumed by init in both trainers
    batches = [next(it) for _ in range(K)]

    t_seq, s_seq = make(1)
    for b in batches:
        s_seq, m_seq = t_seq._train_step(s_seq, b)

    t_scan, s_scan = make(K)
    window = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    s_scan, m_scan = t_scan._train_step_scan(s_scan, window)

    assert int(s_scan.step) == int(s_seq.step)
    np.testing.assert_allclose(
        float(m_scan["train_loss"]), float(m_seq["train_loss"]), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6),
        jax.device_get(s_scan.params), jax.device_get(s_seq.params),
    )


def test_fit_with_scan_steps_smoke():
    """fit() drives scan windows (incl. a ragged single-step tail) and the
    loss still goes down."""
    _, train_toks, _ = tiny_corpus()
    cfg = TrainConfig(
        steps=22, batch_size=8, log_every=4, eval_every=0, scan_steps=4,
        optimizer=OptimizerConfig(max_lr=1e-2, warmup_steps=5, total_steps=30),
    )
    trainer = Trainer(GPT(TINY), cfg)
    it = lm_batch_iterator(train_toks, 8, TINY.block_size, seed=0)
    rows = []

    class Cap:
        def write(self, step, metrics):
            rows.append((step, metrics))

    state = trainer.fit(it, writer=Cap())
    assert int(state.step) == 22
    losses = [m["train_loss"] for _, m in rows if "train_loss" in m]
    assert losses[-1] < losses[0], rows


def test_fit_rejects_misaligned_scan_cadence():
    cfg = TrainConfig(steps=8, batch_size=8, log_every=3, eval_every=0,
                      scan_steps=4)
    trainer = Trainer(GPT(TINY), cfg)
    it = lm_batch_iterator(tiny_corpus()[1], 8, TINY.block_size, seed=0)
    with pytest.raises(ValueError, match="multiple of scan_steps"):
        trainer.fit(it)


def test_cached_decode_equals_full_forward():
    """Greedy decode through the KV cache must match recompute-from-scratch."""
    model = GPT(TINY)
    rng = jax.random.key(0)
    prompt = jax.random.randint(rng, (2, 5), 0, TINY.vocab_size)
    params = model.init({"params": rng}, prompt)["params"]

    out = generate(model, params, prompt, rng, max_new_tokens=8)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))

    # reference: greedy loop recomputing the full prefix each step (no cache)
    toks = prompt
    for _ in range(8):
        logits, _ = model.apply({"params": params}, toks, deterministic=True)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(toks))


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(data=8, fsdp=1, model=1),
        MeshConfig(data=1, fsdp=8, model=1),
        MeshConfig(data=2, fsdp=2, model=2),
    ],
    ids=["dp8", "fsdp8", "dp2_fsdp2_tp2"],
)
def test_sharded_train_matches_single_device(mesh_cfg, devices):
    """3 train steps on a sharded mesh == 3 steps on a 1-device mesh."""
    _, train_toks, _ = tiny_corpus()
    opt = OptimizerConfig(max_lr=1e-3, warmup_steps=0, total_steps=10)

    def run(mesh_config, devs):
        mesh = create_mesh(mesh_config, devs)
        cfg = TrainConfig(steps=3, batch_size=8, log_every=100, eval_every=0,
                          optimizer=opt)
        trainer = Trainer(GPT(TINY), cfg, mesh=mesh)
        it = lm_batch_iterator(
            train_toks, 8, TINY.block_size, seed=7, sharding=batch_sharding(mesh)
        )
        b0 = next(it)
        state = trainer.init_state(b0)
        trainer._build_steps()
        losses = []
        state, m = trainer._train_step(state, b0)
        losses.append(float(m["train_loss"]))
        for _ in range(2):
            state, m = trainer._train_step(state, next(it))
            losses.append(float(m["train_loss"]))
        return losses

    single = run(MeshConfig(data=1, fsdp=1, model=1), devices[:1])
    sharded = run(mesh_cfg, devices)
    np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=2e-5)


def test_generate_with_sampler_topk_runs():
    model = GPT(TINY)
    rng = jax.random.key(1)
    prompt = jnp.zeros((1, 3), jnp.int32)
    params = model.init({"params": rng}, prompt)["params"]
    import functools

    out = generate(
        model, params, prompt, rng, max_new_tokens=5,
        sampler=functools.partial(ops.sample_top_k, k=5, temperature=0.8),
    )
    assert out.shape == (1, 8)
    assert int(jnp.max(out)) < TINY.vocab_size


def test_generate_rejects_past_block_size():
    model = GPT(TINY)
    rng = jax.random.key(2)
    prompt = jnp.zeros((1, 30), jnp.int32)
    params = model.init({"params": rng}, prompt)["params"]
    with pytest.raises(ValueError, match="max positions"):
        generate(model, params, prompt, rng, max_new_tokens=10)  # 40 > block 32


def test_sliding_window_includes_last_start():
    from solvingpapers_tpu.data.batches import sliding_window_split

    toks = np.arange(100)
    x, y = sliding_window_split(toks, block_size=10, stride=1)
    assert x[-1][0] == 89 and y[-1][-1] == 99
    np.testing.assert_array_equal(y, x + 1)


def test_generate_eos_early_stop():
    """deepseekv3 cell 40's stop-on-EOS, static-shape form: after a
    sequence samples EOS every later position is EOS."""
    model = GPT(TINY)
    rng = jax.random.key(3)
    prompt = jnp.zeros((2, 3), jnp.int32)
    params = model.init({"params": rng}, prompt)["params"]

    # immediate EOS: every generated position must be the EOS id
    always_eos = lambda logits, key: jnp.full(  # noqa: E731
        (logits.shape[0],), 7, jnp.int32
    )
    out = generate(model, params, prompt, rng, max_new_tokens=6,
                   sampler=always_eos, eos_id=7)
    np.testing.assert_array_equal(np.asarray(out[:, 3:]), 7)

    # stochastic mid-sequence EOS (seeded -> deterministic): each step emits
    # EOS with p=0.4, so rows hit EOS mid-sequence; after the first hit the
    # done-propagation must pin every later position to EOS
    def sometimes_eos(logits, key):
        hit = jax.random.bernoulli(key, 0.4, (logits.shape[0],))
        return jnp.where(hit, 7, jnp.argmax(logits, -1)).astype(jnp.int32)

    out2 = generate(model, params, prompt, rng, max_new_tokens=10,
                    sampler=sometimes_eos, eos_id=7)
    gen = np.asarray(out2[:, 3:])
    mid_hits = 0
    for row in gen:
        hits = np.where(row == 7)[0]
        if hits.size and hits[0] < len(row) - 1:
            mid_hits += 1
            assert np.all(row[hits[0]:] == 7), row
    assert mid_hits > 0, gen  # the property must actually be exercised
