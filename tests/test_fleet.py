"""Fleet-serving tests (serve/fleet.py + the router surface in
serve/api.py).

Contracts under test. Routing: prefix-cache affinity picks the replica
whose radix tree covers the longest prompt prefix; the health gate
excludes draining / dead-loop / unhealthy replicas; the per-class burn
gate steers SLO traffic away from a burning replica (unless every
candidate burns); a full replica re-routes to a peer with room instead
of bouncing the client (the fleet-wide 503 fix), and `capacity_left`
sums ADMITTING replicas only. Exactness: a 2-replica fleet decodes the
greedy + seeded sampling mix token-identically to a single-engine
reference — routing placement never changes a stream's bytes. Drain:
`FleetRouter.drain` migrates every live stream onto a peer through the
journal + `ServeEngine.adopt` recover path token-exactly, the drained
replica passes `assert_no_leaks` immediately, errors are refused
up-front (no journal -> ValueError, no admitting peer -> RuntimeError).
HTTP: responses carry ``X-Replica-Id``; /statusz grows a ``fleet``
section; /metrics merges fleet histograms (unlabeled series == sum of
``replica``-labeled series); a mid-stream drain closes the SSE stream
WITHOUT a terminal chunk or [DONE] (the reconnect signal), the
Last-Event-ID reconnect resolves on the ADOPTING replica and the
combined bytes equal an uninterrupted run; a blocking request rides the
migration transparently inside one POST.
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_no_leaks
from solvingpapers_tpu.serve import (
    FleetRouter,
    ServeConfig,
    ServeEngine,
)
from solvingpapers_tpu.serve.sampling import SamplingParams


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32,
                          n_layers=2, n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = _gpt_tiny()
    return _MODEL


def _prompts(n, seed=0, size=8):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=size).astype(np.int32)
            for _ in range(n)]


def _cfg(**kw):
    base = dict(n_slots=3, max_len=48, decode_block=4, bucket=8,
                max_prefills_per_step=3)
    base.update(kw)
    return ServeConfig(**base)


def _params_for(i):
    """Greedy + seeded stochastic cycle: every stream replayable."""
    if i % 3 == 1:
        return SamplingParams(temperature=0.8, top_p=0.9, seed=100 + i)
    if i % 3 == 2:
        return SamplingParams(temperature=1.3, top_k=8, seed=200 + i)
    return None


def _fleet(n=2, cfg_for=None, **cfg_kw):
    model, params = _model()
    engines = [
        ServeEngine(model, params,
                    cfg_for(i) if cfg_for else _cfg(**cfg_kw))
        for i in range(n)
    ]
    return FleetRouter(engines, start=False)


def _step_all(router):
    worked = False
    for r in router.replicas:
        if r.engine.has_work():
            with r.loop.lock:
                r.engine.step()
            worked = True
    return worked


def _drain_fleet(router):
    while _step_all(router):
        pass


# ------------------------------------------------------------- routing


def test_prefix_affinity_routes_to_warm_replica():
    """The replica whose radix tree covers the prompt's prefix wins the
    ranking even when a peer is equally empty — affinity is the top
    sort key after the gates."""
    router = _fleet(2, prefix_cache=True)
    rng = np.random.default_rng(5)
    stem = rng.integers(0, 64, size=32).astype(np.int32)
    # warm r1 ONLY: run a stem-prefixed request through it directly
    r1 = router.replica("r1")
    r1.engine.submit(stem, max_new_tokens=4)
    while r1.engine.has_work():
        r1.engine.step()
    assert r1.probe(stem) > 0 and router.replica("r0").probe(stem) == 0
    probe = np.concatenate([stem[:16],
                            rng.integers(0, 64, 8).astype(np.int32)])
    assert router.route(probe).rid == "r1"
    # no cached prefix anywhere -> deterministic least-loaded tiebreak
    cold = rng.integers(0, 64, size=24).astype(np.int32)
    assert router.route(cold).rid == "r0"
    _drain_fleet(router)


def test_health_gate_and_draining_excluded():
    router = _fleet(2)
    p = _prompts(1)[0]
    router.replica("r0").draining = True
    assert router.route(p).rid == "r1"
    router.replica("r1").loop.error = RuntimeError("loop died")
    assert router.route(p) is None
    assert router.submit(p) == (None, None)
    assert router.health == "unhealthy"
    router.replica("r0").draining = False
    assert router.route(p).rid == "r0"
    assert router.health == "healthy"
    router.replica("r1").loop.error = None


def test_burn_gate_steers_slo_class_away():
    """Interactive traffic avoids a replica burning its error budget
    for that class; when EVERY candidate burns the gate yields (routing
    somewhere beats routing nowhere)."""

    class _Hot:
        targets = {"interactive": {"objective": 0.99}}

        def burn_rate(self, cls):
            return 5.0

    router = _fleet(2)
    router.replica("r0").engine._slo = _Hot()
    p = _prompts(1)[0]
    assert router.route(p, slo="interactive").rid == "r1"
    assert router.stats["burn_avoided"] == 1
    # untracked class and no class: the gate does not apply
    assert router.route(p, slo="batch").rid == "r0"
    assert router.route(p).rid == "r0"
    # every candidate burning: gate yields rather than refusing
    router.replica("r1").engine._slo = _Hot()
    assert router.route(p, slo="interactive") is not None
    router.replica("r0").engine._slo = None
    router.replica("r1").engine._slo = None


def test_full_replica_reroutes_to_peer_with_room():
    """A ranked-first replica whose waiting queue is full must NOT
    bounce the client while a peer has room — the router retries down
    the ranking (the fleet-wide 503 fix) and `capacity_left` counts
    admitting replicas only."""
    router = _fleet(2, cfg_for=lambda i: _cfg(prefix_cache=True,
                                              max_waiting=2))
    rng = np.random.default_rng(9)
    stem = rng.integers(0, 64, size=32).astype(np.int32)
    r0 = router.replica("r0")
    r0.engine.submit(stem, max_new_tokens=4)
    while r0.engine.has_work():
        r0.engine.step()
    # fill r0's waiting queue (no stepping: everything queues)
    for p in _prompts(2, seed=3):
        assert r0.engine.submit(p, max_new_tokens=4).state != "rejected"
    assert r0.engine.scheduler.capacity_left == 0
    probe = np.concatenate([stem[:16],
                            rng.integers(0, 64, 8).astype(np.int32)])
    assert router.route(probe).rid == "r0"  # affinity still ranks it first
    rep, req = router.submit(probe, max_new_tokens=4)
    assert rep.rid == "r1" and req.state != "rejected"
    assert router.stats["rerouted_full"] == 1
    # fleet capacity: only ADMITTING replicas count
    total = router.capacity_left
    r1 = router.replica("r1")
    assert total == r1.engine.scheduler.capacity_left
    r1.draining = True
    assert router.capacity_left == 0
    r1.draining = False
    _drain_fleet(router)


def test_duplicate_journal_path_refused(tmp_path):
    model, params = _model()
    cfg = _cfg(journal_path=str(tmp_path / "same.jsonl"))
    engines = [ServeEngine(model, params, cfg)]
    with pytest.raises(ValueError, match="OWN journal"):
        FleetRouter(engines + [ServeEngine(model, params, cfg)],
                    start=False)


# ----------------------------------------------------------- exactness


def test_fleet_token_exact_vs_single_engine():
    """Routing placement never changes a stream's bytes: every request
    through a 2-replica fleet (greedy + seeded sampling mix) decodes
    token-identically to a single-engine reference."""
    model, params = _model()
    prompts = _prompts(9, seed=1)
    ref_eng = ServeEngine(model, params, _cfg())
    refs = [ref_eng.submit(p, max_new_tokens=10, params=_params_for(i))
            for i, p in enumerate(prompts)]
    ref_eng.run()

    router = _fleet(2)
    handles, placed = [], set()
    for i, p in enumerate(prompts):
        rep, req = router.submit(p, max_new_tokens=10,
                                 params=_params_for(i))
        assert req is not None and req.state != "rejected"
        handles.append(req)
        placed.add(rep.rid)
    _drain_fleet(router)
    # the load balancer actually spread the work
    assert placed == {"r0", "r1"}
    for h, r in zip(handles, refs):
        assert h.tokens == r.tokens
    for rep in router.replicas:
        assert_no_leaks(rep.engine)


# --------------------------------------------------------------- drain


def test_drain_migrates_live_streams_token_exact(tmp_path):
    """The headline: drain r0 mid-decode; every live stream finishes on
    the peer byte-identical to an uninterrupted reference, the drained
    replica reclaims to zero leaks IMMEDIATELY, and the report maps
    every migrated id to its adopter."""
    model, params = _model()
    prompts = _prompts(6, seed=2)
    ref_eng = ServeEngine(model, params, _cfg())
    refs = [ref_eng.submit(p, max_new_tokens=12, params=_params_for(i))
            for i, p in enumerate(prompts)]
    ref_eng.run()

    router = _fleet(
        2, cfg_for=lambda i: _cfg(
            journal_path=str(tmp_path / f"r{i}.jsonl")))
    handles, where = [], {}
    for i, p in enumerate(prompts):
        rep, req = router.submit(p, max_new_tokens=12,
                                 params=_params_for(i),
                                 trace_id=f"mig-{i}")
        handles.append(req)
        where[req.trace_id] = rep.rid
    _step_all(router)  # one block everywhere: streams live mid-decode
    live_r0 = [h for h in handles
               if where[h.trace_id] == "r0" and not h.done]
    assert live_r0, "test needs live streams on r0 at drain time"

    report = router.drain("r0")
    assert router.replica("r0").draining
    assert not router.replica("r0").admitting
    assert_no_leaks(router.replica("r0").engine)  # reclaimed at drain
    assert report.entries == len(live_r0)
    assert report.errors == []
    assert sorted(report.targets) == sorted(h.trace_id for h in live_r0)
    for h in live_r0:  # the original request objects force-finished
        assert h.done and h.finish_reason == "migrated"
    assert all(peer == "r1" for peer, _ in report.targets.values())

    _drain_fleet(router)
    assert all(r.done for r in report.migrated)
    succ = {old: router.replica(peer).engine._recovered[new]
            for old, (peer, new) in report.targets.items()}
    for h, r in zip(handles, refs):
        stream = (succ[h.trace_id].tokens if h.trace_id in succ
                  else h.tokens)
        assert stream == r.tokens, h.trace_id
    # owner map follows the stream to its adopter
    for old in report.targets:
        assert router.owner(old).rid == "r1"
    for rep in router.replicas:
        assert_no_leaks(rep.engine)
    assert router.stats["drains"] == 1
    assert router.stats["migrated_streams"] == len(live_r0)
    # nothing admits to a draining replica; undrain reopens it
    assert router.route(prompts[0]).rid == "r1"
    router.undrain("r0")
    assert router.replica("r0").admitting


def test_drain_refusals(tmp_path):
    router = _fleet(2)  # no journals
    with pytest.raises(ValueError, match="journal"):
        router.drain("r0")
    with pytest.raises(KeyError, match="unknown replica"):
        router.drain("r9")
    jrouter = _fleet(
        2, cfg_for=lambda i: _cfg(
            journal_path=str(tmp_path / f"j{i}.jsonl")))
    jrouter.replica("r1").draining = True
    with pytest.raises(RuntimeError, match="no admitting peer"):
        jrouter.drain("r0")
    # the refusal must not have closed r0's admission gate
    assert jrouter.replica("r0").admitting


# ------------------------------------------------------- fleet metrics


def test_prom_sets_merge_equals_sum_of_replicas():
    """The merged (unlabeled) set's histograms equal the exact
    `LogHistogram.merge` of the replicas' — counts and sum — and the
    fleet gauges ride the merged set."""
    router = _fleet(2)
    for i, p in enumerate(_prompts(6, seed=4)):
        router.submit(p, max_new_tokens=6, params=_params_for(i))
    _drain_fleet(router)
    sets = router.prom_sets()
    (step0, labels0, merged), *per = sets
    assert labels0 is None
    assert [lab["replica"] for _, lab, _ in per] == ["r0", "r1"]
    assert merged["fleet/replicas"] == 2.0
    assert merged["fleet/admitting"] == 2.0
    assert merged["fleet/routed"] == 6.0
    from solvingpapers_tpu.metrics.hist import LogHistogram

    hist_names = [k for k, v in merged.items()
                  if isinstance(v, LogHistogram)]
    assert hist_names, "fleet snapshot must carry latency histograms"
    for k in hist_names:
        shards = [snap[k] for _, _, snap in per if k in snap]
        assert merged[k].count == sum(s.count for s in shards)
        assert merged[k].counts.sum() == sum(
            s.counts.sum() for s in shards)
        assert merged[k].sum == pytest.approx(
            sum(s.sum for s in shards))
    for rep in router.replicas:
        assert_no_leaks(rep.engine)


# -------------------------------------------------------- HTTP surface


def _sse(url, body=None, headers=None, timeout=120):
    data = json.dumps(body or {}).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    events = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        replica = r.headers.get("X-Replica-Id")
        cur = None
        for raw in r:
            line = raw.decode().rstrip("\n")
            if line.startswith("id: "):
                cur = line[4:]
            elif line.startswith("data: "):
                if line[6:] == "[DONE]":
                    break
                events.append((cur, json.loads(line[6:])))
    return replica, events


@pytest.fixture(scope="module")
def fleet_server(tmp_path_factory):
    from solvingpapers_tpu.serve.api import ApiServer

    model, params = _model()
    jdir = tmp_path_factory.mktemp("fleet_j")
    engines = [
        ServeEngine(model, params, _cfg(
            api_port=0, n_slots=2,
            journal_path=str(jdir / f"r{i}.jsonl")))
        for i in range(2)
    ]
    router = FleetRouter(engines)  # started loops: the real topology
    srv = ApiServer(
        router=router,
        decode=lambda ids: "".join(chr(97 + i % 26) for i in ids),
        model_name="gpt-tiny",
    )
    yield srv, router
    srv.close()


def test_http_replica_header_and_statusz_fleet(fleet_server):
    srv, router = fleet_server
    body = {"prompt": [1, 2, 3, 4], "max_tokens": 6}
    req = urllib.request.Request(
        srv.url("/v1/completions"), data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=60) as r:
        doc = json.loads(r.read())
        assert r.headers["X-Replica-Id"] in {"r0", "r1"}
    assert doc["choices"][0]["finish_reason"] == "length"
    with urllib.request.urlopen(srv.url("/statusz"), timeout=30) as r:
        status = json.loads(r.read())
    fleet = status["fleet"]
    assert sorted(fleet["replicas"]) == ["r0", "r1"]
    assert fleet["routing"]["routed"] >= 1
    for d in fleet["replicas"].values():
        assert d["admitting"] and d["health"] == "healthy"
    with urllib.request.urlopen(srv.url("/healthz"), timeout=30) as r:
        assert r.read().strip() == b"ok"


def test_http_metrics_merged_plus_labeled(fleet_server):
    """/metrics carries ONE # TYPE per name, the unlabeled fleet merge,
    and per-replica labeled series whose histogram counts SUM to the
    merged series (the scrape-side aggregation contract)."""
    srv, router = fleet_server
    for i in range(3):  # traffic on the fleet so histograms are non-empty
        _sse(srv.url("/v1/completions"),
             {"prompt": [5 + i, 6, 7], "max_tokens": 4, "stream": True})
    with urllib.request.urlopen(srv.url("/metrics"), timeout=30) as r:
        text = r.read().decode()
    lines = text.splitlines()
    types: dict = {}
    for ln in lines:
        if ln.startswith("# TYPE "):
            name, kind = ln.split()[2], ln.split()[3]
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
    assert types.get("fleet_replicas") == "gauge"
    assert "fleet_replicas 2.0" in lines
    # histogram _count invariant: unlabeled == sum over replica series
    hist_names = [n for n, k in types.items() if k == "histogram"]
    assert hist_names
    merged_seen = 0
    for name in hist_names:
        unlabeled = labeled = None
        for ln in lines:
            if ln.startswith(f"{name}_count "):
                unlabeled = int(float(ln.rsplit(" ", 1)[1]))
            elif ln.startswith(f"{name}_count{{"):
                labeled = (labeled or 0) + int(
                    float(ln.rsplit(" ", 1)[1]))
        assert unlabeled is not None
        assert unlabeled == (labeled or 0), name
        merged_seen += unlabeled
    assert merged_seen > 0, "traffic must have recorded observations"
    assert 'replica="r0"' in text and 'replica="r1"' in text


def _live_tokens(rep, rid, max_new):
    e = rep.engine.journal.lookup(rid)
    if (e is None or e.finished or len(e.tokens) >= max_new
            or not rep.engine.journal.is_live(rid)):
        return None
    return len(e.tokens)


def _drain_while_live(router, rid, max_new, thread, deadline_s=60):
    """Catch `rid` live mid-decode and drain its replica UNDER the held
    step lock (RLock: drain's `_locked` re-enters) — the stream is
    deterministically live at the drain, no racing the engine loop.
    Returns ``(owner, report)``; ``(None, None)`` when the stream
    finished before it could be caught (caller retries)."""
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        owner = router.owner(rid)
        if owner is not None:
            with owner.loop.lock:
                if _live_tokens(owner, rid, max_new) is not None:
                    return owner, router.drain(owner.rid)
            if not thread.is_alive():
                return None, None  # finished un-migrated: retry
        time.sleep(0.001)
    pytest.fail(f"{rid} never observed live mid-decode")


def test_http_mid_stream_drain_migrates_sse(fleet_server):
    """The zero-drop protocol end to end: a live SSE stream's replica
    drains; the first connection ends WITHOUT a terminal chunk or
    [DONE]; the Last-Event-ID reconnect lands on the ADOPTING replica
    and the combined bytes equal an uninterrupted reference."""
    srv, router = fleet_server
    model, params = _model()
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    ref_eng = ServeEngine(model, params, _cfg())
    ref = ref_eng.submit(np.asarray(prompt, np.int32),
                         max_new_tokens=40)
    ref_eng.run()
    dec = srv.decode

    for attempt in range(6):
        rid = f"mig-sse-{attempt}"
        first: dict = {}

        def client(rid=rid, first=first):
            req = urllib.request.Request(
                srv.url("/v1/completions"),
                data=json.dumps({"prompt": prompt, "max_tokens": 40,
                                 "temperature": 0,
                                 "stream": True}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid}, method="POST")
            chunks, ids, done = [], [], False
            with urllib.request.urlopen(req, timeout=120) as r:
                first["replica"] = r.headers.get("X-Replica-Id")
                cur = None
                for raw in r:
                    line = raw.decode().rstrip("\n")
                    if line.startswith("id: "):
                        cur = line[4:]
                    elif line.startswith("data: "):
                        if line[6:] == "[DONE]":
                            done = True
                            break
                        chunks.append(json.loads(line[6:]))
                        ids.append(cur)
                    elif line.startswith(": migrated"):
                        first["comment"] = line
            first.update(chunks=chunks, ids=ids, done=done)

        t = threading.Thread(target=client)
        t.start()
        owner, report = _drain_while_live(router, rid, 40, t)
        t.join(timeout=120)
        assert not t.is_alive()
        if owner is not None:
            break
    else:
        pytest.fail("stream always finished before the drain landed")

    assert (rid, ) == tuple(report.targets)
    peer, new_rid = report.targets[rid]
    assert peer != owner.rid and new_rid == rid
    assert not first["done"], "migrated stream must NOT see [DONE]"
    assert "migrated" in first.get("comment", "")
    assert all("finish_reason" not in c["choices"][0]
               or c["choices"][0]["finish_reason"] is None
               for c in first["chunks"])

    seen = len(first["chunks"]) and int(first["ids"][-1].split(":")[1])
    replica2, ev2 = _sse(srv.url("/v1/completions"), {},
                         {"Last-Event-ID": f"{rid}:{seen}"})
    assert replica2 == peer
    head = "".join(c["choices"][0].get("text", "")
                   for c in first["chunks"])
    tail = "".join(e["choices"][0].get("text", "") for _, e in ev2)
    assert ev2[-1][1]["choices"][0]["finish_reason"] == "length"
    assert head + tail == dec(ref.tokens)
    assert ev2[-1][0] == f"{rid}:40"
    assert_no_leaks(router.replica(owner.rid).engine)
    router.undrain(owner.rid)


def test_http_blocking_request_rides_migration(fleet_server):
    """A non-streaming POST in flight across a drain returns ONE
    complete response (the front door swaps to the adopted successor
    internally) with the adopter's X-Replica-Id and reference bytes."""
    srv, router = fleet_server
    model, params = _model()
    prompt = [2, 7, 1, 8, 2, 8]
    ref_eng = ServeEngine(model, params, _cfg())
    ref = ref_eng.submit(np.asarray(prompt, np.int32),
                         max_new_tokens=40)
    ref_eng.run()

    for attempt in range(6):
        rid = f"mig-blk-{attempt}"
        out: dict = {}

        def client(rid=rid, out=out):
            req = urllib.request.Request(
                srv.url("/v1/completions"),
                data=json.dumps({"prompt": prompt, "temperature": 0,
                                 "max_tokens": 40}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid}, method="POST")
            with urllib.request.urlopen(req, timeout=120) as r:
                out["replica"] = r.headers.get("X-Replica-Id")
                out["doc"] = json.loads(r.read())

        t = threading.Thread(target=client)
        t.start()
        owner, report = _drain_while_live(router, rid, 40, t)
        t.join(timeout=120)
        assert not t.is_alive()
        if owner is not None:
            break
    else:
        pytest.fail("request always finished before the drain landed")
    peer, _ = report.targets[rid]
    assert out["replica"] == peer
    doc = out["doc"]
    assert doc["choices"][0]["finish_reason"] == "length"
    assert doc["choices"][0]["text"] == srv.decode(ref.tokens)
    assert doc["usage"]["completion_tokens"] == 40

    # the stitched trail: GET /v1/requests/<id> keeps EVERY hop — the
    # drained replica's husk plus the adopter — and its phase walls
    # (migrate + peer_* included) still partition the server e2e wall
    with urllib.request.urlopen(srv.url(f"/v1/requests/{rid}"),
                                timeout=30) as r:
        trail = json.loads(r.read())
    fl = trail["fleet"]
    assert fl["migrated"] is True and fl["replica"] == peer
    assert len(fl["hops"]) == 2
    assert fl["hops"][0]["replica"] == owner.rid
    assert fl["hops"][0]["finish_reason"] == "migrated"
    assert fl["hops"][1]["replica"] == peer
    assert fl["hops"][1]["finish_reason"] == "length"
    assert "migrate" in trail["phases"]
    assert "peer_decode" in trail["phases"]
    assert trail["phase_sum_s"] == pytest.approx(
        trail["e2e_s"], rel=0.05, abs=1e-3)
    router.undrain(owner.rid)


# ----------------------------------------------------- fleet trace fabric


def test_router_trace_stamps_route_decisions():
    """A traced fleet gives the ROUTER its own flight recorder: an
    accepted submit stamps a `route` span carrying the per-candidate
    score rows the ranking used, each full-queue refusal stamps a
    `reroute` instant naming the replica that bounced the request, and
    the request itself records `fleet_reroutes`/`fleet_route_s` (the
    trail's route phase). An untraced fleet keeps the recorder None."""
    router = _fleet(2, cfg_for=lambda i: _cfg(
        trace=True, prefix_cache=True, max_waiting=2))
    assert router.trace is not None
    rng = np.random.default_rng(9)
    stem = rng.integers(0, 64, size=32).astype(np.int32)
    r0 = router.replica("r0")
    r0.engine.submit(stem, max_new_tokens=4)
    while r0.engine.has_work():
        r0.engine.step()
    for p in _prompts(2, seed=3):  # fill r0's waiting queue
        assert r0.engine.submit(p, max_new_tokens=4).state != "rejected"
    assert r0.engine.scheduler.capacity_left == 0
    probe = np.concatenate([stem[:16],
                            rng.integers(0, 64, 8).astype(np.int32)])
    rep, req = router.submit(probe, max_new_tokens=4)
    assert rep.rid == "r1" and req.state != "rejected"
    assert req.fleet_reroutes == 1 and req.fleet_route_s >= 0.0

    evs = router.trace.events()
    (route,) = [e for e in evs if e.name == "route"]
    assert route.cat == "fleet"
    assert route.args["replica"] == "r1"
    assert route.args["attempts"] == 2
    assert route.args["rid"] == req.trace_id
    rows = {s["replica"]: s for s in route.args["scores"]}
    assert rows["r0"]["match"] > rows["r1"]["match"]  # affinity evidence
    assert rows["r0"]["queue_room"] == 0
    assert "free" in rows["r1"]
    (reroute,) = [e for e in evs if e.name == "reroute"]
    assert reroute.args["rejected_by"] == "r0"
    assert reroute.args["rid"] == req.trace_id
    assert _fleet(2).trace is None  # tracing off -> no router recorder
    _drain_fleet(router)


def test_prom_sets_tags_and_skips_stale_shards():
    """A shard that stopped moving is TAGGED, not silently merged:
    every labeled set carries `serve/shard_age_s` + `serve/shard_stale`;
    a stale shard (NOT admitting and past the cutoff) is skipped by the
    fleet histogram merge while `fleet/stale_shards` counts it — but
    age alone never marks an admitting replica stale, and the labeled
    set keeps serving the frozen numbers either way."""
    from solvingpapers_tpu.metrics.hist import LogHistogram
    from solvingpapers_tpu.serve import metrics as smetrics

    router = _fleet(2)
    for p in _prompts(3, seed=11):
        for r in router.replicas:
            r.engine.submit(p, max_new_tokens=4)
    _drain_fleet(router)
    r1 = router.replica("r1")
    router.stale_shard_cutoff_s = 0.05
    r1.engine.metrics._t_last = smetrics.now() - 1.0
    (_, _, merged), (_, _, s0), (_, _, s1) = router.prom_sets()
    assert s1["serve/shard_age_s"] >= 0.9
    assert s0["serve/shard_stale"] == 0.0
    assert s1["serve/shard_stale"] == 0.0  # old but ADMITTING: not stale
    assert merged["fleet/stale_shards"] == 0.0
    key = next(k for k, v in s0.items()
               if isinstance(v, LogHistogram) and v.count
               and isinstance(s1.get(k), LogHistogram) and s1[k].count)
    assert merged[key].count == s0[key].count + s1[key].count

    r1.draining = True  # not admitting + past the cutoff -> stale
    r1.engine.metrics._t_last = smetrics.now() - 1.0
    (_, _, merged), (_, _, s0), (_, lab1, s1) = router.prom_sets()
    assert lab1 == {"replica": "r1"}
    assert s1["serve/shard_stale"] == 1.0
    assert merged["fleet/stale_shards"] == 1.0
    assert merged["fleet/admitting"] == 1.0
    assert merged[key].count == s0[key].count  # merge skipped the shard
    assert s1[key].count > 0  # ...but the labeled set still serves it
    r1.draining = False
    for r in router.replicas:
        assert_no_leaks(r.engine)


def test_http_rerouted_response_carries_reroute_header():
    """A response whose submit was retried on a peer carries
    ``X-Fleet-Reroutes: <n>`` next to X-Replica-Id; directly-placed
    requests omit the header entirely."""
    from solvingpapers_tpu.serve.api import ApiServer

    model, params = _model()
    engines = [ServeEngine(model, params, _cfg(
        api_port=0, n_slots=1, max_waiting=1, prefix_cache=True))
        for _ in range(2)]
    router = FleetRouter(engines)  # started loops
    srv = ApiServer(
        router=router,
        decode=lambda ids: "".join(chr(97 + i % 26) for i in ids),
        model_name="gpt-tiny")
    try:
        rng = np.random.default_rng(21)
        stem = rng.integers(0, 64, size=24).astype(np.int32)
        r0 = router.replica("r0")
        with r0.loop.lock:
            warm = r0.engine.submit(stem, max_new_tokens=2)
        while not warm.done:
            time.sleep(0.002)
        probe = [int(t) for t in stem[:16]] + [1, 2, 3]

        def post(body):
            req = urllib.request.Request(
                srv.url("/v1/completions"),
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=120) as r:
                return r.headers, json.loads(r.read())

        # directly placed (unrelated prompt): no header at all
        h, _ = post({"prompt": [5, 6, 7], "max_tokens": 2,
                     "temperature": 0})
        assert h.get("X-Fleet-Reroutes") is None

        for _ in range(25):
            while r0.engine.has_work():
                time.sleep(0.002)
            # slot + the 1-deep waiting queue: r0 stays FULL while
            # these decode, yet affinity still ranks it first for the
            # probe — the router must retry down the ranking. `a` has
            # to reach a slot BEFORE `b` queues, or `b` bounces off
            # the 1-deep queue `a` still occupies.
            with r0.loop.lock:
                a = r0.engine.submit(
                    rng.integers(0, 64, 8).astype(np.int32),
                    max_new_tokens=40)
            while a.admit_time is None and not a.done:
                time.sleep(0.001)
            with r0.loop.lock:
                b = r0.engine.submit(
                    rng.integers(0, 64, 8).astype(np.int32),
                    max_new_tokens=40)
            if (a.state == "rejected" or b.state == "rejected"
                    or r0.engine.scheduler.capacity_left > 0):
                continue
            h, doc = post({"prompt": probe, "max_tokens": 2,
                           "temperature": 0})
            if h.get("X-Fleet-Reroutes") == "1":
                assert h["X-Replica-Id"] == "r1"
                assert doc["choices"][0]["finish_reason"] == "length"
                break
        else:
            pytest.fail("never caught r0 full: reroute header unseen")
    finally:
        srv.close()
