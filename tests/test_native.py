"""Native (C++) runtime parity tests: the ctypes-bound hot paths must agree
exactly with their pure-Python oracles (data/bpe.py, data/batches.py), and
everything must degrade gracefully when the library is unavailable."""

import numpy as np
import pytest

from solvingpapers_tpu import native
from solvingpapers_tpu.data.bpe import ByteBPETokenizer
from solvingpapers_tpu.data.synthetic import synthetic_text

pytestmark = [
    pytest.mark.skipif(
        not native.available(),
        reason=f"native lib unavailable: {native.load_error()}",
    ),
    pytest.mark.fast,
]


def _python_only_tokenizer(tok: ByteBPETokenizer) -> ByteBPETokenizer:
    """Clone with the native encoder disabled (pure-Python oracle)."""
    clone = ByteBPETokenizer(dict(tok.vocab), sorted(tok.ranks, key=tok.ranks.get))
    clone._native = False
    return clone


def test_native_encode_matches_python():
    text = synthetic_text(20_000, seed=3)
    tok = ByteBPETokenizer.train(text, vocab_size=400)
    oracle = _python_only_tokenizer(tok)
    for sample in [
        "The quick brown fox jumps over the lazy dog. éü☃",
        text[:3000],
        "",
        "  \n\t mixed   whitespace 123 #tags",
    ]:
        got = tok.encode(sample)
        want = oracle.encode(sample)
        np.testing.assert_array_equal(got, want)
        assert tok.decode(got) == sample


def test_native_train_matches_python_train(monkeypatch):
    text = synthetic_text(15_000, seed=4)
    native_tok = ByteBPETokenizer.train(text, vocab_size=380)
    # force the Python trainer by making the native path report unavailable
    monkeypatch.setattr(ByteBPETokenizer, "_train_native",
                        classmethod(lambda cls, *a, **k: None))
    py_tok = ByteBPETokenizer.train(text, vocab_size=380)
    assert py_tok.ranks == native_tok.ranks
    assert py_tok.vocab == native_tok.vocab


def test_gather_windows_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    for dtype in [np.uint16, np.uint32, np.int32, np.uint8, np.int64]:
        toks = rng.integers(0, 200, size=5_000).astype(dtype)
        path = tmp_path / f"t_{np.dtype(dtype).name}.bin"
        toks.tofile(path)
        mm = np.memmap(path, dtype=dtype, mode="r")
        starts = rng.integers(0, len(toks) - 65, size=16)
        x, y = native.gather_windows_native(mm, starts, 64)
        want_x = np.stack([toks[s : s + 64] for s in starts]).astype(np.int32)
        want_y = np.stack([toks[s + 1 : s + 65] for s in starts]).astype(np.int32)
        np.testing.assert_array_equal(x, want_x)
        np.testing.assert_array_equal(y, want_y)


def test_memmap_iterator_native_equals_python(tmp_path, monkeypatch):
    from solvingpapers_tpu.data.batches import lm_batch_iterator

    toks = np.random.default_rng(1).integers(0, 250, size=4_096).astype(np.uint16)
    path = tmp_path / "toks.bin"
    toks.tofile(path)

    def batches(native_on):
        if not native_on:
            monkeypatch.setattr(native, "available", lambda: False)
        mm = np.memmap(path, dtype=np.uint16, mode="r")
        it = lm_batch_iterator(mm, batch_size=8, block_size=32, seed=7)
        out = [next(it) for _ in range(3)]
        monkeypatch.undo()
        return out

    for a, b in zip(batches(True), batches(False)):
        np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
        np.testing.assert_array_equal(np.asarray(a["y"]), np.asarray(b["y"]))


def test_prefetch_preserves_order_and_values(tmp_path):
    from solvingpapers_tpu.data.batches import lm_batch_iterator, prefetch_batches

    toks = np.random.default_rng(2).integers(0, 250, size=4_096).astype(np.uint16)
    path = tmp_path / "toks.bin"
    toks.tofile(path)
    mm = np.memmap(path, dtype=np.uint16, mode="r")
    plain = lm_batch_iterator(mm, batch_size=4, block_size=16, seed=11)
    fetched = prefetch_batches(
        lm_batch_iterator(mm, batch_size=4, block_size=16, seed=11), depth=3
    )
    for _ in range(5):
        a, b = next(plain), next(fetched)
        np.testing.assert_array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
        np.testing.assert_array_equal(np.asarray(a["y"]), np.asarray(b["y"]))


def test_prefetch_propagates_worker_exception():
    from solvingpapers_tpu.data.batches import prefetch_batches

    def boom():
        yield 1
        raise RuntimeError("data source died")

    it = prefetch_batches(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="data source died"):
        next(it)


def test_gather_rejects_strided_view():
    toks = np.arange(100, dtype=np.uint16)
    with pytest.raises(ValueError, match="C-contiguous"):
        native.gather_windows_native(toks[::2], np.array([0, 3]), 8)


def test_memmap_iterator_falls_back_on_unsupported_dtype(tmp_path):
    from solvingpapers_tpu.data.batches import lm_batch_iterator

    toks = np.random.default_rng(5).integers(0, 100, size=1024).astype(np.int16)
    path = tmp_path / "toks16.bin"
    toks.tofile(path)
    mm = np.memmap(path, dtype=np.int16, mode="r")
    batch = next(lm_batch_iterator(mm, batch_size=4, block_size=16, seed=0))
    assert batch["x"].dtype == np.int32  # numpy fallback path handled it


def test_prefetch_finite_iterator_terminates():
    from solvingpapers_tpu.data.batches import prefetch_batches

    out = list(prefetch_batches(iter(range(10)), depth=2))
    assert out == list(range(10))


def test_native_disabled_env(monkeypatch):
    # a fresh process with the env var set must fall back cleanly
    import subprocess
    import sys

    code = (
        "from solvingpapers_tpu import native;"
        "assert not native.available();"
        "from solvingpapers_tpu.data.bpe import ByteBPETokenizer;"
        "t = ByteBPETokenizer.train('abcabc abcabc the the the', 260);"
        "ids = t.encode('the abc');"
        "assert t.decode(ids) == 'the abc'"
    )
    env = {"SOLVINGPAPERS_TPU_NO_NATIVE": "1", "JAX_PLATFORMS": "cpu",
           "PATH": "/usr/bin:/bin:/usr/local/bin"}
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, cwd="/root/repo")
    assert r.returncode == 0, r.stderr


def test_encoder_cache_eviction_keeps_current_call_resolvable():
    """Regression: when the chunk cache crosses its growth limit, eviction
    must not drop chunks the *current* call still needs (previously cached
    by earlier calls) before the output is assembled — that raised KeyError
    once unique chunks exceeded the limit."""
    text = synthetic_text(20_000, seed=7)
    tok = ByteBPETokenizer.train(text, vocab_size=400)
    enc = tok._native_encoder()
    assert enc is not None

    a = ["alpha ", "beta ", "gamma ", "delta ", "epsilon ", "zeta "]
    b = ["alpha ", "eta ", "theta ", "iota ", "kappa ", "beta "]  # mixes old+new
    want_a = enc.encode_texts(a)  # default limit: no eviction
    want_b = enc.encode_texts(b)

    enc._cache_limit = 4  # force eviction on nearly every call
    enc._chunk_cache.clear()
    got_a = enc.encode_texts(a)
    got_b = enc.encode_texts(b)  # KeyError before the fix
    np.testing.assert_array_equal(got_a, want_a)
    np.testing.assert_array_equal(got_b, want_b)
