"""tools/bench_check.py: the BENCH_serve.json regression gate.

The gate's contract: a synthetically regressed entry fails, the
committed history passes its own self-check, scale-sensitive metrics
are only compared at matching scale, direction is resolved per metric
family, and the trajectory summary covers every committed workload.
"""

import copy
import importlib.util
import json
import pathlib

import pytest

pytestmark = pytest.mark.fast

ROOT = pathlib.Path(__file__).parent.parent


@pytest.fixture(scope="module")
def bc():
    spec = importlib.util.spec_from_file_location(
        "bench_check", ROOT / "tools" / "bench_check.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _poisson_entry(rps=8.8, ttft=1.44, overhead=-0.6, n_requests=32):
    return {
        "schema_version": 2,
        "provenance": {"git_sha": "a" * 40, "timestamp": 1.0},
        "metric": "serve_requests_per_sec", "value": rps, "unit": "req/s",
        "vs_baseline": 2.1,
        "detail": {
            "config": "llama3_shakespeare", "n_requests": n_requests,
            "n_slots": 8, "max_new_tokens": 64, "decode_block": 16,
            "engine_requests_per_sec": rps, "mean_ttft_s": ttft,
            "trace_overhead_pct": overhead, "greedy_agreement_rate": 1.0,
        },
    }


def test_committed_history_passes_and_summary_covers_workloads(bc, capsys):
    """Acceptance: the committed BENCH_serve.json self-checks green and
    the emitted trajectory covers all 7+ existing workloads."""
    entries = bc.load_entries(str(ROOT / "BENCH_serve.json"))
    assert len(entries) >= 8
    workloads = {bc.workload_of(e) for e in entries}
    assert {"poisson", "shared-prefix", "sampling-mix", "paged-vs-lane",
            "http-stream-soak", "speculative-decode", "quant-kv",
            "slo-observatory"} <= workloads
    # every entry is now identifiable: schema + git sha (backfilled for
    # the pre-gate era, measured from schema 2 on)
    for e in entries:
        assert e["schema_version"] in (1, 2)
        assert e["provenance"]["git_sha"]
        assert e["provenance"]["timestamp"]
        if e["schema_version"] >= 2:
            assert e["provenance"]["jax"]
            assert e["provenance"]["device_kind"]
    summary = bc.trajectory_summary(entries)
    for wl in workloads:
        assert wl in summary
    assert bc.check_regressions(entries, []) == []
    assert bc.main(["--history", str(ROOT / "BENCH_serve.json")]) == 0
    out = capsys.readouterr().out
    assert "bench trajectory" in out and "OK" in out


def test_synthetic_regression_fails_the_gate(bc):
    """Acceptance: a regressed entry is caught — throughput collapse,
    latency blow-up, and overhead-band breach each flag."""
    base = _poisson_entry()
    good = _poisson_entry(rps=8.5, ttft=1.5, overhead=1.2)
    assert bc.check_regressions([base], [good]) == []
    slow = _poisson_entry(rps=2.9)  # 3x throughput collapse
    regs = bc.check_regressions([base], [slow])
    assert any("engine_requests_per_sec" in r for r in regs)
    laggy = _poisson_entry(ttft=4.0)  # lower-is-better direction
    regs = bc.check_regressions([base], [laggy])
    assert any("mean_ttft_s" in r for r in regs)
    heavy = _poisson_entry(overhead=25.0)  # pct band is absolute pp
    regs = bc.check_regressions([base], [heavy])
    assert any("trace_overhead_pct" in r for r in regs)
    # IMPROVEMENTS never flag (direction-aware)
    fast = _poisson_entry(rps=30.0, ttft=0.2, overhead=-9.0)
    assert bc.check_regressions([base], [fast]) == []


def test_scale_sensitive_metrics_gated_on_matching_scale(bc):
    """A CI smoke at 8 requests must not be throughput- or rate-
    compared against the committed 32-request measurement (a smoke's
    agreement/acceptance reflects its own shorter training) — only the
    *_pct overheads and exactness booleans gate across scales."""
    base = _poisson_entry()
    smoke = _poisson_entry(rps=0.9, ttft=9.0, n_requests=8)
    smoke["detail"]["greedy_agreement_rate"] = 0.7  # smoke-scale rate
    assert bc.check_regressions([base], [smoke]) == []
    bad_smoke = _poisson_entry(rps=0.9, n_requests=8, overhead=40.0)
    regs = bc.check_regressions([base], [bad_smoke])
    assert regs and all("overhead" in r for r in regs)
    # at MATCHING scale the rate gates
    worse_rate = _poisson_entry()
    worse_rate["detail"]["greedy_agreement_rate"] = 0.7
    regs = bc.check_regressions([base], [worse_rate])
    assert any("greedy_agreement_rate" in r for r in regs)


def test_boolean_exactness_must_not_flip(bc):
    base = _poisson_entry()
    base["detail"]["stream_token_exact"] = True
    flip = _poisson_entry()
    flip["detail"]["stream_token_exact"] = False
    regs = bc.check_regressions([base], [flip])
    assert any("stream_token_exact" in r for r in regs)


def test_history_median_absorbs_one_outlier(bc):
    """Baselines are the MEDIAN of the trailing history: one noisy
    historical rep must not move the gate."""
    hist = [_poisson_entry(rps=8.8), _poisson_entry(rps=9.0),
            _poisson_entry(rps=2.0)]  # one bad historical run
    cand = _poisson_entry(rps=8.0)
    assert bc.check_regressions(hist, [cand]) == []


def test_unknown_workload_and_empty_history(bc):
    novel = copy.deepcopy(_poisson_entry())
    novel["detail"]["workload"] = "brand-new-workload"
    assert bc.check_regressions([_poisson_entry()], [novel]) == []
    regs, notes = bc.compare_entry(novel, [])
    assert regs == [] and any("no trailing history" in n for n in notes)


def test_main_gate_exit_codes(bc, tmp_path, capsys):
    hist = tmp_path / "hist.json"
    hist.write_text(json.dumps(_poisson_entry()) + "\n")
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(_poisson_entry(rps=2.0)) + "\n")
    assert bc.main(["--history", str(hist),
                    "--candidate", str(cand)]) == 2
    err = capsys.readouterr().err
    assert "REGRESSION" in err
    ok = tmp_path / "ok.json"
    ok.write_text(json.dumps(_poisson_entry(rps=8.9)) + "\n")
    assert bc.main(["--history", str(hist), "--candidate", str(ok)]) == 0
