"""Golden-value forward tests (SURVEY.md §4: 'golden-value tests for each
model's forward on fixed PRNG keys'). Values were generated on the CPU
backend with threefry keys; any unintended change to init, layer math, or
layer wiring shifts them. Regenerate deliberately if architecture changes
are intended (see git history of this file).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast

GOLDEN = {
    "gpt": [-0.113971, -0.417388, 1.489783, -0.145843],
    "llama3": [1.271275, 0.720245, 1.602395, -0.731151],
    "gemma": [-0.569685, 0.46484, 1.035346, -1.359757],
    "deepseekv3": [0.136766, 0.103721, -0.037179, 0.024156],
    "vit": [-1.796156, -0.709384, -0.028966, 0.347098],
}


@pytest.fixture()
def fixed_key():
    # goldens were generated under threefry; pin it regardless of defaults
    return jax.random.key(0, impl="threefry2x32")


def toks():
    return jnp.arange(16, dtype=jnp.int32)[None, :] % 7


def check(name, logits_tail):
    np.testing.assert_allclose(
        np.asarray(logits_tail, np.float32), GOLDEN[name], rtol=2e-4, atol=2e-5
    )


def test_gpt_golden(fixed_key):
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    m = GPT(GPTConfig(vocab_size=32, block_size=16, dim=16, n_layers=2,
                      n_heads=2, dropout=0.0))
    p = m.init({"params": fixed_key}, toks())["params"]
    check("gpt", m.apply({"params": p}, toks())[0][0, -1, :4])


def test_llama3_golden(fixed_key):
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    m = Llama(LlamaConfig(vocab_size=32, max_seq_len=16, dim=16, n_layers=2,
                          n_heads=4, n_kv_heads=2, dropout=0.0))
    p = m.init({"params": fixed_key}, toks())["params"]
    check("llama3", m.apply({"params": p}, toks())[0][0, -1, :4])


def test_gemma_golden(fixed_key):
    from solvingpapers_tpu.models.gemma import Gemma, GemmaConfig

    m = Gemma(GemmaConfig(vocab_size=32, max_seq_len=16, dim=16, n_layers=2,
                          n_heads=4, n_kv_heads=2, dropout=0.0))
    p = m.init({"params": fixed_key}, toks())["params"]
    check("gemma", m.apply({"params": p}, toks())[0][0, -1, :4])


def test_deepseekv3_golden(fixed_key):
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config

    m = DeepSeekV3(DeepSeekV3Config(
        vocab_size=32, block_size=16, dim=16, n_layers=2, n_heads=2,
        latent_dim=4, n_experts=4, top_experts=2, dropout=0.0, attn_dropout=0.0,
    ))
    v = m.init({"params": fixed_key}, toks())
    check("deepseekv3", m.apply(v, toks())[0][0, -1, :4])


def test_vit_golden(fixed_key):
    from solvingpapers_tpu.models.vit import ViT, ViTConfig

    m = ViT(ViTConfig(dim=16, n_layers=2, n_heads=2))
    img = jnp.linspace(0, 1, 28 * 28).reshape(1, 28, 28, 1)
    p = m.init({"params": fixed_key}, img)["params"]
    check("vit", m.apply({"params": p}, img)[0, :4])
