"""Durable-serving tests (serve/journal.py + engine recovery wiring +
the HTTP resume surface in serve/api.py).

Contracts under test. Journal mechanics: submit/commit/finish records
round-trip through load; a torn final line (crash mid-write) is
tolerated while mid-file corruption raises; compaction keeps the file
O(live) under sustained finished traffic; concurrent writers never
tear or interleave a record. Crash recovery: killing the engine at
EVERY block boundary of a randomized schedule and replaying the
journal through a fresh engine yields token-exact streams vs an
uninterrupted run (greedy + seeded stochastic, both pools, spec on,
kv_quant on) with `assert_no_leaks` after each restart's drain.
Failure policy: an injected ``journal_write``/``io_error`` degrades to
journal-off with ONE warning while every stream survives; strict mode
propagates instead. HTTP: SSE chunks carry ``id:`` fields, a
``Last-Event-ID`` reconnect replays exactly the missing tail, and
`GET /v1/requests/<id>` falls back to the journal (source "journal")
for requests evicted from the bounded registry.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_no_leaks
from solvingpapers_tpu.serve import (
    Journal,
    JournalError,
    ServeConfig,
    ServeEngine,
)
from solvingpapers_tpu.serve.sampling import SamplingParams


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32,
                          n_layers=2, n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = _gpt_tiny()
    return _MODEL


def _prompts(n, seed=0, size=8):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=size).astype(np.int32)
            for _ in range(n)]


def _cfg(**kw):
    base = dict(n_slots=3, max_len=32, decode_block=4, bucket=8,
                max_prefills_per_step=3)
    base.update(kw)
    return ServeConfig(**base)


def _params_for(i):
    """Greedy + seeded stochastic cycle: every stream replayable."""
    if i % 3 == 1:
        return SamplingParams(temperature=0.8, top_p=0.9, seed=100 + i)
    if i % 3 == 2:
        return SamplingParams(temperature=1.3, top_k=8, seed=200 + i)
    return None


def _run_all(model, params, prompts, cfg, max_new=10, params_for=None):
    eng = ServeEngine(model, params, cfg)
    hs = [eng.submit(p, max_new_tokens=max_new,
                     params=params_for(i) if params_for else None)
          for i, p in enumerate(prompts)]
    eng.run()
    return eng, hs


# --------------------------------------------------------- journal unit


def test_journal_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    j.append_submit("a", [1, 2, 3], 8, None,
                    {"temperature": 0.0}, 1.5)
    j.append_submit("b", [4], 4, 7, {"seed": 3}, 2.0, grammar=True)
    j.append_commit("a", [9, 10])
    j.append_commit("a", [11])
    j.append_finish("b", "eos", {"prompt_tokens": 1,
                                 "completion_tokens": 0})
    j.sync()
    j.close()
    # crash-torn tail: a partial record without its newline
    with open(path, "a") as f:
        f.write('{"kind":"commit","rid":"a","tok')
    j2 = Journal(path)
    live = j2.live_entries()
    assert [e.rid for e in live] == ["a"]
    assert live[0].tokens == [9, 10, 11]
    assert live[0].params == {"temperature": 0.0}
    assert live[0].max_new_tokens == 8 and live[0].arrival == 1.5
    fin = j2.lookup("b")
    assert fin is not None and fin.finished and fin.finish_reason == "eos"
    assert fin.grammar
    j2.close()
    # mid-file corruption is NOT a crash tail: it must raise
    lines = open(path).read().splitlines()
    lines[0] = "garbage{{{"
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(JournalError, match="corrupt"):
        Journal(path)


def test_journal_compaction_keeps_file_o_live(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, rotate_finished=8)
    j.append_submit("live", [1, 2], 16, None, {}, 0.0)
    j.append_commit("live", [5])
    for i in range(40):
        rid = f"r{i}"
        j.append_submit(rid, [1], 4, None, {}, float(i))
        j.append_commit(rid, [2, 3])
        j.append_finish(rid, "length")
    assert j.rotations >= 4
    # the FILE holds only the live set (+ the records since the last
    # rotation) — far below the 40 finished requests' record count
    n_lines = sum(1 for _ in open(path))
    assert n_lines <= 3 * 8 + 2
    # finished entries within the keep window still look up on the
    # LIVE instance (the in-memory window; rotation drops them from
    # disk — that is the compaction contract)
    assert j.lookup("r39") is not None and j.lookup("r39").finished
    j.close()
    j2 = Journal(path)
    live = j2.live_entries()
    assert [e.rid for e in live] == ["live"]
    assert live[0].tokens == [5]  # committed tokens folded into compaction
    assert j2.lookup("r39") is None  # compacted away on disk
    j2.close()


def test_journal_concurrent_writers_never_tear(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = Journal(path, rotate_finished=64)
    n_threads, n_each = 6, 120

    def writer(t):
        for i in range(n_each):
            rid = f"t{t}-{i}"
            j.append_submit(rid, [t, i], 4, None, {"seed": i}, float(i))
            j.append_commit(rid, [1, 2, 3])
            j.append_finish(rid, "length")
            if i % 7 == 0:
                j.sync()

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    j.sync()
    j.close()
    # every line parses whole (no torn/interleaved records), and the
    # reconstructed state balances: everything finished
    kinds = []
    for line in open(path):
        rec = json.loads(line)  # raises on any torn record
        kinds.append(rec["kind"])
    j2 = Journal(path)
    assert not j2.live_entries()
    assert j2.records == 0  # loader rebuilds state, counters are per-run
    j2.close()


# ---------------------------------------------------- crash recovery


def _combined_streams(handles, resumed_by_rid):
    """Full per-request streams after a crash+recover: a handle that
    finished pre-kill keeps its tokens; a live one's stream continues
    in the recovered request object (same committed prefix)."""
    out = []
    for h in handles:
        r = resumed_by_rid.get(h.trace_id)
        out.append((r.tokens if r is not None else h.tokens))
    return out


def _crash_recover_exact(cfg_kw, n_req=5, max_new=10, kill_steps=(2,),
                         params_for=_params_for):
    model, params = _model()
    prompts = _prompts(n_req)
    ref_cfg = _cfg(**cfg_kw)
    _, ref = _run_all(model, params, prompts, ref_cfg, max_new,
                      params_for)
    for k in kill_steps:
        import tempfile

        path = os.path.join(tempfile.mkdtemp(), "j.jsonl")
        jcfg = _cfg(journal_path=path, **cfg_kw)
        eng = ServeEngine(model, params, jcfg)
        hs = [eng.submit(p, max_new_tokens=max_new,
                         params=params_for(i) if params_for else None)
              for i, p in enumerate(prompts)]
        for _ in range(k):
            if eng.has_work():
                eng.step()
        del eng  # SIGKILL stand-in: no close, no drain
        eng2 = ServeEngine(model, params, jcfg)
        resumed = eng2.recover()
        eng2.run()
        by_rid = {r.trace_id: r for r in resumed}
        streams = _combined_streams(hs, by_rid)
        for i, (got, want) in enumerate(zip(streams, ref)):
            assert got == want.tokens, (
                f"kill@{k}: stream {i} diverged after recovery"
            )
        assert_no_leaks(eng2)


def test_recovery_token_exact_every_block_boundary_lane():
    """Kill the engine at EVERY block boundary of a randomized
    schedule (mixed greedy + seeded stochastic): recovery must be
    token-exact at each of them, with zero leaks after each drain."""
    model, params = _model()
    prompts = _prompts(5, seed=3)
    ref_cfg = _cfg()
    _, ref = _run_all(model, params, prompts, ref_cfg, 10, _params_for)
    # total steps an uninterrupted drain takes bounds the kill points
    total = max(len(r.tokens) for r in ref) // ref_cfg.decode_block + 8
    _crash_recover_exact({}, n_req=5, max_new=10,
                         kill_steps=range(1, total))


def test_recovery_token_exact_paged_pool():
    _crash_recover_exact(dict(paged=True, page_size=8, prefix_page=8),
                         kill_steps=(1, 3))


def test_recovery_token_exact_speculative():
    """Greedy streams under speculation: draft-and-verify is lossless
    for greedy (exact argmax match), so recovery — which realigns the
    draft windows at the resume point — stays token-exact. Seeded
    STOCHASTIC streams under speculation are distribution-exact but
    not replay-exact across a realignment (the committed value at a
    position depends on which window element it was — the same
    contract live paged preemption has), so they are deliberately not
    pinned here; spec-off stochastic exactness is pinned above."""
    _crash_recover_exact(dict(speculative="ngram", spec_k=2,
                              spec_rounds=2), kill_steps=(1, 2),
                         params_for=None)


def test_recovery_token_exact_kv_quant():
    _crash_recover_exact(dict(kv_quant="int8", kv_quant_block=8),
                         kill_steps=(1, 3))


def test_recovery_edge_cases(tmp_path):
    """Entries the new engine cannot resume finish "error" instead of
    vanishing; a stream complete at the crash boundary finishes with
    its real reason; recover() without a journal raises."""
    model, params = _model()
    path = str(tmp_path / "j.jsonl")
    j = Journal(path)
    # grammar request: journaled, not replayable
    j.append_submit("g", [1, 2], 8, None, {}, 0.0, grammar=True)
    # complete-at-crash: committed stream already hit its budget
    j.append_submit("done", [1, 2], 3, None, {}, 0.0)
    j.append_commit("done", [4, 5, 6])
    # oversized for this engine's capacity
    j.append_submit("big", list(range(30)), 30, None, {}, 0.0)
    # live, resumable
    j.append_submit("ok", [1, 2, 3], 4, None, {"seed": 9,
                                               "temperature": 1.0}, 0.0)
    j.append_commit("ok", [7])
    j.sync()
    j.close()
    eng = ServeEngine(model, params, _cfg(journal_path=path))
    with pytest.warns(UserWarning, match="cannot be recovered"):
        resumed = eng.recover()
    assert [r.trace_id for r in resumed] == ["ok"]
    assert resumed[0].tokens == [7]
    assert eng.journal.lookup("g").finish_reason == "error"
    assert eng.journal.lookup("big").finish_reason == "error"
    assert eng.journal.lookup("done").finish_reason == "length"
    eng.run()
    assert resumed[0].done and len(resumed[0].tokens) == 4
    assert_no_leaks(eng)
    # journal-off engines cannot recover
    eng2 = ServeEngine(model, params, _cfg())
    with pytest.raises(ValueError, match="journal_path"):
        eng2.recover()


def test_recovered_streams_visible_in_gauges_and_statusz(tmp_path):
    model, params = _model()
    path = str(tmp_path / "j.jsonl")
    cfg = _cfg(journal_path=path)
    eng = ServeEngine(model, params, cfg)
    eng.submit(_prompts(1)[0], max_new_tokens=8)
    eng.step()
    del eng
    eng2 = ServeEngine(model, params, cfg)
    resumed = eng2.recover()
    assert len(resumed) == 1
    snap = eng2.metrics.snapshot()
    assert snap["serve/recovered_requests"] == 1.0
    assert snap["serve/journal_degraded"] == 0.0
    assert snap["serve/journal_live"] == 1.0
    doc = eng2.statusz()
    assert doc["journal"]["recovered_requests"] == 1
    assert doc["journal"]["live"] == 1
    eng2.run()
    assert_no_leaks(eng2)
    # journal-off: the key surface stays clean (present-iff-enabled)
    eng3 = ServeEngine(model, params, _cfg())
    snap3 = eng3.metrics.snapshot()
    assert not any(k.startswith("serve/journal") for k in snap3)
    assert "journal" not in eng3.statusz()


# --------------------------------------------------- failure policy


def test_journal_io_error_degrades_not_kills(tmp_path):
    """An injected journal_write io_error flips the engine to
    journal-off with ONE warning; every stream finishes normally and
    the degraded gauge reports it."""
    model, params = _model()
    prompts = _prompts(4)
    plan = [dict(site="journal_write", kind="io_error", visit=2)]
    cfg = _cfg(journal_path=str(tmp_path / "j.jsonl"), fault_plan=plan)
    eng = ServeEngine(model, params, cfg)
    with pytest.warns(UserWarning, match="degrading to journal-off"):
        hs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run()
    assert all(h.done and h.finish_reason == "length" for h in hs)
    assert eng._journal_degraded
    snap = eng.metrics.snapshot()
    assert snap["serve/journal_degraded"] == 1.0
    assert snap["serve/fault_injected"] >= 1.0
    assert eng.statusz()["journal"]["degraded"] is True
    assert_no_leaks(eng)
    # streams match the journal-free engine's (greedy determinism)
    _, ref = _run_all(model, params, prompts, _cfg(), 8)
    assert [h.tokens for h in hs] == [r.tokens for r in ref]


def test_journal_strict_propagates(tmp_path):
    model, params = _model()
    plan = [dict(site="journal_write", kind="io_error", visit=0)]
    cfg = _cfg(journal_path=str(tmp_path / "j.jsonl"), fault_plan=plan,
               journal_strict=True)
    eng = ServeEngine(model, params, cfg)
    from solvingpapers_tpu.serve.faults import InjectedFault

    with pytest.raises(InjectedFault, match="journal I/O"):
        eng.submit(_prompts(1)[0], max_new_tokens=4)


def test_journal_fault_spec_validation():
    from solvingpapers_tpu.serve.faults import (
        FaultSpec,
        InjectedFault,
        classify_failure,
    )

    FaultSpec(site="journal_write", kind="io_error", visit=0)
    with pytest.raises(ValueError, match="journal_write"):
        FaultSpec(site="decode", kind="io_error", visit=0)
    with pytest.raises(ValueError, match="device-runtime"):
        FaultSpec(site="journal_write", kind="oom", visit=0)
    assert classify_failure(
        InjectedFault("io_error", "journal_write")) == "io"
    assert classify_failure(OSError(28, "No space left")) == "io"
    assert classify_failure(JournalError("disk gone")) == "io"
    assert classify_failure(InjectedFault("oom", "decode")) == "systemic"


def test_journal_strict_without_path_rejected():
    model, params = _model()
    with pytest.raises(ValueError, match="journal_strict"):
        ServeEngine(model, params, _cfg(journal_strict=True))


# ------------------------------------------------------- HTTP surface


def _sse(url, body=None, headers=None, timeout=120):
    import urllib.request

    data = json.dumps(body or {}).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST",
    )
    events = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        rid = r.headers.get("X-Request-Id")
        cur = None
        for raw in r:
            line = raw.decode().rstrip("\n")
            if line.startswith("id: "):
                cur = line[4:]
            elif line.startswith("data: "):
                if line[6:] == "[DONE]":
                    break
                events.append((cur, json.loads(line[6:])))
    return rid, events


@pytest.fixture(scope="module")
def journal_server(tmp_path_factory):
    from solvingpapers_tpu.serve.api import ApiServer

    model, params = _model()
    path = str(tmp_path_factory.mktemp("j") / "serve.jsonl")
    cfg = _cfg(api_port=0, journal_path=path, n_slots=2, max_len=48)
    eng = ServeEngine(model, params, cfg)
    srv = ApiServer(
        eng, decode=lambda ids: "".join(chr(97 + i % 26) for i in ids),
        model_name="gpt-tiny",
    )
    yield srv, eng
    srv.close()


def test_sse_ids_and_last_event_id_resume(journal_server):
    """Every SSE chunk carries an ``id: <rid>:<offset>`` field; a
    reconnect presenting Last-Event-ID replays exactly the missing
    tail (text beyond the offset), and the combined text equals the
    full stream's."""
    srv, eng = journal_server
    body = {"prompt": [1, 2, 3, 4], "max_tokens": 12, "stream": True}
    rid, events = _sse(srv.url("/v1/completions"), body,
                       {"X-Request-Id": "jrn-sse-1"})
    assert rid == "jrn-sse-1"
    ids = [i for i, _ in events]
    assert all(i is not None and i.startswith("jrn-sse-1:") for i in ids)
    assert ids[-1] == "jrn-sse-1:12"
    full = "".join(e["choices"][0].get("text", "") for _, e in events)
    # reconnect claiming we saw only 5 tokens
    rid2, ev2 = _sse(srv.url("/v1/completions"), {},
                     {"Last-Event-ID": "jrn-sse-1:5"})
    tail = "".join(e["choices"][0].get("text", "") for _, e in ev2)
    entry = eng.journal.lookup("jrn-sse-1")
    dec = srv.decode
    assert dec(entry.tokens[:5]) + tail == dec(entry.tokens) == full
    assert ev2[-1][1]["choices"][0]["finish_reason"] == "length"
    # malformed Last-Event-ID -> 400, unknown -> 404
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as ei:
        _sse(srv.url("/v1/completions"), {}, {"Last-Event-ID": "nope"})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _sse(srv.url("/v1/completions"), {},
             {"Last-Event-ID": "ghost:3"})
    assert ei.value.code == 404


def test_requests_endpoint_journal_fallback(journal_server):
    """A request evicted from the bounded in-memory registry still
    answers GET /v1/requests/<id> from the journal, marked
    source="journal" and carrying the committed tokens."""
    import urllib.request

    srv, eng = journal_server
    body = {"prompt": [5, 6, 7], "max_tokens": 6, "stream": True}
    _sse(srv.url("/v1/completions"), body,
         {"X-Request-Id": "jrn-evicted"})
    # registry doc first (normal path, no source marker)
    with urllib.request.urlopen(
        srv.url("/v1/requests/jrn-evicted")
    ) as r:
        doc = json.loads(r.read())
    assert "source" not in doc and doc["state"] == "finished"
    # evict from the registry -> journal fallback
    with srv._timeline_lock:
        srv._timelines.clear()
    with urllib.request.urlopen(
        srv.url("/v1/requests/jrn-evicted")
    ) as r:
        doc = json.loads(r.read())
    assert doc["source"] == "journal"
    assert doc["state"] == "finished"
    assert doc["finish_reason"] == "length"
    assert len(doc["tokens"]) == 6
    assert doc["usage"]["completion_tokens"] == 6
    assert doc["facts"]["prompt_tokens"] == 3


def test_resume_after_restart_replays_recovered_stream(tmp_path):
    """The cross-process resume shape, in-process: journaled engine
    dies mid-stream; a fresh engine + server on the same journal
    recovers; a Last-Event-ID reconnect on the NEW server replays the
    committed prefix past the client's offset and streams the live
    tail to [DONE] — byte-identical to an uninterrupted run."""
    from solvingpapers_tpu.serve.api import ApiServer, EngineLoop

    model, params = _model()
    dec = lambda ids: "".join(chr(97 + i % 26) for i in ids)  # noqa: E731
    prompt = np.asarray([3, 1, 4, 1, 5], np.int32)
    ref_eng = ServeEngine(model, params, _cfg())
    ref = ref_eng.submit(prompt, max_new_tokens=12)
    ref_eng.run()

    path = str(tmp_path / "j.jsonl")
    cfg = _cfg(api_port=0, journal_path=path)
    eng = ServeEngine(model, params, cfg)
    req = eng.submit(prompt, max_new_tokens=12, trace_id="restart-1")
    eng.step()  # first block committed + fsynced
    assert 0 < len(req.tokens) < 12
    seen = len(req.tokens)
    del eng  # crash

    eng2 = ServeEngine(model, params, cfg)
    resumed = eng2.recover()
    assert [r.trace_id for r in resumed] == ["restart-1"]
    srv = ApiServer(eng2, decode=dec,
                    loop=EngineLoop(eng2))
    try:
        _, ev = _sse(srv.url("/v1/completions"), {},
                     {"Last-Event-ID": f"restart-1:{seen}"})
        tail = "".join(e["choices"][0].get("text", "") for _, e in ev)
        assert dec(ref.tokens[:seen]) + tail == dec(ref.tokens)
        assert ev[-1][1]["choices"][0]["finish_reason"] == "length"
        assert ev[-1][0] == "restart-1:12"
    finally:
        srv.close()
    assert resumed[0].tokens == ref.tokens


def test_recovery_duplicate_rid_deadline_and_stop_string(tmp_path):
    """Post-review contracts: a client re-using a LIVE request id gets
    a fresh durable id (two streams never merge commits into one
    journal record); a journaled deadline re-arms its original
    relative budget at recovery; a committed stream that already
    completed a stop-STRING match finishes "stop" at recovery instead
    of resuming past it."""
    model, params = _model()

    def dec(ids):
        return "".join(chr(97 + i % 26) for i in ids)

    path = str(tmp_path / "j.jsonl")
    cfg = _cfg(journal_path=path)
    eng = ServeEngine(model, params, cfg, detokenize=dec)
    a = eng.submit(_prompts(1)[0], max_new_tokens=20, trace_id="dup")
    b = eng.submit(_prompts(2)[1], max_new_tokens=20, trace_id="dup")
    assert a.trace_id == "dup" and b.trace_id != "dup"
    assert eng.journal.is_live("dup") and eng.journal.is_live(b.trace_id)
    c = eng.submit(_prompts(1)[0], max_new_tokens=20, deadline_s=30.0,
                   trace_id="ddl")
    eng.step()
    assert not c.done
    # stop-string-complete entry, as a prior process would have left
    # it: committed tokens decode to text containing the stop string,
    # the finish record lost to the crash
    eng.journal.append_submit(
        "stopped", [1, 2], 8, None,
        {"stop": ["ab"], "temperature": 0.0}, 0.0)
    eng.journal.append_commit("stopped", [0, 1])
    eng.journal.sync()
    del eng

    eng2 = ServeEngine(model, params, cfg, detokenize=dec)
    resumed = eng2.recover()
    by_rid = {r.trace_id: r for r in resumed}
    assert set(by_rid) == {"dup", b.trace_id, "ddl"}
    # the deadline re-armed its ORIGINAL relative budget from recovery
    ddl = by_rid["ddl"]
    assert ddl.deadline is not None
    assert abs((ddl.deadline - ddl.submit_time) - 30.0) < 1e-6
    assert by_rid["dup"].deadline is None
    # the stop-string-complete stream finished without resuming
    done = eng2.journal.lookup("stopped")
    assert done.finished and done.finish_reason == "stop"
    assert done.tokens == [0, 1]
    eng2.run()
    assert all(r.done for r in resumed)
    assert_no_leaks(eng2)
