"""Kernel microbench harness tests (serve/kernel_bench.py).

The contracts under test:
  * the full (pool layout x kv_quant) grid runs at tiny shapes and
    every op family produces a FINITE, positive wall time — the CI
    smoke's gate, held in tier-1 too;
  * entries are BENCH-shaped JSON (json round trip, workload keys per
    grid cell, shape-encoding config tag, _wall_us detail per family);
  * `paged_decode_decomposition` yields shares in [0, 100] that sum to
    <= 100 + rounding, an honest 0.0 dequant share on f32 pools, and a
    positive dequant share on int8 pools when measurable;
  * cli kernel-bench writes JSON-lines that bench_check can load and
    classify (the BENCH_kernels.json gate's plumbing).
"""

import json
import math

import jax
import jax.numpy as jnp
import pytest

from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve.kernel_bench import (
    KV_QUANTS,
    OP_FAMILIES,
    POOL_LAYOUTS,
    bench_kernel_cell,
    fenced_wall_s,
    paged_decode_decomposition,
    run_kernel_bench,
)

pytestmark = pytest.mark.fast

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)
SHAPES = dict(n_slots=2, max_len=32, page_size=8, quant_block=8,
              sample_cap=16, spec_k=2)


@pytest.fixture(scope="module")
def gpt_tiny():
    return GPT(GPT_TINY)


def test_fenced_wall_is_finite_positive():
    wall = fenced_wall_s(lambda a: a * 2.0, (jnp.ones((8, 8)),), reps=2)
    assert math.isfinite(wall) and wall > 0


def test_every_family_times_on_every_grid_cell(gpt_tiny):
    for pool in POOL_LAYOUTS:
        for kv_quant in KV_QUANTS:
            cell = bench_kernel_cell(
                gpt_tiny, pool=pool, kv_quant=kv_quant,
                vocab=GPT_TINY.vocab_size, reps=1, **SHAPES,
            )
            for family in OP_FAMILIES:
                wall = cell[family]
                assert math.isfinite(wall) and wall > 0, (
                    pool, kv_quant, family, wall)
            assert cell["_view_bytes"] > 0 and cell["_pool_bytes"] > 0


def test_run_kernel_bench_entry_shape(monkeypatch, gpt_tiny):
    # reuse the module-scope model: run_kernel_bench would otherwise
    # rebuild via the config registry (compile cost for nothing here)
    import solvingpapers_tpu.serve.bench as bench_mod

    monkeypatch.setattr(
        bench_mod, "build_serve_model",
        lambda config: (gpt_tiny, None, None, GPT_TINY.vocab_size),
    )
    entries = run_kernel_bench(config="gpt_tiny", reps=1, **{
        k: v for k, v in SHAPES.items() if k != "sample_cap"
    }, sample_cap=16)
    assert len(entries) == len(POOL_LAYOUTS) * len(KV_QUANTS)
    workloads = {e["detail"]["workload"] for e in entries}
    assert workloads == {
        f"kernels-{p}-{d or 'f32'}"
        for p in POOL_LAYOUTS for d in (None, "int8")
    }
    for e in entries:
        line = json.dumps(e)  # BENCH files are JSON-lines
        back = json.loads(line)
        det = back["detail"]
        assert back["value"] > 0
        assert det["config"].startswith("gpt_tiny@")
        for family in OP_FAMILIES:
            assert det[f"{family}_wall_us"] > 0
        assert det["gather_gbps"] > 0
        assert det["pool"] in POOL_LAYOUTS
        # true storage dtype recorded (grid label "f32" is not a dtype
        # claim — a bf16-compute model's exact pool stores bf16)
        if det["kv_quant"]:
            assert det["kv_dtype"] == det["kv_quant"]
        else:
            assert det["kv_dtype"] and det["kv_dtype"] != "int8"
        # kernel entries carry no vs_baseline: bench_check would gate
        # it higher-better, and no ratio of op walls points one way
        assert "vs_baseline" not in back


def test_paged_decomposition_shares(gpt_tiny):
    for kv_quant in (None, "int8"):
        d = paged_decode_decomposition(
            gpt_tiny, n_slots=2, max_len=32, page_size=8, decode_block=4,
            step_wall_s=0.05, kv_quant=kv_quant, reps=1,
        )
        shares = [d["gather_share_pct"], d["dequant_share_pct"],
                  d["scatter_share_pct"], d["attention_share_pct"]]
        for s in shares:
            assert 0.0 <= s <= 100.0, d
        assert sum(shares) <= 100.0 + 0.1, d
        if kv_quant is None:
            # an honest explicit zero, not an absence
            assert d["dequant_share_pct"] == 0.0
            assert d["dequant_wall_s"] == 0.0
        assert d["decode_step_wall_s"] == 0.05
        assert "decomposition_clamped" not in d
    # a step wall smaller than the isolated op walls (noisy host, or a
    # nonsense denominator): the measured components rescale to a
    # 100% partition and the clamp is DISCLOSED, never silent
    tiny = paged_decode_decomposition(
        gpt_tiny, n_slots=2, max_len=32, page_size=8, decode_block=4,
        step_wall_s=1e-7, reps=1,
    )
    assert tiny["decomposition_clamped"] is True
    assert tiny["attention_share_pct"] == 0.0
    assert abs(tiny["gather_share_pct"] + tiny["dequant_share_pct"]
               + tiny["scatter_share_pct"] - 100.0) <= 0.1
    with pytest.raises(ValueError):
        paged_decode_decomposition(
            gpt_tiny, n_slots=2, max_len=32, page_size=8, decode_block=4,
            step_wall_s=0.0,
        )


def test_bench_check_classifies_kernel_fields():
    from tools.bench_check import classify, classify_entry_field

    assert classify("gather_wall_us") == ("rel", False)
    assert classify("sample_wall_us") == ("rel", False)
    assert classify("gather_gbps") == ("rel", True)
    # shares are geometry-dependent: absolute pp band, matching scale
    # only (a tiny-shape smoke must not gate against full-scale medians)
    assert classify("gather_share_pct") == ("pct_scaled", False)
    assert classify("dequant_share_pct") == ("pct_scaled", False)
    assert classify("anatomy_overhead_pct") == ("pct", False)
    # the remainder share GROWS as the taxes die — deliberately ungated
    assert classify("attention_share_pct") is None
    assert classify_entry_field("entry.value") == ("rel", True)


def test_cli_kernel_bench_writes_jsonlines(monkeypatch, tmp_path, gpt_tiny,
                                           capsys):
    import solvingpapers_tpu.serve.bench as bench_mod
    from solvingpapers_tpu.cli import main as cli_main
    from tools.bench_check import load_entries, workload_of

    monkeypatch.setattr(
        bench_mod, "build_serve_model",
        lambda config: (gpt_tiny, None, None, GPT_TINY.vocab_size),
    )
    out = tmp_path / "BENCH_kernels.json"
    rc = cli_main([
        "kernel-bench", "--config", "gpt_tiny", "--slots", "2",
        "--max-len", "32", "--page-size", "8", "--kv-quant-block", "8",
        "--sample-cap", "16", "--spec-k", "2", "--reps", "1",
        "--out", str(out),
    ])
    assert rc == 0
    entries = load_entries(str(out))
    assert len(entries) == 4
    for e in entries:
        assert e["schema_version"] >= 2
        assert e["provenance"]["timestamp"] > 0
        assert workload_of(e).startswith("kernels-")
