"""Long-context generation tests (SURVEY.md §3.4; deepseekv3 cell 40's
sampling loop is part of the reference flagship).

The prefill path passes a static attend_len so cached attention runs
end-aligned causal over only the written cache slots — these tests pin
(a) chunked prefill == single-shot prefill == full-prefix recompute, and
(b) weights trained under context parallelism export to a plain decode.
"""

import dataclasses as dc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)
DSV3_TINY = DeepSeekV3Config(
    vocab_size=64, block_size=64, dim=32, n_layers=2, n_heads=4, latent_dim=8,
    rope_dim=8, n_experts=4, top_experts=2, dropout=0.0, attn_dropout=0.0,
)


def _full_forward_decode(model, variables, prompt, n):
    toks = prompt
    for _ in range(n):
        out = model.apply(variables, toks, deterministic=True)
        logits = out[0]
        toks = jnp.concatenate(
            [toks, jnp.argmax(logits[:, -1], -1)[:, None]], axis=1
        )
    return toks


@pytest.mark.parametrize("chunk", [None, 5, 8], ids=["one-shot", "chunk5", "chunk8"])
def test_gpt_chunked_prefill_matches_full_forward(chunk):
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    prompt = jax.random.randint(rng, (2, 17), 0, GPT_TINY.vocab_size)
    params = model.init({"params": rng}, prompt)["params"]
    out = generate(model, params, prompt, rng, max_new_tokens=6,
                   prefill_chunk=chunk)
    ref = _full_forward_decode(model, {"params": params}, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.parametrize("use_flash", [False, True], ids=["dense", "flash"])
@pytest.mark.parametrize("chunk", [None, 8], ids=["one-shot", "chunk8"])
def test_dsv3_chunked_prefill_matches_full_forward(chunk, use_flash):
    cfg = dc.replace(DSV3_TINY, use_flash=use_flash)
    model = DeepSeekV3(cfg)
    rng = jax.random.key(1)
    prompt = jax.random.randint(rng, (2, 17), 0, cfg.vocab_size)
    variables = model.init({"params": rng}, prompt)
    out = generate(model, variables["params"], prompt, rng, max_new_tokens=6,
                   extra_variables={"moe_state": variables["moe_state"]},
                   prefill_chunk=chunk)
    ref = _full_forward_decode(model, variables, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_cp_trained_weights_export_to_plain_decode(devices):
    """Weights trained under context parallelism (replicated at rest) decode
    on a non-CP model config: cached decode == full-prefix recompute with
    the SAME trained params — the export path for dsv3_long_cp."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.sharding import MeshConfig, batch_sharding, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, Trainer, TrainConfig
    from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

    cp_cfg = dc.replace(DSV3_TINY, block_size=32, context_parallel=True)
    mesh_cfg = MeshConfig(data=2, context=4)
    mesh = create_mesh(mesh_cfg, devices)
    tcfg = TrainConfig(
        steps=2, batch_size=4, log_every=100, eval_every=0,
        context_parallel=True, mesh=mesh_cfg,
        optimizer=OptimizerConfig(max_lr=1e-3, warmup_steps=0, total_steps=4),
    )
    tr = Trainer(DeepSeekV3(cp_cfg), tcfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn, mesh=mesh)
    toks = np.arange(4096) % cp_cfg.vocab_size
    it = lm_batch_iterator(toks, 4, cp_cfg.block_size,
                           sharding=batch_sharding(mesh, context=True))
    state = tr.fit(it)

    # export: CP params are replicated at rest -> plain host pytrees
    params = jax.device_get(state.params)
    moe_state = jax.device_get(state.model_state["moe_state"])

    decode_cfg = dc.replace(cp_cfg, context_parallel=False)
    model = DeepSeekV3(decode_cfg)
    prompt = jnp.asarray(np.arange(10)[None, :] % decode_cfg.vocab_size,
                         jnp.int32)
    out = generate(model, params, prompt, jax.random.key(2), max_new_tokens=5,
                   extra_variables={"moe_state": moe_state})
    ref = _full_forward_decode(
        model, {"params": params, "moe_state": moe_state}, prompt, 5
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_autochunks_long_flash_prefill():
    """A use_flash model prefilling a >4096 prompt with no prefill_chunk
    must auto-chunk instead of raising from the kernel's block picker mid-
    trace (advisor r3: only the CLI auto-chunked; direct generate() callers
    hit an avoidable ValueError on e.g. a 4500-token prompt)."""
    cfg = dc.replace(
        GPT_TINY, block_size=4608, n_layers=1, use_flash=True
    )
    model = GPT(cfg)
    prompt = jax.random.randint(jax.random.key(0), (1, 4500), 0,
                                cfg.vocab_size)
    params = model.init({"params": jax.random.key(1)}, prompt[:, :8])["params"]
    out = generate(model, params, prompt, jax.random.key(2),
                   max_new_tokens=2)
    assert out.shape == (1, 4502)
    np.testing.assert_array_equal(np.asarray(out[:, :4500]),
                                  np.asarray(prompt))


def test_batched_generate_per_sequence_eos_pads_with_eos():
    """Per-sequence eos_id early-stop in a batched generate: sequences
    hitting EOS at different steps must pad the rest of their row with
    EOS (done-flag semantics), not keep sampling — and rows that never
    emit EOS must be byte-identical to the eos_id=None stream."""
    model = GPT(GPT_TINY)
    rng = jax.random.key(7)
    params = model.init(
        {"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    n = 12
    eos = prompts = ref_gen = None
    for seed in range(8):
        prompts = jax.random.randint(jax.random.key(seed), (3, 9), 0,
                                     GPT_TINY.vocab_size)
        ref = generate(model, params, prompts, jax.random.key(0),
                       max_new_tokens=n)
        ref_gen = np.asarray(ref[:, 9:])
        # an eos candidate the greedy streams emit at >= 2 DIFFERENT
        # steps, early enough that the padded tail is non-empty
        for cand in range(GPT_TINY.vocab_size):
            firsts = [np.flatnonzero(row == cand) for row in ref_gen]
            hits = [f[0] for f in firsts if f.size]
            if len(set(hits)) >= 2 and all(h < n - 1 for h in hits):
                eos = cand
                break
        if eos is not None:
            break
    assert eos is not None, "no staggered-EOS candidate in 8 seeds"

    out = generate(model, params, prompts, jax.random.key(0),
                   max_new_tokens=n, eos_id=eos)
    np.testing.assert_array_equal(np.asarray(out[:, :9]),
                                  np.asarray(prompts))
    out_gen = np.asarray(out[:, 9:])
    stops = []
    for row_ref, row_out in zip(ref_gen, out_gen):
        first = np.flatnonzero(row_ref == eos)
        if first.size:
            i = int(first[0])
            stops.append(i)
            np.testing.assert_array_equal(row_out[: i + 1], row_ref[: i + 1])
            assert (row_out[i + 1:] == eos).all(), (
                f"row kept sampling past its EOS at step {i}: {row_out}"
            )
        else:
            np.testing.assert_array_equal(row_out, row_ref)
    assert len(set(stops)) >= 2, "rows did not finish at different steps"


def test_llama_prefill_matches_full_forward():
    cfg = LlamaConfig(vocab_size=64, max_seq_len=64, dim=32, n_layers=2,
                      n_heads=4, n_kv_heads=2, dropout=0.0)
    model = Llama(cfg)
    rng = jax.random.key(3)
    prompt = jax.random.randint(rng, (2, 13), 0, cfg.vocab_size)
    params = model.init({"params": rng}, prompt)["params"]
    out = generate(model, params, prompt, rng, max_new_tokens=5,
                   prefill_chunk=4)
    ref = _full_forward_decode(model, {"params": params}, prompt, 5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
