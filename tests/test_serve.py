"""Continuous-batching serving engine tests (solvingpapers_tpu/serve/).

The contract under test: iteration-level scheduling over a slot pool must
be invisible in the tokens — every request's stream is exactly what a
per-request one-shot `generate` (greedy) would produce, no matter how
requests interleave, which lane they land in, how prompts are bucketed,
or how prefill is chunked. Plus the serving-specific behaviors: a lane
freed by early EOS is re-acquired by a queued request before the batch
drains, admission control bounds the queue, and decode priority bounds
per-iteration prefills.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import (
    FIFOScheduler,
    KVSlotPool,
    Request,
    ServeConfig,
    ServeEngine,
)

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompts(n, seed=0, lo=4, hi=24):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, GPT_TINY.vocab_size,
                     size=int(rng.integers(lo, hi))).astype(np.int32)
        for _ in range(n)
    ]


def _ref_stream(model, params, prompt, max_new, eos_id=None):
    """Per-request one-shot generate, trimmed at the first EOS inclusive
    (generate pads with EOS after that — a static-shape artifact, not
    part of the stream contract)."""
    out = generate(model, params, jnp.asarray(prompt)[None, :],
                   jax.random.key(0), max_new_tokens=max_new, eos_id=eos_id)
    gen = np.asarray(out[0, len(prompt):]).tolist()
    if eos_id is not None and eos_id in gen:
        gen = gen[: gen.index(eos_id) + 1]
    return gen


# ----------------------------------------------------------------- engine


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
def test_staggered_requests_match_one_shot_generate(gpt_tiny, paged):
    """S slots, 2*S requests submitted in two staggered waves: every
    stream must be token-exact vs per-request one-shot generate — on
    both pool layouts (the paged pool's page-table indirection must be
    invisible in the tokens)."""
    model, params = gpt_tiny
    S = 4
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=S, max_len=64, decode_block=4, bucket=8, paged=paged,
        page_size=8 if paged else None,
    ))
    prompts = _prompts(2 * S, seed=1)
    handles = [eng.submit(p, max_new_tokens=12) for p in prompts[:S]]
    for _ in range(3):  # first wave mid-flight when the second arrives
        eng.step()
    handles += [eng.submit(p, max_new_tokens=12) for p in prompts[S:]]
    eng.run()
    assert all(h.done for h in handles)
    assert all(h.finish_reason == "length" for h in handles)
    for p, h in zip(prompts, handles):
        assert h.tokens == _ref_stream(model, params, p, 12), (
            f"request {h.id} (slot {h.slot}, prompt len {len(p)}) diverged"
        )
    snap = eng.metrics.snapshot()
    assert snap["serve/requests_finished"] == 2 * S
    assert snap["serve/tokens_out"] == 2 * S * 12
    assert 0 < snap["serve/slot_occupancy"] <= 1


def test_early_eos_frees_slot_for_queued_request(gpt_tiny):
    """A slot freed by early EOS must be re-acquired by a queued request
    while the rest of the batch is still decoding."""
    model, params = gpt_tiny
    prompts = _prompts(4, seed=2, lo=6, hi=12)
    # pick an EOS id that the greedy stream of request 0 emits early
    ref0 = _ref_stream(model, params, prompts[0], 16)
    eos = ref0[2]
    assert eos not in ref0[:2]

    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=2, bucket=8,
    ))
    h0 = eng.submit(prompts[0], max_new_tokens=16, eos_id=eos)
    rest = [eng.submit(p, max_new_tokens=16) for p in prompts[1:]]
    eng.run()
    assert h0.finish_reason == "eos"
    assert h0.tokens == _ref_stream(model, params, prompts[0], 16, eos_id=eos)
    assert h0.tokens[-1] == eos and len(h0.tokens) < 16
    for p, h in zip(prompts[1:], rest):
        assert h.finish_reason == "length"
        assert h.tokens == _ref_stream(model, params, p, 16)
    # the lane h0 vacated went to a queued request before the batch drained
    reused = [h for h in rest if h.slot == h0.slot and
              h.admit_time > h0.finish_time]
    assert reused, "freed slot was never re-acquired"
    still_decoding = [h for h in rest
                      if h.admit_time < reused[0].admit_time
                      and h.finish_time > reused[0].admit_time]
    assert still_decoding, "pool had drained before the slot was reused"


def test_chunked_prefill_and_bucketing_are_invisible(gpt_tiny):
    """Prefill chunking + right-pad bucketing must not change streams —
    including the case where the last real token's logits live in a
    non-final chunk (prompt 9 pads to 24, chunk 8: row in chunk 2 of 3)."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=24, prefill_chunk=8,
    ))
    prompts = [_prompts(1, seed=s, lo=9, hi=10)[0] for s in range(3)]
    prompts.append(_prompts(1, seed=9, lo=17, hi=18)[0])
    handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.run()
    for p, h in zip(prompts, handles):
        assert h.tokens == _ref_stream(model, params, p, 8)


def test_deepseekv3_serves_with_latent_cache_lanes():
    """The flagship's MLA LatentCache pools/serves through the same
    engine (lane carving is pytree-generic), moe_state riding
    extra_variables exactly as in generate."""
    import dataclasses as dc

    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config

    cfg = DeepSeekV3Config(
        vocab_size=64, block_size=64, dim=32, n_layers=2, n_heads=4,
        latent_dim=8, rope_dim=8, n_experts=4, top_experts=2, dropout=0.0,
        attn_dropout=0.0,
    )
    model = DeepSeekV3(cfg)
    rng = jax.random.key(3)
    prompts = _prompts(3, seed=4, lo=5, hi=14)
    variables = model.init({"params": rng}, jnp.asarray(prompts[0])[None, :])
    params, extra = variables["params"], {"moe_state": variables["moe_state"]}

    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=2, bucket=8,
    ), extra_variables=extra)
    handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run()
    for p, h in zip(prompts, handles):
        out = generate(model, params, jnp.asarray(p)[None, :],
                       jax.random.key(0), max_new_tokens=6,
                       extra_variables=extra)
        assert h.tokens == np.asarray(out[0, len(p):]).tolist()


def test_submit_validates_capacity(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(n_slots=1, max_len=32))
    with pytest.raises(ValueError, match="exceeds the engine capacity"):
        eng.submit(np.zeros(30, np.int32), max_new_tokens=8)
    with pytest.raises(ValueError, match="at least one token"):
        eng.submit(np.zeros(0, np.int32))


def test_submit_validates_prompt_dtype_and_shape(gpt_tiny):
    """Bad prompts raise host-side at submit, never inside a traced
    program: float dtypes (silent truncation hazard), non-1-D shapes,
    non-positive budgets and deadlines."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(n_slots=1, max_len=32))
    with pytest.raises(ValueError, match="integer token ids"):
        eng.submit(np.asarray([1.0, 2.5, 3.0]), max_new_tokens=4)
    with pytest.raises(ValueError, match="must be 1-D"):
        eng.submit(np.zeros((2, 4), np.int32), max_new_tokens=4)
    with pytest.raises(ValueError, match="must be 1-D"):
        eng.submit(np.int32(3), max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens must be >= 1"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="deadline_s must be > 0"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=4,
                   deadline_s=0.0)
    # a python list of ints is still fine (integer-kind after asarray)
    h = eng.submit([1, 2, 3], max_new_tokens=4)
    assert h.prompt.dtype == np.int32


def test_submit_rejects_bad_sampling_params(gpt_tiny):
    """SamplingParams validates at construction (so the error carries the
    bad field, not a trace-time shape error), and stop strings demand a
    detokenizer."""
    from solvingpapers_tpu.serve import SamplingParams

    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(n_slots=1, max_len=32))
    for bad in (
        dict(temperature=-0.5),
        dict(top_p=0.0),
        dict(top_p=1.5),
        dict(min_p=1.0001),
        dict(top_k=-1),
        dict(seed=-3),
        dict(seed=2**31),  # must fit the engine's int32 control mirrors
        dict(max_tokens=0),
        dict(stop=("",)),
        dict(stop_token_ids=(50256.9,)),  # int() would stop on wrong id
        dict(stop_token_ids="abc"),
    ):
        with pytest.raises(ValueError):
            SamplingParams(**bad)
    # a lone id normalizes like a lone stop string does
    assert SamplingParams(stop_token_ids=7).stop_token_ids == (7,)
    with pytest.raises(ValueError, match="detokenize"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=4,
                   params=SamplingParams(stop=("xy",)))
    # max_tokens overrides the submit budget and still checks capacity
    with pytest.raises(ValueError, match="exceeds the engine capacity"):
        eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=1,
                   params=SamplingParams(max_tokens=64))


def test_admission_control_rejects_beyond_queue(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, max_waiting=2,
    ))
    handles = [eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=4)
               for _ in range(3)]
    assert [h.state for h in handles] == ["waiting", "waiting", "rejected"]
    assert eng.metrics.requests_rejected == 1
    eng.run()
    assert [h.done for h in handles] == [True, True, False]


# ------------------------------------------------------------------- pool


def test_kv_pool_acquire_release(gpt_tiny):
    model, _ = gpt_tiny
    pool = KVSlotPool(model, n_slots=3, max_len=16)
    assert pool.caches[0].k.shape[0] == 3  # slot dim IS the batch dim
    slots = [pool.acquire() for _ in range(3)]
    assert sorted(slots) == [0, 1, 2]
    assert pool.acquire() is None and pool.n_free == 0
    pool.release(slots[1])
    assert pool.occupancy == pytest.approx(2 / 3)
    assert pool.acquire() == slots[1]  # LIFO: freshest lane first
    pool.release(slots[1])
    with pytest.raises(ValueError, match="double release"):
        pool.release(slots[1])


def test_kv_pool_release_guard_is_membership_tracked(gpt_tiny):
    """Regression for the O(n_slots) `slot in free_list` scan on the
    hot release path: free membership is a boolean mask kept in sync
    with the LIFO list through arbitrary acquire/release interleavings,
    and the double-release guard still fires from any state."""
    model, _ = gpt_tiny
    pool = KVSlotPool(model, n_slots=4, max_len=16)
    held = [pool.acquire() for _ in range(4)]
    for s in held:
        assert not pool._free_mask[s]
    pool.release(held[2])
    pool.release(held[0])
    assert pool._free_mask[held[0]] and pool._free_mask[held[2]]
    assert pool.acquire() == held[0]  # LIFO order preserved by the list
    with pytest.raises(ValueError, match="double release"):
        pool.release(held[2])
    # mask and list agree exactly after the churn
    assert sorted(pool._free) == sorted(np.flatnonzero(pool._free_mask))


def test_kv_pool_acquire_on_exhausted_is_stable(gpt_tiny):
    """Exhaustion returns None (no exception, no state damage) and stays
    None until a release; the released lane is handed out next."""
    model, _ = gpt_tiny
    pool = KVSlotPool(model, n_slots=2, max_len=16)
    a, b = pool.acquire(), pool.acquire()
    for _ in range(3):
        assert pool.acquire() is None
    assert pool.n_free == 0 and pool.n_active == 2
    pool.release(a)
    assert pool.acquire() == a
    assert pool.acquire() is None


def test_kv_pool_splice_and_extract_roundtrip(gpt_tiny):
    """extract_prefix snapshots a COPY; splice_prefix writes it back at
    an offset without touching other lanes or the rest of the lane."""
    model, _ = gpt_tiny
    pool = KVSlotPool(model, n_slots=2, max_len=16)
    # fill lane 1's slots [0, 8) with a recognizable ramp
    ramp = jax.tree_util.tree_map(
        lambda a: jnp.arange(np.prod(a.shape[2:]) * 8, dtype=jnp.float32)
        .reshape((1, 8) + a.shape[2:]).astype(a.dtype),
        pool.extract_prefix(1, 0, 8),
    )
    pool.splice_prefix(1, ramp, offset=0)
    seg = pool.extract_prefix(1, 4, 4)
    np.testing.assert_array_equal(
        np.asarray(seg[0].k), np.asarray(ramp[0].k[:, 4:8])
    )
    # splice the snapshot into lane 0 at offset 8; lane 1 is untouched
    before_lane1 = np.asarray(pool.caches[0].k[1])
    pool.splice_prefix(0, seg, offset=8)
    np.testing.assert_array_equal(
        np.asarray(pool.caches[0].k[0, 8:12]), np.asarray(ramp[0].k[0, 4:8])
    )
    np.testing.assert_array_equal(np.asarray(pool.caches[0].k[1]), before_lane1)
    with pytest.raises(ValueError, match="exceeds the lane capacity"):
        pool.splice_prefix(0, seg, offset=14)
    with pytest.raises(ValueError, match="exceeds the lane capacity"):
        pool.extract_prefix(0, 14, 4)


def test_store_lane_and_splice_reject_dtype_mismatch(gpt_tiny):
    """The pool write paths never cast: a silent astype would down-cast
    an fp32 segment into a bf16 pool and quietly change every stream
    decoded over it. Mismatches must raise at trace time."""
    from solvingpapers_tpu.serve import extract_lane, store_lane

    model, _ = gpt_tiny
    pool = KVSlotPool(model, n_slots=2, max_len=16)
    lane = extract_lane(pool.caches, 0)
    pool_dtype = pool.caches[0].k.dtype
    wrong_dtype = jnp.bfloat16 if pool_dtype == jnp.float32 else jnp.float32
    wrong = jax.tree_util.tree_map(lambda a: a.astype(wrong_dtype), lane)
    with pytest.raises(TypeError, match="cast explicitly"):
        store_lane(pool.caches, wrong, 0)
    seg = jax.tree_util.tree_map(
        lambda a: a.astype(wrong_dtype), pool.extract_prefix(0, 0, 4)
    )
    with pytest.raises(TypeError, match="cast explicitly"):
        pool.splice_prefix(0, seg, offset=0)
    # matching dtypes round-trip fine
    pool.caches = store_lane(pool.caches, lane, 0)
    pool.splice_prefix(0, pool.extract_prefix(0, 0, 4), offset=4)


def test_kv_pool_positions_track_lane_fill(gpt_tiny):
    """`pool.positions[slot]` is the lane's real KV fill level — prompt
    plus every emitted token except the newest (whose KV lands only when
    it is fed back next step), no decode-block overshoot — and resets to
    0 on release."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
    ))
    prompts = _prompts(2, seed=7, lo=5, hi=11)
    handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.step()  # prefill + one block: both lanes mid-stream
    for p, h in zip(prompts, handles):
        if not h.done:
            assert eng.pool.positions[h.slot] == len(p) + len(h.tokens) - 1
    eng.run()
    assert all(h.done for h in handles)
    np.testing.assert_array_equal(eng.pool.positions, 0)


# -------------------------------------------------------------- scheduler


def _req(n=4):
    return Request(prompt=np.arange(n, dtype=np.int32), max_new_tokens=4,
                   eos_id=None)


def test_scheduler_decode_priority_bounds_prefills():
    sched = FIFOScheduler(decode_priority=True, max_prefills_per_step=1)
    for _ in range(3):
        sched.submit(_req())
    # active decodes present: one prefill per iteration
    assert len(sched.pick(n_free=3, n_active=2)) == 1
    # idle pool: fill every free slot at once
    assert len(sched.pick(n_free=3, n_active=0)) == 2


def test_scheduler_wait_budget_overrides_decode_priority():
    sched = FIFOScheduler(decode_priority=True, max_prefills_per_step=1,
                          max_wait_steps=2)
    for _ in range(3):
        sched.submit(_req())
    for _ in range(3):
        sched.tick()
    # head waited past the budget: prefill gets the free slots despite
    # active decodes
    assert len(sched.pick(n_free=2, n_active=4)) == 2


def test_scheduler_admission_control():
    sched = FIFOScheduler(max_waiting=1)
    assert sched.submit(_req())
    overflow = _req()
    assert not sched.submit(overflow)
    assert overflow.state == "rejected"
    assert len(sched) == 1
