"""Test environment: force an 8-device CPU platform before JAX initializes.

This is the TPU-world substitute for a fake distributed backend
(SURVEY.md §4): all sharding/collective tests run against a virtual
8-device host mesh.
"""

import os

# SPTPU_TEST_PLATFORM=tpu runs hardware-gated tests (e.g. the in-kernel
# dropout suite — interpret-mode pltpu.prng_random_bits is a zero stub)
# against the real chip instead of the virtual CPU mesh.
_platform = os.environ.get("SPTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if _platform == "cpu" and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The hosting environment pins JAX_PLATFORMS=axon (real TPU) via sitecustomize;
# the config update is what actually wins after import.
if _platform == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    if len(devs) != 8:
        pytest.skip(f"needs the 8-virtual-device CPU mesh, have {len(devs)}")
    return devs
