"""Test environment: force an 8-device CPU platform before JAX initializes.

This is the TPU-world substitute for a fake distributed backend
(SURVEY.md §4): all sharding/collective tests run against a virtual
8-device host mesh.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The hosting environment pins JAX_PLATFORMS=axon (real TPU) via sitecustomize;
# the config update is what actually wins after import.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs
