"""Test environment: force an 8-device CPU platform before JAX initializes.

This is the TPU-world substitute for a fake distributed backend
(SURVEY.md §4): all sharding/collective tests run against a virtual
8-device host mesh.
"""

import os

# SPTPU_TEST_PLATFORM=tpu runs hardware-gated tests (e.g. the in-kernel
# dropout suite — interpret-mode pltpu.prng_random_bits is a zero stub)
# against the real chip instead of the virtual CPU mesh.
_platform = os.environ.get("SPTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if _platform == "cpu" and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The hosting environment pins JAX_PLATFORMS=axon (real TPU) via sitecustomize;
# the config update is what actually wins after import.
if _platform == "cpu":
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    if len(devs) != 8:
        pytest.skip(f"needs the 8-virtual-device CPU mesh, have {len(devs)}")
    return devs


def assert_no_leaks(eng):
    """The serve engine's drained-pool leak invariant, shared across
    the paged-pool, fault/quarantine and degradation suites (apply
    after every test drain): every slot back on the free list with a
    consistent `_free_mask`; on paged pools every page back on the free
    list (the prefix tree — the one legitimate post-drain holder — is
    fully evicted first), the refcount sum back at the trash page's
    permanent 1, and the free list exactly the zero-refcount pages; on
    quantized pools the exact-lane free list intact."""
    pool = eng.pool
    assert pool.n_active == 0, "slots still active after drain"
    assert pool._free_mask.all(), "slot leaked (_free_mask inconsistent)"
    assert sorted(pool._free) == list(range(pool.n_slots)), \
        "slot free list leaked or duplicated"
    assert all(r is None for r in eng._slot_req), \
        "engine slot mirror still holds a request"
    if eng.prefix_cache is not None:
        while eng.prefix_cache.evict_one():
            pass
    if hasattr(pool, "refcount"):  # paged pool
        assert pool.pages_free == pool.page_budget, (
            f"pages leaked: {pool.pages_free} free of "
            f"{pool.page_budget} budgeted"
        )
        assert int(pool.refcount.sum()) == 1, (
            "refcounts leaked (expected only the trash page's "
            f"permanent hold): sum={int(pool.refcount.sum())}"
        )
        free = set(pool._free_pages)
        zero = {p for p in range(1, pool.n_pages)
                if pool.refcount[p] == 0}
        assert free == zero, "free list != zero-refcount pages"
        assert len(pool._free_pages) == len(free), "duplicate free entries"
    if getattr(pool, "exact_lanes", 0):
        assert sorted(eng._exact_free) == list(
            range(1, pool.exact_lanes + 1)
        ), "exact-lane free list leaked"
