"""DeepSeekV3 tests (SURVEY.md §4 plan): MoE routing mass, aux-free bias
sign updates, dispatch-vs-dense equality, shared-expert passthrough, MLA
cached-decode equivalence, MTP shapes/loss, loss-goes-down smoke, and
expert-parallel sharded equality on the virtual 8-device mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator
from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config
from solvingpapers_tpu.sharding import MeshConfig, create_mesh
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer
from solvingpapers_tpu.train.objectives import dsv3_init_fn, dsv3_loss_fn

TINY = DeepSeekV3Config(
    vocab_size=64, block_size=32, dim=32, n_layers=2, n_heads=4, latent_dim=8,
    n_experts=4, top_experts=2, dropout=0.0, attn_dropout=0.0,
)


def init_model(cfg=TINY, seed=0, seq=16, batch=2):
    model = DeepSeekV3(cfg)
    toks = jnp.zeros((batch, seq), jnp.int32)
    variables = model.init(
        {"params": jax.random.key(seed)}, toks, return_mtp=cfg.mtp_heads > 0
    )
    return model, variables


# ------------------------------------------------------------------- routing


def test_topk_gate_probs_mass_and_support():
    logits = jax.random.normal(jax.random.key(0), (64, 8))
    probs = ops.moe.topk_gate_probs(logits, 2)
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, rtol=1e-6)
    assert int((probs > 0).sum(-1).max()) == 2
    assert int((probs > 0).sum(-1).min()) == 2


def test_aux_free_bias_update_signs():
    # expert 0 overloaded, expert 3 starved -> bias moves down for 0, up for 3
    probs = jnp.array([[1.0, 0.0, 0.0, 0.0]] * 30 + [[0.0, 0.5, 0.5, 0.0]] * 10)
    bias = jnp.zeros(4)
    new = ops.moe.aux_free_bias_update(probs, bias, rate=0.001)
    assert float(new[0]) < 0 and float(new[3]) > 0


def test_dispatch_equals_dense_when_capacity_ample():
    d, h, e, t = 16, 24, 4, 64
    key = jax.random.key(1)
    x = jax.random.normal(key, (t, d))
    w1 = jax.random.normal(jax.random.key(2), (e, d, h)) * 0.1
    w2 = jax.random.normal(jax.random.key(3), (e, d, h)) * 0.1
    w3 = jax.random.normal(jax.random.key(4), (e, h, d)) * 0.1
    probs = ops.moe.topk_gate_probs(jax.random.normal(jax.random.key(5), (t, e)), 2)

    def f(xe):
        a = jnp.einsum("ecd,edh->ech", xe, w1)
        g = jnp.einsum("ecd,edh->ech", xe, w2)
        return jnp.einsum("ech,ehd->ecd", ops.swish(a) * g, w3)

    def f_all(xt):
        a = jnp.einsum("td,edh->eth", xt, w1)
        g = jnp.einsum("td,edh->eth", xt, w2)
        return jnp.einsum("eth,ehd->etd", ops.swish(a) * g, w3)

    out_dispatch = ops.moe.moe_dispatch_combine(x, probs, f, capacity=t)
    out_dense = ops.moe.moe_dense_combine(x, probs, f_all)
    np.testing.assert_allclose(
        np.asarray(out_dispatch), np.asarray(out_dense), rtol=1e-5, atol=1e-5
    )


def test_moe_dense_and_dispatch_model_agree():
    import dataclasses

    cfg_disp = dataclasses.replace(TINY, moe_impl="dispatch", capacity_factor=8.0)
    cfg_dense = dataclasses.replace(TINY, moe_impl="dense")
    model_d, variables = init_model(cfg_disp)
    model_e = DeepSeekV3(cfg_dense)
    toks = jax.random.randint(jax.random.key(7), (2, 16), 0, TINY.vocab_size)
    out_d, _ = model_d.apply(variables, toks)
    out_e, _ = model_e.apply(variables, toks)  # same params, different routing impl
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_e), rtol=2e-5, atol=2e-5)


# --------------------------------------------------------------------- model


def test_forward_shape_and_weight_tying():
    model, variables = init_model()
    toks = jnp.zeros((2, 16), jnp.int32)
    logits, caches = model.apply(variables, toks)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert caches is None
    assert "lm_head" not in variables["params"]  # tied to tok_emb
    assert "routing_bias" in variables["moe_state"]["layer_0"]["moe"]


@pytest.mark.parametrize("rope_dim", [0, 8], ids=["norope", "rope"])
def test_cached_decode_equals_full_forward(rope_dim):
    import dataclasses as dc

    model, variables = init_model(cfg=dc.replace(TINY, rope_dim=rope_dim))
    rng = jax.random.key(1)
    prompt = jax.random.randint(rng, (2, 5), 0, TINY.vocab_size)
    params = variables["params"]
    moe_state = {"moe_state": variables["moe_state"]}

    out = generate(model, params, prompt, rng, max_new_tokens=8,
                   extra_variables=moe_state)
    toks = prompt
    for _ in range(8):
        logits, _ = model.apply({"params": params, **moe_state}, toks)
        toks = jnp.concatenate([toks, jnp.argmax(logits[:, -1], -1)[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(toks))


def test_flash_mla_matches_dense_mla():
    """use_flash MLA (absorbed-query attention == MQA over the latent
    stream, served by the Pallas kernel) must match the dense einsum path —
    same params, values, and grads."""
    import dataclasses

    model_d, variables = init_model()
    cfg_f = dataclasses.replace(TINY, use_flash=True)
    model_f = DeepSeekV3(cfg_f)
    toks = jax.random.randint(jax.random.key(3), (2, 16), 0, TINY.vocab_size)

    out_d, _ = model_d.apply(variables, toks)
    out_f, _ = model_f.apply(variables, toks)  # same param structure
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), rtol=2e-4, atol=2e-4
    )

    def loss(m):
        def f(p):
            logits, _ = m.apply({**variables, "params": p}, toks)
            return ops.cross_entropy(logits, toks)
        return jax.grad(f)(variables["params"])

    gd, gf = loss(model_d), loss(model_f)
    flat_d = jax.tree_util.tree_flatten_with_path(gd)[0]
    flat_f = jax.tree_util.tree_flatten_with_path(gf)[0]
    assert [str(p) for p, _ in flat_d] == [str(p) for p, _ in flat_f]
    for (pa, a), (_, bv) in zip(flat_d, flat_f):
        np.testing.assert_allclose(
            np.asarray(bv), np.asarray(a), rtol=5e-3, atol=5e-4,
            err_msg=str(pa),
        )


def test_mtp_shapes_and_loss():
    import dataclasses

    cfg = dataclasses.replace(TINY, mtp_heads=2)
    model, variables = init_model(cfg)
    toks = jax.random.randint(jax.random.key(2), (2, 16), 0, cfg.vocab_size)
    (logits, mtp_logits), _ = model.apply(variables, toks, return_mtp=True)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert mtp_logits.shape == (2, 16, 2, cfg.vocab_size)

    batch = {"x": toks, "y": jnp.roll(toks, -1, axis=1)}
    loss, aux, ms = dsv3_loss_fn(
        model, variables["params"], batch, jax.random.key(3),
        {"moe_state": variables["moe_state"]}, True,
    )
    assert jnp.isfinite(loss)
    assert "mtp_loss" in aux and jnp.isfinite(aux["mtp_loss"])


# ------------------------------------------------------------------ training


def _train(mesh_cfg=None, devices=None, steps=30, cfg=TINY, seed=0):
    mesh = create_mesh(
        mesh_cfg or MeshConfig(data=1, fsdp=1, model=1),
        devices if devices is not None else jax.devices()[:1],
    )
    _, train_toks, _ = load_char_corpus(synthetic_chars=20_000)
    tcfg = TrainConfig(
        steps=steps, batch_size=8, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=5, total_steps=steps),
    )
    trainer = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                      init_fn=dsv3_init_fn, mesh=mesh)
    from solvingpapers_tpu.sharding import batch_sharding

    it = lm_batch_iterator(train_toks, 8, cfg.block_size, seed=seed,
                           sharding=batch_sharding(mesh))
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    losses = []
    state, m = trainer._train_step(state, b0)
    losses.append(float(m["train_loss"]))
    for _ in range(steps):
        state, m = trainer._train_step(state, next(it))
        losses.append(float(m["train_loss"]))
    return losses, state


def test_loss_decreases_and_bias_updates():
    losses, state = _train(steps=30)
    assert losses[-1] < losses[0] - 0.3, losses[:3] + losses[-3:]
    bias = jax.device_get(
        state.model_state["moe_state"]["layer_0"]["moe"]["routing_bias"]
    )
    assert np.any(bias != 0.0), "aux-free routing bias never updated"


@pytest.mark.parametrize(
    "mesh_cfg",
    [
        MeshConfig(data=2, fsdp=1, model=1, expert=4),
        MeshConfig(data=2, fsdp=2, model=2, expert=1),
    ],
    ids=["ep4_dp2", "dp2_fsdp2_tp2"],
)
def test_sharded_train_matches_single_device(mesh_cfg, devices):
    single, _ = _train(steps=2, seed=11)
    sharded, _ = _train(mesh_cfg, devices, steps=2, seed=11)
    np.testing.assert_allclose(sharded[:3], single[:3], rtol=5e-4, atol=5e-5)


# -------------------------------------------------------- MoE observability


def test_moe_overload_reports_drops_and_bias_reacts():
    """Feeding identical tokens collapses routing onto one top-k expert set:
    the sown metrics must report drops > 0 at finite capacity and the
    aux-free bias must push the hot experts down within the same step
    (VERDICT r1 item 4 / SURVEY.md hard part #1)."""
    from solvingpapers_tpu.models.deepseekv3 import MoELayer

    cfg = DeepSeekV3Config(
        vocab_size=64, block_size=64, dim=16, n_layers=1, n_heads=2,
        latent_dim=8, n_experts=8, top_experts=2, dropout=0.0,
        attn_dropout=0.0, capacity_factor=1.0,
    )
    layer = MoELayer(cfg)
    x = jnp.broadcast_to(
        jax.random.normal(jax.random.key(0), (1, 1, 16)), (1, 64, 16)
    )
    variables = layer.init({"params": jax.random.key(1)}, x)
    (_, mutated) = layer.apply(
        {"params": variables["params"], "moe_state": variables["moe_state"]},
        x, deterministic=False,
        mutable=["moe_state", "moe_metrics"],
        rngs={"dropout": jax.random.key(2)},
    )
    stats = jax.tree.leaves(
        mutated["moe_metrics"],
        is_leaf=lambda v: isinstance(v, dict) and "load_entropy" in v,
    )[0]
    # 64 identical tokens x top-2 -> 2 experts get 64 each; cap = 16
    assert float(stats["drop_fraction"]) > 0.5
    assert float(stats["load_max_fraction"]) > 0.4
    assert float(stats["load_entropy"]) < 0.5
    # bias_norm is sown AFTER the in-step update: it must have moved
    assert float(stats["bias_norm"]) > 0.0
    bias = np.asarray(mutated["moe_state"]["routing_bias"])
    assert (bias < 0).sum() == 2 and (bias > 0).sum() == 6, bias


def test_moe_metrics_flow_through_train_step():
    """The Trainer's train metrics must carry the aggregated moe_* fields."""
    cfg = TINY
    model = DeepSeekV3(cfg)
    tcfg = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        optimizer=OptimizerConfig(max_lr=1e-3, total_steps=4),
    )
    trainer = Trainer(model, tcfg, loss_fn=dsv3_loss_fn, init_fn=dsv3_init_fn,
                      mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    text_toks = np.arange(2048) % cfg.vocab_size
    it = lm_batch_iterator(text_toks, 4, cfg.block_size)
    batch = next(it)
    st = trainer.init_state(batch)
    trainer._build_steps()
    _, m = trainer._train_step(st, batch)
    m = jax.device_get(m)
    for k in ("train_moe_load_entropy", "train_moe_load_max_fraction",
              "train_moe_drop_fraction", "train_moe_bias_norm"):
        assert k in m, sorted(m)
        assert np.isfinite(m[k])
    assert 0.0 <= m["train_moe_drop_fraction"] <= 1.0
    assert 0.0 <= m["train_moe_load_entropy"] <= 1.0 + 1e-6


# ------------------------------------------------------- context parallelism


@pytest.mark.parametrize(
    "use_flash,rope_dim",
    [(False, 0), (True, 0), (False, 8), (True, 8)],
    ids=["jnp", "flash", "jnp_rope", "flash_rope"],
)
def test_dsv3_cp_train_step_matches_dense(devices, use_flash, rope_dim):
    """The flagship under CP: MLA rings over the LATENT stream (k = v =
    latents, one shared kv head) inside the stock CP Trainer; the MoE
    routing-bias update is psum'd so state stays shard-invariant. One step
    must equal the dense single-device step — params AND moe_state.
    (Parity is exact in the drop-free regime; once capacity binds, CP
    decides drops per shard — standard distributed-MoE semantics.)"""
    import dataclasses as dc

    cfg = dc.replace(
        TINY, block_size=32, dropout=0.0, attn_dropout=0.0,
        rope_dim=rope_dim,  # decoupled-RoPE k rides the latent ring (cat)
    )
    batch_x = jax.random.randint(jax.random.key(0), (4, 32), 0, cfg.vocab_size)
    batch = {"x": batch_x, "y": jnp.roll(batch_x, -1, axis=1)}
    tcfg = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )

    dense = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                    init_fn=dsv3_init_fn,
                    mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    cp_cfg = dc.replace(cfg, context_parallel=True, use_flash=use_flash)
    cp_tcfg = dc.replace(tcfg, context_parallel=True,
                         mesh=MeshConfig(data=2, context=4))
    cp = Trainer(DeepSeekV3(cp_cfg), cp_tcfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn,
                 mesh=create_mesh(MeshConfig(data=2, context=4), devices))
    c_state = cp.init_state(batch)
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    # the aux-free routing bias must update identically (shard-invariant)
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)
    # moe observability flows under CP too
    assert "train_moe_load_entropy" in c_metrics


def test_balance_loss_composes_with_mtp():
    """The total must carry BOTH auxiliary terms: loss = main +
    w_bal*balance + w_mtp*mtp (a loss = main + w_mtp*mtp overwrite
    silently dropped the balance term whenever MTP was on)."""
    import dataclasses as dc

    cfg = dc.replace(TINY, mtp_heads=1, balance_loss_weight=0.01,
                     dropout=0.0, attn_dropout=0.0)
    model = DeepSeekV3(cfg)
    toks = jax.random.randint(jax.random.key(0), (2, 16), 0, cfg.vocab_size)
    batch = {"x": toks, "y": jnp.roll(toks, -1, axis=1)}
    params, ms = dsv3_init_fn(model, {"params": jax.random.key(1)}, batch)
    loss, aux, _ = dsv3_loss_fn(model, params, batch, jax.random.key(2),
                                ms, True)
    main = jnp.log(aux["perplexity"])
    expect = (main + 0.01 * aux["balance_loss"]
              + cfg.mtp_loss_weight * aux["mtp_loss"])
    np.testing.assert_allclose(float(loss), float(expect), rtol=1e-6)


def test_dsv3_cp_mtp_train_step_matches_dense(devices):
    """MTP under context parallelism (VERDICT r3 missing #3): the i+k
    target shift crosses shard boundaries, resolved by a k-token ppermute
    halo from the right neighbor (sharding.cp_halo_right) for both the
    shifted-embedding stream and the loss targets, with the MTP loss
    psum'ing sum/count over 'context' so the global mean is exact. One CP
    step with mtp_heads=2 must equal the dense single-device step —
    dsv3_mtp and dsv3_long_cp are no longer mutually exclusive."""
    import dataclasses as dc

    cfg = dc.replace(TINY, block_size=32, dropout=0.0, attn_dropout=0.0,
                     mtp_heads=2)
    batch_x = jax.random.randint(jax.random.key(4), (4, 32), 0, cfg.vocab_size)
    batch = {"x": batch_x, "y": jnp.roll(batch_x, -1, axis=1)}
    tcfg = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )

    dense = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                    init_fn=dsv3_init_fn,
                    mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    cp_cfg = dc.replace(cfg, context_parallel=True)
    cp_tcfg = dc.replace(tcfg, context_parallel=True,
                         mesh=MeshConfig(data=2, context=4))
    cp = Trainer(DeepSeekV3(cp_cfg), cp_tcfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn,
                 mesh=create_mesh(MeshConfig(data=2, context=4), devices))
    c_state = cp.init_state(batch)
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_mtp_loss"])),
        float(jax.device_get(d_metrics["train_mtp_loss"])), rtol=2e-5,
    )
    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_moe_expert_sliced_combine_matches_unsharded(devices):
    """The shard_map EP compute pattern: expert weights sliced over the
    'expert' axis, each member dispatching its local columns, partial
    combines psum'd — must equal the unsharded dispatch."""
    from jax.sharding import PartitionSpec as P

    d, h, e, t = 16, 24, 4, 64
    mesh = create_mesh(MeshConfig(data=1, expert=4), devices[:4])
    x = jax.random.normal(jax.random.key(0), (t, d))
    w1 = jax.random.normal(jax.random.key(1), (e, d, h)) * 0.1
    w2 = jax.random.normal(jax.random.key(2), (e, d, h)) * 0.1
    w3 = jax.random.normal(jax.random.key(3), (e, h, d)) * 0.1
    probs = ops.moe.topk_gate_probs(
        jax.random.normal(jax.random.key(4), (t, e)), 2)

    def fn(w1, w2, w3):
        def f(xe):
            a = jnp.einsum("ecd,edh->ech", xe, w1)
            g = jnp.einsum("ecd,edh->ech", xe, w2)
            return jnp.einsum("ech,ehd->ecd", ops.swish(a) * g, w3)
        return f

    ref = ops.moe.moe_dispatch_combine(x, probs, fn(w1, w2, w3), capacity=t)

    def local(x, probs, w1, w2, w3):
        # w* arrive as this member's (1, ...) expert slice, so the op's
        # `start` index is unused here (weights are already local)
        return ops.moe.moe_expert_sliced_combine(
            x, probs, lambda xe, start: fn(w1, w2, w3)(xe), capacity=t)

    out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P("expert"), P("expert"), P("expert")),
        out_specs=P(),
    )(x, probs, w1, w2, w3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_cp_decode_matches_dense_generate(devices):
    """The inference half of the CP story: generate_cp over a context=4
    mesh (context-sharded CPLatentCache, ring prefill, distributed-softmax
    decode steps) must emit token-for-token the dense single-device
    generate's greedy output."""
    import dataclasses as dc

    from solvingpapers_tpu.infer import generate_cp

    cfg = dc.replace(TINY, block_size=64, rope_dim=8, pe_scale=0.02)
    model, variables = init_model(cfg, seq=16, batch=2)
    params = variables["params"]
    extra = {"moe_state": variables["moe_state"]}
    prompt = jax.random.randint(jax.random.key(7), (2, 32), 0, cfg.vocab_size)

    ref = generate(model, params, prompt, jax.random.key(1),
                   max_new_tokens=12, extra_variables=extra)

    cp_cfg = dc.replace(cfg, context_parallel=True)
    mesh = create_mesh(MeshConfig(data=1, context=4), devices[:4])
    out = generate_cp(DeepSeekV3(cp_cfg), params, prompt, jax.random.key(1),
                      mesh, max_new_tokens=12, extra_variables=extra)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_cp_decode_32k_prompt(devices):
    """Long-context generation beyond one chip's worth of cache: a
    32k-token prompt sharded over the 8-device mesh prefills via the
    latent ring and decodes under CP — the dsv3_long_cp inference path at
    reduced width (full width runs on real chips; this pins that the
    sharded-cache machinery executes at ≥32k length)."""
    from solvingpapers_tpu.infer import generate_cp

    s0, new = 32768, 4
    cfg = DeepSeekV3Config(
        vocab_size=256, block_size=s0 + 16, dim=64, n_layers=1, n_heads=2,
        latent_dim=16, rope_dim=8, pe_scale=0.02, n_experts=4, top_experts=2,
        capacity_factor=1.0, dropout=0.0, attn_dropout=0.0,
        context_parallel=True,
    )
    # init params via a short dense twin (params are seq-length independent)
    import dataclasses as dc

    dense = DeepSeekV3(dc.replace(cfg, context_parallel=False))
    variables = dense.init(
        {"params": jax.random.key(0)}, jnp.zeros((1, 16), jnp.int32)
    )
    prompt = jax.random.randint(jax.random.key(3), (1, s0), 0, cfg.vocab_size)
    mesh = create_mesh(MeshConfig(data=1, context=8), devices)
    out = generate_cp(
        DeepSeekV3(cfg), variables["params"], prompt, jax.random.key(1),
        mesh, max_new_tokens=new,
        extra_variables={"moe_state": variables["moe_state"]},
    )
    assert out.shape == (1, s0 + new)
    gen = np.asarray(out[:, s0:])
    assert ((gen >= 0) & (gen < cfg.vocab_size)).all()


@pytest.mark.parametrize("ep", [2, 4])
def test_moe_all_to_all_combine_matches_unsharded(devices, ep):
    """Token-dispatch EP: tokens AND expert weights sharded over 'expert',
    tokens physically moved by two tiled all_to_alls — must equal the
    unsharded dispatch in the drop-free regime (ep2 and ep4)."""
    from jax.sharding import PartitionSpec as P

    d, h, e, t = 16, 24, 8, 64
    mesh = create_mesh(MeshConfig(data=1, expert=ep), devices[:ep])
    x = jax.random.normal(jax.random.key(0), (t, d))
    w1 = jax.random.normal(jax.random.key(1), (e, d, h)) * 0.1
    w2 = jax.random.normal(jax.random.key(2), (e, d, h)) * 0.1
    w3 = jax.random.normal(jax.random.key(3), (e, h, d)) * 0.1
    probs = ops.moe.topk_gate_probs(
        jax.random.normal(jax.random.key(4), (t, e)), 2)

    def fn(w1, w2, w3):
        def f(xe):
            a = jnp.einsum("ecd,edh->ech", xe, w1)
            g = jnp.einsum("ecd,edh->ech", xe, w2)
            return jnp.einsum("ech,ehd->ecd", ops.swish(a) * g, w3)
        return f

    ref = ops.moe.moe_dispatch_combine(x, probs, fn(w1, w2, w3), capacity=t)

    def local(x, probs, w1, w2, w3):
        # w* arrive as this member's local expert slice -> start unused
        return ops.moe.moe_all_to_all_combine(
            x, probs, lambda xe, start: fn(w1, w2, w3)(xe),
            capacity=x.shape[0])

    out = jax.shard_map(
        local, mesh=mesh,
        in_specs=(P("expert"), P("expert"), P("expert"), P("expert"),
                  P("expert")),
        out_specs=P("expert"),
    )(x, probs, w1, w2, w3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_dsv3_cp_ep_all_to_all_train_step_matches_dense(devices):
    """ep_impl='all_to_all' under the CP shard_map (data=2 x context=2 x
    expert=2): one train step — loss, moe_state, params — must equal the
    dense single-device step, same bar as the sliced path's test."""
    import dataclasses as dc

    cfg = dc.replace(TINY, block_size=32, dropout=0.0, attn_dropout=0.0)
    batch_x = jax.random.randint(jax.random.key(5), (4, 32), 0, cfg.vocab_size)
    batch = {"x": batch_x, "y": jnp.roll(batch_x, -1, axis=1)}
    tcfg = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )

    dense = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                    init_fn=dsv3_init_fn,
                    mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    mesh_cfg = MeshConfig(data=2, context=2, expert=2)
    cp_cfg = dc.replace(cfg, context_parallel=True, ep_impl="all_to_all")
    cp_tcfg = dc.replace(tcfg, context_parallel=True, mesh=mesh_cfg)
    cp = Trainer(DeepSeekV3(cp_cfg), cp_tcfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn,
                 mesh=create_mesh(mesh_cfg, devices))
    c_state = cp.init_state(batch)
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_moe_drop_fraction"])),
        float(jax.device_get(d_metrics["train_moe_drop_fraction"])),
        atol=1e-6,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_dsv3_cp_ep_train_step_matches_dense(devices):
    """CP composed with an 'expert' mesh axis (data=2 x context=2 x
    expert=2): expert weights are STORED sharded over 'expert' (ZeRO
    layout at rest, gathered in-step), sequence rings over 'context'. One
    step must equal the dense single-device step — params and moe_state."""
    import dataclasses as dc

    cfg = dc.replace(TINY, block_size=32, dropout=0.0, attn_dropout=0.0)
    batch_x = jax.random.randint(jax.random.key(5), (4, 32), 0, cfg.vocab_size)
    batch = {"x": batch_x, "y": jnp.roll(batch_x, -1, axis=1)}
    tcfg = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4, grad_clip=1.0),
    )

    dense = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                    init_fn=dsv3_init_fn,
                    mesh=create_mesh(MeshConfig(data=1), jax.devices()[:1]))
    d_state = dense.init_state(batch)
    dense._build_steps()
    d_state, d_metrics = dense._train_step(d_state, batch)

    mesh_cfg = MeshConfig(data=2, context=2, expert=2)
    cp_cfg = dc.replace(cfg, context_parallel=True)
    cp_tcfg = dc.replace(tcfg, context_parallel=True, mesh=mesh_cfg)
    cp = Trainer(DeepSeekV3(cp_cfg), cp_tcfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn,
                 mesh=create_mesh(mesh_cfg, devices))
    c_state = cp.init_state(batch)
    # expert weights must be STORED sharded over the expert axis
    w1 = c_state.params["layer_0"]["moe"]["w1"]
    assert "expert" in str(w1.sharding.spec), w1.sharding.spec
    cp._build_steps()
    c_state, c_metrics = cp._train_step(c_state, batch)

    np.testing.assert_allclose(
        float(jax.device_get(c_metrics["train_loss"])),
        float(jax.device_get(d_metrics["train_loss"])), rtol=2e-5,
    )
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.model_state)),
                    jax.tree.leaves(jax.device_get(d_state.model_state))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(jax.device_get(c_state.params)),
                    jax.tree.leaves(jax.device_get(d_state.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_cp_ep_uses_sliced_expert_compute(devices, monkeypatch):
    """Under the CP shard_map the MoE layer must go through
    moe_expert_sliced_combine (sharded expert FLOPs), not the replicated
    full-stack dispatch — the equality test above would pass either way."""
    import dataclasses as dc

    from solvingpapers_tpu import ops as sp_ops

    calls = {"sliced": 0}
    real = sp_ops.moe.moe_expert_sliced_combine

    def spy(*args, **kwargs):
        calls["sliced"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(sp_ops.moe, "moe_expert_sliced_combine", spy)

    cfg = dc.replace(TINY, block_size=32, dropout=0.0, attn_dropout=0.0,
                     context_parallel=True)
    batch_x = jax.random.randint(jax.random.key(5), (4, 32), 0, cfg.vocab_size)
    batch = {"x": batch_x, "y": jnp.roll(batch_x, -1, axis=1)}
    mesh_cfg = MeshConfig(data=2, context=2, expert=2)
    tcfg = TrainConfig(
        steps=1, batch_size=4, log_every=1, eval_every=0,
        context_parallel=True, mesh=mesh_cfg,
        optimizer=OptimizerConfig(name="sgd", max_lr=1e-1, warmup_steps=0,
                                  total_steps=4),
    )
    tr = Trainer(DeepSeekV3(cfg), tcfg, loss_fn=dsv3_loss_fn,
                 init_fn=dsv3_init_fn, mesh=create_mesh(mesh_cfg, devices))
    state = tr.init_state(batch)
    tr._build_steps()
    state, metrics = tr._train_step(state, batch)
    assert calls["sliced"] > 0, "CP step did not take the sliced-EP path"
    assert float(jax.device_get(metrics["train_loss"])) > 0


def test_balance_loss_recovers_induced_overload():
    """VERDICT r2: an induced routing overload must recover. Gate kernel
    initialized to send ~every token to expert 0 (load_max ~1); training
    with the sequence-wise balance loss (aux-free bias off, to isolate the
    mechanism) must spread the load back out."""
    import dataclasses as dc

    cfg = dc.replace(TINY, use_aux_free=False, balance_loss_weight=0.2)
    model = DeepSeekV3(cfg)
    toks = jax.random.randint(jax.random.key(0), (8, 16), 0, cfg.vocab_size)
    batch = {"x": toks, "y": jnp.roll(toks, -1, axis=1)}
    variables = model.init({"params": jax.random.key(1)}, toks)
    params = variables["params"]
    # induce collapse: every layer's gate strongly prefers expert 0
    for lname in [k for k in params if k.startswith("layer_")]:
        kern = params[lname]["moe"]["gate"]["kernel"]
        biased = jnp.zeros_like(kern).at[:, 0].set(2.0)
        params[lname]["moe"]["gate"]["kernel"] = biased
    ms = {"moe_state": variables["moe_state"]}

    import optax

    tx = optax.adam(2e-2)
    opt_state = tx.init(params)

    def step(params, opt_state, key):
        def loss_fn(p):
            loss, aux, _ = dsv3_loss_fn(model, p, batch, key, ms, True)
            return loss, aux
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, aux

    _, aux0, _ = dsv3_loss_fn(model, params, batch, jax.random.key(2), ms, True)
    assert float(aux0["moe_load_max_fraction"]) > 0.9  # overload induced
    for i in range(120):
        params, opt_state, aux = step(params, opt_state, jax.random.key(i))
    # meaningful recovery (full rebalance is asymptotic through the
    # top-k renormalization): max load sheds >= 0.2, entropy rises, the
    # balance objective itself decreases
    assert float(aux["moe_load_max_fraction"]) < float(
        aux0["moe_load_max_fraction"]) - 0.2, aux
    assert float(aux["moe_load_entropy"]) > float(
        aux0["moe_load_entropy"]) + 0.2, aux
    assert float(aux["balance_loss"]) < float(aux0["balance_loss"]) - 0.2
