"""Paged KV pool tests (solvingpapers_tpu/serve/kv_pool.py PagedKVPool
+ the paged engine paths in serve/engine.py).

Three contracts under test. Allocator mechanics: page tables, refcounted
sharing, and the free list must balance under arbitrary interleavings of
acquire / ensure / share / release — no leaked pages, no doubly-owned
pages, and the physical pool NEVER grows (`nbytes` constant is the
design's whole premise). Engine exactness: greedy streams through the
paged pool must be token-exact vs one-shot `generate`, including across
preemption/recompute (a stream evicted on page exhaustion and resumed
later must be indistinguishable in its tokens). Zero-copy sharing: a
prefix-cache hit on the paged pool must dispatch NO device program —
asserted through the compile registry, which records every jitted
program the engine runs.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from conftest import assert_no_leaks
from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.infer.cache import KVCache
from solvingpapers_tpu.serve import PagedKVPool, ServeConfig, ServeEngine
from solvingpapers_tpu.serve.kv_pool import TRASH_PAGE


class _CacheOnly:
    """Minimal model stub for allocator-level tests: just enough to
    build a physical pool (one KVCache layer)."""

    def init_caches(self, batch, max_len, dtype=None):
        return [KVCache.init(batch, max_len, 2, 4, jnp.float32)]


# --------------------------------------------------------- allocator units


def test_allocator_acquire_ensure_release_roundtrip():
    pool = PagedKVPool(_CacheOnly(), n_slots=2, max_len=16, page_size=4,
                       page_budget=6)
    nbytes0 = pool.nbytes
    assert pool.pages_free == 6 and pool.pages_active == 0
    s = pool.acquire()
    assert pool.ensure(s, 10)  # 3 pages
    assert pool.n_alloc[s] == 3 and pool.pages_free == 3
    assert pool.ensure(s, 10)  # idempotent
    assert pool.n_alloc[s] == 3
    # table entries beyond the allocation rest at the trash page
    assert pool.table[s, 3] == TRASH_PAGE
    pool.release(s)
    assert pool.pages_free == 6
    assert (pool.refcount[1:] == 0).all()
    assert pool.nbytes == nbytes0
    with pytest.raises(ValueError, match="double release"):
        pool.release(s)


def test_allocator_exhaustion_keeps_partial_and_reports_false():
    pool = PagedKVPool(_CacheOnly(), n_slots=2, max_len=16, page_size=4,
                       page_budget=4)
    a, b = pool.acquire(), pool.acquire()
    assert pool.ensure(a, 12)  # 3 of 4 pages
    assert not pool.ensure(b, 8)  # needs 2, only 1 free: partial kept
    assert pool.n_alloc[b] == 1 and pool.pages_free == 0
    pool.release(a)
    assert pool.ensure(b, 8)  # retry succeeds after reclaim
    pool.release(b)
    assert pool.pages_free == 4


def test_shared_pages_survive_owner_release():
    """The refcount contract: a page shared with the tree outlives its
    producing slot, and frees only when the LAST holder drops it."""
    pool = PagedKVPool(_CacheOnly(), n_slots=2, max_len=16, page_size=4,
                       page_budget=6)
    s = pool.acquire()
    assert pool.ensure(s, 16)
    tree_refs = pool.share_range(s, 0, 8)  # the "radix tree" holds 2 pages
    assert (pool.refcount[tree_refs] == 2).all()
    pool.release(s)
    # shared pages alive under the tree's reference, owned ones freed
    assert (pool.refcount[tree_refs] == 1).all()
    assert pool.pages_free == 4
    # a second slot reuses them zero-copy
    s2 = pool.acquire()
    pool.append_shared(s2, tree_refs)
    assert pool.table[s2, :2].tolist() == tree_refs
    pool.release(s2)
    pool.decref(tree_refs)
    assert pool.pages_free == 6
    with pytest.raises(ValueError, match="over-released"):
        pool.decref(tree_refs)


def test_share_range_validates_alignment_and_coverage():
    pool = PagedKVPool(_CacheOnly(), n_slots=1, max_len=16, page_size=4,
                       page_budget=4)
    s = pool.acquire()
    pool.ensure(s, 8)
    with pytest.raises(ValueError, match="page-aligned"):
        pool.share_range(s, 2, 4)
    with pytest.raises(ValueError, match="exceeds slot"):
        pool.share_range(s, 0, 12)
    with pytest.raises(ValueError, match="cannot cover even one"):
        PagedKVPool(_CacheOnly(), n_slots=1, max_len=16, page_size=4,
                    page_budget=3)
    with pytest.raises(ValueError, match="not a multiple"):
        PagedKVPool(_CacheOnly(), n_slots=1, max_len=10, page_size=4)


def test_randomized_soak_refcounts_balance_and_pool_never_grows():
    """Randomized acquire / ensure / prefix-share / decref / release
    soak against a shadow model: after every op, (1) every page's
    refcount equals its slot-table references plus tree holds, (2) the
    free list is exactly the zero-refcount pages, (3) no page appears
    in two different slots' OWNED (refcount-1, unshared) positions, and
    (4) the physical pool's bytes never change."""
    rng = np.random.default_rng(0)
    pool = PagedKVPool(_CacheOnly(), n_slots=4, max_len=32, page_size=4,
                       page_budget=20)
    nbytes0 = pool.nbytes
    tree_holds: list[list[int]] = []  # page-id runs the "tree" references
    active: list[int] = []

    def check():
        # shadow refcount: slot-table references + tree references
        shadow = np.zeros(pool.n_pages, np.int64)
        shadow[TRASH_PAGE] = 1
        for s in range(pool.n_slots):
            for pid in pool.table[s, : pool.n_alloc[s]]:
                shadow[pid] += 1
        for run in tree_holds:
            for pid in run:
                shadow[pid] += 1
        np.testing.assert_array_equal(shadow, pool.refcount)
        free = set(pool._free_pages)
        zero = {p for p in range(1, pool.n_pages) if pool.refcount[p] == 0}
        assert free == zero, "free list != zero-refcount pages"
        assert len(free) == len(pool._free_pages), "duplicate free entries"
        assert pool.nbytes == nbytes0, "physical pool grew"

    for _ in range(400):
        op = rng.integers(0, 5)
        if op == 0 and len(active) < pool.n_slots:
            s = pool.acquire()
            assert s is not None
            active.append(s)
            pool.ensure(s, int(rng.integers(1, 33)))
        elif op == 1 and active:
            s = active[int(rng.integers(len(active)))]
            pool.ensure(s, int(rng.integers(1, 33)))
        elif op == 2 and active:
            s = active[int(rng.integers(len(active)))]
            covered = int(pool.n_alloc[s]) * pool.page_size
            if covered >= pool.page_size:
                pages = int(rng.integers(1, covered // pool.page_size + 1))
                off = int(rng.integers(
                    0, covered // pool.page_size - pages + 1))
                tree_holds.append(pool.share_range(
                    s, off * pool.page_size, pages * pool.page_size))
        elif op == 3 and tree_holds:
            run = tree_holds.pop(int(rng.integers(len(tree_holds))))
            pool.decref(run)
        elif op == 4 and active:
            s = active.pop(int(rng.integers(len(active))))
            pool.release(s)
        check()
    while active:
        pool.release(active.pop())
    while tree_holds:
        pool.decref(tree_holds.pop())
    check()
    assert pool.pages_free == pool.page_budget, "pages leaked"


# ------------------------------------------------------- engine integration


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _ref_stream(model, params, prompt, max_new):
    out = generate(model, params, jnp.asarray(prompt)[None, :],
                   jax.random.key(0), max_new_tokens=max_new)
    return np.asarray(out[0, len(prompt):]).tolist()


def _prompts(n, seed=0, lo=4, hi=24):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


def test_preemption_recompute_streams_token_exact():
    """A page budget too small for three full streams forces mid-stream
    preemption; the evicted request resumes by recompute and every
    greedy stream stays token-exact — the whole point of
    requeue-and-recompute over corrupt-or-crash."""
    model, params = _gpt_tiny()
    prompts = [p[:8] for p in _prompts(3, seed=5, lo=8, hi=9)]
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=3, max_len=32, decode_block=4, bucket=8, paged=True,
        page_size=4, page_budget=10, max_prefills_per_step=3,
    ))
    handles = [eng.submit(p, max_new_tokens=12) for p in prompts]
    eng.run()
    assert all(h.done for h in handles)
    for p, h in zip(prompts, handles):
        assert h.tokens == _ref_stream(model, params, p, 12), (
            "preemption/recompute corrupted a stream"
        )
    snap = eng.metrics.snapshot()
    assert snap["serve/preemptions"] >= 1, "budget never forced preemption"
    assert snap["serve/recompute_tokens"] > 0
    # drained engine: every page/slot back on the free lists
    assert_no_leaks(eng)


def test_paged_prefix_hit_dispatches_no_splice_program():
    """Acceptance: a full-page prefix hit on the paged pool is a
    host-side page-table append — the compile registry (which records
    EVERY jitted program the engine runs) must show no splice/extract
    program, while the same traffic on the lane pool compiles both."""
    model, params = _gpt_tiny()
    rng = np.random.default_rng(7)
    stem = rng.integers(0, 64, size=12).astype(np.int32)
    prompts = [np.concatenate([stem,
                               rng.integers(0, 64, size=5).astype(np.int32)])
               for _ in range(5)]

    def run(paged):
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=32, decode_block=4, bucket=8, paged=paged,
            prefix_cache=True, prefix_page=4, xla_obs=True,
        ))
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run()
        assert eng.metrics.snapshot()["serve/prefix_hits"] >= 3
        return handles, set(eng.registry.snapshot()["programs"])

    paged_handles, paged_progs = run(True)
    lane_handles, lane_progs = run(False)
    assert "splice_program" not in paged_progs
    assert "extract_program" not in paged_progs
    assert "splice_program" in lane_progs  # the baseline really splices
    for hp, hl in zip(paged_handles, lane_handles):
        assert hp.tokens == hl.tokens


def test_more_slots_than_lane_equivalent_hbm():
    """Capacity decoupling: at the BYTE budget of a 3-slot lane pool,
    the paged engine runs 6 slots concurrently (short streams book
    pages, not worst-case lanes) — slot count is no longer proportional
    to max_seq."""
    from solvingpapers_tpu.serve import KVSlotPool

    model, params = _gpt_tiny()
    page_size, max_len = 8, 64
    lane_equiv = 3 * (max_len // page_size)  # 3 lanes' worth of pages
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=6, max_len=max_len, decode_block=4, bucket=8, paged=True,
        page_size=page_size, page_budget=lane_equiv,
        max_prefills_per_step=6, decode_priority=False,
    ))
    lane_pool = KVSlotPool(model, n_slots=3, max_len=max_len)
    # equal HBM modulo the one reserved trash page
    assert eng.pool.nbytes == lane_pool.nbytes + eng.pool.page_nbytes
    prompts = _prompts(6, seed=9, lo=6, hi=12)
    handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.step()  # one admission wave fills every slot
    assert eng.pool.n_active == 6, "paged pool could not seat 2x the slots"
    eng.run()
    for p, h in zip(prompts, handles):
        assert h.tokens == _ref_stream(model, params, p, 6)


def test_tree_hoarded_pages_never_livelock_admission():
    """Livelock regression: with a small page budget the radix tree's
    references can pin (nearly) the whole pool after every stream
    drains; a new no-hit request must still be admitted — the idle
    engine sheds tree leaves for the page-starved head instead of
    spinning forever on a blocked `can_admit` gate."""
    model, params = _gpt_tiny()
    rng = np.random.default_rng(13)
    # budget = exactly one lane: after the first prompt is cached, the
    # tree holds most of the pool
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8, paged=True,
        prefix_cache=True, prefix_page=4, page_budget=8,
    ))
    a = rng.integers(0, 64, size=12).astype(np.int32)
    h0 = eng.submit(a, max_new_tokens=4)
    eng.run()
    assert h0.done
    assert eng.pool.pages_free < 8, "tree holds no pages — test is vacuous"
    # a DIFFERENT prompt (no cached prefix) needing more pages than free
    b = rng.integers(0, 64, size=20).astype(np.int32)
    h1 = eng.submit(b, max_new_tokens=4)
    for _ in range(50):  # bounded: a livelocked engine would spin here
        if not eng.has_work():
            break
        eng.step()
    assert h1.done, "page-starved head was never admitted (livelock)"
    assert h1.tokens == _ref_stream(model, params, b, 4)
    assert_no_leaks(eng)


def test_paged_engine_validates_config():
    model, params = _gpt_tiny()
    with pytest.raises(ValueError, match="paged=True"):
        ServeEngine(model, params, ServeConfig(n_slots=1, max_len=32,
                                               page_size=8))
    with pytest.raises(ValueError, match="not a multiple"):
        ServeEngine(model, params, ServeConfig(n_slots=1, max_len=30,
                                               paged=True, page_size=8))
    with pytest.raises(ValueError, match="prefix_page"):
        ServeEngine(model, params, ServeConfig(
            n_slots=1, max_len=32, paged=True, page_size=8,
            prefix_cache=True, prefix_page=4,
        ))


def test_paged_statusz_reports_page_pool():
    model, params = _gpt_tiny()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8, paged=True,
        page_size=4,
    ))
    eng.submit(np.arange(8, dtype=np.int32), max_new_tokens=4)
    eng.run()
    doc = eng.statusz()
    pages = doc["kv_pages"]
    assert pages["page_size"] == 4
    assert pages["page_budget"] == 2 * (32 // 4)
    assert pages["pages_free"] == pages["page_budget"]
    assert len(pages["per_slot_pages"]) == 2
