"""OpenAI-compatible HTTP front door (serve/api.py + serve/openai.py).

The serving contract over a REAL socket: an SSE stream is token-exact
vs direct `engine.submit` for the same prompt/params, client
disconnects cancel the request and free its slot (and, on the paged
pool, every page) within a block boundary, validation failures are
structured 400s in the OpenAI error envelope, admission pressure is a
503 with Retry-After, and shutdown is ordered and idempotent.
"""

import json
import socket
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import (
    ApiServer,
    EngineLoop,
    ServeConfig,
    ServeEngine,
)

ALPHABET = '{}[]":,-.0123456789 \nabcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOP\\'
TABLE = list(ALPHABET[:64])
STOI = {c: i for i, c in enumerate(TABLE)}

GPT_TINY = GPTConfig(vocab_size=64, block_size=128, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


def _encode(s):
    return [STOI[c] for c in s]


def _decode(ids):
    return "".join(TABLE[int(i)] for i in ids)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def server(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=4, max_len=128, decode_block=4, bucket=8, api_port=0,
    ), detokenize=_decode)
    srv = ApiServer(eng, encode=_encode, decode=_decode,
                    model_name="gpt-tiny")
    yield srv, eng
    srv.close()


def _post(srv, path, body, timeout=120):
    req = urllib.request.Request(
        srv.url(path), data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _stream_events(srv, body, timeout=120):
    """POST with stream=true over a raw socket; returns parsed SSE
    events (the trailing '[DONE]' sentinel included as a string)."""
    payload = json.dumps({**body, "stream": True}).encode()
    s = socket.create_connection((srv.host, srv.port), timeout=timeout)
    s.sendall(
        b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
        b"Content-Type: application/json\r\nContent-Length: "
        + str(len(payload)).encode() + b"\r\n\r\n" + payload
    )
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(4096)
    head, buf = buf.split(b"\r\n\r\n", 1)
    assert b"200" in head.split(b"\r\n")[0], head
    events = []
    while True:
        while b"\n\n" not in buf:
            chunk = s.recv(4096)
            if not chunk:
                s.close()
                return events
            buf += chunk
        frame, buf = buf.split(b"\n\n", 1)
        frame = frame.strip()
        if not frame.startswith(b"data: "):
            continue  # heartbeat comments
        payload = frame[6:]
        if payload == b"[DONE]":
            s.close()
            events.append("DONE")
            return events
        events.append(json.loads(payload))


# ------------------------------------------------------------- happy path


def test_stream_token_exact_vs_direct_submit(server):
    """Acceptance: the SSE stream carries exactly the tokens
    `engine.submit` produces for the same prompt/params."""
    srv, eng = server
    prompt = list(range(20, 28))
    events = _stream_events(srv, {
        "prompt": prompt, "max_tokens": 12, "temperature": 0,
    })
    assert events[-1] == "DONE"
    chunks = [e for e in events if e != "DONE"]
    text = "".join(c["choices"][0]["text"] for c in chunks)
    terminal = [c for c in chunks if c["choices"][0]["finish_reason"]]
    assert terminal and terminal[-1]["choices"][0]["finish_reason"] == "length"
    assert terminal[-1]["usage"]["completion_tokens"] == 12

    ref = srv.loop.submit(np.asarray(prompt, np.int32), max_new_tokens=12)
    deadline = time.monotonic() + 60
    while not ref.done and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ref.done
    assert text == _decode(ref.tokens)


def test_nonstreaming_completion_shape(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(10, 16)), "max_tokens": 8, "temperature": 0,
    })
    assert st == 200
    assert doc["object"] == "text_completion"
    choice = doc["choices"][0]
    assert choice["finish_reason"] == "length"
    assert doc["usage"] == {"prompt_tokens": 6, "completion_tokens": 8,
                            "total_tokens": 14}
    # same prompt, same params -> same greedy text (served twice)
    st2, _, doc2 = _post(srv, "/v1/completions", {
        "prompt": list(range(10, 16)), "max_tokens": 8, "temperature": 0,
    })
    assert doc2["choices"][0]["text"] == choice["text"]


def test_string_prompt_and_stop_strings(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": "abcd", "max_tokens": 16, "temperature": 0,
    })
    assert st == 200 and len(doc["choices"][0]["text"]) == 16
    gen = doc["choices"][0]["text"]
    stop = gen[2:4]  # a substring the greedy stream will emit
    st, _, doc2 = _post(srv, "/v1/completions", {
        "prompt": "abcd", "max_tokens": 16, "temperature": 0,
        "stop": stop,
    })
    assert st == 200
    assert doc2["choices"][0]["finish_reason"] == "stop"
    assert doc2["choices"][0]["text"].endswith(stop)


def test_chat_completion_shape(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "abc"}],
        "max_tokens": 6, "temperature": 0,
    })
    assert st == 200
    assert doc["object"] == "chat.completion"
    msg = doc["choices"][0]["message"]
    assert msg["role"] == "assistant" and len(msg["content"]) == 6


def test_json_mode_parses(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(5, 10)), "max_tokens": 24, "temperature": 0,
        "response_format": {"type": "json_object"},
    })
    assert st == 200
    assert doc["choices"][0]["finish_reason"] == "stop"
    json.loads(doc["choices"][0]["text"])


def test_models_and_status_surface(server):
    srv, _ = server
    with urllib.request.urlopen(srv.url("/v1/models"), timeout=30) as r:
        models = json.loads(r.read())
    assert models["data"][0]["id"] == "gpt-tiny"
    with urllib.request.urlopen(srv.url("/healthz"), timeout=30) as r:
        assert r.read() == b"ok\n"
    with urllib.request.urlopen(srv.url("/metrics"), timeout=30) as r:
        prom = r.read().decode()
    assert "serve_http_requests" in prom
    assert "serve_http_connections" in prom
    with urllib.request.urlopen(srv.url("/statusz"), timeout=30) as r:
        doc = json.loads(r.read())
    assert "engine" in doc and "slots" in doc


# ----------------------------------------------------------- error mapping


@pytest.mark.parametrize("body,param", [
    ({"prompt": [1, 2], "temperature": -1}, None),
    ({"prompt": [1, 2], "top_p": 0}, None),
    ({"prompt": "abc", "n": 2}, "n"),
    ({"prompt": "abc", "echo": True}, "echo"),
    ({"prompt": [], "max_tokens": 4}, "prompt"),
    ({"prompt": [999999]}, "prompt"),
    ({"prompt": [1, 2], "stop": [1]}, "stop"),
    ({"prompt": [1, 2], "logprobs": 5}, "logprobs"),
    ({"prompt": [1, 2], "response_format": {"type": "xml"}},
     "response_format"),
    ({"prompt": [1, 2], "timeout_s": -1}, "timeout_s"),
])
def test_400_envelope(server, body, param):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", body)
    assert st == 400, doc
    err = doc["error"]
    assert err["type"] == "invalid_request_error"
    assert err["message"]
    if param is not None:
        assert err["param"] == param


def test_400_submit_validation_maps_to_envelope(server):
    """Engine-side ValueErrors (host-side submit validation) come back
    as the same structured envelope — never a traceback."""
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(8)), "max_tokens": 10_000,
    })
    assert st == 400
    assert doc["error"]["code"] == "context_length_exceeded"
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(8)), "top_k": 4096,  # over sample_cap
    })
    assert st == 400
    assert "sample_cap" in doc["error"]["message"]


def test_400_malformed_json(server):
    srv, _ = server
    req = urllib.request.Request(
        srv.url("/v1/completions"), data=b"{not json",
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 400
    assert "not valid JSON" in json.loads(ei.value.read())["error"]["message"]


def test_503_retry_after_when_queue_full(gpt_tiny):
    """A full waiting queue (the admission gate) maps to 503 +
    Retry-After instead of an unbounded backlog. The engine loop is
    deliberately NOT running, so the queue cannot drain mid-test."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=128, decode_block=4, bucket=8, api_port=0,
        max_waiting=2,
    ))
    loop = EngineLoop(eng, start=False)
    srv = ApiServer(eng, decode=_decode, loop=loop)
    try:
        for _ in range(2):
            eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=4)
        st, headers, doc = _post(srv, "/v1/completions", {
            "prompt": [1, 2, 3], "max_tokens": 4,
        })
        assert st == 503
        assert headers.get("Retry-After") == "1"
        assert doc["error"]["code"] == "overloaded"
    finally:
        srv.close()


# -------------------------------------------------- disconnect-driven cancel


def test_disconnect_cancels_and_frees_pages(gpt_tiny):
    """Acceptance: a client dropping mid-stream cancels the request
    within a block boundary — the slot frees, `serve/finish_cancelled`
    counts it, and the paged pool leaks ZERO pages (refcounts return
    to the trash-page-only baseline)."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=128, decode_block=4, bucket=8, api_port=0,
        paged=True, page_size=8,
    ))
    srv = ApiServer(eng, decode=_decode)
    try:
        payload = json.dumps({
            "prompt": [5, 6, 7, 8], "max_tokens": 100, "temperature": 0,
            "stream": True,
        }).encode()
        s = socket.create_connection((srv.host, srv.port), timeout=60)
        s.sendall(
            b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(payload)).encode() + b"\r\n\r\n" + payload
        )
        buf = b""
        while buf.count(b"data: ") < 2:
            buf += s.recv(4096)
        s.close()  # the disconnect
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = eng.metrics.snapshot()
            if (snap.get("serve/finish_cancelled", 0) >= 1
                    and eng.pool.n_active == 0):
                break
            time.sleep(0.02)
        snap = eng.metrics.snapshot()
        assert snap.get("serve/finish_cancelled", 0) == 1, snap
        assert snap["serve/tokens_out"] < 100, "cancel missed the stream"
        assert eng.pool.n_active == 0
        # no leaked pages: free count back to the full budget and the
        # only live refcount is the permanently-held trash page
        assert eng.pool.pages_free == eng.pool.page_budget
        assert int(eng.pool.refcount.sum()) == 1
        assert snap["serve/http_disconnects"] >= 1
    finally:
        srv.close()


def test_timeout_s_maps_to_deadline(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(6)), "max_tokens": 100, "temperature": 0,
        "timeout_s": 0.001,
    })
    assert st == 200
    assert doc["choices"][0]["finish_reason"] == "timeout"


# ------------------------------------------------------------------ close


def test_close_is_ordered_and_idempotent(gpt_tiny):
    """Double-close regression: close() drains, closes the engine, and
    a second close is a no-op — no exception, no double shutdown."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=128, decode_block=4, bucket=8, api_port=0,
        drain_timeout_s=5.0,
    ))
    srv = ApiServer(eng, decode=_decode)
    h = srv.loop.submit(np.arange(4, dtype=np.int32), max_new_tokens=8)
    srv.close()
    assert h.done  # drained, not abandoned
    assert not srv.loop._thread.is_alive()
    srv.close()  # idempotent
    # the port is actually released: a fresh connect fails
    with pytest.raises(OSError):
        socket.create_connection((srv.host, srv.port), timeout=1)
