"""OpenAI-compatible HTTP front door (serve/api.py + serve/openai.py).

The serving contract over a REAL socket: an SSE stream is token-exact
vs direct `engine.submit` for the same prompt/params, client
disconnects cancel the request and free its slot (and, on the paged
pool, every page) within a block boundary, validation failures are
structured 400s in the OpenAI error envelope, admission pressure is a
503 with Retry-After, and shutdown is ordered and idempotent.
"""

import json
import socket
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import (
    ApiServer,
    EngineLoop,
    ServeConfig,
    ServeEngine,
)

ALPHABET = '{}[]":,-.0123456789 \nabcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOP\\'
TABLE = list(ALPHABET[:64])
STOI = {c: i for i, c in enumerate(TABLE)}

GPT_TINY = GPTConfig(vocab_size=64, block_size=128, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


def _encode(s):
    return [STOI[c] for c in s]


def _decode(ids):
    return "".join(TABLE[int(i)] for i in ids)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


@pytest.fixture(scope="module")
def server(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=4, max_len=128, decode_block=4, bucket=8, api_port=0,
    ), detokenize=_decode)
    srv = ApiServer(eng, encode=_encode, decode=_decode,
                    model_name="gpt-tiny")
    yield srv, eng
    srv.close()


def _post(srv, path, body, timeout=120):
    req = urllib.request.Request(
        srv.url(path), data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _stream_events(srv, body, timeout=120):
    """POST with stream=true over a raw socket; returns parsed SSE
    events (the trailing '[DONE]' sentinel included as a string)."""
    payload = json.dumps({**body, "stream": True}).encode()
    s = socket.create_connection((srv.host, srv.port), timeout=timeout)
    s.sendall(
        b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
        b"Content-Type: application/json\r\nContent-Length: "
        + str(len(payload)).encode() + b"\r\n\r\n" + payload
    )
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(4096)
    head, buf = buf.split(b"\r\n\r\n", 1)
    assert b"200" in head.split(b"\r\n")[0], head
    events = []
    while True:
        while b"\n\n" not in buf:
            chunk = s.recv(4096)
            if not chunk:
                s.close()
                return events
            buf += chunk
        frame, buf = buf.split(b"\n\n", 1)
        frame = frame.strip()
        # SSE frames are field lines: chunks now lead with an
        # ``id: <rid>:<offset>`` resume cursor before their data line
        data_lines = [ln for ln in frame.split(b"\n")
                      if ln.startswith(b"data: ")]
        if not data_lines:
            continue  # heartbeat comments
        payload = data_lines[-1][6:]
        if payload == b"[DONE]":
            s.close()
            events.append("DONE")
            return events
        events.append(json.loads(payload))


# ------------------------------------------------------------- happy path


def test_stream_token_exact_vs_direct_submit(server):
    """Acceptance: the SSE stream carries exactly the tokens
    `engine.submit` produces for the same prompt/params."""
    srv, eng = server
    prompt = list(range(20, 28))
    events = _stream_events(srv, {
        "prompt": prompt, "max_tokens": 12, "temperature": 0,
    })
    assert events[-1] == "DONE"
    chunks = [e for e in events if e != "DONE"]
    text = "".join(c["choices"][0]["text"] for c in chunks)
    terminal = [c for c in chunks if c["choices"][0]["finish_reason"]]
    assert terminal and terminal[-1]["choices"][0]["finish_reason"] == "length"
    assert terminal[-1]["usage"]["completion_tokens"] == 12

    ref = srv.loop.submit(np.asarray(prompt, np.int32), max_new_tokens=12)
    deadline = time.monotonic() + 60
    while not ref.done and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ref.done
    assert text == _decode(ref.tokens)


def test_nonstreaming_completion_shape(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(10, 16)), "max_tokens": 8, "temperature": 0,
    })
    assert st == 200
    assert doc["object"] == "text_completion"
    choice = doc["choices"][0]
    assert choice["finish_reason"] == "length"
    assert doc["usage"] == {"prompt_tokens": 6, "completion_tokens": 8,
                            "total_tokens": 14}
    # same prompt, same params -> same greedy text (served twice)
    st2, _, doc2 = _post(srv, "/v1/completions", {
        "prompt": list(range(10, 16)), "max_tokens": 8, "temperature": 0,
    })
    assert doc2["choices"][0]["text"] == choice["text"]


def test_string_prompt_and_stop_strings(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": "abcd", "max_tokens": 16, "temperature": 0,
    })
    assert st == 200 and len(doc["choices"][0]["text"]) == 16
    gen = doc["choices"][0]["text"]
    stop = gen[2:4]  # a substring the greedy stream will emit
    st, _, doc2 = _post(srv, "/v1/completions", {
        "prompt": "abcd", "max_tokens": 16, "temperature": 0,
        "stop": stop,
    })
    assert st == 200
    assert doc2["choices"][0]["finish_reason"] == "stop"
    assert doc2["choices"][0]["text"].endswith(stop)


def test_chat_completion_shape(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/chat/completions", {
        "messages": [{"role": "user", "content": "abc"}],
        "max_tokens": 6, "temperature": 0,
    })
    assert st == 200
    assert doc["object"] == "chat.completion"
    msg = doc["choices"][0]["message"]
    assert msg["role"] == "assistant" and len(msg["content"]) == 6


def test_json_mode_parses(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(5, 10)), "max_tokens": 24, "temperature": 0,
        "response_format": {"type": "json_object"},
    })
    assert st == 200
    assert doc["choices"][0]["finish_reason"] == "stop"
    json.loads(doc["choices"][0]["text"])


def test_models_and_status_surface(server):
    srv, _ = server
    with urllib.request.urlopen(srv.url("/v1/models"), timeout=30) as r:
        models = json.loads(r.read())
    assert models["data"][0]["id"] == "gpt-tiny"
    with urllib.request.urlopen(srv.url("/healthz"), timeout=30) as r:
        assert r.read() == b"ok\n"
    with urllib.request.urlopen(srv.url("/metrics"), timeout=30) as r:
        prom = r.read().decode()
    assert "serve_http_requests" in prom
    assert "serve_http_connections" in prom
    with urllib.request.urlopen(srv.url("/statusz"), timeout=30) as r:
        doc = json.loads(r.read())
    assert "engine" in doc and "slots" in doc


# ----------------------------------------------------------- error mapping


@pytest.mark.parametrize("body,param", [
    ({"prompt": [1, 2], "temperature": -1}, None),
    ({"prompt": [1, 2], "top_p": 0}, None),
    ({"prompt": "abc", "n": 2}, "n"),
    ({"prompt": "abc", "echo": True}, "echo"),
    ({"prompt": [], "max_tokens": 4}, "prompt"),
    ({"prompt": [999999]}, "prompt"),
    ({"prompt": [1, 2], "stop": [1]}, "stop"),
    ({"prompt": [1, 2], "logprobs": 5}, "logprobs"),
    ({"prompt": [1, 2], "response_format": {"type": "xml"}},
     "response_format"),
    ({"prompt": [1, 2], "timeout_s": -1}, "timeout_s"),
])
def test_400_envelope(server, body, param):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", body)
    assert st == 400, doc
    err = doc["error"]
    assert err["type"] == "invalid_request_error"
    assert err["message"]
    if param is not None:
        assert err["param"] == param


def test_400_submit_validation_maps_to_envelope(server):
    """Engine-side ValueErrors (host-side submit validation) come back
    as the same structured envelope — never a traceback."""
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(8)), "max_tokens": 10_000,
    })
    assert st == 400
    assert doc["error"]["code"] == "context_length_exceeded"
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(8)), "top_k": 4096,  # over sample_cap
    })
    assert st == 400
    assert "sample_cap" in doc["error"]["message"]


def test_400_malformed_json(server):
    srv, _ = server
    req = urllib.request.Request(
        srv.url("/v1/completions"), data=b"{not json",
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=30)
    assert ei.value.code == 400
    assert "not valid JSON" in json.loads(ei.value.read())["error"]["message"]


def test_503_retry_after_when_queue_full(gpt_tiny):
    """A full waiting queue (the admission gate) maps to 503 +
    Retry-After instead of an unbounded backlog. The engine loop is
    deliberately NOT running, so the queue cannot drain mid-test."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=128, decode_block=4, bucket=8, api_port=0,
        max_waiting=2,
    ))
    loop = EngineLoop(eng, start=False)
    srv = ApiServer(eng, decode=_decode, loop=loop)
    try:
        for _ in range(2):
            eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=4)
        st, headers, doc = _post(srv, "/v1/completions", {
            "prompt": [1, 2, 3], "max_tokens": 4,
        })
        assert st == 503
        assert headers.get("Retry-After") == "1"
        assert doc["error"]["code"] == "overloaded"
    finally:
        srv.close()


# -------------------------------------------------- disconnect-driven cancel


def test_disconnect_cancels_and_frees_pages(gpt_tiny):
    """Acceptance: a client dropping mid-stream cancels the request
    within a block boundary — the slot frees, `serve/finish_cancelled`
    counts it, and the paged pool leaks ZERO pages (refcounts return
    to the trash-page-only baseline)."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=128, decode_block=4, bucket=8, api_port=0,
        paged=True, page_size=8,
    ))
    srv = ApiServer(eng, decode=_decode)
    try:
        payload = json.dumps({
            "prompt": [5, 6, 7, 8], "max_tokens": 100, "temperature": 0,
            "stream": True,
        }).encode()
        s = socket.create_connection((srv.host, srv.port), timeout=60)
        s.sendall(
            b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\nContent-Length: "
            + str(len(payload)).encode() + b"\r\n\r\n" + payload
        )
        buf = b""
        while buf.count(b"data: ") < 2:
            buf += s.recv(4096)
        s.close()  # the disconnect
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = eng.metrics.snapshot()
            if (snap.get("serve/finish_cancelled", 0) >= 1
                    and eng.pool.n_active == 0):
                break
            time.sleep(0.02)
        snap = eng.metrics.snapshot()
        assert snap.get("serve/finish_cancelled", 0) == 1, snap
        assert snap["serve/tokens_out"] < 100, "cancel missed the stream"
        assert eng.pool.n_active == 0
        # no leaked pages: free count back to the full budget and the
        # only live refcount is the permanently-held trash page
        assert eng.pool.pages_free == eng.pool.page_budget
        assert int(eng.pool.refcount.sum()) == 1
        assert snap["serve/http_disconnects"] >= 1
    finally:
        srv.close()


def test_timeout_s_maps_to_deadline(server):
    srv, _ = server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(6)), "max_tokens": 100, "temperature": 0,
        "timeout_s": 0.001,
    })
    assert st == 200
    assert doc["choices"][0]["finish_reason"] == "timeout"


# ------------------------------------------------------------------ close


def test_close_is_ordered_and_idempotent(gpt_tiny):
    """Double-close regression: close() drains, closes the engine, and
    a second close is a no-op — no exception, no double shutdown."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=128, decode_block=4, bucket=8, api_port=0,
        drain_timeout_s=5.0,
    ))
    srv = ApiServer(eng, decode=_decode)
    h = srv.loop.submit(np.arange(4, dtype=np.int32), max_new_tokens=8)
    srv.close()
    assert h.done  # drained, not abandoned
    assert not srv.loop._thread.is_alive()
    srv.close()  # idempotent
    # the port is actually released: a fresh connect fails
    with pytest.raises(OSError):
        socket.create_connection((srv.host, srv.port), timeout=1)


# --------------------------------------------- request tracing / timeline


@pytest.fixture(scope="module")
def traced_server(gpt_tiny):
    """Front door with the flight recorder + SLO accounting on and a
    1-token decode block, so requests run long enough (many engine
    steps) for the client-wall partition pin to be meaningful."""
    from solvingpapers_tpu.serve.slo import DEFAULT_SLO_TARGETS

    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=128, decode_block=1, bucket=8, api_port=0,
        trace=True, slo_targets=DEFAULT_SLO_TARGETS,
    ), detokenize=_decode)
    srv = ApiServer(eng, encode=_encode, decode=_decode,
                    model_name="gpt-tiny-traced")
    # warm every program shape so the pinned request pays no compile
    _post(srv, "/v1/completions", {"prompt": list(range(8)),
                                   "max_tokens": 4, "temperature": 0})
    yield srv, eng
    srv.close()


def _stream_with_rid(srv, body, rid=None, timeout=120):
    """Raw-socket SSE POST; returns (response headers dict, events,
    t_start, t_done) with the wall clock read immediately around the
    socket's life — the client-observed e2e."""
    payload = json.dumps({**body, "stream": True}).encode()
    hdrs = (b"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n")
    if rid is not None:
        hdrs += b"X-Request-Id: " + rid.encode() + b"\r\n"
    hdrs += b"Content-Length: " + str(len(payload)).encode() + b"\r\n\r\n"
    t_start = time.monotonic()
    s = socket.create_connection((srv.host, srv.port), timeout=timeout)
    s.sendall(hdrs + payload)
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(4096)
    head, buf = buf.split(b"\r\n\r\n", 1)
    lines = head.decode().split("\r\n")
    assert "200" in lines[0], head
    headers = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        headers[k.strip().lower()] = v.strip()
    events = []
    t_done = None
    while True:
        while b"\n\n" not in buf:
            chunk = s.recv(4096)
            if not chunk:
                s.close()
                return headers, events, t_start, t_done or time.monotonic()
        frame, buf = buf.split(b"\n\n", 1)
        frame = frame.strip()
        if not frame.startswith(b"data: "):
            continue
        if frame[6:] == b"[DONE]":
            t_done = time.monotonic()
            s.close()
            return headers, events, t_start, t_done
        events.append(json.loads(frame[6:]))


def _get_json(srv, path):
    try:
        with urllib.request.urlopen(srv.url(path), timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_request_id_round_trip_and_timeline_partition(traced_server):
    """Acceptance: X-Request-Id round-trips, GET /v1/requests/<id>
    returns the end-to-end timeline, and its phases (accept -> parse ->
    queue_handoff -> queue -> prefill -> decode -> sse_drain) partition
    the client-observed e2e wall within 5%."""
    srv, eng = traced_server
    rid = "pin-req-001"
    headers, events, t_start, t_done = _stream_with_rid(
        srv, {"prompt": list(range(12)), "max_tokens": 96,
              "temperature": 0, "slo": "standard"}, rid=rid)
    assert headers.get("x-request-id") == rid
    client_wall = t_done - t_start
    st, ghdrs, doc = _get_json(srv, f"/v1/requests/{rid}")
    assert st == 200
    assert ghdrs.get("X-Request-Id") == rid
    assert doc["request_id"] == rid
    assert doc["state"] == "finished"
    assert doc["finish_reason"] == "length"
    phases = doc["phases"]
    assert set(phases) == {"accept", "parse", "queue_handoff", "queue",
                           "prefill", "decode", "sse_drain"}
    assert all(v >= 0 for v in phases.values())
    # server-side partition is exact by construction (contiguous stamps
    # on one clock)...
    assert doc["phase_sum_s"] == pytest.approx(doc["e2e_s"], abs=2e-5)
    # ...and covers the CLIENT-observed wall within 5% (the remainder
    # is TCP connect + request write ahead of the accept stamp)
    assert doc["phase_sum_s"] == pytest.approx(client_wall, rel=0.05)
    # the timeline carries the request's serving facts
    facts = doc["facts"]
    assert facts["prompt_tokens"] == 12
    assert facts["completion_tokens"] == 96
    assert facts["kv_quant"] is None and facts["kv_exact"] is False
    assert doc["slo"]["class"] == "standard"
    assert doc["slo"]["attained"] in (True, False)
    assert set(doc["slo"]["latencies"]) >= {"ttft_s", "e2e_s"}


def test_request_id_minted_when_absent_or_malformed(traced_server):
    srv, _ = traced_server
    headers, _, _, _ = _stream_with_rid(
        srv, {"prompt": list(range(8)), "max_tokens": 4,
              "temperature": 0})
    minted = headers.get("x-request-id")
    assert minted and len(minted) == 32  # uuid4 hex
    st, _, doc = _get_json(srv, f"/v1/requests/{minted}")
    assert st == 200 and doc["request_id"] == minted
    # hostile/malformed ids are replaced, never echoed back verbatim
    headers, _, _, _ = _stream_with_rid(
        srv, {"prompt": list(range(8)), "max_tokens": 4,
              "temperature": 0}, rid="bad id\x7f!" )
    assert headers.get("x-request-id") != "bad id\x7f!"


def test_request_timeline_unknown_id_404_and_blocking_path(traced_server):
    srv, _ = traced_server
    st, _, doc = _get_json(srv, "/v1/requests/never-seen")
    assert st == 404
    assert doc["error"]["code"] == "request_not_found"
    # non-streaming responses carry the id + timeline too
    req = urllib.request.Request(
        srv.url("/v1/completions"),
        data=json.dumps({"prompt": list(range(6)), "max_tokens": 6,
                         "temperature": 0}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "blocking-1"}, method="POST")
    with urllib.request.urlopen(req, timeout=120) as r:
        assert r.status == 200
        assert r.headers.get("X-Request-Id") == "blocking-1"
        json.loads(r.read())
    st, _, doc = _get_json(srv, "/v1/requests/blocking-1")
    assert st == 200
    assert doc["stream"] is False
    assert doc["phases"]["sse_drain"] >= 0  # response-write drain


def test_http_spans_join_engine_trace(traced_server):
    """The recorder holds http-category spans for served requests, and
    summarize_trace assembles rows with BOTH engine and http phases."""
    from solvingpapers_tpu.metrics.trace import summarize_trace

    srv, eng = traced_server
    rid = "trace-join-1"
    _stream_with_rid(srv, {"prompt": list(range(10)), "max_tokens": 8,
                           "temperature": 0}, rid=rid)
    names = {e.name for e in eng.trace.events() if e.cat == "http"}
    assert {"accept", "parse", "queue_handoff", "sse_drain"} <= names
    accept = next(e for e in eng.trace.events()
                  if e.cat == "http" and e.name == "accept"
                  and (e.args or {}).get("trace_id") == rid)
    summary = summarize_trace(eng.trace.to_chrome())
    row = next(r for r in summary["requests"]
               if r["req"] == accept.req)
    assert {"accept", "parse", "queue_handoff",
            "sse_drain"} <= set(row["http_phases"])
    assert row["e2e_s"] > row["total_s"]
    assert "http" in summary


def test_service_tier_alias_is_best_effort(traced_server):
    """The explicit `slo` field validates strictly (typo -> 400), but
    OpenAI's `service_tier` only maps when it names a configured class
    — stock values this server has no class for must not turn a valid
    OpenAI request into a 400."""
    srv, _ = traced_server
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(6)), "max_tokens": 4, "temperature": 0,
        "service_tier": "flex",  # documented OpenAI value, no class here
    })
    assert st == 200, doc
    st, _, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(6)), "max_tokens": 4, "temperature": 0,
        "service_tier": "interactive",  # names a configured class
    })
    assert st == 200
    st, hdrs, doc = _post(srv, "/v1/completions", {
        "prompt": list(range(6)), "max_tokens": 4, "temperature": 0,
        "slo": "platinum",  # explicit field stays strict
    })
    assert st == 400
    assert "unknown SLO class" in doc["error"]["message"]
    assert hdrs.get("X-Request-Id")  # even the 400 carries an id


def test_400_envelope_carries_request_id(traced_server):
    srv, _ = traced_server
    req = urllib.request.Request(
        srv.url("/v1/completions"),
        data=json.dumps({"prompt": "x", "temperature": -1}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "err-1"}, method="POST")
    try:
        urllib.request.urlopen(req, timeout=60)
        raise AssertionError("expected a 400")
    except urllib.error.HTTPError as e:
        assert e.code == 400
        assert e.headers.get("X-Request-Id") == "err-1"
        assert json.loads(e.read())["error"]["type"] == \
            "invalid_request_error"


# ------------------------------------------------------- fault tolerance


from conftest import assert_no_leaks  # noqa: E402


def _fault_server(gpt_tiny, plan, **cfg_kw):
    model, params = gpt_tiny
    base = dict(n_slots=2, max_len=128, decode_block=4, bucket=8,
                api_port=0, fault_plan=plan)
    base.update(cfg_kw)
    eng = ServeEngine(model, params, ServeConfig(**base),
                      detokenize=_decode)
    srv = ApiServer(eng, encode=_encode, decode=_decode,
                    model_name="gpt-tiny")
    return srv, eng


def test_sse_error_protocol_on_quarantine(gpt_tiny):
    """The mid-stream error contract: a quarantined stream must end
    with a structured OpenAI error event, a terminal chunk carrying
    finish_reason "error", and [DONE] — never a silently dropped
    connection."""
    plan = [dict(site="decode", kind="nan", visit=1, slot=0)]
    srv, eng = _fault_server(gpt_tiny, plan)
    try:
        events = _stream_events(srv, {
            "prompt": list(range(20, 28)), "max_tokens": 24,
            "temperature": 0,
        })
        assert events[-1] == "DONE", "stream must terminate cleanly"
        err_events = [e for e in events[:-1] if "error" in e]
        assert err_events, "no structured error event before [DONE]"
        assert err_events[0]["error"]["type"] == "server_error"
        terminal = [e for e in events[:-1] if "choices" in e
                    and e["choices"][0]["finish_reason"]]
        assert terminal and \
            terminal[-1]["choices"][0]["finish_reason"] == "error"
        assert_no_leaks(eng)
    finally:
        srv.close()


def test_injected_socket_reset_drives_disconnect_cancel(gpt_tiny):
    """A socket_reset fault at the sse_write site maps to the
    disconnect path: the engine cancels at the block boundary and the
    drained pool leaks nothing."""
    plan = [dict(site="sse_write", kind="socket_reset", visit=1)]
    srv, eng = _fault_server(gpt_tiny, plan, paged=True, page_size=8)
    try:
        events = _stream_events(srv, {
            "prompt": list(range(16, 24)), "max_tokens": 64,
            "temperature": 0,
        })
        assert "DONE" not in events, "reset stream cannot complete"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = eng.metrics.snapshot()
            if snap.get("serve/finish_cancelled"):
                break
            time.sleep(0.02)
        assert eng.metrics.snapshot().get("serve/finish_cancelled") == 1.0
        assert snap.get("serve/fault_injected") == 1.0
        deadline = time.monotonic() + 10
        while eng.pool.n_active and time.monotonic() < deadline:
            time.sleep(0.02)
        with srv.loop.lock:
            assert_no_leaks(eng)
    finally:
        srv.close()


def test_retry_after_is_jittered_and_carries_rung(gpt_tiny):
    """503s must not synchronize retry herds: the Retry-After hint is
    drawn per response (observably non-constant over a handful of
    draws) and the current degradation rung rides a response header."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=128, decode_block=4, bucket=8, api_port=0,
        max_waiting=1,
    ), detokenize=_decode)
    loop = EngineLoop(eng, start=False)  # engine never steps: queue fills
    srv = ApiServer(eng, encode=_encode, decode=_decode,
                    model_name="gpt-tiny", loop=loop)
    try:
        # fill the 1-deep waiting queue directly (the loop never steps,
        # so it stays full and every HTTP submission bounces 503)
        srv.loop.submit(np.asarray([1, 2, 3], np.int32),
                        max_new_tokens=4)
        hints = set()
        for _ in range(12):
            st, hdrs, doc = _post(srv, "/v1/completions",
                                  {"prompt": [1, 2, 3], "max_tokens": 4})
            if st != 503:
                continue
            assert doc["error"]["code"] == "overloaded"
            assert hdrs.get("X-Degradation-Rung") == "0"
            retry = int(hdrs["Retry-After"])
            assert 1 <= retry <= 4
            hints.add(retry)
        assert len(hints) > 1, f"Retry-After never varied: {hints}"
    finally:
        srv.close()


def test_unhealthy_engine_503s_then_recovers_token_exact(gpt_tiny):
    """End-to-end recovery through the front door: persistent systemic
    faults drain the engine (blocking response = 500 envelope, /healthz
    = 503, new submissions = 503 engine_unhealthy), and after the
    backoff a fresh HTTP request streams token-exactly vs direct
    submit on the recovered engine."""
    plan = [dict(site="decode", kind="xla_error", visit=0, count=2)]
    srv, eng = _fault_server(
        gpt_tiny, plan, fault_max_retries=1, fault_retry_backoff_s=0.001,
        fault_recover_backoff_s=0.6,
    )
    try:
        prompt = list(range(30, 38))
        st, _, doc = _post(srv, "/v1/completions",
                           {"prompt": prompt, "max_tokens": 12,
                            "temperature": 0})
        assert st == 500, (st, doc)
        assert doc["error"]["code"] == "engine_error"
        with urllib.request.urlopen(srv.url("/healthz"),
                                    timeout=30) as r:
            raise AssertionError(f"healthz answered {r.status}")
    except urllib.error.HTTPError as e:
        assert e.code == 503 and e.read() == b"unhealthy\n"
        # inside the backoff: the front door sheds with the reason
        st, hdrs, doc = _post(srv, "/v1/completions",
                              {"prompt": prompt, "max_tokens": 12,
                               "temperature": 0})
        assert st == 503 and doc["error"]["code"] == "engine_unhealthy"
        assert "Retry-After" in hdrs
        time.sleep(0.65)
        st, _, doc = _post(srv, "/v1/completions",
                           {"prompt": prompt, "max_tokens": 12,
                            "temperature": 0})
        assert st == 200, (st, doc)
        assert doc["choices"][0]["finish_reason"] == "length"
        with urllib.request.urlopen(srv.url("/healthz"),
                                    timeout=30) as r:
            assert r.status == 200
        # token-exact vs direct submit on the recovered engine
        ref = srv.loop.submit(np.asarray(prompt, np.int32),
                              max_new_tokens=12)
        deadline = time.monotonic() + 60
        while not ref.done and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ref.done
        assert doc["choices"][0]["text"] == _decode(ref.tokens)
    finally:
        srv.close()


def test_server_close_bounded_under_injected_stall(gpt_tiny):
    """SIGTERM cannot hang on a wedged request: with every step
    stalling, ApiServer.close() force-cancels and returns within its
    bound instead of waiting out 64 stalled steps."""
    plan = [dict(site="decode", kind="stall", visit=0, stall_s=0.3,
                 count=1000)]
    srv, eng = _fault_server(gpt_tiny, plan, drain_timeout_s=0.2)
    req = srv.loop.submit(np.asarray(list(range(8)), np.int32),
                          max_new_tokens=64)
    time.sleep(0.2)  # let the loop start stepping (and stalling)
    t0 = time.monotonic()
    srv.close()
    took = time.monotonic() - t0
    assert took < 6.0, f"close took {took:.1f}s — unbounded shutdown"
    assert req.done
    assert_no_leaks(eng)
