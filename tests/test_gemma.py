"""Gemma model tests: forward/grouped-head structure, cached decode
equivalence, loss-goes-down smoke, and the shared-RoPE speedup premise
(decode is jitted with a cache — the reference's cell 21 latency complaint
stemmed from rebuilding rotation matrices per token per layer).
"""

import jax
import jax.numpy as jnp
import numpy as np

from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator
from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.models.gemma import Gemma, GemmaConfig
from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

TINY = GemmaConfig(
    vocab_size=64, max_seq_len=32, dim=32, n_layers=2, n_heads=4, n_kv_heads=2,
    dropout=0.0,
)


def test_forward_shape_and_geglu_hidden():
    model = Gemma(TINY)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = model.init({"params": jax.random.key(0)}, toks)["params"]
    logits, caches = model.apply({"params": params}, toks)
    assert logits.shape == (2, 16, TINY.vocab_size)
    assert caches is None
    # GeGLU hidden = 4*dim (gemma.ipynb cell 9)
    ffn = params["block_0"]["ffn"]
    assert ffn["gate"]["kernel"].shape == (TINY.dim, 4 * TINY.dim)
    assert "bias" not in ffn["gate"]


def test_cached_decode_equals_full_forward():
    model = Gemma(TINY)
    rng = jax.random.key(1)
    prompt = jax.random.randint(rng, (2, 5), 0, TINY.vocab_size)
    params = model.init({"params": rng}, prompt)["params"]

    out = generate(model, params, prompt, rng, max_new_tokens=8)
    toks = prompt
    for _ in range(8):
        logits, _ = model.apply({"params": params}, toks, deterministic=True)
        toks = jnp.concatenate([toks, jnp.argmax(logits[:, -1], -1)[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(toks))


def test_loss_decreases():
    _, train_toks, _ = load_char_corpus(synthetic_chars=20_000)
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(data=1, fsdp=1, model=1), jax.devices()[:1])
    cfg = TrainConfig(
        steps=40, batch_size=8, log_every=100, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=5, total_steps=40),
    )
    trainer = Trainer(Gemma(TINY), cfg, mesh=mesh)
    it = lm_batch_iterator(train_toks, 8, TINY.max_seq_len, seed=0)
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    state, m0 = trainer._train_step(state, b0)
    first = float(m0["train_loss"])
    for _ in range(cfg.steps):
        state, m = trainer._train_step(state, next(it))
    assert float(m["train_loss"]) < first - 0.3
