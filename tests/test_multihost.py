"""REAL multi-process (multi-"host") tests: two spawned processes form a
jax.distributed cluster over CPU (Gloo collectives across processes — the
DCN stand-in this environment allows), build one global mesh through
`sharding.distributed.initialize`, feed host-local batch slices, and run a
sharded GPT train step. The resulting loss/params must match the
single-process run on the same global batch — upgrading the multi-host row
(SURVEY.md §2.3) from unit-tested helpers to an executed cross-process
training step.

These tests spawn subprocesses with their own JAX runtimes, so they do NOT
use the session fixture's in-process devices.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent("""
    import json, os, sys
    pid = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from solvingpapers_tpu.sharding.distributed import (
        host_batch_slice,
        host_seed,
        initialize,
    )

    assert initialize(f"localhost:{port}", num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs

    import jax.numpy as jnp
    import numpy as np

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    cfg = GPTConfig(vocab_size=64, block_size=16, dim=16, n_layers=1,
                    n_heads=2, dropout=0.0)
    tcfg = TrainConfig(steps=1, batch_size=8, log_every=100, eval_every=0,
                       optimizer=OptimizerConfig(name="sgd", max_lr=1e-1,
                                                 warmup_steps=0, total_steps=4))
    mesh = create_mesh(MeshConfig(data=-1))  # all 4 global devices
    trainer = Trainer(GPT(cfg), tcfg, mesh=mesh)

    # the SAME deterministic global batch on every host; each host feeds
    # only its slice via jax.make_array_from_process_local_data
    rng = np.random.default_rng(0)
    gx = rng.integers(0, cfg.vocab_size, size=(tcfg.batch_size, cfg.block_size))
    gy = np.roll(gx, -1, axis=1)
    per, off = host_batch_slice(tcfg.batch_size)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(("data", "fsdp"), None))
    batch = {
        "x": jax.make_array_from_process_local_data(
            sh, gx[off:off + per].astype(np.int32), gx.shape),
        "y": jax.make_array_from_process_local_data(
            sh, gy[off:off + per].astype(np.int32), gy.shape),
    }
    state = trainer.init_state(batch)
    trainer._build_steps()
    state, metrics = trainer._train_step(state, batch)
    loss = float(jax.device_get(metrics["train_loss"]))
    p0 = np.asarray(jax.device_get(
        jax.tree.leaves(state.params)[0])).ravel()[:4].tolist()
    seeds = host_seed(7)
    print("RESULT " + json.dumps({
        "pid": pid, "loss": loss, "p0": p0, "host_seed": seeds,
        "devices": len(jax.devices()),
    }))
""")


def _free_port() -> str:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return str(s.getsockname()[1])


def _run_cluster(nprocs=2):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), str(nprocs), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(nprocs)
    ]
    results = {}
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            assert p.returncode == 0, out[-2000:]
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    r = json.loads(line[len("RESULT "):])
                    results[r["pid"]] = r
    finally:
        for p in procs:  # no orphaned coordinators holding the port
            if p.poll() is None:
                p.kill()
    assert len(results) == nprocs, results
    return results


@pytest.mark.multihost  # deselect with -m "not multihost" where TCP is blocked
def test_two_process_training_step_matches_single_process():
    res = _run_cluster()
    # both processes see the 4-device global mesh and agree on the loss
    assert res[0]["devices"] == 4 and res[1]["devices"] == 4
    np.testing.assert_allclose(res[0]["loss"], res[1]["loss"], rtol=1e-6)
    np.testing.assert_allclose(res[0]["p0"], res[1]["p0"], rtol=1e-6)
    # per-host seeds are distinct and deterministic
    assert res[0]["host_seed"] != res[1]["host_seed"]
    assert res[0]["host_seed"] == 7 * 1_000_003

    # single-process oracle on the identical global batch
    oracle_port = _free_port()
    code = _WORKER.replace('int(sys.argv[1])', '0').replace(
        'int(sys.argv[2])', '1')
    code = code.replace('device_count=2', 'device_count=4')
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    out = subprocess.run(
        [sys.executable, "-c", code, "0", "1", oracle_port],
        capture_output=True, text=True, env=env, timeout=300,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    single = json.loads(
        [l for l in out.stdout.splitlines() if l.startswith("RESULT ")][0][7:]
    )
    np.testing.assert_allclose(res[0]["loss"], single["loss"], rtol=1e-5)
    np.testing.assert_allclose(res[0]["p0"], single["p0"], rtol=1e-4)
