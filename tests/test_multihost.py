"""REAL multi-process (multi-"host") tests: two spawned processes form a
jax.distributed cluster over CPU (Gloo collectives across processes — the
DCN stand-in this environment allows), build one global mesh through
`sharding.distributed.initialize`, feed host-local batch slices, and run a
sharded GPT train step. The resulting loss/params must match the
single-process run on the same global batch — upgrading the multi-host row
(SURVEY.md §2.3) from unit-tested helpers to an executed cross-process
training step.

These tests spawn subprocesses with their own JAX runtimes, so they do NOT
use the session fixture's in-process devices.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent("""
    import json, os, sys
    pid = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from solvingpapers_tpu.sharding.distributed import (
        host_batch_slice,
        host_seed,
        initialize,
    )

    assert initialize(f"localhost:{port}", num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs

    import numpy as np

    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    cfg = GPTConfig(vocab_size=64, block_size=16, dim=16, n_layers=1,
                    n_heads=2, dropout=0.0)
    tcfg = TrainConfig(steps=1, batch_size=8, log_every=100, eval_every=0,
                       optimizer=OptimizerConfig(name="sgd", max_lr=1e-1,
                                                 warmup_steps=0, total_steps=4))
    mesh = create_mesh(MeshConfig(data=-1))  # all 4 global devices
    trainer = Trainer(GPT(cfg), tcfg, mesh=mesh)

    # the SAME deterministic global batch on every host; each host feeds
    # only its slice via jax.make_array_from_process_local_data
    rng = np.random.default_rng(0)
    gx = rng.integers(0, cfg.vocab_size, size=(tcfg.batch_size, cfg.block_size))
    gy = np.roll(gx, -1, axis=1)
    per, off = host_batch_slice(tcfg.batch_size)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(("data", "fsdp"), None))
    batch = {
        "x": jax.make_array_from_process_local_data(
            sh, gx[off:off + per].astype(np.int32), gx.shape),
        "y": jax.make_array_from_process_local_data(
            sh, gy[off:off + per].astype(np.int32), gy.shape),
    }
    state = trainer.init_state(batch)
    trainer._build_steps()
    state, metrics = trainer._train_step(state, batch)
    loss = float(jax.device_get(metrics["train_loss"]))
    p0 = np.asarray(jax.device_get(
        jax.tree.leaves(state.params)[0])).ravel()[:4].tolist()
    seeds = host_seed(7)
    print("RESULT " + json.dumps({
        "pid": pid, "loss": loss, "p0": p0, "host_seed": seeds,
        "devices": len(jax.devices()),
    }))
""")


def _free_port() -> str:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return str(s.getsockname()[1])


def _run_cluster(worker_src=None, nprocs=2, _retries=1):
    """Spawn an nprocs jax.distributed cluster running `worker_src` and
    collect each process's RESULT line. Retries once: _free_port has an
    inherent bind-release-rebind race if another process steals the port
    before the coordinator binds it."""
    worker_src = worker_src or _WORKER
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, str(i), str(nprocs), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for i in range(nprocs)
    ]
    results = {}
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            assert p.returncode == 0, out[-2000:]
            for line in out.splitlines():
                if line.startswith("RESULT "):
                    r = json.loads(line[len("RESULT "):])
                    results[r["pid"]] = r
    except AssertionError:
        if _retries > 0:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            return _run_cluster(worker_src, nprocs, _retries - 1)
        raise
    finally:
        for p in procs:  # no orphaned coordinators holding the port
            if p.poll() is None:
                p.kill()
    assert len(results) == nprocs, results
    return results


@pytest.mark.multihost  # deselect with -m "not multihost" where TCP is blocked
def test_two_process_training_step_matches_single_process():
    res = _run_cluster()
    # both processes see the 4-device global mesh and agree on the loss
    assert res[0]["devices"] == 4 and res[1]["devices"] == 4
    np.testing.assert_allclose(res[0]["loss"], res[1]["loss"], rtol=1e-6)
    np.testing.assert_allclose(res[0]["p0"], res[1]["p0"], rtol=1e-6)
    # per-host seeds are distinct and deterministic
    assert res[0]["host_seed"] != res[1]["host_seed"]
    assert res[0]["host_seed"] == 7 * 1_000_003

    # single-process oracle on the identical global batch (4 local devices)
    single = _run_cluster(
        _WORKER.replace("device_count=2", "device_count=4"), nprocs=1
    )[0]
    # atol: cross-process Gloo reduction order vs single-process on values
    # that can be gradient-sized near zero (first leaf is a bias)
    np.testing.assert_allclose(res[0]["loss"], single["loss"],
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(res[0]["p0"], single["p0"],
                               rtol=1e-4, atol=1e-6)


_CP_WORKER = textwrap.dedent("""
    import json, os, sys
    pid = int(sys.argv[1])
    nprocs = int(sys.argv[2])
    port = sys.argv[3]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from solvingpapers_tpu.sharding.distributed import initialize

    assert initialize(f"localhost:{port}", num_processes=nprocs, process_id=pid)

    import jax.numpy as jnp
    import numpy as np

    from solvingpapers_tpu import ops
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh
    from solvingpapers_tpu.sharding.ring_attention import ring_attention

    # context axis spans BOTH processes: the ring's ppermute crosses the
    # process boundary over the Gloo transport (the DCN stand-in)
    mesh = create_mesh(MeshConfig(data=1, context=4))
    rng = np.random.default_rng(3)
    qkv = rng.standard_normal((2, 32, 2, 8)).astype(np.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P

    sh = NamedSharding(mesh, P(("data", "fsdp"), "context", None, None))
    per = qkv.shape[1] // nprocs
    local = qkv[:, pid * per:(pid + 1) * per]
    q = jax.make_array_from_process_local_data(sh, local, qkv.shape)
    out = ring_attention(q, q, q, mesh, causal=True)
    # gather this process's output shard and compare to the local dense ref
    ref = ops.dot_product_attention(
        jnp.asarray(qkv), jnp.asarray(qkv), jnp.asarray(qkv), causal=True
    )
    err = 0.0
    for shard in out.addressable_shards:
        sl = shard.index
        err = max(err, float(jnp.max(jnp.abs(
            shard.data - jax.device_get(ref[sl])))))
    print("RESULT " + json.dumps({"pid": pid, "err": err}))
""")


@pytest.mark.multihost
def test_ring_attention_crosses_process_boundary():
    """Ring attention's ppermute KV rotation over a context axis spanning
    two PROCESSES == dense attention — the collectives ride the
    cross-process transport, the closest this environment gets to DCN."""
    results = _run_cluster(_CP_WORKER)
    for r in results.values():
        assert r["err"] < 2e-5, results
