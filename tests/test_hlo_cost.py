"""Program-anatomy observatory tests (metrics/hlo_cost.py + the
CompileRegistry anatomy/hlo_dir integration).

The contracts under test:
  * `parse_hlo_costs` classifies defining ops into the documented
    categories with output-shape bytes and XLA-convention flops, skips
    sub-computation parameters, ranks top ops, and keeps the jax-level
    op_name source;
  * on a PINNED known program the ledger's flops/bytes totals reconcile
    with the executable's own `cost_analysis()` within tolerance
    (flops tight — the conventions match; bytes within the documented
    output-shape-proxy factor);
  * the anatomy surface is present IFF the observatory parses it:
    `CompileRegistry(anatomy=True)` -> per-program `anatomy` in
    `snapshot()["programs"]` / `anatomy_stats()`; a registry without
    the flag has NO anatomy key anywhere;
  * an engine with `xla_obs` exposes `compile.programs.<name>.anatomy`
    through /statusz with a paged decode program whose ledger actually
    names gather ops, and the category totals reconcile with the
    program's recorded cost_analysis flops;
  * traces: compile events carry the anatomy ledger, `summarize_trace`
    rebuilds an "anatomy" section present IFF the events carry it —
    PR-4/5-era traces (no anatomy args) summarize with the key ABSENT;
  * `obs_hlo_dir` dumps one HLO text file per TRUE compile, atomically,
    with sanitized names;
  * `ServeMetrics.snapshot()` survives a raising gauge provider: warn
    once, skip its keys, keep every healthy provider reporting.
"""

import json
import os
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from solvingpapers_tpu.metrics.hlo_cost import (
    CATEGORIES,
    classify_op,
    format_anatomy,
    parse_hlo_costs,
)
from solvingpapers_tpu.metrics.trace import summarize_trace
from solvingpapers_tpu.metrics.xla_obs import CompileRegistry, clear_aot_cache
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import ServeConfig, ServeEngine

pytestmark = pytest.mark.fast

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompts(n, seed=0, lo=4, hi=24):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, GPT_TINY.vocab_size,
                     size=int(rng.integers(lo, hi))).astype(np.int32)
        for _ in range(n)
    ]


# ------------------------------------------------------------- the parser


CRAFTED_HLO = """\
HloModule jit_f, entry_computation_layout={(f32[8,16]{1,0})->f32[8,4]{1,0}}

%fused_computation (param_0.1: f32[8,16]) -> f32[8,16] {
  %param_0.1 = f32[8,16]{1,0} parameter(0)
  %constant.1 = f32[] constant(0)
  %broadcast.1 = f32[8,16]{1,0} broadcast(f32[] %constant.1), dimensions={}
  ROOT %maximum.1 = f32[8,16]{1,0} maximum(f32[8,16]{1,0} %param_0.1, f32[8,16]{1,0} %broadcast.1), metadata={op_name="jit(f)/relu/max" source_file="x.py" source_line=3}
}

ENTRY %main.9 (Arg_0.1: f32[8,16]) -> f32[8,4] {
  %Arg_0.1 = f32[8,16]{1,0} parameter(0)
  %idx = s32[3]{0} constant({0, 1, 2})
  %relu_fusion = f32[8,16]{1,0} fusion(f32[8,16]{1,0} %Arg_0.1), kind=kLoop, calls=%fused_computation
  %weights = f32[16,4]{1,0} constant({...})
  %dot.3 = f32[8,4]{1,0} dot(f32[8,16]{1,0} %relu_fusion, f32[16,4]{1,0} %weights), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/dot_general"}
  %gather.2 = f32[3,4]{1,0} gather(f32[8,4]{1,0} %dot.3, s32[3]{0} %idx), offset_dims={1}, collapsed_slice_dims={0}
  %convert.5 = bf16[8,4]{1,0} convert(f32[8,4]{1,0} %dot.3)
  %dus.6 = f32[8,4]{1,0} dynamic-update-slice(f32[8,4]{1,0} %dot.3, f32[3,4]{1,0} %gather.2, s32[] %idx, s32[] %idx)
  %cc.7 = f32[8,4]{1,0} custom-call(f32[8,4]{1,0} %dus.6), custom_call_target="foo"
  ROOT %scatter.8 = f32[8,4]{1,0} scatter(f32[8,4]{1,0} %cc.7, s32[3]{0} %idx, f32[3,4]{1,0} %gather.2), to_apply=%add_comp
}
"""


def test_parser_categories_flops_bytes():
    led = parse_hlo_costs(CRAFTED_HLO)
    cats = led["categories"]
    # one op per named category surfaced from the crafted module
    assert cats["dot"]["ops"] == 1
    assert cats["gather"]["ops"] == 1
    assert cats["scatter"]["ops"] == 1
    assert cats["convert"]["ops"] == 1
    assert cats["fusion"]["ops"] == 1
    assert cats["dynamic-slice"]["ops"] == 1
    assert cats["custom-call"]["ops"] == 1
    # dot flops = 2 * out(8*4) * contraction(16) = 1024, parsed from the
    # operand shape + lhs_contracting_dims
    assert cats["dot"]["flops"] == 2 * 8 * 4 * 16
    # data movement is zero-flop; elementwise counts output elements
    assert cats["gather"]["flops"] == 0
    assert cats["scatter"]["flops"] == 0
    assert cats["convert"]["flops"] == 8 * 4
    # output-shape bytes: gather (3,4) f32 = 48; convert (8,4) bf16 = 64
    assert cats["gather"]["bytes"] == 48
    assert cats["convert"]["bytes"] == 64
    # ENTRY parameter counted (argument traffic), fused-computation
    # parameter skipped (it aliases an operand)
    assert cats["parameter"]["ops"] == 1
    assert cats["parameter"]["bytes"] == 8 * 16 * 4
    # the fused maximum is counted in "other" with its flops
    assert cats["other"]["flops"] >= 8 * 16
    assert led["ops"] == sum(c["ops"] for c in cats.values())
    assert led["flops"] == sum(c["flops"] for c in cats.values())
    assert led["bytes"] == sum(c["bytes"] for c in cats.values())
    # every category name is a documented one
    assert set(cats) <= set(CATEGORIES)


def test_parser_top_ops_ranked_with_source():
    led = parse_hlo_costs(CRAFTED_HLO, top_k=3)
    top = led["top_ops"]
    assert len(top) == 3
    weights = [max(t["flops"], t["bytes"]) for t in top]
    assert weights == sorted(weights, reverse=True)
    # the dot is the heaviest (1024 flops) and carries its op_name
    assert top[0]["name"] == "dot.3"
    assert top[0]["source"] == "jit(f)/dot_general"


def test_parser_empty_and_format():
    led = parse_hlo_costs("")
    assert led == {"ops": 0, "flops": 0, "bytes": 0, "categories": {},
                   "top_ops": []}
    assert format_anatomy({}) == ""
    text = format_anatomy({"decode_block": parse_hlo_costs(CRAFTED_HLO)})
    assert "decode_block" in text and "gather" in text
    assert "heaviest ops" in text


def test_classify_op_mapping():
    assert classify_op("gather") == "gather"
    assert classify_op("dynamic-update-slice") == "dynamic-slice"
    assert classify_op("convolution") == "dot"
    assert classify_op("maximum") == "other"


def test_ledger_reconciles_with_cost_analysis_on_pinned_program():
    """The acceptance pin: on a known program (matmul + relu + gather —
    the categories the decomposition cares about), the ledger's flops
    total matches cost_analysis() within 10% and the bytes total is
    within the documented output-shape-proxy factor [0.5x, 2x]."""

    def f(a, b, t):
        x = jnp.dot(a, b)
        return x[t], jax.nn.relu(x).astype(jnp.bfloat16)

    a = jnp.zeros((64, 32))
    b = jnp.zeros((32, 128))
    t = jnp.zeros((8,), jnp.int32)
    compiled = jax.jit(f).lower(a, b, t).compile()
    led = parse_hlo_costs(compiled.as_text())
    ca = compiled.cost_analysis()
    d = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    flops = float(d.get("flops", 0.0))
    nbytes = float(d.get("bytes accessed", 0.0))
    if flops <= 0 or nbytes <= 0:
        pytest.skip("backend reports no cost_analysis totals")
    assert abs(led["flops"] - flops) <= 0.10 * flops, (led["flops"], flops)
    assert 0.5 * nbytes <= led["bytes"] <= 2.0 * nbytes, (
        led["bytes"], nbytes)
    # the dot dominates and is categorized as such
    assert led["categories"]["dot"]["flops"] >= 0.9 * flops


# ------------------------------------------- registry anatomy key surface


def _run_registry(anatomy: bool, hlo_dir=None):
    clear_aot_cache()
    reg = CompileRegistry(anatomy=anatomy, hlo_dir=hlo_dir)

    def f(a, b):
        return jnp.dot(a, b)

    jitted = jax.jit(f)
    args = (jnp.ones((8, 16)), jnp.ones((16, 4)))
    reg.call("matmul", (8,), jitted, args)
    reg.call("matmul", (8,), jitted, args)
    return reg


def test_registry_anatomy_present_iff_enabled():
    reg = _run_registry(anatomy=True)
    snap = reg.snapshot()
    anatomy = snap["programs"]["matmul"].get("anatomy")
    assert anatomy, "anatomy missing with the flag on"
    assert anatomy["categories"]["dot"]["flops"] == 2 * 8 * 4 * 16
    stats = reg.anatomy_stats()
    assert "matmul" in stats and stats["matmul"]["ops"] > 0

    off = _run_registry(anatomy=False)
    snap_off = off.snapshot()
    assert "anatomy" not in snap_off["programs"]["matmul"]
    assert off.anatomy_stats() == {}


def test_registry_hlo_dir_dumps_one_file_per_signature(tmp_path):
    hlo_dir = tmp_path / "hlo"
    _run_registry(anatomy=True, hlo_dir=str(hlo_dir))
    files = sorted(os.listdir(hlo_dir))
    assert len(files) == 1, files  # one signature, one TRUE compile
    assert files[0].startswith("matmul__") and files[0].endswith(".hlo.txt")
    text = (hlo_dir / files[0]).read_text()
    assert "HloModule" in text
    assert not [f for f in files if f.startswith(".hlo_tmp_")]


# --------------------------------------------- engine + statusz + trace


def test_engine_statusz_carries_paged_anatomy(gpt_tiny):
    """A paged engine's decode program must expose an anatomy ledger
    through the statusz document that actually NAMES the paged tax:
    gather ops present, and the ledger flops reconciling with the
    program's recorded cost_analysis flops (within the elementwise-
    convention tolerance)."""
    model, params = gpt_tiny
    clear_aot_cache()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        paged=True, page_size=8, xla_obs=True,
    ))
    for p in _prompts(2, lo=4, hi=8):
        eng.submit(p, max_new_tokens=8)
    eng.run()
    doc = eng.statusz()
    progs = doc["compile"]["programs"]
    decode = progs.get("decode_block")
    assert decode is not None
    anatomy = decode.get("anatomy")
    assert anatomy, "paged decode program has no anatomy ledger"
    assert anatomy["categories"].get("gather", {}).get("ops", 0) > 0, (
        "the paged decode gather does not appear in the ledger")
    cost_flops = decode["flops_per_call"]
    if cost_flops > 0:
        assert 0.5 * cost_flops <= anatomy["flops"] <= 2.0 * cost_flops, (
            anatomy["flops"], cost_flops)
    # the document is JSON-serializable end to end (the statusz wire
    # contract)
    json.dumps(doc, default=str)
    eng.close()


def test_trace_anatomy_section_present_iff_recorded(gpt_tiny, tmp_path):
    model, params = gpt_tiny
    clear_aot_cache()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        trace=True, xla_obs=True,
    ))
    for p in _prompts(2, lo=4, hi=8):
        eng.submit(p, max_new_tokens=6)
    eng.run()
    path = tmp_path / "trace.json"
    eng.trace.export_chrome(str(path))
    eng.close()
    summary = summarize_trace(str(path))
    assert "anatomy" in summary
    assert "decode_block" in summary["anatomy"]
    assert summary["anatomy"]["decode_block"]["ops"] > 0

    # PR-4/5-era trace: same events with the anatomy args stripped must
    # summarize with the key ABSENT — pinned backward compat
    events = json.loads(path.read_text())["traceEvents"]
    for e in events:
        if e.get("cat") == "xla" and (e.get("args") or {}).get("anatomy"):
            del e["args"]["anatomy"]
    old = summarize_trace(events)
    assert "anatomy" not in old


def test_trace_summary_cli_prints_anatomy(gpt_tiny, tmp_path, capsys):
    from solvingpapers_tpu.cli import main as cli_main

    model, params = gpt_tiny
    clear_aot_cache()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        trace=True, xla_obs=True,
    ))
    for p in _prompts(2, lo=4, hi=8):
        eng.submit(p, max_new_tokens=6)
    eng.run()
    path = tmp_path / "trace.json"
    eng.trace.export_chrome(str(path))
    eng.close()
    rc = cli_main(["trace-summary", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "program anatomy" in out
    assert "gather" in out


# ------------------------------------------ snapshot provider hardening


def test_snapshot_survives_raising_provider():
    from solvingpapers_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise RuntimeError("boom")

    m.add_gauge_provider(lambda: {"ok/first": 1.0})
    m.add_gauge_provider(broken)
    m.add_gauge_provider(lambda: {"ok/second": 2.0})

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        snap = m.snapshot()
    assert snap["ok/first"] == 1.0 and snap["ok/second"] == 2.0
    assert not any(k.startswith("broken") for k in snap)
    assert sum("gauge provider" in str(x.message) for x in w) == 1

    # second snapshot: still healthy, NO second warning (warn once per
    # provider), the broken provider still polled (self-heal on
    # transient failures)
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        snap2 = m.snapshot()
    assert snap2["ok/first"] == 1.0
    assert calls["n"] == 2
    assert not any("gauge provider" in str(x.message) for x in w2)

    # prom_snapshot rides the same hardened path
    assert m.prom_snapshot()["ok/second"] == 2.0
