"""Multi-host utilities (single-process semantics) + MFU accounting."""

import jax
import numpy as np
import pytest

from solvingpapers_tpu.metrics import active_param_count
from solvingpapers_tpu.sharding import host_batch_slice, host_seed, initialize_distributed

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast


def test_initialize_is_noop_single_process():
    assert initialize_distributed() is False
    assert jax.process_count() == 1


def test_host_seed_and_slice():
    assert host_seed(7) == 7 * 1_000_003  # process_index 0
    per, off = host_batch_slice(64)
    assert (per, off) == (64, 0)
    with pytest.raises(ValueError, match="not divisible"):
        # impossible single-process, construct directly
        from solvingpapers_tpu.sharding.distributed import host_batch_slice as f

        # 1 host divides everything; exercise the error with a fake count
        import unittest.mock as mock

        with mock.patch.object(jax, "process_count", return_value=3):
            f(64)


def test_active_param_count_moe():
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3, DeepSeekV3Config

    cfg = DeepSeekV3Config(
        vocab_size=64, block_size=16, dim=16, n_layers=1, n_heads=2,
        latent_dim=4, n_experts=4, top_experts=2, dropout=0.0, attn_dropout=0.0,
    )
    model = DeepSeekV3(cfg)
    params = model.init({"params": jax.random.key(0)},
                        jax.numpy.zeros((1, 8), jax.numpy.int32))["params"]
    total = sum(x.size for x in jax.tree.leaves(params))
    active = active_param_count(params, cfg.top_experts, cfg.n_experts)
    # routed expert weights: per layer 4 experts x (2*d*h + h*d)
    h = cfg.expert_hidden
    routed = cfg.n_layers * cfg.n_experts * 3 * cfg.dim * h
    assert active == total - routed // 2  # top-2 of 4 experts active
    assert active < total
