"""Rolling in-process time series (metrics/timeseries.py).

`TimeSeriesStore` is the fleet's retrospective memory: a fixed-budget
ring of periodic snapshots — raw gauges plus per-window DELTAS of
cumulative counters — sampled opportunistically from the engine's
`step()` (no timer thread). These tests pin the store's semantics with
a fake clock (delta-vs-raw rules, None alignment for late series, ring
eviction, the `due()` cadence guard), the sparkline rendering, the
AnomalyMonitor attachment (every anomaly dump carries the preceding
retrospective), and the engine integration: `/statusz` sparklines,
`statusz_providers`' `timeseries_fn`, and the `timeseries=False`
opt-out leaving every surface absent rather than empty.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.metrics.timeseries import TimeSeriesStore, sparkline
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import ServeConfig, ServeEngine

pytestmark = pytest.mark.fast


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


# ------------------------------------------------------------ sparkline


def test_sparkline_scales_min_to_max():
    s = sparkline([0.0, 1.0, 2.0, 3.0])
    assert len(s) == 4
    assert s[0] == "▁" and s[-1] == "█"


def test_sparkline_flat_nones_width_and_empty():
    assert sparkline([5.0, 5.0, 5.0]) == "▁▁▁"  # flat -> lowest block
    assert sparkline([None, 1.0, None, 2.0])[0] == " "
    assert sparkline([None, 1.0, None, 2.0])[2] == " "
    # width keeps the NEWEST points (right edge is "now")
    assert sparkline([0.0, 0.0, 9.0, 9.0], width=2) == "▁▁"
    assert sparkline([]) == ""
    assert sparkline([None, None]) == ""


# ----------------------------------------------------------- the store


def test_store_validates_knobs():
    with pytest.raises(ValueError, match="capacity"):
        TimeSeriesStore(capacity=0)
    with pytest.raises(ValueError, match="interval_s"):
        TimeSeriesStore(interval_s=0.0)


def test_due_follows_the_interval():
    clk = FakeClock()
    ts = TimeSeriesStore(capacity=8, interval_s=1.0, clock=clk)
    assert ts.due()  # never sampled
    ts.sample({"g": 1.0})
    assert not ts.due()
    clk.t += 0.5
    assert not ts.due()
    clk.t += 0.5
    assert ts.due()


def test_cumulative_stores_deltas_first_window_raw_and_clamps():
    clk = FakeClock()
    ts = TimeSeriesStore(capacity=8, interval_s=1.0, clock=clk)
    ts.sample({}, cumulative={"tok": 10.0})
    clk.t += 1
    ts.sample({}, cumulative={"tok": 25.0})
    clk.t += 1
    ts.sample({}, cumulative={"tok": 5.0})  # counter went BACKWARDS
    rows = ts.doc()["series"]["tok"]
    # first window = raw (pre-store life is window 0), then deltas,
    # and a backwards counter clamps to 0 instead of a negative rate
    assert rows == [10.0, 15.0, 0.0]


def test_late_series_backfills_and_absent_records_none():
    clk = FakeClock()
    ts = TimeSeriesStore(capacity=8, interval_s=1.0, clock=clk)
    ts.sample({"a": 1.0})
    clk.t += 1
    ts.sample({"a": 2.0, "b": 7.0})  # b appears mid-run
    clk.t += 1
    ts.sample({"b": 8.0})  # a absent this window
    doc = ts.doc()
    assert doc["series"]["a"] == [1.0, 2.0, None]
    assert doc["series"]["b"] == [None, 7.0, 8.0]
    assert doc["n"] == 3 and len(doc["t"]) == 3


def test_ring_evicts_oldest_at_capacity():
    clk = FakeClock()
    ts = TimeSeriesStore(capacity=3, interval_s=1.0, clock=clk)
    for i in range(5):
        ts.sample({"g": float(i)})
        clk.t += 1
    doc = ts.doc()
    assert doc["n"] == 3 and len(ts) == 3
    assert doc["series"]["g"] == [2.0, 3.0, 4.0]
    assert doc["t"] == [102.0, 103.0, 104.0]


def test_sparklines_render_and_omit_all_none_series():
    clk = FakeClock()
    ts = TimeSeriesStore(capacity=8, interval_s=1.0, clock=clk)
    ts.sample({"busy": 0.0})
    clk.t += 1
    ts.sample({"busy": 1.0, "late": None})
    lines = ts.sparklines(width=10)
    assert lines["busy"] == "▁█"
    assert "late" not in lines  # no finite point yet -> omitted


# ----------------------------------------------- anomaly-dump attachment


def test_anomaly_dump_carries_the_retrospective(tmp_path):
    import json

    from solvingpapers_tpu.metrics.trace import AnomalyMonitor, FlightRecorder

    clk = FakeClock()
    ts = TimeSeriesStore(capacity=4, interval_s=1.0, clock=clk)
    ts.sample({"queue_depth": 3.0})
    rec = FlightRecorder()
    rec.instant("ctx", "engine", "engine")
    mon = AnomalyMonitor(rec, str(tmp_path / "anom.jsonl"),
                         snapshot_fn=lambda: {"serve/steps": 1.0},
                         min_steps=4, slow_step_factor=5.0,
                         timeseries_fn=ts.doc)
    for _ in range(8):
        mon.observe_step(0.01)
    mon.observe_step(0.5)
    assert mon.dumps == 1
    (d,) = [json.loads(ln) for ln in
            (tmp_path / "anom.jsonl").read_text().splitlines()]
    assert d["timeseries"]["series"]["queue_depth"] == [3.0]
    assert d["timeseries"]["n"] == 1


# ------------------------------------------------------ engine integration


@pytest.fixture(scope="module")
def gpt_tiny():
    cfg = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                    n_heads=2, dropout=0.0)
    model = GPT(cfg)
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _run_traffic(eng, n=3):
    rng = np.random.default_rng(5)
    for _ in range(n):
        eng.submit(rng.integers(0, 64, size=8).astype(np.int32),
                   max_new_tokens=6)
    eng.run()


def test_engine_samples_windows_and_statusz_sparklines(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        # every step is due: a short run still rolls several windows
        timeseries_interval_s=1e-9, timeseries_capacity=16,
    ))
    _run_traffic(eng)
    assert eng.timeseries is not None and len(eng.timeseries) >= 2
    doc = eng.timeseries.doc()
    for key in ("occupancy", "queue_depth", "serve/tokens_out",
                "serve/steps", "serve/itl_s_count"):
        assert key in doc["series"], key
    # the counter rows are per-window deltas: their sum equals the
    # cumulative total the metrics snapshot reports
    snap = eng.metrics.snapshot()
    total = sum(v for v in doc["series"]["serve/tokens_out"]
                if v is not None)
    assert total == snap["serve/tokens_out"]
    d = eng.statusz()
    assert d["timeseries"]["windows"] == len(eng.timeseries)
    assert d["timeseries"]["sparklines"]  # at least one rendered series
    eng.close()


def test_timeseries_opt_out_leaves_surfaces_absent(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        timeseries=False,
    ))
    _run_traffic(eng, n=1)
    assert eng.timeseries is None
    assert "timeseries" not in eng.statusz()
    eng.close()


def test_status_server_serves_timeseriesz():
    import json
    import urllib.request

    from solvingpapers_tpu.metrics.http import StatusServer

    clk = FakeClock()
    ts = TimeSeriesStore(capacity=4, interval_s=1.0, clock=clk)
    ts.sample({"g": 1.0})
    srv = StatusServer(statusz_fn=dict, metrics_fn=lambda: (0, {}),
                       timeseries_fn=ts.doc)
    try:
        with urllib.request.urlopen(srv.url("/timeseriesz"),
                                    timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["series"]["g"] == [1.0]
    finally:
        srv.close()
    # no store bound -> 404, not an empty 200
    srv = StatusServer(statusz_fn=dict, metrics_fn=lambda: (0, {}))
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(srv.url("/timeseriesz"), timeout=10)
        assert ei.value.code == 404
    finally:
        srv.close()
