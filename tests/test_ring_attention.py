"""Context-parallel attention tests on the virtual 8-device mesh:
ring attention and Ulysses must equal single-device dense attention
(SURVEY.md §4 multichip test plan; capability added beyond the reference).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.sharding import MeshConfig, create_mesh
from solvingpapers_tpu.sharding.ring_attention import (
    ring_attention,
    ulysses_attention,
)


def make_qkv(key, b, s, n, h, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, n, h), dtype),
        jax.random.normal(kk, (b, s, n, h), dtype),
        jax.random.normal(kv, (b, s, n, h), dtype),
    )


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
@pytest.mark.parametrize("ctx", [4, 8])
def test_ring_attention_matches_dense(devices, causal, ctx):
    mesh = create_mesh(
        MeshConfig(data=8 // ctx, fsdp=1, model=1, expert=1, context=ctx), devices
    )
    q, k, v = make_qkv(jax.random.key(0), 2, 64, 2, 16)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = ops.dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_flow(devices):
    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    q, k, v = make_qkv(jax.random.key(1), 2, 32, 2, 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(ops.dot_product_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
def test_ulysses_matches_dense(devices, causal):
    ctx = 4
    mesh = create_mesh(MeshConfig(data=2, context=ctx), devices)
    # heads must be divisible by the context axis
    q, k, v = make_qkv(jax.random.key(2), 2, 32, 4, 16)
    attn_fn = functools.partial(ops.dot_product_attention, causal=causal)
    out = ulysses_attention(q, k, v, mesh, attn_fn)
    ref = ops.dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_long_sequence_streams(devices):
    """8-way context split of a longer sequence (the memory win: each device
    only ever holds S/8 of K/V plus one in-flight chunk)."""
    mesh = create_mesh(MeshConfig(data=1, context=8), devices)
    q, k, v = make_qkv(jax.random.key(3), 1, 512, 2, 16)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_llama_context_parallel_training_matches_dense(devices):
    """End-to-end CP: a Llama forward+backward with its attention running
    the ppermute ring inside shard_map (sequence sharded over 'context')
    must match the dense single-device model exactly."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    base = LlamaConfig(vocab_size=64, max_seq_len=64, dim=32, n_layers=2,
                       n_heads=4, n_kv_heads=2, dropout=0.0)
    cp_cfg = dataclasses.replace(base, context_parallel=True)
    dense, cp = Llama(base), Llama(cp_cfg)

    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    toks = jax.random.randint(jax.random.key(0), (2, 64), 0, base.vocab_size)
    targets = jnp.roll(toks, -1, axis=1)
    positions = jnp.broadcast_to(jnp.arange(64), (2, 64))
    params = dense.init({"params": jax.random.key(1)}, toks)["params"]

    tok_spec = P(("data",), "context")

    def local_loss(params, x, pos, y):
        logits, _ = cp.apply({"params": params}, x, positions=pos)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, y[..., None], axis=-1)[..., 0]
        total = jax.lax.psum(jnp.sum(nll), ("data", "context"))
        count = jax.lax.psum(nll.size, ("data", "context"))
        return total / count

    cp_loss = jax.shard_map(
        local_loss, mesh=mesh,
        in_specs=(P(), tok_spec, tok_spec, tok_spec), out_specs=P(),
    )

    def dense_loss(params):
        logits, _ = dense.apply({"params": params}, toks, positions=positions)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    l_cp, g_cp = jax.value_and_grad(
        lambda p: cp_loss(p, toks, positions, targets)
    )(params)
    l_d, g_d = jax.value_and_grad(dense_loss)(params)
    np.testing.assert_allclose(float(l_cp), float(l_d), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_cp), jax.tree.leaves(g_d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_ring_gqa_repeats_inside_ring(devices):
    """K/V enter the ring with n_kv heads (less ppermute traffic) and are
    repeated per step; result equals dense GQA attention."""
    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    kq, kk, kv = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(kq, (2, 64, 4, 16))
    k = jax.random.normal(kk, (2, 64, 2, 16))
    v = jax.random.normal(kv, (2, 64, 2, 16))
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_cp_llama_forward_matches_dense(devices, impl):
    """CP model forward (positions defaulted -> must derive GLOBAL positions
    from the axis index) == dense, for both context impls, with GQA heads
    (8 q / 4 kv over a 4-way axis exercises the head-split + repeat_kv
    composition)."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    base = LlamaConfig(vocab_size=64, max_seq_len=32, dim=32, n_layers=1,
                       n_heads=8, n_kv_heads=4, dropout=0.0)
    cp = Llama(dataclasses.replace(base, context_parallel=True,
                                   context_impl=impl))
    dense = Llama(base)
    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    toks = jax.random.randint(jax.random.key(2), (2, 32), 0, 64)
    params = dense.init({"params": jax.random.key(3)}, toks)["params"]
    out = jax.shard_map(
        lambda p, x: cp.apply({"params": p}, x)[0],
        mesh=mesh, in_specs=(P(), P(("data",), "context")),
        out_specs=P(("data",), "context", None),
    )(params, toks)
    ref, _ = dense.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_cp_model_rejects_plain_decode_cache(devices):
    """CP decode is supported as of round 5 — but only through the
    context-sharded CPKVCache; a PLAIN per-shard KVCache would silently
    attend only local slots and must be rejected with a pointer to the
    right API."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    cfg = LlamaConfig(vocab_size=64, max_seq_len=32, dim=16, n_layers=1,
                      n_heads=2, n_kv_heads=2, dropout=0.0,
                      context_parallel=True)
    model = Llama(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    mesh = create_mesh(MeshConfig(data=1, context=4), devices[:4])

    def run(p, x):
        caches = model.init_caches(1, 32)  # plain KVCache: wrong under CP
        out, _ = model.apply({"params": p}, x, caches=caches)
        return out

    base = Llama(dataclasses.replace(cfg, context_parallel=False))
    params = base.init({"params": jax.random.key(0)}, toks)["params"]
    with pytest.raises(TypeError, match="CPKVCache"):
        jax.shard_map(run, mesh=mesh,
                      in_specs=(P(), P(("data",), "context")),
                      out_specs=P(("data",), "context", None))(params, toks)


# ---------------------------------------------------------------- ring-flash


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
@pytest.mark.parametrize("ctx", [4, 8])
def test_ring_flash_matches_dense(devices, causal, ctx):
    """Ring attention with the Pallas kernel per chunk (interpret mode on
    the CPU mesh) == single-device dense attention."""
    from solvingpapers_tpu.sharding.ring_attention import ring_flash_attention

    mesh = create_mesh(MeshConfig(data=8 // ctx, context=ctx), devices)
    q, k, v = make_qkv(jax.random.key(7), 2, 128, 2, 16)
    out = ring_flash_attention(q, k, v, mesh, causal=causal)
    ref = ops.dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_gqa_matches_dense(devices):
    from solvingpapers_tpu.sharding.ring_attention import ring_flash_attention

    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    kq, kk, kv = jax.random.split(jax.random.key(8), 3)
    q = jax.random.normal(kq, (2, 128, 4, 16))
    k = jax.random.normal(kk, (2, 128, 2, 16))
    v = jax.random.normal(kv, (2, 128, 2, 16))
    out = ring_flash_attention(q, k, v, mesh, causal=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_grads_match_dense(devices):
    """The custom-VJP ring backward (per-chunk _bwd_chunk sweeps with the
    global lse, dk/dv traveling the ring) == dense gradients, GQA shapes."""
    from solvingpapers_tpu.sharding.ring_attention import ring_flash_attention

    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    kq, kk, kv = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(kq, (2, 64, 4, 16))
    k = jax.random.normal(kk, (2, 64, 2, 16))
    v = jax.random.normal(kv, (2, 64, 2, 16))

    def loss_ring(q, k, v):
        return jnp.sum(ring_flash_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(ops.dot_product_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_cp_llama_ring_flash_forward_matches_dense(devices):
    """use_flash + context_parallel ring through the model layer == dense."""
    import dataclasses

    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    base = LlamaConfig(vocab_size=64, max_seq_len=128, dim=32, n_layers=1,
                       n_heads=4, n_kv_heads=2, dropout=0.0)
    cp = Llama(dataclasses.replace(base, context_parallel=True,
                                   context_impl="ring", use_flash=True))
    dense = Llama(base)
    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    toks = jax.random.randint(jax.random.key(10), (2, 128), 0, 64)
    params = dense.init({"params": jax.random.key(11)}, toks)["params"]
    out = jax.shard_map(
        lambda p, x: cp.apply({"params": p}, x)[0],
        mesh=mesh, in_specs=(P(), P(("data",), "context")),
        out_specs=P(("data",), "context", None),
        check_vma=False,  # pallas-in-scan vs the jax-0.9 vma checker
    )(params, toks)
    ref, _ = dense.apply({"params": params}, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
