"""Context-parallel attention tests on the virtual 8-device mesh:
ring attention and Ulysses must equal single-device dense attention
(SURVEY.md §4 multichip test plan; capability added beyond the reference).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.sharding import MeshConfig, create_mesh
from solvingpapers_tpu.sharding.ring_attention import (
    ring_attention,
    ulysses_attention,
)


def make_qkv(key, b, s, n, h, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (b, s, n, h), dtype),
        jax.random.normal(kk, (b, s, n, h), dtype),
        jax.random.normal(kv, (b, s, n, h), dtype),
    )


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
@pytest.mark.parametrize("ctx", [4, 8])
def test_ring_attention_matches_dense(devices, causal, ctx):
    mesh = create_mesh(
        MeshConfig(data=8 // ctx, fsdp=1, model=1, expert=1, context=ctx), devices
    )
    q, k, v = make_qkv(jax.random.key(0), 2, 64, 2, 16)
    out = ring_attention(q, k, v, mesh, causal=causal)
    ref = ops.dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_flow(devices):
    mesh = create_mesh(MeshConfig(data=2, context=4), devices)
    q, k, v = make_qkv(jax.random.key(1), 2, 32, 2, 8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(ops.dot_product_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False], ids=["causal", "bidir"])
def test_ulysses_matches_dense(devices, causal):
    ctx = 4
    mesh = create_mesh(MeshConfig(data=2, context=ctx), devices)
    # heads must be divisible by the context axis
    q, k, v = make_qkv(jax.random.key(2), 2, 32, 4, 16)
    attn_fn = functools.partial(ops.dot_product_attention, causal=causal)
    out = ulysses_attention(q, k, v, mesh, attn_fn)
    ref = ops.dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_ring_long_sequence_streams(devices):
    """8-way context split of a longer sequence (the memory win: each device
    only ever holds S/8 of K/V plus one in-flight chunk)."""
    mesh = create_mesh(MeshConfig(data=1, context=8), devices)
    q, k, v = make_qkv(jax.random.key(3), 1, 512, 2, 16)
    out = ring_attention(q, k, v, mesh, causal=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
