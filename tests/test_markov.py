"""Entropy-calibrated corpus tests (SURVEY.md §4 items 1-2).

The MarkovSource gives held-out loss an ABSOLUTE target offline: its exact
entropy rate H is the floor for per-token cross-entropy on held-out text.
These tests pin (a) the entropy math, (b) determinism, and (c) that a small
model actually closes most of the gap to H — the property the reference
demonstrates with real-data val losses (gpt-jax.ipynb cell 18).
"""

import numpy as np

from solvingpapers_tpu.data.synthetic import MarkovSource, markov_entropy_nats
import pytest

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast


def test_uniform_chain_entropy_is_log_vocab():
    # alpha -> inf makes every conditional ~uniform; H -> ln V
    src = MarkovSource(vocab=16, order=1, alpha=1e6, seed=0)
    assert abs(src.entropy_rate_nats - np.log(16)) < 1e-3


def test_entropy_matches_empirical_loglik():
    """The true model's log-loss on its own sample estimates H."""
    src = MarkovSource(vocab=32, order=2, alpha=0.15, seed=7)
    text = src.sample(200_000, seed=3)
    idx = {c: i for i, c in enumerate(src.alphabet)}
    ids = np.array([idx[c] for c in text])
    states = ids[:-2] * src.vocab + ids[1:-1]
    nll = -np.log(src.T[states, ids[2:]]).mean()
    assert abs(nll - src.entropy_rate_nats) < 0.02


def test_stationary_is_fixed_point():
    src = MarkovSource(vocab=8, order=2, alpha=0.2, seed=1)
    pi = src.stationary
    V, S = src.vocab, src.n_states
    target = (np.arange(S)[:, None] % (S // V)) * V + np.arange(V)[None, :]
    nxt = np.bincount(target.ravel(), weights=(pi[:, None] * src.T).ravel(),
                      minlength=S)
    np.testing.assert_allclose(nxt, pi, atol=1e-10)
    assert abs(pi.sum() - 1.0) < 1e-12


def test_deterministic_and_helper():
    a = MarkovSource(seed=5).sample(2000, seed=2)
    b = MarkovSource(seed=5).sample(2000, seed=2)
    assert a == b
    assert MarkovSource(seed=5).sample(2000, seed=3) != a
    h = markov_entropy_nats({"markov_vocab": 64, "markov_order": 2,
                             "markov_alpha": 0.1, "markov_seed": 1234})
    assert 1.5 < h < 3.5  # the pinned parity chain's rate (~2.362)


def test_factory_builds_markov_run():
    import dataclasses

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_char_lm_run

    cfg = get_config("gpt_markov", steps=2)
    cfg = dataclasses.replace(cfg, data={**cfg.data, "n_chars": 50_000})
    cfg, model, tok, train_iter, eval_iter_fn = build_char_lm_run(cfg)
    assert tok.vocab_size <= 64
    b = next(train_iter)
    assert b["x"].shape == (cfg.train.batch_size, cfg.data["block_size"])


def test_small_model_closes_gap_to_entropy():
    """A tiny GPT on a tiny chain must land near H — far below both the
    untrained ln(V) and what sequence memorization yields on held-out text."""
    import dataclasses

    from solvingpapers_tpu.configs.factory import (
        build_char_lm_run, init_fn_for, loss_fn_for, rules_for,
    )
    from solvingpapers_tpu.configs.registry import RunConfig
    from solvingpapers_tpu.models.gpt import GPTConfig
    from solvingpapers_tpu.train import OptimizerConfig, Trainer, TrainConfig

    data = {"kind": "char", "source": "markov", "block_size": 64,
            "n_chars": 120_000, "markov_vocab": 8, "markov_order": 1,
            "markov_alpha": 0.3, "markov_seed": 11}
    cfg = RunConfig(
        name="markov_smoke", model_family="gpt",
        model=GPTConfig(vocab_size=8, block_size=64, dim=64, n_layers=2,
                        n_heads=2, dropout=0.0),
        train=TrainConfig(
            steps=250, batch_size=32, log_every=1000, eval_every=0,
            eval_batches=8,
            optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=20,
                                      total_steps=250),
        ),
        data=data,
    )
    cfg, model, tok, train_iter, eval_iter_fn = build_char_lm_run(cfg)
    trainer = Trainer(model, cfg.train, loss_fn=loss_fn_for(cfg),
                      init_fn=init_fn_for(cfg), rules=rules_for(cfg))
    state = trainer.fit(train_iter)
    val = trainer.evaluate(state, eval_iter_fn())
    h = markov_entropy_nats(data)
    gap = float(val["val_loss"]) - h
    # untrained is ln(8) - H above the floor; require >75% of that closed
    assert gap < 0.25 * (np.log(8) - h), (gap, h, float(val["val_loss"]))
