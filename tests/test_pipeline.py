"""Pipeline parallelism: GPipe schedule == sequential stage application
(SURVEY.md §2.3 PP row), including gradient flow through the pipeline.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.sharding import MeshConfig, create_mesh
from solvingpapers_tpu.sharding.pipeline import pipeline_apply, stack_stage_params


def mlp_stage(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def make_stages(key, n_stages, d, h):
    stages = []
    for i in range(n_stages):
        k1, k2, key = jax.random.split(jax.random.fold_in(key, i), 3)
        stages.append({
            "w1": jax.random.normal(k1, (d, h)) * 0.3,
            "b1": jnp.zeros(h),
            "w2": jax.random.normal(k2, (h, d)) * 0.3,
            "b2": jnp.zeros(d),
        })
    return stages


def sequential(stages, x):
    for p in stages:
        x = mlp_stage(p, x)
    return x


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(devices, n_micro):
    n_stages = 4
    mesh = create_mesh(MeshConfig(data=2, pipe=n_stages), devices)
    stages = make_stages(jax.random.key(0), n_stages, d=16, h=32)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(1), (16, 16))

    out = pipeline_apply(stacked, x, mlp_stage, mesh, n_microbatches=n_micro)
    ref = sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential(devices):
    n_stages = 4
    mesh = create_mesh(MeshConfig(data=1, pipe=n_stages), devices[:4])
    stages = make_stages(jax.random.key(2), n_stages, d=8, h=16)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(3), (8, 8))

    def loss_pipe(stacked):
        return jnp.sum(pipeline_apply(stacked, x, mlp_stage, mesh, n_microbatches=4) ** 2)

    def loss_seq(stacked):
        stages = [jax.tree.map(lambda a: a[i], stacked) for i in range(n_stages)]
        return jnp.sum(sequential(stages, x) ** 2)

    gp = jax.grad(loss_pipe)(stacked)
    gs = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_pipeline_rejects_bad_microbatching(devices):
    mesh = create_mesh(MeshConfig(data=2, pipe=4), devices)
    stages = make_stages(jax.random.key(0), 4, d=8, h=8)
    x = jnp.zeros((10, 8))
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_apply(stack_stage_params(stages), x, mlp_stage, mesh, n_microbatches=4)


def test_1f1b_matches_sequential_grads(devices):
    """1F1B (per-microbatch backward interleaved with forwards, live
    activations bounded by pipe depth) must produce the same loss and the
    same stage/head/input gradients as jax.grad over the sequential stage
    loop (the same oracle GPipe is tested against) — VERDICT r4 ask 4."""
    from jax.sharding import PartitionSpec as P

    from solvingpapers_tpu.sharding.pipeline import (
        pipeline_1f1b_value_and_grad,
        shard_map_compat,
    )

    n_stages, d, m, mb = 4, 8, 8, 2
    mesh = create_mesh(MeshConfig(data=1, pipe=n_stages), devices[:4])
    stages = make_stages(jax.random.key(2), n_stages, d=d, h=16)
    stacked = stack_stage_params(stages)
    head = {"w": jax.random.normal(jax.random.key(5), (d, d)) * 0.3}
    micro = jax.random.normal(jax.random.key(3), (m, mb, d))
    targets = jax.random.normal(jax.random.key(4), (m, mb, d))

    def loss_fn(hp, y, t):
        return jnp.mean((y @ hp["w"] - t) ** 2)

    def seq_loss(stages, head, micro):
        losses = []
        for i in range(m):
            x = micro[i]
            for p in stages:
                x = mlp_stage(p, x)
            losses.append(loss_fn(head, x, targets[i]))
        return jnp.mean(jnp.stack(losses))

    l_ref, (dstage_ref, dhead_ref, dmicro_ref) = jax.value_and_grad(
        seq_loss, argnums=(0, 1, 2)
    )(stages, head, micro)

    def f1b(stage_local, head, micro, targets):
        return pipeline_1f1b_value_and_grad(
            stage_local, head, micro, targets, mlp_stage, loss_fn
        )

    l_new, dstage_new, dhead_new, dmicro_new = shard_map_compat(
        f1b, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("pipe"), stacked), P(), P(), P()),
        out_specs=(P(), jax.tree.map(lambda _: P("pipe"), stacked), P(),
                   P()),
    )(stacked, head, micro, targets)

    np.testing.assert_allclose(float(l_new), float(l_ref), rtol=1e-6)
    dstage_ref_stacked = stack_stage_params(dstage_ref)
    for a, b in zip(jax.tree.leaves(dstage_new),
                    jax.tree.leaves(dstage_ref_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(dhead_new), jax.tree.leaves(dhead_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dmicro_new), np.asarray(dmicro_ref),
                               rtol=1e-5, atol=1e-6)
