"""Radix-tree prefix cache tests (solvingpapers_tpu/serve/prefix_cache.py).

Two contracts under test. Tree mechanics: page-aligned matching, edge
splits, LRU eviction under a byte budget, refcount pinning (a pinned
path survives any pressure). Engine exactness: greedy streams must be
token-exact with the prefix cache enabled vs disabled vs per-request
one-shot `generate`, for all four decoder families — splicing a cached
prefix segment into a lane is bitwise the same computation the lane
would have run itself, and eviction churn must never corrupt an active
lane's stream (lanes own copy-on-acquire copies).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.serve import (
    FIFOScheduler,
    PrefixCache,
    Request,
    ServeConfig,
    ServeEngine,
)
from solvingpapers_tpu.serve.prefix_cache import segment_bytes, segment_length

# ------------------------------------------------------------- tree units


def _seg(length, fill=0.0, dtype=jnp.bfloat16):
    """A fake batch-1 KV segment pytree: one 'layer', k and v leaves."""
    return [{"k": jnp.full((1, length, 2, 4), fill, dtype),
             "v": jnp.full((1, length, 2, 4), fill, dtype)}]


def _toks(*vals):
    return np.asarray(vals, np.int32)


def test_match_miss_then_hit_after_insert():
    pc = PrefixCache(page=4, max_bytes=1 << 20)
    tokens = np.arange(12, dtype=np.int32)
    assert pc.match(tokens).length == 0
    new = pc.insert(tokens, lambda off, n: _seg(n, fill=off))
    assert new == 12 and pc.n_nodes == 1
    m = pc.match(tokens)
    assert m.length == 12 and len(m.nodes) == 1
    assert segment_length(m.nodes[0].segment) == 12
    assert pc.bytes_held == segment_bytes(m.nodes[0].segment)


def test_partial_match_splits_at_page_boundary():
    pc = PrefixCache(page=4, max_bytes=1 << 20)
    pc.insert(np.arange(12, dtype=np.int32), lambda off, n: _seg(n))
    # diverges at token 6 -> common prefix 6, page-aligned match 4
    probe = np.concatenate([np.arange(6), [99, 99]]).astype(np.int32)
    m = pc.match(probe[:8])
    assert m.length == 4
    assert [n.length for n in m.nodes] == [4]
    # the original 12-token edge is now 4 + 8 under it
    assert pc.n_nodes == 2
    full = pc.match(np.arange(12, dtype=np.int32))
    assert m.nodes[0] is full.nodes[0]
    assert [n.length for n in full.nodes] == [4, 8]
    # segments were sliced consistently with the split
    assert segment_length(full.nodes[0].segment) == 4
    assert segment_length(full.nodes[1].segment) == 8


def test_sub_page_common_prefix_is_a_miss():
    pc = PrefixCache(page=8, max_bytes=1 << 20)
    pc.insert(np.arange(8, dtype=np.int32), lambda off, n: _seg(n))
    probe = np.concatenate([np.arange(5), [99, 99, 99]]).astype(np.int32)
    assert pc.match(probe).length == 0
    assert pc.n_nodes == 1  # no split happened


def test_peek_is_readonly():
    pc = PrefixCache(page=4, max_bytes=1 << 20)
    pc.insert(np.arange(12, dtype=np.int32), lambda off, n: _seg(n))
    probe = np.concatenate([np.arange(6), [99]]).astype(np.int32)
    assert pc.peek(probe) == 4
    assert pc.n_nodes == 1, "peek must not split edges"
    assert pc.peek(np.arange(12, dtype=np.int32)) == 12


def test_insert_rejects_unaligned_length():
    pc = PrefixCache(page=8, max_bytes=1 << 20)
    with pytest.raises(ValueError, match="not a multiple"):
        pc.insert(np.arange(10, dtype=np.int32), lambda off, n: _seg(n))


def test_insert_extracts_only_the_uncached_tail():
    pc = PrefixCache(page=4, max_bytes=1 << 20)
    calls = []

    def extract(off, n):
        calls.append((off, n))
        return _seg(n)

    pc.insert(np.arange(8, dtype=np.int32), extract)
    pc.insert(np.arange(16, dtype=np.int32), extract)  # 8 cached, 8 new
    assert calls == [(0, 8), (8, 8)]
    assert pc.insert(np.arange(16, dtype=np.int32), extract) == 0  # all cached
    assert calls == [(0, 8), (8, 8)]


def test_subpage_divergence_caches_both_stems_as_siblings():
    """Two stems sharing less than a page (4 of 16 tokens) start with the
    same token but different first PAGES — page-keyed children let both
    live side by side (single-token keys would collide, and either insert
    would clobber the other's subtree)."""
    pc = PrefixCache(page=16, max_bytes=1 << 20)
    a = np.arange(64, dtype=np.int32)
    pc.insert(a, lambda off, n: _seg(n))
    held = pc.bytes_held
    b = np.concatenate([a[:4], np.full(60, 99)]).astype(np.int32)
    assert pc.insert(b, lambda off, n: _seg(n)) == 64
    assert pc.peek(a) == 64, "existing stem was clobbered"
    assert pc.peek(b) == 64
    assert pc.bytes_held == 2 * held and pc.n_nodes == 2


def test_subpage_divergence_past_aligned_split_branches_lower():
    """Divergence at token 20 with page 16: the edge splits at 16 and the
    remainders (same first token, different pages) become SIBLINGS under
    the split-off upper — both full stems stay cacheable."""
    pc = PrefixCache(page=16, max_bytes=1 << 20)
    a = np.arange(64, dtype=np.int32)
    pc.insert(a, lambda off, n: _seg(n))
    b = np.concatenate([a[:20], np.full(44, 99)]).astype(np.int32)
    assert pc.insert(b, lambda off, n: _seg(n)) == 48
    assert pc.peek(a) == 64
    assert pc.peek(b) == 64
    assert pc.n_nodes == 3  # shared upper [0,16) + two 48-token branches
    # matching each stem walks its own branch, segments sliced consistently
    ma, mb = pc.match(a), pc.match(b)
    assert ma.nodes[0] is mb.nodes[0]
    assert ma.nodes[1] is not mb.nodes[1]
    assert segment_length(mb.nodes[1].segment) == 48


def test_lru_eviction_respects_budget():
    one_seg_bytes = segment_bytes(_seg(8))
    pc = PrefixCache(page=8, max_bytes=2 * one_seg_bytes)
    rng = np.random.default_rng(0)
    branches = [rng.integers(100, 200, size=8).astype(np.int32)
                for _ in range(4)]
    for b in branches:
        pc.insert(b, lambda off, n: _seg(n))
    assert pc.bytes_held <= pc.max_bytes
    assert pc.evictions == 2 and pc.n_nodes == 2
    # the two most recently inserted branches survived
    assert pc.match(branches[0]).length == 0
    assert pc.match(branches[3]).length == 8


def test_match_refreshes_lru_order():
    one_seg_bytes = segment_bytes(_seg(8))
    pc = PrefixCache(page=8, max_bytes=2 * one_seg_bytes)
    a = np.arange(100, 108, dtype=np.int32)
    b = np.arange(200, 208, dtype=np.int32)
    pc.insert(a, lambda off, n: _seg(n))
    pc.insert(b, lambda off, n: _seg(n))
    pc.match(a)  # a is now the most recently used
    pc.insert(np.arange(300, 308, dtype=np.int32), lambda off, n: _seg(n))
    assert pc.match(a).length == 8
    assert pc.match(b).length == 0  # b was the LRU victim


def test_pinned_path_survives_eviction_pressure():
    one_seg_bytes = segment_bytes(_seg(8))
    pc = PrefixCache(page=8, max_bytes=one_seg_bytes)  # room for ONE node
    a = np.arange(100, 108, dtype=np.int32)
    pc.insert(a, lambda off, n: _seg(n))
    m = pc.match(a)
    pc.pin(m)
    # inserting another branch overflows the budget; the pinned node must
    # survive, so the NEW node is the only evictable leaf and goes instead
    pc.insert(np.arange(200, 208, dtype=np.int32), lambda off, n: _seg(n))
    assert pc.match(a).length == 8, "pinned node was evicted"
    pc.unpin(m)
    pc.insert(np.arange(300, 308, dtype=np.int32), lambda off, n: _seg(n))
    assert pc.match(a).length == 0, "unpinned LRU node should now be evictable"


def test_split_preserves_pin_protection_and_unpin_balances():
    pc = PrefixCache(page=4, max_bytes=1 << 20)
    tokens = np.arange(12, dtype=np.int32)
    pc.insert(tokens, lambda off, n: _seg(n))
    m = pc.match(tokens)
    pc.pin(m)
    # a partial match splits the pinned 12-edge at 4; the pinned original
    # keeps its count as the lower half, and the new upper is protected
    # transitively (eviction only takes CHILDLESS leaves)
    probe = np.concatenate([np.arange(6), [99, 99]]).astype(np.int32)
    pc.match(probe)
    upper, lower = pc.match(tokens).nodes
    assert upper.refcount == 0 and lower.refcount == 1
    pc.max_bytes = 0
    pc._evict_to_budget()
    assert pc.match(tokens).length == 12, "pinned path evicted after split"
    # unpin fully balances the counts (no leaked refs on the upper half)
    pc.unpin(m)
    assert upper.refcount == 0 and lower.refcount == 0
    pc._evict_to_budget()
    assert pc.n_nodes == 0 and pc.bytes_held == 0


def test_evicting_leaf_exposes_parent():
    one_seg = segment_bytes(_seg(4))
    pc = PrefixCache(page=4, max_bytes=8 * one_seg)
    pc.insert(np.arange(12, dtype=np.int32), lambda off, n: _seg(n))
    probe = np.concatenate([np.arange(4), [50, 50, 50, 50]]).astype(np.int32)
    pc.insert(probe, lambda off, n: _seg(n))  # splits -> 3 nodes
    assert pc.n_nodes == 3
    pc.max_bytes = 0
    pc._evict_to_budget()
    assert pc.n_nodes == 0 and pc.bytes_held == 0
    assert pc.evictions == 3


# --------------------------------------------------- scheduler integration


def _req(prompt):
    return Request(prompt=np.asarray(prompt, np.int32), max_new_tokens=4,
                   eos_id=None)


def test_scheduler_prefers_shortest_uncovered_suffix():
    cached = {8: 0, 16: 12, 24: 24}  # prompt len -> match len

    def lookup(prompt):
        return cached[prompt.size]

    sched = FIFOScheduler(decode_priority=False, prefer_cached=True,
                          prefix_lookup=lookup)
    reqs = [_req(np.arange(n)) for n in (8, 16, 24)]
    for r in reqs:
        sched.submit(r)
    # uncovered suffixes: 8, 4, 0 -> admit order reversed vs FIFO
    picked = sched.pick(n_free=2, n_active=0)
    assert picked == [reqs[2], reqs[1]]
    assert list(sched.queue) == [reqs[0]]


def test_scheduler_wait_budget_beats_prefix_preference():
    sched = FIFOScheduler(decode_priority=False, prefer_cached=True,
                          max_wait_steps=2,
                          prefix_lookup=lambda p: 0 if p.size == 8 else p.size)
    starved = _req(np.arange(8))   # zero cached -> longest suffix
    sched.submit(starved)
    for _ in range(3):
        sched.tick()               # starved is now past the wait budget
    fresh = _req(np.arange(16))    # fully cached -> shortest suffix
    sched.submit(fresh)
    assert sched.pick(n_free=1, n_active=0) == [starved]


# ------------------------------------------------------ engine exactness


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params, None


def _llama3_tiny():
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    model = Llama(LlamaConfig(vocab_size=64, max_seq_len=64, dim=32,
                              n_layers=2, n_heads=4, n_kv_heads=2,
                              dropout=0.0))
    params = model.init({"params": jax.random.key(1)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params, None


def _gemma_tiny():
    from solvingpapers_tpu.models.gemma import Gemma, GemmaConfig

    model = Gemma(GemmaConfig(vocab_size=64, max_seq_len=64, dim=32,
                              n_layers=2, n_heads=4, n_kv_heads=2,
                              dropout=0.0))
    params = model.init({"params": jax.random.key(2)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params, None


def _dsv3_tiny():
    from solvingpapers_tpu.models.deepseekv3 import (
        DeepSeekV3, DeepSeekV3Config,
    )

    model = DeepSeekV3(DeepSeekV3Config(
        vocab_size=64, block_size=64, dim=32, n_layers=2, n_heads=4,
        latent_dim=8, rope_dim=8, n_experts=4, top_experts=2, dropout=0.0,
        attn_dropout=0.0,
    ))
    variables = model.init({"params": jax.random.key(3)},
                           jnp.zeros((1, 8), jnp.int32))
    return model, variables["params"], {"moe_state": variables["moe_state"]}


_FAMILIES = {
    "gpt": _gpt_tiny,
    "llama3": _llama3_tiny,
    "gemma": _gemma_tiny,
    "deepseekv3": _dsv3_tiny,
}


def _shared_prefix_prompts(n, n_stems=2, stem_len=14, tail_len=5, seed=0):
    rng = np.random.default_rng(seed)
    stems = [rng.integers(0, 64, size=stem_len).astype(np.int32)
             for _ in range(n_stems)]
    return [
        np.concatenate(
            [stems[i % n_stems],
             rng.integers(0, 64, size=tail_len).astype(np.int32)]
        )
        for i in range(n)
    ]


def _ref_stream(model, params, extra, prompt, max_new, eos_id=None):
    out = generate(model, params, jnp.asarray(prompt)[None, :],
                   jax.random.key(0), max_new_tokens=max_new, eos_id=eos_id,
                   extra_variables=extra)
    gen = np.asarray(out[0, len(prompt):]).tolist()
    if eos_id is not None and eos_id in gen:
        gen = gen[: gen.index(eos_id) + 1]
    return gen


@pytest.mark.parametrize("paged", [False, True], ids=["lane", "paged"])
@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_streams_token_exact_with_cache_on_off_all_families(family, paged):
    """Greedy streams: cache-on == cache-off == one-shot generate, across
    shared-prefix traffic, on BOTH pool layouts. The cache-on runs must
    actually hit — and on the paged pool a hit is a zero-copy page-table
    append rather than a lane splice, which must be just as invisible in
    the tokens."""
    model, params, extra = _FAMILIES[family]()
    prompts = _shared_prefix_prompts(6, seed=11)
    streams = {}
    for on in (True, False):
        eng = ServeEngine(
            model, params,
            ServeConfig(n_slots=2, max_len=32, decode_block=4, bucket=8,
                        prefix_cache=on, prefix_page=4, paged=paged,
                        page_size=4 if paged else None),
            extra_variables=extra,
        )
        handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run()
        streams[on] = [h.tokens for h in handles]
        if on:
            snap = eng.metrics.snapshot()
            assert snap["serve/prefix_hits"] >= 4, "shared stems never hit"
            assert snap["serve/tokens_prefilled_saved"] >= 4 * 12
    assert streams[True] == streams[False]
    for p, got in zip(prompts, streams[True]):
        assert got == _ref_stream(model, params, extra, p, 6), (
            f"{family}: cached stream diverged from one-shot generate"
        )


def test_eviction_churn_never_corrupts_streams():
    """A byte budget sized for ~2 segments forces constant LRU churn;
    every stream must stay token-exact (lanes own their spliced copies,
    pinned nodes never evict mid-splice)."""
    model, params, extra = _gpt_tiny()
    prompts = _shared_prefix_prompts(10, n_stems=3, stem_len=14, tail_len=5,
                                     seed=23)
    probe = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, prefix_cache=True, prefix_page=4,
    ))
    seg = probe.pool.extract_prefix(0, 0, 12)
    from solvingpapers_tpu.serve.prefix_cache import segment_bytes as sb

    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        prefix_cache=True, prefix_page=4, prefix_cache_bytes=2 * sb(seg),
    ))
    handles = [eng.submit(p, max_new_tokens=6) for p in prompts]
    eng.run()
    assert eng.prefix_cache.evictions > 0, "budget never forced an eviction"
    assert eng.prefix_cache.bytes_held <= eng.prefix_cache.max_bytes
    for p, h in zip(prompts, handles):
        assert h.tokens == _ref_stream(model, params, extra, p, 6)
    assert eng.metrics.snapshot()["serve/prefix_evictions"] > 0


def test_lane_reuse_after_early_eos_with_splice_pending():
    """One slot: request A stops on early EOS, queued B (sharing A's
    stem) immediately re-acquires the lane WITH a prefix splice into it —
    the spliced prefix must overwrite A's leftovers exactly."""
    model, params, extra = _gpt_tiny()
    prompts = _shared_prefix_prompts(3, n_stems=1, stem_len=14, tail_len=5,
                                     seed=31)
    ref0 = _ref_stream(model, params, extra, prompts[0], 12)
    # an EOS id the greedy stream emits early but not immediately
    i, eos = next((i, t) for i, t in enumerate(ref0[1:-1], 1)
                  if t not in ref0[:i])

    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=48, decode_block=2, bucket=8,
        prefix_cache=True, prefix_page=4,
    ))
    h0 = eng.submit(prompts[0], max_new_tokens=12, eos_id=eos)
    rest = [eng.submit(p, max_new_tokens=12) for p in prompts[1:]]
    eng.run()
    assert h0.finish_reason == "eos"
    assert h0.tokens == _ref_stream(model, params, extra, prompts[0], 12,
                                    eos_id=eos)
    for p, h in zip(prompts[1:], rest):
        assert h.slot == h0.slot  # single lane: every request reused it
        assert h.tokens == _ref_stream(model, params, extra, p, 12)
    # B and C shared A's stem: both admissions spliced
    assert eng.metrics.prefix_hits >= 2


def test_prefix_metrics_flow_through_snapshot():
    model, params, _ = _gpt_tiny()
    prompts = _shared_prefix_prompts(4, n_stems=1, seed=7)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=32, decode_block=4, bucket=8,
        prefix_cache=True, prefix_page=4,
    ))
    for p in prompts:
        eng.submit(p, max_new_tokens=4)
    eng.run()
    snap = eng.metrics.snapshot()
    assert snap["serve/prefix_lookups"] == 4
    assert 0 < snap["serve/prefix_hit_rate"] <= 1
    assert snap["serve/prefix_cached_tokens"] == \
        snap["serve/tokens_prefilled_saved"] > 0
    assert snap["serve/prefix_hbm_bytes"] > 0
    assert snap["serve/prefix_evictions"] == 0
    # prefilled counts only what the engine actually ran prefill over
    total_prompt = sum(len(p) for p in prompts)
    assert snap["serve/tokens_prefilled"] == \
        total_prompt - snap["serve/prefix_cached_tokens"]


def test_prefix_sched_requires_prefix_cache():
    model, params, _ = _gpt_tiny()
    with pytest.raises(ValueError, match="prefix_cache=True"):
        ServeEngine(model, params, ServeConfig(
            n_slots=1, max_len=32, prefix_cache=False, prefix_sched=True,
        ))


def test_cache_disabled_has_no_tree_and_no_counters():
    model, params, _ = _gpt_tiny()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=32, prefix_cache=False,
    ))
    assert eng.prefix_cache is None
    eng.submit(np.arange(8, dtype=np.int32), max_new_tokens=2)
    eng.run()
    assert "serve/prefix_lookups" not in eng.metrics.snapshot()
