"""Vision/MLP zoo tests: ViT accuracy, AE/VAE reconstruction, KD pipeline,
AlexNet forward, LRN vs torch semantics (SURVEY.md §4 targets: 97.25% ViT /
97.50% KD on MNIST — here asserted as 'well above chance' on the synthetic
class-separable set, since MNIST itself is not downloadable offline).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.data.images import image_batch_iterator, load_image_dataset
from solvingpapers_tpu.models.alexnet import AlexNet, AlexNetConfig
from solvingpapers_tpu.models.autoencoder import (
    AutoEncoder,
    AutoEncoderConfig,
    VAE,
    VAEConfig,
)
from solvingpapers_tpu.models.kd import MLPClassifier, student_config, teacher_config
from solvingpapers_tpu.models.vit import ViT, ViTConfig
from solvingpapers_tpu.train import (
    OptimizerConfig,
    TrainConfig,
    Trainer,
    classification_loss_fn,
    make_kd_loss_fn,
    reconstruction_loss_fn,
    vae_loss_fn,
)


def small_train_cfg(steps, lr=1e-3, batch=32):
    return TrainConfig(
        steps=steps, batch_size=batch, log_every=10_000, eval_every=0,
        optimizer=OptimizerConfig(max_lr=lr, warmup_steps=5, total_steps=steps),
    )


def one_device_mesh():
    """Single-device mesh: the 8-virtual-device default oversubscribes the
    1-core CPU host and can deadlock the all-reduce rendezvous (40s XLA
    timeout). Multi-device meshes are exercised only by the short
    sharded-equality tests."""
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=1, fsdp=1, model=1), jax.devices()[:1])


def run_steps(trainer, it, steps):
    b0 = next(it)
    state = trainer.init_state(b0)
    trainer._build_steps()
    state, m = trainer._train_step(state, b0)
    first = jax.device_get(m)
    for _ in range(steps):
        state, m = trainer._train_step(state, next(it))
    return state, first, jax.device_get(m)


def test_vit_learns_classification():
    tx, ty, _, _ = load_image_dataset(n_train=2048, n_test=1)
    cfg = ViTConfig(dim=32, n_layers=2, n_heads=2)
    trainer = Trainer(ViT(cfg), small_train_cfg(120, lr=3e-3),
                      loss_fn=classification_loss_fn, mesh=one_device_mesh())
    it = image_batch_iterator(tx, ty, 32, seed=0)
    _, first, last = run_steps(trainer, it, 120)
    assert last["train_accuracy"] > 0.65, (first, last)
    assert last["train_loss"] < first["train_loss"]


def test_autoencoder_reconstructs():
    tx, ty, _, _ = load_image_dataset(n_train=1024, n_test=1)
    model = AutoEncoder(AutoEncoderConfig())
    trainer = Trainer(model, small_train_cfg(80, lr=2e-3),
                      loss_fn=reconstruction_loss_fn, mesh=one_device_mesh())
    it = image_batch_iterator(tx, ty, 32, seed=0, flatten=True)
    _, first, last = run_steps(trainer, it, 80)
    # untrained MSE vs mean-ish reconstruction; must drop substantially
    assert last["train_loss"] < 0.6 * first["train_loss"], (first, last)


def test_vae_elbo_decreases_and_parts_logged():
    tx, ty, _, _ = load_image_dataset(n_train=1024, n_test=1)
    model = VAE(VAEConfig(latent_dim=16, hidden_dim=64))
    trainer = Trainer(model, small_train_cfg(80, lr=1e-3), loss_fn=vae_loss_fn,
                      mesh=one_device_mesh())
    it = image_batch_iterator(tx, ty, 32, seed=0, flatten=True)
    _, first, last = run_steps(trainer, it, 80)
    assert last["train_loss"] < first["train_loss"]
    assert "train_bce" in last and "train_kl" in last
    assert last["train_kl"] >= 0.0


def test_kd_student_learns_from_frozen_teacher():
    """kd.py pipeline: pretrain teacher, freeze, distill student."""
    tx, ty, _, _ = load_image_dataset(n_train=2048, n_test=1)

    teacher = MLPClassifier(teacher_config())
    t_trainer = Trainer(teacher, small_train_cfg(100, lr=1e-3),
                        loss_fn=classification_loss_fn, mesh=one_device_mesh())
    t_it = image_batch_iterator(tx, ty, 64, seed=0, flatten=True)
    t_state, _, t_last = run_steps(t_trainer, t_it, 100)
    assert t_last["train_accuracy"] > 0.7, t_last

    student = MLPClassifier(student_config())
    s_trainer = Trainer(
        student, small_train_cfg(100, lr=1e-3),
        loss_fn=make_kd_loss_fn(teacher, jax.device_get(t_state.params)),
        mesh=one_device_mesh(),
    )
    s_it = image_batch_iterator(tx, ty, 64, seed=1, flatten=True)
    _, s_first, s_last = run_steps(s_trainer, s_it, 100)
    assert s_last["train_accuracy"] > 0.7, (s_first, s_last)
    assert s_last["train_loss"] < s_first["train_loss"]


def test_alexnet_forward_shape():
    model = AlexNet(AlexNetConfig(n_classes=10, in_channels=3))
    x = jnp.zeros((2, 224, 224, 3))
    params = model.init({"params": jax.random.key(0)}, x)["params"]
    logits = model.apply({"params": params}, x, deterministic=True)
    assert logits.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_local_response_norm_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.default_rng(0).normal(size=(2, 8, 8, 16)).astype(np.float32)
    ours = np.asarray(ops.local_response_norm(jnp.asarray(x), size=5))
    # torch LRN is NCHW
    xt = torch.from_numpy(x).permute(0, 3, 1, 2)
    ref = torch.nn.LocalResponseNorm(5)(xt).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-6)


def test_vae_sampling_is_stochastic_in_train_mode():
    model = VAE(VAEConfig(latent_dim=4, hidden_dim=16, input_dim=32))
    x = jnp.ones((2, 32)) * 0.5
    params = model.init(
        {"params": jax.random.key(0), "sample": jax.random.key(1)}, x
    )["params"]
    r1, _, _ = model.apply({"params": params}, x, rngs={"sample": jax.random.key(2)})
    r2, _, _ = model.apply({"params": params}, x, rngs={"sample": jax.random.key(3)})
    det, mu, _ = model.apply({"params": params}, x, deterministic=True)
    assert not np.allclose(np.asarray(r1), np.asarray(r2))
    det2, _, _ = model.apply({"params": params}, x, deterministic=True)
    np.testing.assert_array_equal(np.asarray(det), np.asarray(det2))


def test_sharded_vit_matches_single_device(devices):
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    tx, ty, _, _ = load_image_dataset(n_train=512, n_test=1)
    cfg = ViTConfig(dim=32, n_layers=2, n_heads=2)

    def run(mesh_cfg, devs):
        mesh = create_mesh(mesh_cfg, devs)
        trainer = Trainer(ViT(cfg), small_train_cfg(2, lr=1e-3, batch=16),
                          loss_fn=classification_loss_fn, mesh=mesh)
        it = image_batch_iterator(tx, ty, 16, seed=5, mesh=mesh)
        _, first, last = run_steps(trainer, it, 2)
        return [first["train_loss"], last["train_loss"]]

    single = run(MeshConfig(data=1, fsdp=1, model=1), devices[:1])
    sharded = run(MeshConfig(data=4, fsdp=2, model=1), devices)
    np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=2e-5)


def test_gaussian_source_bayes_accuracy_is_exact():
    """The computable ceiling (VERDICT r3 missing #5's calibrated vision
    benchmark): the 1-D integral matches the K=2 closed form, the class
    means are orthonormal, and the matched filter's empirical accuracy on
    a fresh sample lands on the integral (so the ceiling describes the
    actual data, not an idealization)."""
    from math import erf, sqrt

    from solvingpapers_tpu.data.synthetic import GaussianImageSource

    two = GaussianImageSource(n_classes=2, snr=1.7)
    closed = 0.5 * (1 + erf(1.7 / 2.0))  # Phi(snr/sqrt(2))
    np.testing.assert_allclose(two.bayes_accuracy, closed, atol=1e-6)

    src = GaussianImageSource()
    m = src.means.reshape(src.n_classes, -1)
    np.testing.assert_allclose(m @ m.T, np.eye(src.n_classes), atol=1e-12)
    x, y = src.sample(20_000, seed=3)
    emp = src.matched_filter_accuracy(x, y)
    assert abs(emp - src.bayes_accuracy) < 0.01, (emp, src.bayes_accuracy)
    assert 0.8 < src.bayes_accuracy < 0.95  # genuinely non-saturating


def test_bayes_set_classifier_approaches_ceiling_not_one():
    """A small classifier on the Bayes set must climb toward the ceiling
    and CANNOT reach 1.0 — the property the separable set lacks. Short
    schedule on the MLP (the fastest learner of the matched filter);
    within 0.12 of the ceiling is enough to show calibrated learning (the
    parity suite runs the full schedules against the 0.05 absolute
    target)."""
    import dataclasses

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_image_run
    from solvingpapers_tpu.data.synthetic import GaussianImageSource
    from solvingpapers_tpu.models.kd import MLPClassifier, teacher_config
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh
    from solvingpapers_tpu.train import Trainer

    cfg = get_config("kd_bayes", steps=600)
    cfg = dataclasses.replace(cfg, data={**cfg.data, "n_train": 16384})
    mesh = create_mesh(MeshConfig(data=1), jax.devices()[:1])
    _, train_iter, eval_iter_fn, cls_loss = build_image_run(cfg, mesh=mesh)
    tcfg = dataclasses.replace(cfg.train, steps=600, eval_every=0)
    trainer = Trainer(MLPClassifier(teacher_config(dtype=cfg.model.dtype)),
                      tcfg, loss_fn=cls_loss, mesh=mesh)
    state = trainer.fit(train_iter)
    val = trainer.evaluate(state, eval_iter_fn())
    acc = float(val["val_accuracy"])
    ceiling = GaussianImageSource(snr=2.8, seed=cfg.train.seed + 7).bayes_accuracy
    assert acc <= ceiling + 0.03, (acc, ceiling)  # can't beat Bayes
    assert acc > ceiling - 0.12, (acc, ceiling)   # but does approach it
