"""Per-request sampling + request lifecycle (solvingpapers_tpu/serve/).

The contracts under test (serve/sampling.py, engine integration):

* mixed-batch determinism — a greedy-params request decoded alongside
  stochastic slots is token-exact with solo one-shot `generate`, and a
  fixed-seed stochastic request replays the same stream across two
  engine runs (its rng chain folds only (seed, sample index), never the
  slot or engine step), for the gpt AND llama3 families;
* no compile explosion — sampling params are traced operands, so a mixed
  stochastic engine adds ZERO compiled prefill/decode programs over a
  greedy one (pinned via the jit caches);
* lifecycle — cancel mid-stream frees the lane for a waiting request,
  deadlines expire waiting AND active requests ("timeout"), stop strings
  match across block boundaries, stop token-id sets act as multi-token
  EOS ("stop"), and finish reasons are counted in ServeMetrics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.infer import generate
from solvingpapers_tpu.serve import SamplingParams, ServeConfig, ServeEngine
from solvingpapers_tpu.serve import metrics as smetrics
from solvingpapers_tpu.serve.engine import _decode_program, _prefill_program


def _gpt_tiny():
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig

    model = GPT(GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                          n_heads=2, dropout=0.0))
    params = model.init({"params": jax.random.key(0)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _llama3_tiny():
    from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig

    model = Llama(LlamaConfig(vocab_size=64, max_seq_len=64, dim=32,
                              n_layers=2, n_heads=4, n_kv_heads=2,
                              dropout=0.0))
    params = model.init({"params": jax.random.key(1)},
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


_FAMILIES = {"gpt": _gpt_tiny, "llama3": _llama3_tiny}


def _prompts(n, seed=0, lo=4, hi=16):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 64, size=int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


def _ref_stream(model, params, prompt, max_new):
    out = generate(model, params, jnp.asarray(prompt)[None, :],
                   jax.random.key(0), max_new_tokens=max_new)
    return np.asarray(out[0, len(prompt):]).tolist()


# ------------------------------------------------- mixed-batch determinism


@pytest.mark.parametrize("family", sorted(_FAMILIES))
def test_greedy_in_mixed_batch_exact_and_seeded_reproducible(family):
    """One greedy + two stochastic (seeded) requests share every decode
    block. The greedy stream must equal solo generate; the seeded streams
    must replay identically on a fresh engine."""
    model, params = _FAMILIES[family]()
    prompts = _prompts(3, seed=3)

    def run():
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=3, max_len=64, decode_block=4, bucket=8,
        ))
        handles = [
            eng.submit(prompts[0], max_new_tokens=10),
            eng.submit(prompts[1], max_new_tokens=10, params=SamplingParams(
                temperature=1.2, top_p=0.9, seed=7)),
            eng.submit(prompts[2], max_new_tokens=10, params=SamplingParams(
                temperature=0.8, top_k=8, min_p=0.02, seed=11)),
        ]
        eng.run()
        return handles

    a, b = run(), run()
    assert all(h.done for h in a)
    assert a[0].tokens == _ref_stream(model, params, prompts[0], 10), (
        f"{family}: greedy request diverged inside the stochastic batch"
    )
    assert a[1].tokens == b[1].tokens, f"{family}: seed=7 stream not stable"
    assert a[2].tokens == b[2].tokens, f"{family}: seed=11 stream not stable"


def test_seeded_stream_independent_of_batch_composition():
    """The seeded chain folds (seed, sample index) only: the same seeded
    request must replay the same stream whether it shares the engine with
    other traffic or runs alone (different slot, different step counters)."""
    model, params = _gpt_tiny()
    prompt = _prompts(1, seed=9)[0]
    sp = SamplingParams(temperature=1.1, top_p=0.95, seed=42)

    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
    ))
    filler = eng.submit(_prompts(1, seed=10)[0], max_new_tokens=6)
    eng.step()  # filler decodes first: the seeded req lands in slot 1 later
    h_batched = eng.submit(prompt, max_new_tokens=8, params=sp)
    eng.run()
    assert filler.done

    solo = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
    ))
    h_solo = solo.submit(prompt, max_new_tokens=8, params=sp)
    solo.run()
    assert h_batched.tokens == h_solo.tokens


def test_no_compile_explosion_from_param_mix():
    """Sampling params are traced operands: a mixed stochastic engine
    must add ZERO compiled decode/prefill programs over a greedy-only
    engine with the same shapes."""
    model, params = _gpt_tiny()
    prompts = _prompts(4, seed=5, lo=4, hi=8)  # one bucket
    cfg = ServeConfig(n_slots=2, max_len=64, decode_block=4, bucket=8)

    eng = ServeEngine(model, params, cfg)
    for p in prompts:
        eng.submit(p, max_new_tokens=6)
    eng.run()
    decode_progs = _decode_program._cache_size()
    prefill_progs = _prefill_program._cache_size()

    eng = ServeEngine(model, params, cfg)
    mixes = (
        None,
        SamplingParams(temperature=1.3, top_p=0.8, seed=1),
        SamplingParams(temperature=0.7, top_k=5),
        SamplingParams(temperature=1.0, min_p=0.1, seed=2, logprobs=True),
    )
    for p, sp in zip(prompts, mixes):
        eng.submit(p, max_new_tokens=6, params=sp)
    eng.run()
    assert _decode_program._cache_size() == decode_progs
    assert _prefill_program._cache_size() == prefill_progs


def test_logprobs_stream_per_token_and_reproducible():
    model, params = _gpt_tiny()
    prompt = _prompts(1, seed=6)[0]

    def run(sp):
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=1, max_len=64, decode_block=4, bucket=8,
        ))
        h = eng.submit(prompt, max_new_tokens=7, params=sp)
        eng.run()
        return h

    g = run(SamplingParams(logprobs=True))
    assert len(g.logprobs) == len(g.tokens) == 7
    assert all(np.isfinite(lp) and lp <= 0 for lp in g.logprobs)
    s1 = run(SamplingParams(temperature=1.2, seed=3, logprobs=True))
    s2 = run(SamplingParams(temperature=1.2, seed=3, logprobs=True))
    assert s1.logprobs == s2.logprobs and len(s1.logprobs) == 7
    # logprobs off: nothing accumulates
    off = run(SamplingParams(temperature=1.2, seed=3))
    assert off.logprobs == [] and off.tokens == s1.tokens


def test_params_max_tokens_overrides_submit_budget():
    model, params = _gpt_tiny()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8,
    ))
    h = eng.submit(_prompts(1)[0], max_new_tokens=20,
                   params=SamplingParams(max_tokens=3))
    eng.run()
    assert h.finish_reason == "length" and len(h.tokens) == 3


# --------------------------------------------------------------- lifecycle


def test_cancel_mid_stream_frees_lane_for_waiting_request():
    """Cancel an ACTIVE request: it finishes "cancelled" at the next
    block boundary and its lane is re-acquired by the queued request
    (which must still produce an exact greedy stream)."""
    model, params = _gpt_tiny()
    prompts = _prompts(2, seed=12, lo=6, hi=10)
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8,
    ))
    h1 = eng.submit(prompts[0], max_new_tokens=30)
    h2 = eng.submit(prompts[1], max_new_tokens=6)
    eng.step()
    assert h1.state == "active" and not h1.done
    emitted = len(h1.tokens)
    eng.cancel(h1)
    eng.run()
    assert h1.finish_reason == "cancelled" and h1.done
    assert len(h1.tokens) == emitted  # the cancelled block was discarded
    assert h2.done and h2.finish_reason == "length"
    assert h2.slot == h1.slot, "cancel never freed the lane"
    assert h2.tokens == _ref_stream(model, params, prompts[1], 6)
    assert eng.metrics.finish_reasons == {"cancelled": 1, "length": 1}


def test_cancel_waiting_request_leaves_queue_immediately():
    model, params = _gpt_tiny()
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8,
    ))
    h1 = eng.submit(_prompts(1, seed=1)[0], max_new_tokens=8)
    h2 = eng.submit(_prompts(1, seed=2)[0], max_new_tokens=8)
    eng.cancel(h2)
    assert h2.done and h2.finish_reason == "cancelled" and h2.tokens == []
    assert list(eng.scheduler.queue) == [h1]  # h1 still waits its turn
    eng.run()
    assert h1.done and h1.finish_reason == "length"
    # cancelling a finished request is a harmless no-op
    eng.cancel(h1)
    assert h1.finish_reason == "length"


def test_deadline_expiry_mid_decode_frees_lane(monkeypatch):
    """Drive the engine clock by hand: a request whose deadline passes
    between decode blocks finishes "timeout" at the boundary, the
    expired block's tokens are discarded, and the lane goes to the next
    queued request."""
    model, params = _gpt_tiny()
    clock = {"t": 100.0}
    monkeypatch.setattr(smetrics, "now", lambda: clock["t"])
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8,
    ))
    h1 = eng.submit(_prompts(1, seed=20)[0], max_new_tokens=30,
                    deadline_s=5.0)
    h2 = eng.submit(_prompts(1, seed=21)[0], max_new_tokens=4)
    eng.step()  # admit + first block, well inside the deadline
    assert h1.state == "active"
    emitted = len(h1.tokens)
    clock["t"] = 120.0  # past the deadline
    eng.run()
    assert h1.finish_reason == "timeout" and len(h1.tokens) == emitted
    assert h2.done and h2.finish_reason == "length"
    assert h2.slot == h1.slot


def test_deadline_expiry_while_waiting_purges_queue(monkeypatch):
    model, params = _gpt_tiny()
    clock = {"t": 0.0}
    monkeypatch.setattr(smetrics, "now", lambda: clock["t"])
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8,
    ))
    active = eng.submit(_prompts(1, seed=22)[0], max_new_tokens=12)
    starved = eng.submit(_prompts(1, seed=23)[0], max_new_tokens=4,
                         deadline_s=2.0)
    eng.step()
    clock["t"] = 10.0
    eng.run()
    assert starved.finish_reason == "timeout"
    assert starved.tokens == [] and starved.slot is None
    assert active.done and active.finish_reason == "length"


def test_stop_string_spanning_block_boundary():
    """A stop string whose match completes with the first token of a NEW
    decode block must still end the stream (host-side matching re-decodes
    the whole generated text, so matches span boundaries)."""
    model, params = _gpt_tiny()
    block = 4

    def detok(ids):
        return "".join(f"<{t}>" for t in ids)

    # deterministic reference: a seeded stochastic stream (diverse tokens,
    # unlike tiny-model greedy streams which often repeat one id)
    sp = SamplingParams(temperature=1.3, seed=17)
    ref_eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=block, bucket=8,
    ))
    prompt = _prompts(1, seed=30, lo=6, hi=7)[0]
    ref = ref_eng.submit(prompt, max_new_tokens=12, params=sp)
    ref_eng.run()
    text = detok(ref.tokens)
    # tokens[0] is prefill's; block 1 appends [1..block] — so index
    # `block` is a block's last token and `block+1` opens the next block
    spans = [i for i in (block, 2 * block)
             if i + 1 < len(ref.tokens)
             and text.find(detok(ref.tokens[i:i + 2])) ==
             len(detok(ref.tokens[:i]))]
    assert spans, "seeded stream never gave a boundary-spanning unique pair"
    i = spans[0]
    stop = detok(ref.tokens[i:i + 2])

    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=block, bucket=8,
    ), detokenize=detok)
    h = eng.submit(prompt, max_new_tokens=12, params=SamplingParams(
        temperature=1.3, seed=17, stop=(stop,)))
    eng.run()
    assert h.finish_reason == "stop"
    assert h.tokens == ref.tokens[:i + 2], (
        "stream must end at the token completing the cross-boundary match"
    )


def test_stop_token_id_set_acts_as_multi_token_eos():
    """`stop_token_ids` is a per-request multi-token EOS set: the first
    emitted member ends the stream (kept, reason "stop") — and different
    requests can carry different sets in the same batch. Seeded
    stochastic references give diverse streams (tiny-model greedy streams
    often repeat one id, which would make the cut index degenerate)."""
    model, params = _gpt_tiny()
    prompts = _prompts(2, seed=31, lo=6, hi=10)
    base = [SamplingParams(temperature=1.25, seed=50 + j) for j in range(2)]

    def run(extra_ids):
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=64, decode_block=4, bucket=8,
        ))
        handles = [
            eng.submit(prompts[j], max_new_tokens=12,
                       params=SamplingParams(
                           temperature=base[j].temperature,
                           seed=base[j].seed,
                           stop_token_ids=extra_ids[j]))
            for j in range(2)
        ]
        eng.run()
        return handles

    refs = [h.tokens for h in run(((), ()))]
    # stop on a token first emitted at index >= 2, per request
    cut, ids = [], []
    for r in refs:
        k = next(i for i in range(2, len(r)) if r[i] not in r[:i])
        cut.append(k)
        ids.append((int(r[k]), 4095))  # 4095: never-sampled extra member
    handles = run(tuple(ids))
    for j, h in enumerate(handles):
        assert h.finish_reason == "stop"
        assert h.tokens == refs[j][:cut[j] + 1]
        assert h.tokens[-1] in h.params.stop_token_ids


def test_stop_reason_at_prefill_first_token():
    """A stop-set member as the FIRST sampled token finishes the request
    at admission (prefill-only finish), freeing the lane that instant."""
    model, params = _gpt_tiny()
    prompt = _prompts(1, seed=32)[0]
    first = _ref_stream(model, params, prompt, 1)[0]
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=4, bucket=8,
    ))
    h = eng.submit(prompt, max_new_tokens=12,
                   params=SamplingParams(stop_token_ids=(int(first),)))
    eng.run()
    assert h.finish_reason == "stop" and h.tokens == [first]
    assert eng.pool.n_free == 1
