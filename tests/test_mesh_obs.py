"""Mesh observatory tests (metrics/mesh_obs.py + wiring through
sharding/, train/engine.py, metrics/trace.py, metrics/xla_obs.py).

Contracts under test:
  * `parse_hlo_collectives` counts and sizes collectives — pinned on
    synthetic HLO text (tuple outputs, async -start/-done pairs,
    operand references) and on a REAL TP-sharded compiled program
    against an independent hand-count of its HLO text; a single-device
    program reports a TRUE zero (not an absence).
  * `pytree_device_bytes` books `Sharding.shard_shape` bytes per device:
    replicated, TP-sharded, pipeline-stage-stacked, and mixed pytrees
    pinned against analytic byte counts.
  * the schedule algebra (sharding/pipeline.py) matches the schedules'
    tick math, and `bubble_report` reduces to the analytic
    (S-1)/(M+S-1) for balanced stages.
  * a deliberately imbalanced 2-stage pipeline (one stage 2x heavier)
    names the straggler and its MEASURED bubble fraction lands within
    tolerance of the prediction from probed stage costs.
  * `mesh/*` gauges are present IFF mesh_obs is enabled (the PR-5
    `mem/*`/`compile/*` key-surface pattern) and Prometheus-renderable.
  * mesh trace tracks round-trip: per-tick stage spans + bubble_report
    instant -> export -> `summarize_trace` mesh section -> formatter;
    traces recorded WITHOUT mesh events (PR-4/5 era) summarize with the
    mesh key absent — no crash, no invented zeros.
  * the Trainer's 1F1B wiring: a 2-stage pipeline fit with mesh_obs on
    emits bubble + comm gauges and a trace whose summary prints the
    bubble report.
"""

import functools
import json
import re
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu.metrics.mesh_obs import (
    MeshObservatory,
    PipelineScheduleInfo,
    bubble_report,
    parse_hlo_collectives,
    probe_stage_costs,
)
from solvingpapers_tpu.metrics.trace import (
    FlightRecorder,
    format_mesh,
    format_summary,
    summarize_trace,
)
from solvingpapers_tpu.metrics.writer import PrometheusTextWriter
from solvingpapers_tpu.metrics.xla_obs import (
    CompileRegistry,
    clear_aot_cache,
    pytree_bytes,
    pytree_device_bytes,
)
from solvingpapers_tpu.sharding import (
    MeshConfig,
    analytic_bubble_fraction,
    create_mesh,
    schedule_ticks,
    tick_unit,
)
from solvingpapers_tpu.sharding.pipeline import (
    pipeline_apply,
    stack_stage_params,
)

pytestmark = pytest.mark.fast


# ----------------------------------------------------- collective ledger


def test_parse_hlo_collectives_synthetic():
    """Hand-built HLO text: defining ops count (async pairs once, at the
    -start), operand references and -done lines never do, tuple output
    shapes sum their atoms."""
    hlo = "\n".join([
        "ENTRY %main {",
        "  %p = f32[8,128]{1,0} parameter(0)",
        "  %ar = f32[8,128]{1,0} all-reduce(f32[8,128]{1,0} %p), "
        "to_apply=%add",
        "  %ags = (f32[4,8]{1,0}, f32[8,8]{1,0}) all-gather-start("
        "f32[4,8]{1,0} %p), dimensions={0}",
        "  %agd = f32[8,8]{1,0} all-gather-done((f32[4,8], f32[8,8]) "
        "%ags)",
        "  %t = (f32[2,2]{1,0}) tuple(%ar)",
        "  %gte = f32[8,8]{1,0} get-tuple-element(%ags), index=1",
        "  %cp = f32[4]{0} collective-permute(f32[4]{0} %p2), "
        "source_target_pairs={{0,1}}",
        "  %rs = bf16[16]{0} reduce-scatter(bf16[32]{0} %y), "
        "dimensions={0}",
        "}",
    ])
    stats = parse_hlo_collectives(hlo)
    assert stats["ops"] == 4
    assert stats["by_type"]["all-reduce"] == {"ops": 1, "bytes": 8 * 128 * 4}
    # the -start's tuple output: f32[4,8] + f32[8,8]
    assert stats["by_type"]["all-gather"] == {
        "ops": 1, "bytes": (4 * 8 + 8 * 8) * 4,
    }
    assert stats["by_type"]["collective-permute"] == {"ops": 1, "bytes": 16}
    assert stats["by_type"]["reduce-scatter"] == {"ops": 1, "bytes": 32}
    assert stats["bytes"] == sum(
        d["bytes"] for d in stats["by_type"].values()
    )
    # a program with no collectives is a TRUE zero
    empty = parse_hlo_collectives("ENTRY %m {\n  ROOT %d = f32[4]{0} "
                                  "dot(%a, %b)\n}")
    assert empty == {"ops": 0, "bytes": 0, "by_type": {}}


def test_collective_ledger_tp_nonzero_single_device_zero(devices):
    """Acceptance pin: a TP-sharded program reports nonzero comm bytes
    (matching an independent hand-count of its compiled HLO text); a
    single-device program reports exactly zero."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    clear_aot_cache()
    mesh = create_mesh(MeshConfig(data=2, model=4), devices)
    reg = CompileRegistry(collectives=True)
    x = jax.device_put(
        jnp.ones((8, 128)), NamedSharding(mesh, P(("data", "fsdp"), "model"))
    )
    w = jax.device_put(
        jnp.ones((128, 64)), NamedSharding(mesh, P("model", None))
    )
    tp = jax.jit(lambda a, b: a @ b)
    reg.call("tp_matmul", ("sig",), tp, (x, w))
    single = jax.jit(lambda a: a @ a.T)
    reg.call("local_matmul", ("sig",), single, (jnp.ones((4, 4)),))

    stats = reg.collective_stats()
    assert stats["local_matmul"]["ops"] == 0
    assert stats["local_matmul"]["bytes"] == 0
    tp_stats = stats["tp_matmul"]
    assert tp_stats["ops"] >= 1 and tp_stats["bytes"] > 0
    assert "all-reduce" in tp_stats["by_type"]  # contracting-dim TP

    # hand-count: defining collective lines in the compiled HLO text,
    # independently of the parser's regex
    hlo = tp.lower(x, w).compile().as_text()
    hand = 0
    for line in hlo.splitlines():
        if "= " not in line:
            continue
        rhs = line.split("= ", 1)[1]
        for kind in ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute"):
            if re.search(rf"\s{kind}(-start)?\(", " " + rhs):
                hand += 1
                break
    assert tp_stats["ops"] == hand

    # gauges carry the ledger; the observatory key surface is floats
    obs = MeshObservatory(mesh=mesh, registry=reg)
    g = obs.gauges()
    assert g["mesh/comm_bytes_per_step"] == float(tp_stats["bytes"])
    assert g["mesh/comm_programs"] == 1.0  # only the TP program talks
    assert all(isinstance(v, float) for v in g.values())
    # /statusz carries the per-program join
    snap = obs.snapshot()
    assert snap["comm"]["tp_matmul"]["ops"] == tp_stats["ops"]
    assert reg.snapshot()["programs"]["tp_matmul"][
        "comm_bytes_per_call"] == tp_stats["bytes"]


# --------------------------------------------------- per-device HBM math


def test_pytree_device_bytes_sharded_pins(devices):
    """Replicated, TP-sharded, and pipeline-stage-stacked leaves book
    analytic shard_shape bytes per device; a mixed pytree sums them;
    host arrays fall back to global bytes."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh(MeshConfig(data=1, model=2, pipe=4), devices)

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    rep = put(jnp.ones((16, 8), jnp.float32), P())           # 512 B global
    tp = put(jnp.ones((16, 8), jnp.float32), P(None, "model"))  # /2
    stacked = put(jnp.ones((4, 6, 4), jnp.float32), P("pipe"))  # /4

    assert pytree_device_bytes(rep) == 16 * 8 * 4
    assert pytree_device_bytes(tp) == 16 * 8 * 4 // 2
    assert pytree_device_bytes(stacked) == 4 * 6 * 4 * 4 // 4
    # global accounting is unchanged
    assert pytree_bytes(tp) == 16 * 8 * 4
    # mixed replicated + sharded pytree: the per-pool case the HBM
    # ledger books under a mesh
    tree = {"rep": rep, "tp": tp, "stages": {"w": stacked}}
    assert pytree_device_bytes(tree) == 512 + 256 + 96
    assert pytree_bytes(tree) == 512 + 512 + 384
    # host leaves: no sharding -> global bytes (single-device semantics)
    assert pytree_device_bytes({"h": np.ones((3, 3), np.float32)}) == 36


def test_hbm_ledger_books_per_device_bytes(devices):
    """The train engine registers per-device providers: a ledger over a
    pipe-stacked pool must report shard bytes, not global."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from solvingpapers_tpu.metrics.xla_obs import HBMLedger

    mesh = create_mesh(MeshConfig(data=1, model=2, pipe=4), devices)
    stages = jax.device_put(
        jnp.ones((4, 32, 32), jnp.float32), NamedSharding(mesh, P("pipe"))
    )
    ledger = HBMLedger(capacity_bytes=1 << 20)
    ledger.register("params", lambda: pytree_device_bytes(stages))
    assert ledger.pool_bytes()["params"] == 32 * 32 * 4  # one stage row
    assert ledger.headroom_bytes() == (1 << 20) - 32 * 32 * 4


# ----------------------------------------------------- schedule algebra


def test_schedule_algebra_pins():
    assert schedule_ticks(4, 4) == 7                      # gpipe m+P-1
    assert schedule_ticks(8, 2, n_virtual=2) == 17        # m*v+P-1
    assert schedule_ticks(4, 2, schedule="1f1b") == 10    # 2(m+P)-2
    assert analytic_bubble_fraction(4, 4) == pytest.approx(3 / 7)
    assert analytic_bubble_fraction(4, 2, 2) == pytest.approx(1 / 9)

    # gpipe: device d runs microbatch t-d, ramp/drain are bubbles
    assert tick_unit(0, 0, 4, 4) == "F0"
    assert tick_unit(2, 3, 4, 4) == "bubble"
    assert tick_unit(6, 3, 4, 4) == "F3"
    # 1f1b S=2, M=2 (mirrors the schedule: F at t=d+2i, B at
    # t=2P-1-d+2i, everything else a garbage-compute tick)
    labels = {d: [tick_unit(t, d, 2, 2, schedule="1f1b")
                  for t in range(schedule_ticks(2, 2, schedule="1f1b"))]
              for d in (0, 1)}
    assert labels[0] == ["F0", "bubble", "F1", "B0", "bubble", "B1"]
    assert labels[1] == ["bubble", "F0", "B0", "F1", "B1", "bubble"]
    # interleaved: group g member i on slice j
    assert tick_unit(0, 0, 4, 2, n_virtual=2) == "F0.v0"
    assert tick_unit(2, 0, 4, 2, n_virtual=2) == "F0.v1"
    assert tick_unit(4, 0, 4, 2, n_virtual=2) == "F2.v0"


def test_bubble_report_math():
    """Fabricated probe costs pin the report's algebra: balanced
    reduces to the analytic formula; imbalance folds into predicted;
    measured uses the same useful/capacity definition."""
    bal = bubble_report([1.0, 1.0], 4, schedule="gpipe")
    assert bal["predicted_bubble_fraction"] == pytest.approx(
        bal["analytic_bubble_fraction"]
    )
    assert bal["analytic_bubble_fraction"] == pytest.approx(0.2)

    rep = bubble_report([1.0, 2.0], 4, schedule="1f1b",
                        measured_step_s=10.0)
    assert rep["straggler_stage"] == 1
    assert rep["imbalance"] == pytest.approx(2 / 1.5, abs=1e-3)
    # useful = 4*3, capacity = 2 * (4+2-1)*2 -> 1 - 12/20
    assert rep["predicted_bubble_fraction"] == pytest.approx(0.4)
    assert rep["predicted_step_s"] == pytest.approx(10.0)
    # measured capacity = 2 * 10 -> same fraction at the predicted wall
    assert rep["measured_bubble_fraction"] == pytest.approx(0.4)
    with pytest.raises(ValueError, match="empty"):
        bubble_report([], 4)


# ------------------------------------- imbalanced-pipeline acceptance


def _mlp(p, x):
    return jnp.tanh(x @ p["w1"]) @ p["w2"]


def _light(p, x):
    return _mlp(p, x)


def _heavy(p, x):
    return _mlp(p, _mlp(p, x))  # 2x the flops, shape-preserving


def test_imbalanced_pipeline_names_straggler_and_measures_bubble(devices):
    """Acceptance pin: a 2-stage pipeline where stage 1 does 2x the work
    (a lax.switch on the pipe axis index — the schedule stays one SPMD
    program, the per-device cost differs). The probe must name stage 1
    the straggler, and the measured bubble fraction of the real
    ppermute-lockstep schedule must land within tolerance of the
    prediction from the probed stage costs (CPU-mesh timing: tolerance
    is generous, the STRUCTURE — straggler, ordering vs the balanced
    analytic — is the hard assertion)."""
    d, h, mb_rows, m = 384, 1536, 64, 4
    mesh = create_mesh(MeshConfig(data=1, pipe=2), devices[:2])

    def stage_fn(p, x):
        sid = jax.lax.axis_index("pipe")
        return jax.lax.switch(
            sid, [lambda xx: _light(p, xx), lambda xx: _heavy(p, xx)], x
        )

    key = jax.random.key(0)
    stages = [
        {"w1": jax.random.normal(jax.random.fold_in(key, i), (d, h)) * 0.02,
         "w2": jax.random.normal(jax.random.fold_in(key, i + 9),
                                 (h, d)) * 0.02}
        for i in range(2)
    ]
    stacked = stack_stage_params(stages)
    x_mb = jax.random.normal(jax.random.key(1), (mb_rows, d))

    # shared-box CPU contention can inflate one probe's min-of-reps;
    # re-probe (bounded) until the 2x structure is visible, then assert
    # a bound loose enough for a noisy box but tight enough to prove the
    # probe ranks the stages by their real cost
    for _ in range(3):
        stage_s = probe_stage_costs(stacked, x_mb, [_light, _heavy], reps=7)
        if stage_s[1] / stage_s[0] > 1.3:
            break
    assert len(stage_s) == 2 and all(t > 0 for t in stage_s)
    # stage 1 is the 2x stage; probe ratio must reflect it
    assert stage_s[1] > stage_s[0]
    assert 1.1 < stage_s[1] / stage_s[0] < 4.0

    batch = jax.random.normal(jax.random.key(2), (m * mb_rows, d))
    run = jax.jit(functools.partial(
        pipeline_apply, stage_fn=stage_fn, mesh=mesh, n_microbatches=m
    ))
    jax.block_until_ready(run(stacked, batch))  # compile outside timing
    measured = min(
        (lambda t0: (jax.block_until_ready(run(stacked, batch)),
                     time.monotonic() - t0)[1])(time.monotonic())
        for _ in range(5)
    )

    rep = bubble_report(stage_s, m, schedule="gpipe",
                        measured_step_s=measured)
    assert rep["straggler_stage"] == 1
    # imbalance pushes the prediction above the balanced analytic
    assert rep["analytic_bubble_fraction"] == pytest.approx(0.2)
    assert rep["predicted_bubble_fraction"] > rep["analytic_bubble_fraction"]
    # measured within tolerance of the prediction (shared-CPU noise +
    # per-tick collective overhead bound the achievable tightness)
    assert abs(rep["measured_bubble_fraction"]
               - rep["predicted_bubble_fraction"]) < 0.25


# -------------------------------------------------- gauges key surface


class _RowWriter:
    def __init__(self):
        self.rows = []

    def write(self, step, metrics):
        self.rows.append((step, dict(metrics)))

    def close(self):
        pass


def _tiny_fit(mesh_obs: bool, devices, tmp_path=None, steps=2):
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.sharding import batch_sharding
    from solvingpapers_tpu.train import (
        OptimizerConfig,
        TrainConfig,
        Trainer,
    )

    mesh_cfg = MeshConfig(data=8)
    mesh = create_mesh(mesh_cfg, devices)
    cfg = GPTConfig(vocab_size=64, block_size=16, dim=16, n_layers=1,
                    n_heads=2, dropout=0.0)
    tcfg = TrainConfig(
        steps=steps, batch_size=8, log_every=1, eval_every=0,
        mesh=mesh_cfg, mesh_obs=mesh_obs,
        optimizer=OptimizerConfig(max_lr=1e-3, total_steps=10),
    )
    trainer = Trainer(GPT(cfg), tcfg, mesh=mesh)
    toks = np.arange(2048) % 64
    it = lm_batch_iterator(toks, 8, 16, sharding=batch_sharding(mesh))
    w = _RowWriter()
    trainer.fit(it, writer=w)
    return w.rows[-1][1]


def test_mesh_gauges_present_iff_mesh_obs_enabled(devices):
    """The PR-5 key-surface contract extended to mesh/*: a fit without
    mesh_obs must never grow the keys; with it, the collective ledger
    rides every logged row (data-parallel grads all-reduce, so comm
    bytes are nonzero even without a pipeline) and the whole surface
    survives the Prometheus name grammar."""
    row_off = _tiny_fit(False, devices)
    assert not any(k.startswith("mesh/") for k in row_off)

    clear_aot_cache()
    row_on = _tiny_fit(True, devices)
    mesh_keys = {k: v for k, v in row_on.items() if k.startswith("mesh/")}
    assert mesh_keys["mesh/devices"] == 8.0
    assert mesh_keys["mesh/comm_bytes_per_step"] > 0  # DP grad all-reduce
    assert mesh_keys["mesh/comm_programs"] >= 1.0
    # mesh_obs implies the compile registry even with xla_obs off
    assert any(k.startswith("compile/") for k in row_on)
    # no pipeline -> no bubble gauges (absent, not zero)
    assert "mesh/bubble_fraction_analytic" not in mesh_keys
    name_re = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    for k, v in mesh_keys.items():
        assert isinstance(v, float), k
        assert name_re.match(PrometheusTextWriter.sanitize(k)), k


# ------------------------------------------------- trace tracks + compat


def test_mesh_trace_roundtrip(tmp_path):
    """observe_step + set_stage_probe -> chrome export -> summarize:
    the per-stage tick timeline matches the schedule algebra, the bubble
    report instant survives, the formatter names the straggler."""
    rec = FlightRecorder()
    obs = MeshObservatory(
        mesh=None, registry=None, trace=rec,
        schedule=PipelineScheduleInfo(n_stages=2, n_microbatches=2,
                                      schedule="1f1b"),
    )
    obs.set_stage_probe([0.001, 0.002], 2)
    obs.observe_step(ts=0.0, dur_s=0.6)
    path = str(tmp_path / "mesh_trace.json")
    rec.export_chrome(path)

    summary = summarize_trace(path)
    mesh = summary["mesh"]
    # 6 ticks per device; per device: 2 F, 2 B, 2 bubbles (pinned above)
    for stage in ("stage0", "stage1"):
        d = mesh["stages"][stage]
        assert d["ticks"] == 6
        assert d["fwd"] == 2 and d["bwd"] == 2 and d["bubble"] == 2
        assert d["busy_s"] + d["bubble_s"] == pytest.approx(0.6, rel=1e-3)
    assert mesh["bubble"]["straggler_stage"] == 1
    assert mesh["bubble"]["measured_bubble_fraction"] is not None
    text = format_mesh(mesh)
    assert "straggler: stage1" in text
    assert "bubble fraction" in text
    # and through the full formatter (request-less serve summary)
    assert "straggler: stage1" in format_summary(summary)


def test_mesh_span_synthesis_is_capped():
    rec = FlightRecorder()
    obs = MeshObservatory(
        trace=rec,
        schedule=PipelineScheduleInfo(n_stages=2, n_microbatches=2),
        max_step_traces=2,
    )
    for i in range(5):
        obs.observe_step(ts=float(i), dur_s=0.1)
    ticks = schedule_ticks(2, 2)
    assert len(rec) == 2 * 2 * ticks  # 2 steps x 2 stages x ticks


def test_pre_mesh_traces_summarize_without_mesh_key(tmp_path):
    """Backward compat: a PR-4/5-era trace (request lifecycle spans, no
    mesh events) must summarize with NO mesh key — sections absent, not
    zeroed — and `cli trace-summary` must exit 0 on both serve- and
    train-shaped old traces."""
    rec = FlightRecorder()
    rec.instant("submit", "request", "queue", req=1)
    rec.complete("queue", "request", "queue", ts=0.0, dur=0.1, req=1)
    rec.complete("prefill", "request", "slot0", ts=0.1, dur=0.2, req=1,
                 tokens=4)
    rec.complete("decode", "request", "slot0", ts=0.3, dur=0.3, req=1)
    rec.instant("finish", "request", "engine", req=1, reason="eos")
    serve_path = str(tmp_path / "old_serve_trace.json")
    rec.export_chrome(serve_path)

    summary = summarize_trace(serve_path)
    assert "mesh" not in summary
    assert summary["n_requests"] == 1
    out = format_summary(summary)
    assert "bubble" not in out and "collective" not in out

    rec2 = FlightRecorder()
    rec2.complete("step", "train", "train", ts=0.0, dur=0.5, steps=1)
    rec2.instant("goodput", "train", "train", goodput=0.9, step_s=0.5,
                 wall_s=0.55)
    train_path = str(tmp_path / "old_train_trace.json")
    rec2.export_chrome(train_path)
    assert "mesh" not in summarize_trace(train_path)

    from solvingpapers_tpu.cli import cmd_trace_summary

    for p in (serve_path, train_path):
        rc = cmd_trace_summary(types.SimpleNamespace(trace=p, top=5))
        assert rc == 0


# --------------------------------------------------- trainer 1F1B wiring


def test_trainer_1f1b_mesh_obs_end_to_end(devices, tmp_path):
    """A 2-stage 1F1B fit with mesh_obs on: bubble + comm gauges ride
    the log rows, /statusz-shaped snapshots carry the mesh section, and
    the exported trace's summary prints the bubble report."""
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.gpt_pipe import GPTPipe, GPTPipeConfig
    from solvingpapers_tpu.sharding import PP_RULES, batch_sharding
    from solvingpapers_tpu.train import (
        OptimizerConfig,
        TrainConfig,
        Trainer,
    )

    clear_aot_cache()
    mesh_cfg = MeshConfig(data=4, pipe=2)
    mesh = create_mesh(mesh_cfg, devices)
    cfg = GPTPipeConfig(vocab_size=64, block_size=32, dim=32, n_layers=2,
                        n_heads=2, n_stages=2, n_microbatches=4,
                        pipeline_parallel=True)
    trace_path = str(tmp_path / "mesh_train_trace.json")
    tcfg = TrainConfig(
        steps=3, batch_size=16, log_every=1, eval_every=0,
        mesh=mesh_cfg, pipeline_parallel=True, pp_schedule="1f1b",
        mesh_obs=True, trace_path=trace_path,
        optimizer=OptimizerConfig(max_lr=1e-3, total_steps=10),
    )
    trainer = Trainer(GPTPipe(cfg), tcfg, rules=PP_RULES, mesh=mesh)
    toks = np.arange(8192) % 64
    it = lm_batch_iterator(toks, 16, 32, sharding=batch_sharding(mesh))
    w = _RowWriter()
    trainer.fit(it, writer=w)

    # the goodput row is last; the last metrics row carries the gauges
    row = next(m for _, m in reversed(w.rows) if "mesh/devices" in m)
    assert row["mesh/bubble_fraction_analytic"] == pytest.approx(0.2)
    assert "mesh/bubble_fraction_measured" in row
    assert row["mesh/straggler_stage"] in (0.0, 1.0)
    assert row["mesh/stage_imbalance"] >= 1.0
    assert row["mesh/comm_bytes_per_step"] > 0
    assert "mesh/comm_collective_permute_ops" in row  # the ppermute ring

    snap = trainer._mesh_obs.snapshot()
    assert snap["mesh_axes"]["pipe"] == 2
    assert snap["bubble"]["n_devices"] == 2
    json.dumps(snap)  # /statusz-serializable

    summary = summarize_trace(trace_path)
    mesh_section = summary["mesh"]
    assert "stage0" in mesh_section["stages"]
    assert "train_step" in mesh_section["comm"]
    text = format_mesh(mesh_section)
    assert "bubble fraction" in text and "collective ledger" in text
