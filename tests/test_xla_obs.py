"""Compile & memory observatory tests (metrics/xla_obs.py, metrics/http.py).

The contracts under test:
  * the compile registry records every XLA compilation the engine runs
    (program name, signature, compile wall time, cost_analysis flops)
    and the AOT dispatch path is TOKEN-EXACT vs the plain jit path;
  * an induced recompile storm (shape-bucket misses: one new prefill
    signature per request) is counted by the registry AND dumped through
    the existing AnomalyMonitor;
  * HBM-ledger totals for the KV slot pool and the prefix cache match
    the analytically computed lane/node byte sizes;
  * the /healthz /metrics /statusz endpoint serves live engine state,
    with /metrics in parseable Prometheus text exposition format;
  * chip_peak_flops / mfu are NaN-safe on CPU and unknown backends;
  * `cli trace-summary` exits non-zero with a message (no traceback) on
    missing / truncated / malformed trace JSON;
  * summarize_trace joins compile events with measured program spans
    into a per-program roofline section.
"""

import json
import math
import re
import types
import urllib.error
import urllib.request
import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import importlib

# `metrics.mfu` the ATTRIBUTE is the mfu() function (the package re-
# exports it); import the submodule by path to reach the module object
mfu_mod = importlib.import_module("solvingpapers_tpu.metrics.mfu")
from solvingpapers_tpu.metrics.trace import format_summary, summarize_trace
from solvingpapers_tpu.metrics.xla_obs import (
    CompileRegistry,
    HBMLedger,
    clear_aot_cache,
    pytree_bytes,
)
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.serve import ServeConfig, ServeEngine

pytestmark = pytest.mark.fast

GPT_TINY = GPTConfig(vocab_size=64, block_size=64, dim=32, n_layers=2,
                     n_heads=2, dropout=0.0)


@pytest.fixture(scope="module")
def gpt_tiny():
    model = GPT(GPT_TINY)
    rng = jax.random.key(0)
    params = model.init({"params": rng}, jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompts(n, seed=0, lo=4, hi=24):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, GPT_TINY.vocab_size,
                     size=int(rng.integers(lo, hi))).astype(np.int32)
        for _ in range(n)
    ]


# ------------------------------------------------------- compile registry


def test_registry_records_engine_compilations(gpt_tiny):
    model, params = gpt_tiny
    clear_aot_cache()  # observe true compiles, not another test's cache
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8, xla_obs=True,
    ))
    handles = [eng.submit(p, max_new_tokens=6) for p in _prompts(3, seed=1)]
    eng.run()
    assert all(h.done for h in handles)
    snap = eng.registry.snapshot()
    assert "prefill_program" in snap["programs"]
    assert "decode_block" in snap["programs"]
    pf = snap["programs"]["prefill_program"]
    assert pf["compilations"] >= 1
    assert pf["compile_time_s"] > 0  # true cold compile wall time
    assert pf["calls"] == 3  # one prefill per admitted request
    assert pf["flops_per_call"] > 0  # cost_analysis wired through
    assert pf["run_time_s"] > 0  # fenced dispatch accumulates
    dec = snap["programs"]["decode_block"]
    assert dec["signatures"] == 1  # one decode shape per engine
    # gauges ride ServeMetrics.snapshot()
    m = eng.metrics.snapshot()
    assert m["compile/programs"] >= 2.0
    assert m["compile/compilations"] >= 2.0
    assert m["compile/time_s"] > 0
    assert "roofline/prefill_program_flops_per_s" in m
    assert "roofline/prefill_program_intensity" in m
    # CPU has no chip-peak table entry -> MFU gauges must be ABSENT, not
    # garbage (the NaN-sentinel contract)
    if not math.isfinite(eng.registry.peak_flops):
        assert not any(k.endswith("_mfu") for k in m)


def test_observatory_streams_token_exact(gpt_tiny):
    """The AOT dispatch path must be invisible in the tokens."""
    model, params = gpt_tiny
    prompts = _prompts(4, seed=2)
    streams = {}
    for obs in (False, True):
        eng = ServeEngine(model, params, ServeConfig(
            n_slots=2, max_len=64, decode_block=4, bucket=8, xla_obs=obs,
            prefix_cache=True, prefix_page=4,
        ))
        handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
        eng.run()
        streams[obs] = [h.tokens for h in handles]
    assert streams[False] == streams[True]


def test_recompile_storm_flagged_through_anomaly_monitor(gpt_tiny, tmp_path):
    """Induce shape-bucket misses (bucket=4, strictly growing prompt
    lengths -> a NEW prefill signature per admission) and assert the
    registry counts the storm and the AnomalyMonitor dumps it."""
    model, params = gpt_tiny
    dump = str(tmp_path / "anomalies.jsonl")
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=2, bucket=4,
        xla_obs=True, obs_storm_k=3, obs_storm_window_s=600.0,
        trace=True, trace_dump_path=dump,
    ))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the storm warns by design
        for length in (3, 7, 11, 15):  # pads to 4, 8, 12, 16: all misses
            eng.submit(np.arange(length, dtype=np.int32) % 64,
                       max_new_tokens=2)
            eng.run()
    snap = eng.registry.snapshot()
    assert snap["programs"]["prefill_program"]["signatures"] == 4
    assert snap["storms"] >= 1
    assert eng.metrics.snapshot()["compile/storms"] >= 1.0
    records = [json.loads(ln) for ln in open(dump)]
    storm = [r for r in records if r["kind"] == "recompile_storm"]
    assert storm, f"no recompile_storm dump in {[r['kind'] for r in records]}"
    assert storm[0]["detail"]["program"] == "prefill_program"
    assert storm[0]["detail"]["new_signatures"] >= 3
    assert storm[0]["events"], "dump must carry the flight-recorder ring"


def test_storm_warns_once_per_program(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=1, max_len=64, decode_block=2, bucket=4,
        xla_obs=True, obs_storm_k=2, obs_storm_window_s=600.0,
    ))
    with pytest.warns(UserWarning, match="recompile storm"):
        for length in (3, 7, 11):
            eng.submit(np.arange(length, dtype=np.int32) % 64,
                       max_new_tokens=2)
            eng.run()


# ------------------------------------------------------------ HBM ledger


def test_ledger_totals_match_analytic_bytes(gpt_tiny):
    """kv_pool and prefix_cache ledger pools must equal the analytically
    computed lane/node byte sizes from the model config."""
    model, params = gpt_tiny
    n_slots, max_len, page = 2, 64, 4
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=n_slots, max_len=max_len, decode_block=4, bucket=8,
        xla_obs=True, prefix_cache=True, prefix_page=page,
    ))
    # analytic lane bytes: per layer, K and V of shape
    # (slot, max_len, n_heads, head_dim) in fp32
    cfg = GPT_TINY
    head_dim = cfg.dim // cfg.n_heads
    per_token = cfg.n_layers * 2 * cfg.n_heads * head_dim * 4
    kv_expected = n_slots * max_len * per_token
    pools = eng.ledger.pool_bytes()
    assert pools["kv_pool"] == kv_expected
    assert pools["params"] == pytree_bytes({"params": params})
    assert pools["prefix_cache"] == 0  # nothing cached yet

    # one request -> its page-aligned prompt prefix is snapshotted into
    # the radix tree: node bytes = aligned tokens x per-token lane bytes
    prompt = np.arange(11, dtype=np.int32) % 64
    eng.submit(prompt, max_new_tokens=2)
    eng.run()
    aligned = (prompt.size - 1) // page * page  # final token never cached
    assert eng.prefix_cache.bytes_held == aligned * per_token
    assert eng.ledger.pool_bytes()["prefix_cache"] == aligned * per_token
    m = eng.metrics.snapshot()
    assert m["mem/kv_pool_bytes"] == float(kv_expected)
    assert m["mem/live_bytes"] == float(sum(eng.ledger.pool_bytes().values()))
    assert m["mem/projected_peak_bytes"] >= m["mem/live_bytes"]


def test_ledger_headroom_warns_before_capacity_exceeded():
    ledger = HBMLedger(capacity_bytes=1000)
    ledger.register("pool_a", 600)
    ledger.temp_fn = lambda: 300
    assert ledger.check() is False  # 900 <= 1000: quiet
    ledger.register("pool_b", lambda: 200)  # projection now 1100
    with pytest.warns(UserWarning, match="projected HBM peak"):
        assert ledger.check() is True
    assert ledger.check() is True  # still over, but warns only once
    g = ledger.gauges()
    assert g["mem/headroom_bytes"] == pytest.approx(-100.0)
    assert g["mem/capacity_bytes"] == 1000.0
    snap = ledger.snapshot()
    assert snap["pools"] == {"pool_a": 600, "pool_b": 200}
    assert snap["projected_peak_bytes"] == 1100


def test_ledger_without_capacity_omits_headroom():
    ledger = HBMLedger(capacity_bytes=None)
    if ledger.capacity_bytes is not None:
        pytest.skip("backend reports a memory limit")
    ledger.register("p", 128)
    g = ledger.gauges()
    assert "mem/capacity_bytes" not in g
    assert "mem/headroom_bytes" not in g
    assert ledger.check() is False  # no capacity -> never a false alarm


def test_ledger_rejects_duplicate_pool():
    ledger = HBMLedger(capacity_bytes=None)
    ledger.register("p", 1)
    with pytest.raises(ValueError, match="already registered"):
        ledger.register("p", 2)


# ------------------------------------------------------- status endpoint


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def test_status_endpoint_serves_live_engine(gpt_tiny):
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
        xla_obs=True, status_port=0,
    ))
    try:
        handles = [eng.submit(p, max_new_tokens=4)
                   for p in _prompts(2, seed=3)]
        eng.run()
        assert all(h.done for h in handles)
        base = f"http://127.0.0.1:{eng.status.port}"
        code, body = _get(base + "/healthz")
        assert code == 200 and body.strip() == "ok"

        code, body = _get(base + "/metrics")
        assert code == 200
        # series grammar: bare gauge names plus the latency histograms'
        # labeled `_bucket{le="..."}` series (native since the log-
        # bucketed backend replaced the Ring)
        name_re = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{le="[^"]+"\})?$')
        names = set()
        for line in body.splitlines():
            if line.startswith("#"):
                parts = line.split()
                assert parts[1] == "TYPE"
                assert parts[3] in ("gauge", "histogram")
                continue
            name, value = line.rsplit(" ", 1)
            assert name_re.match(name), name
            float(value)  # parseable exposition value
            names.add(name)
        assert len(names) == len([ln for ln in body.splitlines()
                                  if not ln.startswith("#")])  # no dupes
        assert "serve_requests_finished" in names
        assert "compile_compilations" in names
        assert "mem_kv_pool_bytes" in names
        assert "serve_ttft_s_count" in names  # histogram rode the pull path
        assert any(n.startswith('serve_ttft_s_bucket{le="')
                   for n in names)

        code, body = _get(base + "/statusz")
        assert code == 200
        doc = json.loads(body)
        assert doc["engine"]["n_slots"] == 2
        assert len(doc["slots"]) == 2
        assert all(s["req"] is None for s in doc["slots"])  # drained
        assert "prefill_program" in doc["compile"]["programs"]
        assert doc["mem"]["pools"]["kv_pool"] > 0
        assert doc["metrics"]["serve/requests_finished"] == 2.0

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/nope")
        assert ei.value.code == 404
    except urllib.error.HTTPError as e:  # surface the 500 body on failure
        raise AssertionError(f"{e.url}: {e.read().decode()}") from e
    finally:
        eng.close()
    assert eng.status is None
    eng.close()  # idempotent


# -------------------------------------------------------- mfu NaN-safety


def test_chip_peak_flops_known_and_unknown_kinds():
    v5e = types.SimpleNamespace(device_kind="TPU v5e")
    assert mfu_mod.chip_peak_flops(v5e) == 197e12
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        cpu = types.SimpleNamespace(device_kind="cpu")
        assert math.isnan(mfu_mod.chip_peak_flops(cpu))
        weird = types.SimpleNamespace(device_kind=None)
        assert math.isnan(mfu_mod.chip_peak_flops(weird))


def test_mfu_nan_safe_never_raises():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        cpu = types.SimpleNamespace(device_kind="cpu")
        assert math.isnan(mfu_mod.mfu(1e4, 1e9, device=cpu))
        v5e = types.SimpleNamespace(device_kind="TPU v5e")
        val = mfu_mod.mfu(1e4, 1e9, device=v5e)
        assert val == pytest.approx(1e4 * 1e9 / 197e12)
        assert math.isnan(mfu_mod.mfu(float("nan"), 1e9, device=v5e))


def test_unknown_kind_warns_once():
    mfu_mod._warned_kinds.discard("never seen kind")
    dev = types.SimpleNamespace(device_kind="never seen kind")
    with pytest.warns(UserWarning, match="unrecognized device_kind"):
        mfu_mod.chip_peak_flops(dev)
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a second warning would raise
        assert math.isnan(mfu_mod.chip_peak_flops(dev))


# ---------------------------------------------- trace-summary robustness


def test_trace_summary_missing_file_exits_nonzero(capsys):
    from solvingpapers_tpu.cli import main

    rc = main(["trace-summary", "/nonexistent/trace.json"])
    assert rc == 2
    assert "no trace file" in capsys.readouterr().err


def test_trace_summary_truncated_json_exits_nonzero(tmp_path, capsys):
    from solvingpapers_tpu.cli import main

    p = tmp_path / "truncated.json"
    p.write_text('{"traceEvents": [{"ph": "X", "name": "st')  # cut mid-write
    rc = main(["trace-summary", str(p)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "not valid JSON" in err and "Traceback" not in err


def test_trace_summary_malformed_json_exits_nonzero(tmp_path, capsys):
    from solvingpapers_tpu.cli import main

    p = tmp_path / "wrong.json"
    p.write_text('"a bare string is not a trace"')
    rc = main(["trace-summary", str(p)])
    assert rc == 2
    assert "Traceback" not in capsys.readouterr().err


# ------------------------------------------------- per-program roofline


def test_roofline_joins_compiles_with_spans_in_trace_summary(gpt_tiny,
                                                             tmp_path):
    """With trace AND xla_obs on, the exported trace carries compile
    events; summarize_trace joins them with the measured program spans
    into the per-program roofline surfaced by `cli trace-summary`."""
    model, params = gpt_tiny
    eng = ServeEngine(model, params, ServeConfig(
        n_slots=2, max_len=64, decode_block=4, bucket=8,
        xla_obs=True, trace=True,
    ))
    for p in _prompts(3, seed=4):
        eng.submit(p, max_new_tokens=6)
    eng.run()
    path = str(tmp_path / "trace.json")
    eng.trace.export_chrome(path)
    summary = summarize_trace(path)
    progs = summary["programs"]
    assert "prefill_program" in progs and "decode_block" in progs
    pf = progs["prefill_program"]
    assert pf["compilations"] >= 1
    assert pf["calls"] == 3
    assert pf["total_s"] > 0
    assert pf["achieved_flops_per_s"] > 0
    assert pf["intensity_flops_per_byte"] > 0
    text = format_summary(summary)
    assert "per-program roofline" in text
    assert "prefill_program" in text
    # a plain PR-4 trace (no compile events) keeps its old summary shape
    plain = summarize_trace({"traceEvents": []})
    assert plain["programs"] == {}
    assert "per-program roofline" not in format_summary(plain)


def test_pytree_bytes_counts_leaves():
    tree = {"a": np.zeros((4, 2), np.float32), "b": np.zeros(3, np.int8),
            "c": 7}  # non-array leaves are skipped, not crashed on
    assert pytree_bytes(tree) == 4 * 2 * 4 + 3


def test_registry_storm_knob_validation():
    with pytest.raises(ValueError, match="storm_k"):
        CompileRegistry(storm_k=1)
    with pytest.raises(ValueError, match="storm_window_s"):
        CompileRegistry(storm_window_s=0)
