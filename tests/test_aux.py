"""Aux-subsystem tests (SURVEY.md §5): BPE tokenizer round-trips and
training, checkpoint resume, preemption-signal save, metrics sinks.
"""

import json
import os
import signal

import jax
import numpy as np
import pytest

from solvingpapers_tpu.data.bpe import ByteBPETokenizer, bytes_to_unicode
from solvingpapers_tpu.data.synthetic import synthetic_text

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast


def test_bytes_to_unicode_bijective():
    m = bytes_to_unicode()
    assert len(m) == 256
    assert len(set(m.values())) == 256


def test_bpe_train_roundtrip_and_compression():
    text = synthetic_text(30_000, seed=0)
    tok = ByteBPETokenizer.train(text, vocab_size=512)
    assert 256 < tok.vocab_size <= 512
    sample = "The quick brown fox jumps over the lazy dog. éü☃"
    ids = tok.encode(sample)
    assert tok.decode(ids) == sample  # byte-level: exact round-trip, no <unk>
    # merges must actually compress the training distribution
    assert len(tok.encode(text[:5000])) < 5000 * 0.6


def test_bpe_save_load_identical(tmp_path):
    # '#' is a legitimate merge symbol (GPT-2 has '# #' -> '##'); the loader
    # must only skip the '#version' header, not every '#'-prefixed line
    text = synthetic_text(10_000, seed=1) + " ## hashtag # code # comment" * 200
    tok = ByteBPETokenizer.train(text, vocab_size=400)
    vp, mp = str(tmp_path / "vocab.json"), str(tmp_path / "merges.txt")
    tok.save(vp, mp)
    tok2 = ByteBPETokenizer.from_files(vp, mp)
    assert tok2.ranks == tok.ranks
    s = "hello world, shall we proceed anon? ## tags #1"
    np.testing.assert_array_equal(tok.encode(s), tok2.encode(s))


def test_bpe_lm_run_builds():
    import dataclasses

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_char_lm_run

    cfg = get_config("gpt_tiny")
    cfg = dataclasses.replace(
        cfg, data={**cfg.data, "kind": "bpe", "bpe_vocab_size": 300,
                   "block_size": 32}
    )
    cfg2, model, tok, train_iter, eval_iter_fn = build_char_lm_run(cfg)
    assert cfg2.model.vocab_size == tok.vocab_size
    batch = next(train_iter)
    assert batch["x"].shape == (cfg.train.batch_size, 32)
    assert int(batch["x"].max()) < tok.vocab_size


def test_preemption_signal_saves_checkpoint(tmp_path):
    """SIGTERM mid-fit must write a resumable checkpoint and stop the loop."""
    from solvingpapers_tpu.data import load_char_corpus
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    tiny = GPTConfig(vocab_size=64, block_size=16, dim=16, n_layers=1,
                     n_heads=2, dropout=0.0)
    _, toks, _ = load_char_corpus(synthetic_chars=5_000)
    ckdir = str(tmp_path / "ck")
    mesh = create_mesh(MeshConfig(data=1), jax.devices()[:1])

    class SignalingIter:
        """Raises SIGTERM in-process after a few batches."""

        def __init__(self, inner, at):
            self.inner, self.n, self.at = inner, 0, at

        def __iter__(self):
            return self

        def __next__(self):
            self.n += 1
            if self.n == self.at:
                os.kill(os.getpid(), signal.SIGTERM)
            return next(self.inner)

    cfg = TrainConfig(
        steps=50, batch_size=4, log_every=1000, eval_every=0,
        checkpoint_dir=ckdir, ckpt_every=1000,  # periodic save never fires
        optimizer=OptimizerConfig(max_lr=1e-3, total_steps=50),
    )
    trainer = Trainer(GPT(tiny), cfg, mesh=mesh)
    it = SignalingIter(lm_batch_iterator(toks, 4, tiny.block_size, seed=0), at=4)
    trainer.fit(it, None)

    from solvingpapers_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckdir, save_every=0)
    step = mgr.latest_step()
    assert step is not None and 0 < step < 50, step


def test_jsonl_and_console_writers(tmp_path, capsys):
    from solvingpapers_tpu.metrics import ConsoleWriter, JSONLWriter, MultiWriter

    path = str(tmp_path / "m.jsonl")
    w = MultiWriter(ConsoleWriter(), JSONLWriter(path))
    w.write(10, {"loss": 1.5, "lr": 0.001})
    w.close()
    out = capsys.readouterr().out
    assert "step 10" in out and "loss=1.5" in out
    rec = json.loads(open(path).read().strip())
    assert rec["step"] == 10 and rec["loss"] == 1.5


def test_tensorboard_writer(tmp_path):
    pytest.importorskip("torch.utils.tensorboard")
    from solvingpapers_tpu.metrics import TensorBoardWriter

    w = TensorBoardWriter(str(tmp_path / "tb"))
    w.write(1, {"loss": 2.0})
    w.close()
    assert any(f.startswith("events") for f in os.listdir(tmp_path / "tb"))


def test_token_file_run_uses_prefetched_batches(tmp_path):
    """kind=tokens runs stream from the mmap'd file through the prefetch
    wrapper (host gathers overlap the device step) with seed-deterministic
    batches."""
    import dataclasses

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_char_lm_run
    from solvingpapers_tpu.data import tokenize_to_file

    text = synthetic_text(20_000, seed=6)
    tok = ByteBPETokenizer.train(text, vocab_size=300)
    path = str(tmp_path / "toks.bin")
    tokenize_to_file(text, tok, path)
    cfg = get_config("gpt_tiny")
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(cfg.model, vocab_size=tok.vocab_size),
        data={"kind": "tokens", "path": path, "block_size": 32},
    )
    _, _, _, train_iter, _ = build_char_lm_run(cfg)
    # the factory must actually wrap memmap streams in the prefetcher (a
    # plain lm_batch_iterator would satisfy every other assertion here)
    assert train_iter.gi_code.co_name == "prefetch_batches"
    a = next(train_iter)
    b = next(train_iter)
    assert a["x"].shape == (cfg.train.batch_size, 32)
    assert not np.array_equal(np.asarray(a["x"]), np.asarray(b["x"]))
    # re-building with the same seed yields the same stream (prefetch wrap
    # preserves order/determinism)
    _, _, _, train_iter2, _ = build_char_lm_run(cfg)
    np.testing.assert_array_equal(np.asarray(a["x"]),
                                  np.asarray(next(train_iter2)["x"]))


def test_token_file_roundtrip_and_mmap(tmp_path):
    from solvingpapers_tpu.data import load_token_file, tokenize_to_file

    text = synthetic_text(5_000, seed=2)
    tok = ByteBPETokenizer.train(text, vocab_size=300)
    path = str(tmp_path / "toks.bin")
    ids = tokenize_to_file(text, tok, path)
    assert ids.dtype == np.uint16  # vocab 300 fits
    loaded = load_token_file(path)
    assert isinstance(loaded, np.memmap)
    np.testing.assert_array_equal(ids, loaded)
    # npy variant
    npy = str(tmp_path / "toks.npy")
    tokenize_to_file(text, tok, npy)
    np.testing.assert_array_equal(ids, load_token_file(npy))
