"""Flash attention kernel vs. the dense jnp reference (interpret mode).

SURVEY.md §4 test plan: every kernel ships with a pure-jnp reference and
interpret-mode equality tests — forward and gradients, causal and
bidirectional, MHA and GQA/MQA head layouts.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops
from solvingpapers_tpu.kernels import flash_attention

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast


def make_qkv(key, b, sq, skv, n, n_kv, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, n, d), dtype)
    k = jax.random.normal(kk, (b, skv, n_kv, d), dtype)
    v = jax.random.normal(kv, (b, skv, n_kv, d), dtype)
    return q, k, v


CASES = [
    # (b, sq, skv, n, n_kv, d, causal)
    pytest.param(2, 128, 128, 4, 4, 64, True, id="mha_causal"),
    pytest.param(2, 128, 128, 4, 4, 64, False, id="mha_bidir"),
    pytest.param(2, 128, 128, 4, 2, 32, True, id="gqa_causal"),
    pytest.param(1, 128, 128, 4, 1, 32, True, id="mqa_causal"),
    pytest.param(1, 256, 256, 2, 2, 64, True, id="multiblock_causal"),
    pytest.param(1, 64, 256, 2, 2, 32, False, id="cross_qkv_lens"),
    # end-aligned causal mask: query i sees kv <= i + (skv - sq)
    pytest.param(1, 64, 256, 2, 2, 32, True, id="cross_qkv_lens_causal"),
    pytest.param(2, 128, 192, 4, 2, 32, True, id="cross_gqa_causal"),
]


@pytest.mark.parametrize("b,sq,skv,n,n_kv,d,causal", CASES)
def test_forward_matches_dense(b, sq, skv, n, n_kv, d, causal):
    q, k, v = make_qkv(jax.random.key(0), b, sq, skv, n, n_kv, d)
    out = flash_attention(q, k, v, causal=causal, interpret=True, block_q=64, block_k=64)
    ref = ops.dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "b,sq,skv,n,n_kv,d,causal",
    [CASES[0], CASES[2], CASES[4], CASES[1], CASES[6]],
)
def test_grads_match_dense(b, sq, skv, n, n_kv, d, causal):
    q, k, v = make_qkv(jax.random.key(1), b, sq, skv, n, n_kv, d)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True,
                            block_q=64, block_k=64)
        return jnp.sum(o * jnp.cos(o))

    def loss_dense(q, k, v):
        o = ops.dot_product_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4)


def test_causal_seq_q_longer_than_seq_k():
    """seq_q > seq_k causal: the end-aligned mask leaves the earliest q rows
    with no visible kv. The kernel emits 0 for those rows (guarded softmax
    denominator) — not NaN — so a caller summing over all rows keeps finite
    values and gradients; visible rows must match dense exactly. Also a
    regression for the DMA-elision clamp, whose unfloored form indexed
    before the kv array here."""
    q, k, v = make_qkv(jax.random.key(6), 1, 192, 64, 2, 2, 32)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          block_q=64, block_k=64)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    ref_np, out_np = np.asarray(ref), np.asarray(out)
    # offset = 64 - 192 = -128: q rows < 128 see nothing -> 0 output (the
    # dense path's big-neg fill degenerates to a uniform average there; both
    # are arbitrary for an all-masked row, but 0 is finite and grad-safe).
    assert (out_np[:, :128] == 0.0).all()
    assert np.isfinite(out_np).all()
    np.testing.assert_allclose(out_np[:, 128:], ref_np[:, 128:],
                               rtol=2e-5, atol=2e-5)
    # A sum over ALL rows (empty ones included) must give finite grads, and
    # grads w.r.t. the visible region must match dense.
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=True, interpret=True, block_q=64, block_k=64) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(jnp.where(
        jnp.arange(192)[None, :, None, None] >= 128,
        ops.dot_product_attention(*a, causal=True), 0.0) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        assert np.isfinite(np.asarray(a)).all()
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("bq,bk", [(32, 64), (64, 32), (128, 32)])
def test_asymmetric_blocks_match_dense(bq, bk):
    """Non-square (block_q, block_k) exercise the clamped causal index maps
    (dead-step DMA elision) with q/kv block boundaries out of phase."""
    q, k, v = make_qkv(jax.random.key(5), 1, 128, 192, 4, 2, 32)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          block_q=bq, block_k=bk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=True, interpret=True, block_q=bq, block_k=bk) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(
        ops.dot_product_attention(*a, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_odd_seq_falls_back_to_smaller_blocks():
    # 96 = 64 + 32; _pick_block must find a divisor block (32)
    q, k, v = make_qkv(jax.random.key(2), 1, 96, 96, 2, 2, 32)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_bf16_forward_close():
    q, k, v = make_qkv(jax.random.key(3), 1, 128, 128, 2, 2, 64, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), rtol=2e-2, atol=2e-2
    )


def test_rejects_bad_head_ratio():
    q, k, v = make_qkv(jax.random.key(4), 1, 64, 64, 3, 2, 32)
    with pytest.raises(ValueError, match="not a multiple"):
        flash_attention(q, k, v, interpret=True)


def test_sharded_flash_matches_dense(devices):
    """shard_map-wrapped kernel under dp/fsdp/tp == single-device dense
    (interpret mode inside shard_map on the virtual CPU mesh)."""
    from solvingpapers_tpu.kernels import sharded_flash_attention
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(data=2, fsdp=2, model=2), devices)
    q, k, v = make_qkv(jax.random.key(9), 4, 128, 128, 4, 2, 32)
    out = sharded_flash_attention(q, k, v, mesh, causal=True, interpret=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_sharded_flash_grads_match(devices):
    from solvingpapers_tpu.kernels import sharded_flash_attention
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(data=2, model=4), devices)
    q, k, v = make_qkv(jax.random.key(10), 2, 64, 64, 4, 4, 16)

    def loss_sharded(q, k, v):
        o = sharded_flash_attention(q, k, v, mesh, causal=True, interpret=True)
        return jnp.sum(o**2)

    def loss_dense(q, k, v):
        return jnp.sum(ops.dot_product_attention(q, k, v, causal=True) ** 2)

    gs = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gs, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_sharded_flash_rejects_bad_head_split(devices):
    from solvingpapers_tpu.kernels import sharded_flash_attention
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(data=2, model=4), devices)
    q, k, v = make_qkv(jax.random.key(11), 2, 64, 64, 4, 2, 16)  # kv 2 < tp 4
    with pytest.raises(ValueError, match="divide the model axis"):
        sharded_flash_attention(q, k, v, mesh, interpret=True)


def test_sharded_flash_mqa_kv1_replicated(devices):
    """MLA's absorbed-query shape: one shared kv head stays replicated over
    the model axis while q heads shard (local q->kv map resolves to 0)."""
    from solvingpapers_tpu.kernels import sharded_flash_attention
    from solvingpapers_tpu.sharding import MeshConfig, create_mesh

    mesh = create_mesh(MeshConfig(data=2, model=4), devices)
    q, k, v = make_qkv(jax.random.key(12), 2, 64, 64, 8, 1, 16)
    out = sharded_flash_attention(q, k, v, mesh, causal=True, interpret=True)
    ref = ops.dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_trainer_routes_flash_through_sharded_kernel_under_tp(devices, monkeypatch):
    """A use_flash model on a model>1 mesh must go through the shard_map
    wrapper (pallas_call is GSPMD-opaque: the direct call would all-gather
    q/k/v) and still match single-device flash training bit-for-bit-ish."""
    import solvingpapers_tpu.kernels as kernels
    from solvingpapers_tpu.data import load_char_corpus
    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.models.gpt import GPT, GPTConfig
    from solvingpapers_tpu.sharding import MeshConfig, batch_sharding, create_mesh
    from solvingpapers_tpu.train import OptimizerConfig, Trainer, TrainConfig

    model_cfg = GPTConfig(vocab_size=64, block_size=32, dim=32, n_layers=2,
                          n_heads=4, dropout=0.0, use_flash=True)
    _, train_toks, _ = load_char_corpus(synthetic_chars=20_000)
    opt = OptimizerConfig(max_lr=1e-3, warmup_steps=0, total_steps=10)

    calls = {"sharded": 0}
    real = kernels.sharded_flash_attention

    def spy(*args, **kwargs):
        calls["sharded"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(kernels, "sharded_flash_attention", spy)

    def run(mesh_config, devs):
        mesh = create_mesh(mesh_config, devs)
        cfg = TrainConfig(steps=2, batch_size=8, log_every=100, eval_every=0,
                          optimizer=opt)
        trainer = Trainer(GPT(model_cfg), cfg, mesh=mesh)
        it = lm_batch_iterator(train_toks, 8, model_cfg.block_size, seed=7,
                               sharding=batch_sharding(mesh))
        b0 = next(it)
        state = trainer.init_state(b0)
        trainer._build_steps()
        losses = []
        state, m = trainer._train_step(state, b0)
        losses.append(float(m["train_loss"]))
        state, m = trainer._train_step(state, next(it))
        losses.append(float(m["train_loss"]))
        return losses

    single = run(MeshConfig(data=1), devices[:1])
    assert calls["sharded"] == 0  # 1-device mesh: direct kernel, no wrapper
    sharded = run(MeshConfig(data=2, fsdp=1, model=2), devices[:4])
    assert calls["sharded"] > 0, "TP mesh did not route through sharded flash"
    np.testing.assert_allclose(sharded, single, rtol=2e-4, atol=2e-5)
