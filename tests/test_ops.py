"""Unit tests for the shared ops library against closed-form references.

Implements the SURVEY.md §4 plan: RoPE complex vs. cos/sin vs. rotation
matrix must agree; norms vs. NumPy; losses vs. manual formulas.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from solvingpapers_tpu import ops

# sub-minute correctness core: `pytest -m fast` is the ~4-minute gate
pytestmark = pytest.mark.fast


def test_rms_norm_matches_numpy():
    x = np.random.default_rng(0).normal(size=(4, 7, 16)).astype(np.float32)
    w = np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
    got = ops.rms_norm(jnp.asarray(x), jnp.asarray(w), eps=1e-6)
    want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-6) * w
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rms_norm_bf16_stats_in_f32():
    x = jnp.full((2, 8), 3.0, dtype=jnp.bfloat16)
    y = ops.rms_norm(x)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y, np.float32), 1.0, rtol=1e-2)


def test_layer_norm_matches_numpy():
    x = np.random.default_rng(2).normal(size=(3, 5, 12)).astype(np.float32)
    w = np.random.default_rng(3).normal(size=(12,)).astype(np.float32)
    b = np.random.default_rng(4).normal(size=(12,)).astype(np.float32)
    got = ops.layer_norm(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), eps=1e-5)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5) * w + b
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("theta", [10000.0, 100000.0])
def test_rope_three_formulations_agree(theta):
    head_dim, seq, heads = 16, 12, 3
    x = jax.random.normal(jax.random.key(0), (2, seq, heads, head_dim))

    cos, sin = ops.precompute_rope(head_dim, seq, theta)
    got = ops.apply_rope(x, cos, sin)

    freqs_cis = ops.precompute_freqs_cis(head_dim, seq, theta)
    want_complex = ops.apply_rotary_emb_complex(x, freqs_cis)
    np.testing.assert_allclose(got, want_complex, rtol=1e-5, atol=1e-5)

    mats = ops.rope_rotation_matrix(head_dim, seq, theta)
    want_matrix = jnp.einsum("tij,bthj->bthi", mats, x)
    np.testing.assert_allclose(got, want_matrix, rtol=1e-5, atol=1e-5)


def test_rope_positions_slice_equals_prefix():
    """Decoding one token at offset p must equal position p of the full roll."""
    head_dim, seq = 8, 10
    x = jax.random.normal(jax.random.key(1), (1, seq, 2, head_dim))
    cos, sin = ops.precompute_rope(head_dim, seq)
    full = ops.apply_rope(x, cos, sin)
    p = 7
    one = ops.apply_rope(x[:, p : p + 1], cos, sin, positions=jnp.array([p]))
    np.testing.assert_allclose(one[:, 0], full[:, p], rtol=1e-6, atol=1e-6)


def test_repeat_kv():
    x = jnp.arange(2 * 3 * 2 * 4).reshape(2, 3, 2, 4).astype(jnp.float32)
    y = ops.repeat_kv(x, 3)
    assert y.shape == (2, 3, 6, 4)
    # each kv head appears n_rep consecutive times
    np.testing.assert_array_equal(y[:, :, 0], y[:, :, 2])
    np.testing.assert_array_equal(y[:, :, 3], y[:, :, 5])
    assert not np.array_equal(np.asarray(y[:, :, 0]), np.asarray(y[:, :, 3]))


def test_causal_attention_matches_manual():
    b, s, n, h = 2, 6, 2, 8
    rng = jax.random.key(2)
    q, k, v = jax.random.normal(rng, (3, b, s, n, h))
    got = ops.dot_product_attention(q, k, v, causal=True)
    # manual per-head softmax with tril mask
    scores = np.einsum("bqnh,bknh->bnqk", q, k) / np.sqrt(h)
    mask = np.tril(np.ones((s, s), bool))
    scores = np.where(mask, scores, -1e30)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.einsum("bnqk,bknh->bqnh", probs, v)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_gqa_equals_explicit_repeat():
    b, s, n, n_kv, h = 1, 5, 4, 2, 8
    q = jax.random.normal(jax.random.key(3), (b, s, n, h))
    k = jax.random.normal(jax.random.key(4), (b, s, n_kv, h))
    v = jax.random.normal(jax.random.key(5), (b, s, n_kv, h))
    got = ops.dot_product_attention(q, k, v, causal=True)
    want = ops.dot_product_attention(
        q, ops.repeat_kv(k, 2), ops.repeat_kv(v, 2), causal=True
    )
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_cached_decode_mask_alignment():
    """causal_mask with kv_len > q_len lets the last query see everything."""
    m = ops.causal_mask(1, 5)
    np.testing.assert_array_equal(np.asarray(m), np.ones((1, 5), bool))
    m2 = ops.causal_mask(2, 5)
    np.testing.assert_array_equal(np.asarray(m2[0]), [1, 1, 1, 1, 0])


def test_luong_attention():
    b, t, d = 2, 4, 6
    st = jax.random.normal(jax.random.key(6), (b, d))
    hs = jax.random.normal(jax.random.key(7), (b, t, d))
    ctx, w = ops.luong_attention(st, hs)
    assert ctx.shape == (b, d) and w.shape == (b, t)
    np.testing.assert_allclose(np.asarray(w).sum(-1), 1.0, rtol=1e-5)
    scores = np.einsum("bd,btd->bt", st, hs)
    e = np.exp(scores - scores.max(-1, keepdims=True))
    np.testing.assert_allclose(w, e / e.sum(-1, keepdims=True), rtol=1e-5, atol=1e-6)


def test_cross_entropy_matches_manual_log_softmax():
    logits = jax.random.normal(jax.random.key(8), (4, 9))
    labels = jnp.array([0, 3, 8, 2])
    got = ops.cross_entropy(logits, labels)
    lp = np.asarray(jax.nn.log_softmax(logits, -1))
    want = -lp[np.arange(4), np.asarray(labels)].mean()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cross_entropy_ignore_index():
    logits = jax.random.normal(jax.random.key(9), (4, 9))
    labels = jnp.array([0, 3, -100, 2])
    got = ops.cross_entropy(logits, labels, ignore_index=-100)
    lp = np.asarray(jax.nn.log_softmax(logits, -1))
    want = -lp[[0, 1, 3], [0, 3, 2]].mean()
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cross_entropy_chunked_matches_unchunked():
    """chunk_size path (scan + checkpoint) == single pass, values and grads,
    with and without ignore_index, including non-divisible row counts."""
    logits = jax.random.normal(jax.random.key(12), (2, 37, 11))
    clean = jax.random.randint(jax.random.key(13), (2, 37), 0, 11)
    for ignore in (None, -100):
        labels = clean if ignore is None else clean.at[0, 5].set(-100)
        want = ops.cross_entropy(logits, labels, ignore_index=ignore)
        got = ops.cross_entropy(logits, labels, ignore_index=ignore, chunk_size=8)
        np.testing.assert_allclose(got, want, rtol=1e-6)
        gw = jax.grad(lambda lg: ops.cross_entropy(lg, labels, ignore_index=ignore))(logits)
        gg = jax.grad(
            lambda lg: ops.cross_entropy(lg, labels, ignore_index=ignore, chunk_size=8)
        )(logits)
        np.testing.assert_allclose(np.asarray(gg), np.asarray(gw), rtol=2e-5, atol=1e-7)


def test_distillation_loss_limits():
    """alpha=1 reduces to plain CE; identical logits give ~zero KL term."""
    s = jax.random.normal(jax.random.key(10), (6, 10))
    t = jax.random.normal(jax.random.key(11), (6, 10))
    labels = jnp.arange(6)
    np.testing.assert_allclose(
        ops.distillation_loss(s, t, labels, alpha=1.0),
        ops.cross_entropy(s, labels),
        rtol=1e-6,
    )
    same = ops.distillation_loss(s, s, labels, temperature=7.0, alpha=0.0)
    np.testing.assert_allclose(same, 0.0, atol=1e-5)


def test_vae_loss_components():
    mu = jnp.zeros((2, 3))
    logvar = jnp.zeros((2, 3))
    x = jnp.full((2, 4), 0.5)
    recon = jnp.full((2, 4), 0.5)
    total, bce, kl = ops.vae_loss(recon, x, mu, logvar)
    np.testing.assert_allclose(kl, 0.0, atol=1e-6)
    np.testing.assert_allclose(bce, -8 * np.log(0.5), rtol=1e-5)
    np.testing.assert_allclose(total, bce + kl, rtol=1e-6)


def test_mtp_loss_gathers_correct_targets():
    b, t, k, v = 1, 3, 2, 5
    tokens = jnp.arange(t + k)[None, :] % v
    # logits that put all mass on the correct target => loss ~ 0
    idx = np.arange(t)[:, None] + np.arange(1, k + 1)[None, :]
    targets = np.asarray(tokens)[0][idx]
    logits = np.full((b, t, k, v), -30.0, np.float32)
    for i in range(t):
        for j in range(k):
            logits[0, i, j, targets[i, j]] = 30.0
    loss = ops.mtp_loss(jnp.asarray(logits), tokens, num_heads=k)
    assert float(loss) < 1e-3


def test_activations_closed_form():
    x = jnp.linspace(-3, 3, 13)
    np.testing.assert_allclose(ops.relu(x), np.maximum(x, 0))
    np.testing.assert_allclose(ops.leaky_relu(x, 0.1), np.where(x >= 0, x, 0.1 * x))
    np.testing.assert_allclose(ops.elu(x), np.where(x >= 0, x, np.expm1(x)), rtol=1e-6)
    np.testing.assert_allclose(ops.silu(x), x / (1 + np.exp(-x)), rtol=1e-5)
    np.testing.assert_allclose(ops.swish(x, 1.0), ops.silu(x), rtol=1e-6)
    # tanh-approx GELU tracks exact GELU to ~1e-3
    exact = np.asarray(jax.nn.gelu(x, approximate=False))
    np.testing.assert_allclose(ops.gelu_tanh(x), exact, atol=2e-3)


def test_samplers():
    logits = jnp.array([[0.0, 10.0, -5.0, 3.0]])
    assert int(ops.sample_greedy(logits)[0]) == 1
    rng = jax.random.key(12)
    tok = ops.sample_top_k(logits, rng, k=2, temperature=1.0)
    assert int(tok[0]) in (1, 3)  # only top-2 logits survive
    # categorical at tiny temperature is effectively greedy
    tok2 = ops.sample_categorical(logits, rng, temperature=1e-4)
    assert int(tok2[0]) == 1


def test_sample_top_p_disabled_equals_categorical():
    """top_p=1.0 keeps every token, so the draw is bit-identical to plain
    categorical sampling under the same key (the mask is a no-op and the
    gumbel noise is the same shape)."""
    logits = jax.random.normal(jax.random.key(5), (3, 32))
    for i in range(8):
        rng = jax.random.key(100 + i)
        want = ops.sample_categorical(logits, rng)
        got = ops.sample_top_p(logits, rng, p=1.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sample_min_p_disabled_equals_categorical():
    logits = jax.random.normal(jax.random.key(6), (3, 32))
    for i in range(8):
        rng = jax.random.key(200 + i)
        want = ops.sample_categorical(logits, rng)
        got = ops.sample_min_p(logits, rng, min_p=0.0)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_top_k_one_is_greedy():
    """k=1 truncation leaves only the argmax, so any draw is greedy —
    both through the static sample_top_k and the traced top_k_mask."""
    logits = jax.random.normal(jax.random.key(7), (4, 32))
    want = np.asarray(ops.sample_greedy(logits))
    for i in range(8):
        rng = jax.random.key(300 + i)
        np.testing.assert_array_equal(
            np.asarray(ops.sample_top_k(logits, rng, k=1)), want
        )
        masked = ops.top_k_mask(logits, jnp.ones((4, 1), jnp.int32))
        np.testing.assert_array_equal(
            np.asarray(jax.random.categorical(rng, masked, axis=-1)), want
        )


def test_top_p_mass_cutoff_on_handbuilt_distribution():
    """Hand-built distribution [0.5, 0.3, 0.15, 0.05]: p=0.7 keeps the
    smallest prefix reaching 0.7 = {0, 1} (token 1 crosses the boundary
    and is kept); p=0.81 pulls in token 2; every draw stays inside the
    nucleus."""
    probs = np.array([0.5, 0.3, 0.15, 0.05])
    logits = jnp.log(jnp.asarray(probs))[None, :]
    masked = np.asarray(ops.top_p_mask(logits, 0.7))[0]
    assert np.isfinite(masked[:2]).all() and np.isinf(masked[2:]).all()
    masked = np.asarray(ops.top_p_mask(logits, 0.81))[0]
    assert np.isfinite(masked[:3]).all() and np.isinf(masked[3:]).all()
    draws = {
        int(ops.sample_top_p(logits, jax.random.key(i), p=0.7)[0])
        for i in range(64)
    }
    assert draws <= {0, 1} and len(draws) == 2


def test_min_p_cutoff_on_handbuilt_distribution():
    """min_p=0.35 with max prob 0.5 sets the floor at 0.175: keeps
    {0.5, 0.3}, drops {0.15, 0.05}."""
    probs = np.array([0.5, 0.3, 0.15, 0.05])
    logits = jnp.log(jnp.asarray(probs))[None, :]
    masked = np.asarray(ops.min_p_mask(logits, 0.35))[0]
    assert np.isfinite(masked[:2]).all() and np.isinf(masked[2:]).all()
    draws = {
        int(ops.sample_min_p(logits, jax.random.key(i), min_p=0.35)[0])
        for i in range(64)
    }
    assert draws <= {0, 1} and len(draws) == 2


def test_truncation_masks_accept_per_row_traced_cutoffs():
    """The serve path's requirement: one (S, V) logits block, DIFFERENT
    k/p/min_p per row, all traced — row 0 disabled, row 1 truncated."""
    logits = jnp.stack([jnp.arange(8.0), jnp.arange(8.0)])
    k = jnp.asarray([[0], [2]], jnp.int32)
    masked = np.asarray(ops.top_k_mask(logits, k))
    assert np.isfinite(masked[0]).all()
    assert np.isinf(masked[1][:6]).all() and np.isfinite(masked[1][6:]).all()
    p = jnp.asarray([[1.0], [1e-6]])
    masked = np.asarray(ops.top_p_mask(logits, p))
    assert np.isfinite(masked[0]).all()
    assert np.isfinite(masked[1]).sum() == 1  # only the top token survives
