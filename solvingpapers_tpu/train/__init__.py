"""The single training engine (L5) parameterizing every workload."""

from solvingpapers_tpu.train.optim import warmup_cosine, make_optimizer, OptimizerConfig
from solvingpapers_tpu.train.state import TrainState
from solvingpapers_tpu.train.engine import Trainer, TrainConfig, lm_loss_fn
from solvingpapers_tpu.train.objectives import (
    classification_loss_fn,
    reconstruction_loss_fn,
    vae_loss_fn,
    make_kd_loss_fn,
)
