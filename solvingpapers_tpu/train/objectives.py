"""Task objectives for the shared Trainer.

Each reference training loop's objective as a LossFn
(model, params, batch, rng, model_state, train) -> (loss, aux, model_state):

  * lm_loss_fn (train/engine.py)    — gpt/llama3/gemma/deepseekv3 LM CE
  * classification_loss_fn          — ViT.ipynb cell 13, kd.py teacher
  * reconstruction_loss_fn          — autoencoder.ipynb cells 6-7 (MSE)
  * vae_loss_fn                     — variational autoencoder.ipynb cell 6
  * make_kd_loss_fn                 — kd.py:48-68 distillation objective
                                      (teacher frozen under stop_gradient)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from solvingpapers_tpu import ops


def classification_loss_fn(model, params, batch, rng, model_state, train):
    """CE over class logits + accuracy (ViT.ipynb cells 13-15; kd.py:145-156)."""
    logits = model.apply(
        {"params": params},
        batch["x"],
        deterministic=not train,
        rngs={"dropout": rng} if train else None,
    )
    loss = ops.cross_entropy(logits, batch["y"])
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32))
    return loss, {"accuracy": acc}, model_state


def reconstruction_loss_fn(model, params, batch, rng, model_state, train):
    """Mean-square reconstruction of the input (autoencoder.ipynb cell 7)."""
    recon = model.apply({"params": params}, batch["x"], deterministic=not train)
    x32 = batch["x"].astype(jnp.float32)
    loss = jnp.mean(jnp.square(recon.astype(jnp.float32) - x32))
    return loss, {}, model_state


def vae_loss_fn(model, params, batch, rng, model_state, train):
    """Summed BCE + KL ELBO (variational autoencoder.ipynb cells 6, 8)."""
    recon, mu, logvar = model.apply(
        {"params": params},
        batch["x"],
        deterministic=not train,
        rngs={"sample": rng} if train else None,
    )
    total, bce, kl = ops.vae_loss(recon, batch["x"], mu, logvar)
    # reference reports the batch-summed loss; optimize the per-sample mean
    # so LR settings are batch-size independent
    n = batch["x"].shape[0]
    return total / n, {"bce": bce / n, "kl": kl / n}, model_state


def dsv3_init_fn(model, rngs, batch):
    """Init returning (params, model_state): DeepSeekV3 carries the MoE
    routing bias in the 'moe_state' collection (deepseekv3 cell 23 buffer).
    Initializes through the MTP branch when enabled so its params exist."""
    variables = model.init(rngs, batch["x"], return_mtp=model.cfg.mtp_heads > 0)
    return variables["params"], {"moe_state": variables["moe_state"]}


def _aggregate_moe_metrics(collection) -> dict:
    """Mean each sown per-layer MoE stat (models/deepseekv3.py MoELayer)
    into one train-metric scalar: moe_load_entropy, moe_load_max_fraction,
    moe_drop_fraction, moe_bias_norm."""
    layer_stats = jax.tree.leaves(
        collection,
        is_leaf=lambda x: isinstance(x, dict) and "load_entropy" in x,
    )
    layer_stats = [s for s in layer_stats if isinstance(s, dict)]
    if not layer_stats:
        return {}
    keys = [k for k in layer_stats[0] if k != "ci"]  # ci is an (E,) vector
    return {
        f"moe_{k}": jnp.mean(jnp.stack([s[k] for s in layer_stats]))
        for k in keys
    }


def dsv3_loss_fn(model, params, batch, rng, model_state, train):
    """DeepSeekV3 objective: next-token CE (+ weighted MTP loss when
    mtp_heads > 0), threading the mutable MoE routing bias through the step
    (the functional form of cell 23's no-grad buffer update + cell 54's loss).
    """
    cfg = model.cfg
    use_mtp = cfg.mtp_heads > 0
    variables = {"params": params, **(model_state or {})}
    kwargs = dict(deterministic=not train, return_mtp=use_mtp)
    moe_metrics = {}
    balance_terms: list = []
    if train:
        (out, _), mutated = model.apply(
            variables,
            batch["x"],
            rngs={"dropout": rng},
            mutable=["moe_state", "moe_metrics"],
            **kwargs,
        )
        new_ms = {"moe_state": mutated["moe_state"]}
        raw_metrics = mutated.get("moe_metrics", {})
        moe_metrics = _aggregate_moe_metrics(raw_metrics)
        if getattr(cfg, "balance_loss_weight", 0.0) > 0.0:
            # sown per layer by MoELayer (differentiable, unlike the stats)
            balance_terms = [
                leaf
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    raw_metrics
                )[0]
                if any(
                    getattr(k, "key", None) == "balance_loss" for k in path
                )
            ]
    else:
        out, _ = model.apply(variables, batch["x"], **kwargs)
        new_ms = model_state
    if use_mtp:
        logits, mtp_logits = out
    else:
        logits, mtp_logits = out, None

    main = ops.cross_entropy(logits, batch["y"])
    aux = {"perplexity": jnp.exp(main), **moe_metrics}
    loss = main
    if balance_terms:
        bal = jnp.mean(jnp.stack(balance_terms))
        aux["balance_loss"] = bal
        loss = loss + cfg.balance_loss_weight * bal
    if mtp_logits is not None:
        # mtp_loss wants the stream shifted so head j's target is token
        # i+(j+1)+1; y already holds tokens 1..T, pad the unknown tail.
        # Under CP the tail of a shard is the HEAD of the right neighbor:
        # a k-token halo (ppermute) replaces the pad except on the last
        # shard, and the loss psums sum/count over 'context' so the global
        # mean matches the dense computation exactly.
        k = cfg.mtp_heads
        if getattr(cfg, "context_parallel", False):
            from solvingpapers_tpu.sharding import cp_halo_right

            # append (not shift): mtp_loss wants the local T columns PLUS
            # the k halo columns as the target stream
            stream = jnp.concatenate(
                [batch["y"], cp_halo_right(batch["y"], k, fill=-1)], axis=1
            )
            mtp = ops.mtp_loss(mtp_logits, stream, k, ignore_index=-1,
                               axis_names=("context",))
        else:
            pad = jnp.full((batch["y"].shape[0], k), -1, batch["y"].dtype)
            mtp = ops.mtp_loss(
                mtp_logits, jnp.concatenate([batch["y"], pad], axis=1), k,
                ignore_index=-1,
            )
        aux["mtp_loss"] = mtp
        # add to the accumulated loss (main + any balance term), not to
        # `main` — overwriting silently dropped the balance loss whenever
        # MTP was on
        loss = loss + cfg.mtp_loss_weight * mtp
    return loss, aux, new_ms


def make_kd_loss_fn(teacher_model, teacher_params, temperature=7.0, alpha=0.3):
    """Distillation objective with a frozen teacher (kd.py:48-68, 110-142).

    The teacher forward runs inside the jitted step under stop_gradient —
    the functional equivalent of the reference's `with torch.no_grad()`.
    """

    def kd_loss_fn(model, params, batch, rng, model_state, train):
        teacher_logits = jax.lax.stop_gradient(
            teacher_model.apply(
                {"params": teacher_params}, batch["x"], deterministic=True
            )
        )
        student_logits = model.apply(
            {"params": params},
            batch["x"],
            deterministic=not train,
            rngs={"dropout": rng} if train else None,
        )
        loss = ops.distillation_loss(
            student_logits, teacher_logits, batch["y"], temperature, alpha
        )
        acc = jnp.mean(
            (jnp.argmax(student_logits, -1) == batch["y"]).astype(jnp.float32)
        )
        return loss, {"accuracy": acc}, model_state

    return kd_loss_fn
