"""Training state pytree."""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    """Functional train state: params + optimizer state + step + PRNG +
    optional non-differentiable model state (e.g. MoE aux-free router bias,
    deepseekv3 cell 23's `routing_bias` buffer)."""

    step: jax.Array
    params: Any
    opt_state: optax.OptState
    rng: jax.Array
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    model_state: Any = None

    @classmethod
    def create(cls, *, apply_fn, params, tx, rng, model_state=None):
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng,
            model_state=model_state,
            apply_fn=apply_fn,
            tx=tx,
        )

    def apply_gradients(self, grads, new_model_state=None):
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            model_state=new_model_state if new_model_state is not None else self.model_state,
        )
