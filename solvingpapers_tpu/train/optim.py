"""Optimizer construction.

Covers the reference's optimizer settings from one config:
  * AdamW β=(0.9, 0.95), wd 0.1, eps 1e-8, grad-clip 1.0, linear warmup →
    cosine decay to 0.1·max_lr (deepseekv3/deepseekv3.ipynb cells 42-44, 54)
  * optax.adamw TrainState (gpt/gpt-jax.ipynb cell 16)
  * plain SGD kept as an option for llama3 parity (LLaMA-jax.ipynb cell 29's
    hand-rolled p - lr·g)
Gradient accumulation is optax.MultiSteps — the functional replacement for
the torch accumulate-then-step inner loop; loss scaling is unnecessary
because TPU training runs bf16, not fp16 (no GradScaler equivalent needed).
"""

from __future__ import annotations

import dataclasses

import optax


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    name: str = "adamw"  # adamw | sgd
    max_lr: float = 3e-4
    min_lr_ratio: float = 0.1
    warmup_steps: int = 0
    total_steps: int = 10_000
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.1
    grad_clip: float = 1.0
    accum_steps: int = 1


def warmup_cosine(
    max_lr: float, warmup_steps: int, total_steps: int, min_lr_ratio: float = 0.1
) -> optax.Schedule:
    """Linear warmup then cosine decay to min_lr_ratio·max_lr (dsv3 cell 44)."""
    if warmup_steps <= 0:
        return optax.cosine_decay_schedule(
            max_lr, max(total_steps, 1), alpha=min_lr_ratio
        )
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=max_lr,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=max_lr * min_lr_ratio,
    )


def make_optimizer(cfg: OptimizerConfig) -> tuple[optax.GradientTransformation, optax.Schedule]:
    schedule = warmup_cosine(cfg.max_lr, cfg.warmup_steps, cfg.total_steps, cfg.min_lr_ratio)
    if cfg.name == "adamw":
        opt = optax.adamw(
            schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps, weight_decay=cfg.weight_decay
        )
    elif cfg.name == "sgd":
        opt = optax.sgd(schedule)
    elif cfg.name == "adam":
        opt = optax.adam(schedule, b1=cfg.b1, b2=cfg.b2, eps=cfg.eps)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    chain = [optax.clip_by_global_norm(cfg.grad_clip)] if cfg.grad_clip > 0 else []
    chain.append(opt)
    tx = optax.chain(*chain)
    if cfg.accum_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.accum_steps)
    return tx, schedule
