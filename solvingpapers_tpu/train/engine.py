"""The training engine — one implementation of the loop every reference
notebook hand-rolls (gpt cell 18, llama3 cell 31, gemma cell 18,
deepseekv3 cell 54, kd.py:85-142, ViT cell 14, autoencoder cell 7).

Features (capability superset of deepseekv3's `train()`):
  * jitted, sharded train/eval steps over a ('data','fsdp','model','expert')
    mesh — DataParallel's replacement is a PartitionSpec, not a wrapper class
  * bf16 compute policy (replaces torch AMP/GradScaler — no loss scaling
    needed in bf16), grad accumulation (optax.MultiSteps), global-norm clip
  * warmup-cosine LR, periodic eval, periodic checkpointing with resume
  * metrics: loss, perplexity, lr, grad_norm, tokens, step_time,
    tokens/sec, MFU — wandb-compatible names via MetricsWriter sinks
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import time
from typing import Any, Callable, Iterator

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from solvingpapers_tpu import ops
from solvingpapers_tpu.checkpoint import CheckpointManager
from solvingpapers_tpu.metrics import ConsoleWriter, MetricsWriter
from solvingpapers_tpu.sharding import (
    LM_RULES,
    MeshConfig,
    ambient_mesh,
    batch_sharding,
    create_mesh,
    param_specs,
)
from solvingpapers_tpu.sharding.pipeline import shard_map_compat
from solvingpapers_tpu.train.optim import OptimizerConfig, make_optimizer
from solvingpapers_tpu.train.state import TrainState

# loss_fn(model, params, batch, rng, model_state, train) -> (loss, aux, new_model_state)
LossFn = Callable[..., tuple[jax.Array, dict, Any]]

# vma typing (jax.typeof / jax.shard_map's check_vma) exists from jax 0.9;
# on older jax `shard_map_compat` (sharding/pipeline.py) runs the
# legacy experimental shard_map with its rep checker off, which matches
# the check_vma=False semantics every schedule here is also written for
# — `Trainer._check_vma` reports False on such jax so the pmean paths
# reduce over all axes, the plain SPMD semantics (hasattr swallows the
# module-level deprecation getattr)
_HAS_VMA = hasattr(jax, "typeof")


def _pp_param_spec(path, _leaf) -> P:
    """shard_map in_spec for pipeline-parallel params: the stage-stacked
    subtree (top-level 'stages' key, models/gpt_pipe.py) over 'pipe',
    everything else replicated. One definition for both PP and CP+PP."""
    key = getattr(path[0], "key", None) if path else None
    return P("pipe") if key == "stages" else P()


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    steps: int = 1000
    batch_size: int = 32
    log_every: int = 50
    eval_every: int = 500
    eval_batches: int = 20
    ckpt_every: int = 0  # 0 = disabled
    checkpoint_dir: str | None = None
    keep_n: int = 3
    # periodic saves return after the device->host snapshot and write to
    # disk in a background thread (final/preemption saves always block);
    # safe with donated step buffers because Orbax completes the D2H copy
    # before save() returns
    async_checkpointing: bool = True
    seed: int = 0
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    tokens_per_step: int | None = None  # enables tokens/sec + MFU metrics
    flops_per_token: float | None = None
    # TPU-fast PRNG for dropout masks etc. (threefry bit-gen dominates the
    # reference GPT config's step time: 37.7 -> 25.9 ms/step on v5e with
    # rbg, same Bernoulli distribution, different stream). Applied to this
    # trainer's key stream only; None = the jax default (threefry). Note:
    # checkpoints store key data, so resume with the impl that wrote them.
    prng_impl: str | None = "rbg"
    # on-device training window: lax.scan `scan_steps` train steps per
    # dispatch (one host->device batch transfer of K stacked batches, one
    # fused XLA program). Amortizes per-step dispatch latency — the
    # dominant cost for small models and high-latency transports (the
    # tunnelled bench chip: ~12% of the reference-GPT step). Semantically
    # identical to K sequential steps (tests/test_engine.py pins equality);
    # log/eval/ckpt cadences must be multiples of scan_steps since the
    # host only sees window boundaries.
    scan_steps: int = 1
    # aux subsystems (SURVEY.md §5)
    debug_nans: bool = False  # jax_debug_nans: fail fast at the faulting op
    profile_dir: str | None = None  # jax.profiler trace output (TensorBoard)
    profile_steps: tuple[int, int] = (10, 15)  # [start, stop) steps to trace
    # flight recorder (metrics/trace.py): record data-wait / step / eval /
    # checkpoint / callback spans on a "train" track and export a Chrome
    # trace-event JSON here when fit() ends (also on exceptions — the
    # post-mortem case). Adds a goodput metric (traced step time / wall:
    # the fraction of the run actually training vs waiting on data, eval,
    # and checkpoints). Observability mode: each dispatch is fenced with
    # block_until_ready so step spans are true durations — do not leave it
    # on for production throughput runs.
    trace_path: str | None = None
    # compile & memory observatory (metrics/xla_obs.py, opt-in): the
    # train/eval steps route through a CompileRegistry (every XLA
    # compilation recorded — signature, wall time, cost_analysis
    # flops/bytes — with recompile-storm flagging) and an HBMLedger
    # tracks params/opt_state live bytes + projected peak vs device
    # capacity; compile/* + mem/* + roofline/* gauges ride each logged
    # metrics row. Observability mode (steps are fenced like trace_path)
    # — leave off for production throughput runs.
    xla_obs: bool = False
    # mesh observatory (metrics/mesh_obs.py, opt-in): extends the
    # compile observatory with (1) a collective ledger — every compiled
    # program's HLO parsed for all-reduce/all-gather/reduce-scatter/
    # all-to-all/collective-permute ops, per-program comm bytes as
    # mesh/comm_* gauges; (2) pipeline-bubble diagnosis when
    # pipeline_parallel — each stage_fn probed standalone after the
    # compile step, analytic (S-1)/(M+S-1) vs measured bubble fraction
    # and the straggler stage in gauges, /statusz and trace-summary;
    # (3) per-device HBM ledger math (shard_shape bytes, not global);
    # (4) per-tick stage<N> trace tracks when trace_path is also set.
    # Implies the compile registry (xla_obs); observability mode —
    # steps are fenced, so leave off for production throughput runs.
    mesh_obs: bool = False
    # live /healthz /metrics /statusz endpoint during fit()
    # (metrics/http.py); port 0 = ephemeral, None = off
    status_port: int | None = None
    status_host: str = "127.0.0.1"
    # context parallelism: shard the sequence dim of (B, S) token batches
    # over the mesh 'context' axis and run the whole loss inside shard_map
    # (the model must be built with context_parallel=True so its attention
    # runs the ppermute ring / Ulysses all_to_all). Composes with the data
    # axes AND fsdp: params stay stored in their ZeRO layout (sharded over
    # 'fsdp') and are all-gathered inside the step, grads reduce-scatter.
    context_parallel: bool = False
    # pipeline parallelism: the model's stage-stacked decoder params (under
    # a top-level 'stages' key, models/gpt_pipe.py) are sharded over the
    # mesh 'pipe' axis and the loss runs inside shard_map with the GPipe
    # microbatch schedule. Composes with the data axis; use rules=PP_RULES.
    pipeline_parallel: bool = False
    # memory-bounded PP training: split the batch into `pp_grad_groups`
    # groups and run loss+backward PER GROUP in a lax.scan, accumulating
    # gradients — each group is one pipeline flush, so the backward's live
    # residuals cover ONE group's schedule ticks instead of the whole
    # batch's. With the model's n_microbatches set to the pipe size, live
    # activation memory scales with n_stages rather than the total
    # microbatch count (GPipe's weakness at depth); the price is one
    # fill+drain bubble per group. Gradients equal the single-flush step
    # up to fp reassociation (tests/test_pipeline_model.py pins this);
    # model_state (MoE routing bias) threads through groups sequentially.
    pp_grad_groups: int = 1
    # PP backward schedule: "gpipe" = jax.grad through the forward
    # schedule (activation memory grows with total microbatches; pair with
    # pp_grad_groups to bound it at the cost of per-group bubbles).
    # "1f1b" = one-forward-one-backward (sharding.pipeline
    # .pipeline_1f1b_value_and_grad): each microbatch's backward runs as
    # soon as its loss exists, bounding live activations by PIPE DEPTH
    # with no extra bubble. Requires a model exposing f1b_value_and_grad
    # (GPTPipe, LlamaPipe); dropout trains via per-(stage, microbatch)
    # regenerable keys; data x pipe meshes and the LM objective in v1.
    pp_schedule: str = "gpipe"


def lm_loss_fn(model, params, batch, rng, model_state, train):
    """Default LM objective: next-token CE on batch['x'] -> batch['y']."""
    logits, _ = model.apply(
        {"params": params},
        batch["x"],
        deterministic=not train,
        rngs={"dropout": rng} if train else None,
    )
    loss = ops.cross_entropy(logits, batch["y"])  # auto-chunks at scale
    return loss, {"perplexity": jnp.exp(loss)}, model_state


class Trainer:
    def __init__(
        self,
        model,
        config: TrainConfig,
        loss_fn: LossFn = lm_loss_fn,
        rules=LM_RULES,
        init_fn: Callable | None = None,
        mesh=None,
    ):
        self.model = model
        self.config = config
        # debug_nans is enabled inside fit() and restored on exit so the
        # process-global flag does not leak across Trainers; prng_impl is
        # scoped to this trainer's key stream (init_state), not the global
        self.loss_fn = loss_fn
        self.rules = rules
        self.mesh = mesh if mesh is not None else create_mesh(config.mesh)
        self.tx, self.schedule = make_optimizer(config.optimizer)
        # init_fn(model, rngs, batch) -> params dict
        self.init_fn = init_fn or (
            lambda model, rngs, batch: model.init(rngs, batch["x"])["params"]
        )
        self._train_step = None
        self._train_step_scan = None
        self._eval_step = None
        self._state_shardings = None
        self._batch_shardings = None
        # compile & memory observatory (TrainConfig.xla_obs) and mesh
        # observatory (TrainConfig.mesh_obs); built in fit() so the
        # ledger can track the live TrainState
        self._registry = None
        self._ledger = None
        self._mesh_obs = None
        self._status = None

    def _dispatch(self, name: str, jitted, state, batch):
        """Run a jitted step, through the compile registry when the
        observatory is on (signature = the batch's leaf shapes; the
        state's shapes are fixed after init) — one branch when off."""
        if self._registry is None:
            return jitted(state, batch)
        key = tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            for leaf in jax.tree_util.tree_leaves(batch)
        )
        return self._registry.call(name, key, jitted, (state, batch))

    # ------------------------------------------------------------------ init

    def init_state(self, example_batch: dict) -> TrainState:
        cfg = self.config

        def make(rng):
            p_rng, d_rng, s_rng = jax.random.split(rng, 3)
            rngs = {"params": p_rng, "dropout": d_rng}
            if cfg.context_parallel:
                # a CP model's forward calls axis collectives, so init must
                # also run inside shard_map; identical rngs/shapes on every
                # shard make the params replicated (out_specs P())
                out = shard_map_compat(
                    lambda r, b: self.init_fn(self.model, r, b),
                    mesh=self.mesh, in_specs=(P(), self._batch_specs()),
                    out_specs=P(), check_vma=self._check_vma(),
                )(rngs, example_batch)
            else:
                # PP models init their blocks on tiny unsharded dummies —
                # routing those through the sharded flash wrapper would be
                # wrong (and the real PP step runs inside shard_map, where
                # the direct kernel is correct); only GSPMD-partitioned
                # inits mark the mesh
                with ambient_mesh(
                    None if cfg.pipeline_parallel else self.mesh
                ):
                    out = self.init_fn(self.model, rngs, example_batch)
            # init_fn may return params alone or (params, model_state)
            params, model_state = out if isinstance(out, tuple) else (out, None)
            return TrainState.create(
                apply_fn=self.model.apply, params=params, tx=self.tx, rng=s_rng,
                model_state=model_state,
            )

        # the impl is carried by the key itself: split/fold_in preserve it,
        # so every dropout/init key in this trainer derives from it without
        # touching the process-global default
        rng = (
            jax.random.key(cfg.seed, impl=cfg.prng_impl)
            if cfg.prng_impl
            else jax.random.key(cfg.seed)
        )
        self._set_batch_shardings(example_batch)
        abstract = jax.eval_shape(make, rng)
        specs = param_specs(abstract, self.rules, mesh=self.mesh)
        self._state_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        state = jax.jit(make, out_shardings=self._state_shardings)(rng)
        return state

    def _set_batch_shardings(self, example_batch: dict) -> None:
        """Record rank-appropriate batch shardings (x may be 2-D tokens or
        4-D images; y may be 2-D targets or 1-D labels). Under context
        parallelism, the sequence dim of rank-2 arrays under the
        sequence-aligned keys 'x'/'y' is sharded over 'context' in addition
        to the batch dim over (data, fsdp) — the key gate keeps a rank-2
        non-sequence array (e.g. (B, n_classes) soft labels) from being
        silently mis-sharded over 'context'."""
        cp = self.config.context_parallel

        def shard(path, a):
            key = getattr(path[0], "key", None) if path else None
            seq = cp and jnp.ndim(a) == 2 and key in ("x", "y")
            return batch_sharding(self.mesh, jnp.ndim(a) - 1, context=seq)

        self._batch_shardings = jax.tree_util.tree_map_with_path(
            shard, example_batch
        )

    def _batch_specs(self):
        """PartitionSpec pytree of the recorded batch shardings."""
        return jax.tree.map(
            lambda s: s.spec, self._batch_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )

    # ------------------------------------------------------------------ steps

    def _cp_loss_call(self):
        """Build the context-parallel loss: the model applies inside
        shard_map with the sequence sharded over 'context' (its attention
        runs the ppermute ring / Ulysses all_to_all); params enter in their
        STORED layout — sharded over 'fsdp' (ZeRO) when that axis is > 1,
        replicated otherwise — and are all-gathered inside the step. The
        per-shard loss is pmean'd back to the global mean (equal shard
        sizes make that exact); gradients psum/reduce-scatter through
        shard_map's transpose automatically."""
        self._reject_axes(
            "context_parallel", ("model", "pipe"),
            "replicates params inside shard_map",
        )
        if not getattr(getattr(self.model, "cfg", None), "context_parallel", False):
            raise ValueError(
                "TrainConfig.context_parallel=True but the model was not "
                "built with context_parallel=True: it would attend only "
                "within each local sequence shard (no ring collectives, "
                "positions restarting at 0) and train a silently wrong "
                "objective"
            )
        # FSDP composes: params enter shard_map in their stored (sharded)
        # layout and are all-gathered over 'fsdp' inside the step — the
        # gather's transpose reduce-scatters the grads, i.e. ZeRO-3, so
        # per-device param memory stays 1/fsdp at rest. The 'expert' axis
        # composes as ZeRO over expert STORAGE (sharded at rest, gathered
        # in-step, grads reduce-scattered) plus sliced expert COMPUTE:
        # MoELayer under context_parallel dispatches only its E/ep expert
        # columns and psums the partial combines over 'expert'
        # (ops.moe.moe_expert_sliced_combine — flax validates param shapes
        # at apply, so slicing happens inside the layer after the gather,
        # not in the param pytree). Decorrelate dropout
        # across every shard: each holds a different (batch, seq) slice.
        # 'expert' is in the reduce axes only for typing: gathered expert
        # weights read as expert-varying (all_gather proves no invariance),
        # and the pmean — a numeric no-op across identical members — is
        # what certifies the out_specs P() replication
        return self._shard_map_loss_call(
            ("data", "fsdp", "context", "expert"), self._fsdp_param_specs(),
            rng_axes=("data", "fsdp", "context"), gather_fsdp=True,
        )

    def _fsdp_param_specs(self, axes: tuple = ("fsdp", "expert")):
        """(path, leaf) -> P giving each param's STORED layout restricted
        to `axes` (default 'fsdp' + 'expert') — derived from the same rule
        table/mesh as the state shardings, so it needs no init_state
        precondition (evaluate / fit with an external state build steps
        without one). The kept axes' dims are gathered in-step (ZeRO
        layout at rest). model/pipe are rejected above; their size-1 names
        in the rule table would otherwise mark values conservatively
        varying over those axes — the same reason the PP path passes
        axes=('fsdp',): its mesh rejects 'expert', and an all_gather over
        the size-1 axis would still type every expert-weight consumer as
        expert-varying, failing the out_specs P() contract."""
        from solvingpapers_tpu.sharding.rules import leaf_spec

        def keep(spec):
            def f(entry):
                names = entry if isinstance(entry, tuple) else (entry,)
                kept = tuple(n for n in names if n in axes)
                if len(kept) > 1:
                    # gather_param reassembles one name at a time, which
                    # would interleave a jointly-sharded dim's chunks in
                    # the wrong order — no shipped rule co-shards a dim
                    # over both axes, so refuse rather than corrupt
                    raise NotImplementedError(
                        f"dim jointly sharded over {kept} is not supported "
                        "by the in-step ZeRO gather"
                    )
                return kept[0] if kept else None

            return P(*(f(e) if e is not None else None for e in spec))

        return lambda path, leaf: keep(
            leaf_spec(path, leaf, self.rules, self.mesh)
        )

    def _pp_loss_call(self):
        """Build the pipeline-parallel loss: stage-stacked params (leading
        stage dim under 'stages') are sharded over 'pipe'; inside shard_map
        the model runs the GPipe ppermute schedule (models/gpt_pipe.py).
        Every pipe device computes the identical global loss (the pipeline
        output is psum-broadcast), so the pmean over 'pipe' is exact.

        FSDP composes: non-stage params (embedding/norm/head) enter in
        their stored fsdp layout and are all-gathered in-step (ZeRO —
        same mechanism as the CP path); stage params stay 'pipe'-local
        (the GPipe body wants exactly its own stage)."""
        self._reject_axes(
            "pipeline_parallel", ("model", "expert", "context"),
            "replicates non-stage params inside shard_map",
        )
        mcfg = getattr(self.model, "cfg", None)
        if not getattr(mcfg, "pipeline_parallel", False):
            raise ValueError(
                "TrainConfig.pipeline_parallel=True but the model was not "
                "built with pipeline_parallel=True: it would scan stages "
                "sequentially on every pipe device"
            )
        self._check_pp_stages(mcfg)
        # identical rng on every pipe device (they compute the same loss);
        # decorrelate only across data shards. The loss is already
        # invariant over 'pipe' (the pipeline output is psum-broadcast),
        # so only the data axes are reduced.
        return self._shard_map_loss_call(
            ("data", "fsdp"), self._pp_param_specs(),
            rng_axes=("data", "fsdp"), gather_fsdp=True,
        )

    def _pp_param_specs(self):
        """(path, leaf) -> P for PP in-specs: the stage-stacked subtree is
        sharded over 'pipe' (NOT gathered — each device's GPipe body uses
        its own stage), non-stage params carry their stored fsdp/expert
        layout and are all-gathered in-step by gather_param (which only
        touches the kept names, leaving 'pipe' dims local). 'expert' is
        excluded: the PP mesh rejects that axis (size 1), and gathering
        over it would only poison the vma typing (see _fsdp_param_specs)."""
        fsdp = self._fsdp_param_specs(axes=("fsdp",))

        def spec(path, leaf):
            if path and getattr(path[0], "key", None) == "stages":
                return P("pipe")
            return fsdp(path, leaf)

        return spec

    def _cp_pp_loss_call(self):
        """CP x PP composition: the sequence is sharded over 'context' AND
        the stage-stacked params over 'pipe' — each stage's attention runs
        the ppermute ring within its pipe coordinate's context group while
        microbatches hop stages (orthogonal axes, uniform schedule). The
        loss is invariant over 'pipe' (pipeline output psum-broadcast) and
        pmean'd over the data/context axes (the vma-aware pmean reduces
        exactly the axes each value varies over)."""
        self._reject_axes(
            "context_parallel+pipeline_parallel", ("fsdp", "model", "expert"),
            "replicates non-stage params inside shard_map",
        )
        mcfg = getattr(self.model, "cfg", None)
        for flag in ("context_parallel", "pipeline_parallel"):
            if not getattr(mcfg, flag, False):
                raise ValueError(
                    f"TrainConfig CP+PP but the model was not built with "
                    f"{flag}=True"
                )
        self._check_pp_stages(mcfg)
        return self._shard_map_loss_call(
            ("data", "fsdp", "context"), _pp_param_spec,
            rng_axes=("data", "fsdp", "context"),
        )

    def _check_pp_stages(self, mcfg) -> None:
        pipe = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get("pipe", 1)
        v = getattr(mcfg, "virtual_stages", 1)
        if getattr(mcfg, "n_stages", None) != pipe * v:
            raise ValueError(
                f"model n_stages ({getattr(mcfg, 'n_stages', None)}) must "
                f"equal the mesh 'pipe' axis size ({pipe}) x virtual_stages "
                f"({v}): each device holds exactly virtual_stages slices"
            )

    def _reject_axes(self, mode: str, axes: tuple, why: str) -> None:
        sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
        bad = {a: sizes[a] for a in axes if sizes.get(a, 1) > 1}
        if bad:
            raise NotImplementedError(
                f"{mode} {why} and does not compose with {bad} axes yet"
            )

    def _check_vma(self) -> bool:
        """vma checking must be off whenever the model's attention core is
        a pallas kernel: a pallas_call inside lax.scan under the jax-0.9
        vma checker KeyErrors in the closed_call lowering cache. One gate
        for every shard_map this Trainer builds (CP loss, PP loss, CP init).
        Always False on jax without vma typing (the legacy shard_map path)."""
        return _HAS_VMA and not getattr(
            getattr(self.model, "cfg", None), "use_flash", False
        )

    def _pp_1f1b_vg_call(self):
        """Loss AND grads via the 1F1B schedule (TrainConfig.pp_schedule
        = "1f1b"): the model's f1b_value_and_grad runs inside shard_map —
        per-microbatch backwards interleaved with forwards, live
        activations bounded by pipe depth (BENCHMARKS.md PP memory table)
        — so the engine consumes grads directly instead of wrapping the
        forward in jax.value_and_grad."""
        self._reject_axes(
            "pp_schedule='1f1b'", ("model", "expert", "context", "fsdp"),
            "v1 supports data x pipe meshes only",
        )
        mcfg = getattr(self.model, "cfg", None)
        if not getattr(mcfg, "pipeline_parallel", False):
            raise ValueError(
                "pp_schedule='1f1b' requires a model built with "
                "pipeline_parallel=True"
            )
        self._check_pp_stages(mcfg)
        if not hasattr(self.model, "f1b_value_and_grad"):
            raise NotImplementedError(
                f"{type(self.model).__name__} does not implement "
                "f1b_value_and_grad (GPTPipe and LlamaPipe do); use "
                "pp_schedule='gpipe'"
            )
        if getattr(mcfg, "virtual_stages", 1) != 1:
            raise NotImplementedError(
                "pp_schedule='1f1b' x virtual_stages is not composed; "
                "use pp_schedule='gpipe' for the interleaved schedule"
            )
        if self.config.pp_grad_groups > 1:
            raise NotImplementedError(
                "pp_schedule='1f1b' already bounds activation memory by "
                "pipe depth; pp_grad_groups adds only bubbles — use one "
                "or the other"
            )
        from solvingpapers_tpu.train.objectives import (
            dsv3_loss_fn as _dsv3_loss_fn,
        )

        if self.loss_fn is not lm_loss_fn and self.loss_fn is not _dsv3_loss_fn:
            raise NotImplementedError(
                "pp_schedule='1f1b' computes its objective inside the "
                "schedule (the model's f1b_value_and_grad), so a custom "
                "Trainer loss_fn would be silently ignored — use "
                "pp_schedule='gpipe' for custom objectives"
            )
        batch_specs = self._batch_specs()
        param_in_specs = self._pp_param_specs()

        def call(params, model_state, batch, rng):
            p_specs = jax.tree_util.tree_map_with_path(
                param_in_specs, params
            )

            sizes = dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            n_shards = sizes.get("data", 1) * sizes.get("fsdp", 1)

            def mean_over_data(a):
                # every cross-shard reduction is explicit on this path:
                # psum the per-shard local value and divide by the shard
                # count (the mean the replicated-param grads need)
                return jax.lax.psum(a, ("data", "fsdp")) / n_shards

            def local(params, ms, batch, rng):
                # decorrelate dropout masks across data shards (pipe
                # devices share the key: they must agree on the masks the
                # schedule's units regenerate)
                rng = jax.random.fold_in(
                    rng, jax.lax.axis_index(("data", "fsdp"))
                )
                out = self.model.f1b_value_and_grad(
                    params, batch, rng=rng, model_state=ms
                )
                loss, grads, new_ms = out[0], out[1], out[2]
                # optional 4th element: extra train metrics (the
                # flagship's MoE routing stats)
                extra = out[3] if len(out) > 3 else {}
                loss = mean_over_data(loss)
                grads = jax.tree.map(mean_over_data, grads)
                aux = {
                    "perplexity": jnp.exp(loss),
                    **{k: mean_over_data(v) for k, v in extra.items()},
                }
                return loss, aux, grads, new_ms

            # check_vma OFF deliberately (not just for flash models): under
            # the vma checker, vjp cotangents w.r.t. data-replicated params
            # carry a pending cross-shard sum whose materialization point
            # differs per leaf (measured: stage-param grads came back
            # doubled after pmean while head grads did not) — with the
            # checker off the body has plain SPMD semantics, every device
            # holds its shard-local grads (verified against per-shard
            # oracles), and the ONE explicit psum/n above is the whole
            # cross-shard story.
            loss, aux, grads, new_ms = shard_map_compat(
                local, mesh=self.mesh,
                in_specs=(p_specs, P(), batch_specs, P()),
                out_specs=(P(), P(), p_specs, P()),
                check_vma=False,
            )(params, model_state, batch, rng)
            return loss, aux, new_ms, grads

        return call

    def _shard_map_loss_call(self, axes, param_in_specs, rng_axes,
                             gather_fsdp: bool = False):
        """Common shard_map loss wrapper for CP/PP. `param_in_specs` is a
        spec pytree/prefix, or a (path, leaf) -> P function evaluated
        against the abstract params at call time. With `gather_fsdp`, each
        param enters in its stored (sharded) layout and is all-gathered
        along the dims its spec shards before the model applies — the
        gather's transpose reduce-scatters the grads (ZeRO-3)."""
        batch_specs = self._batch_specs()
        check_vma = self._check_vma()

        def gather_param(p, spec):
            for dim, entry in enumerate(spec):
                if entry is None:
                    continue
                for name in (entry if isinstance(entry, tuple) else (entry,)):
                    # only ZeRO axes are gathered in-step; a 'pipe' entry
                    # (PP stage stacks) marks a dim that must STAY local
                    if name in ("fsdp", "expert"):
                        p = jax.lax.all_gather(p, name, axis=dim, tiled=True)
            return p

        def pmean(a):
            # aux may mix shard-varying values (per-shard loss terms) with
            # already-invariant ones (psum'd MoE stats). Under the vma
            # checker, reduce only the axes a value actually varies over;
            # without vma tracking (check_vma=False, incl. pre-vma jax),
            # the plain pmean of an invariant value is a numeric no-op.
            if check_vma:  # only ever True when jax.typeof exists
                vma = getattr(jax.typeof(a), "vma", None)
                if vma is not None:
                    ax = tuple(x for x in axes if x in vma)
                    return jax.lax.pmean(a, ax) if ax else a
            return jax.lax.pmean(a, axes)

        def call(params, model_state, batch, rng, train):
            if model_state is not None and not check_vma and getattr(
                getattr(self.model, "cfg", None), "stats_axes", None
            ) is None:
                # with vma checking off (flash models) the out_specs P()
                # contract below is unverified — require the model to
                # declare shard-invariant state updates explicitly, or a
                # per-shard-varying state would be silently mis-replicated
                raise NotImplementedError(
                    "model_state under shard_map without vma checking: the "
                    "model must declare shard-invariant state updates "
                    "(cfg.stats_axes, psum'd like DeepSeekV3's MoE load)"
                )
            p_specs = (
                jax.tree_util.tree_map_with_path(param_in_specs, params)
                if callable(param_in_specs)
                else param_in_specs
            )

            def local(params, ms, batch, rng):
                if gather_fsdp:
                    # p_specs nodes are matched whole at params' leaf
                    # boundary (flatten_up_to), so each leaf pairs with its P
                    params = jax.tree.map(gather_param, params, p_specs)
                rng = jax.random.fold_in(rng, jax.lax.axis_index(rng_axes))
                loss, aux, new_ms = self.loss_fn(
                    self.model, params, batch, rng, ms, train
                )
                loss = pmean(loss)
                if "perplexity" in aux:
                    # reduce in log space: exp of the global-mean MAIN CE
                    # (the loss fn's exp(main)), not the pmean of local
                    # exps — and not exp(total loss), which would fold MTP
                    # and balance aux terms into the reported perplexity
                    aux = dict(aux, perplexity=jnp.log(aux["perplexity"]))
                aux = jax.tree.map(pmean, aux)
                if "perplexity" in aux:
                    aux = dict(aux, perplexity=jnp.exp(aux["perplexity"]))
                return loss, aux, new_ms

            # model_state (e.g. the MoE routing bias) enters replicated and
            # must leave replicated: the model's in-step updates have to be
            # shard-invariant (psum'd loads — DeepSeekV3Config.stats_axes);
            # out_specs P() asserts that contract under the vma checker
            loss, aux, new_ms = shard_map_compat(
                local, mesh=self.mesh,
                in_specs=(p_specs, P(), batch_specs, P()),
                out_specs=(P(), P(), P()), check_vma=check_vma,
            )(params, model_state, batch, rng)
            return loss, aux, new_ms

        return call

    def _build_steps(self):
        replicated = NamedSharding(self.mesh, P())
        if self.config.context_parallel and self.config.pipeline_parallel:
            loss_call = self._cp_pp_loss_call()
        elif self.config.context_parallel:
            loss_call = self._cp_loss_call()
        elif self.config.pipeline_parallel:
            loss_call = self._pp_loss_call()
        else:
            def loss_call(params, ms, batch, rng, train):
                # mark the GSPMD mesh while the model traces so use_flash
                # attention routes through the shard_map-wrapped kernel on
                # >1-device meshes (pallas_call is opaque to GSPMD — the
                # direct call would all-gather q/k/v)
                with ambient_mesh(self.mesh):
                    return self.loss_fn(self.model, params, batch, rng, ms, train)

        pp_groups = (
            self.config.pp_grad_groups if self.config.pipeline_parallel else 1
        )

        def grouped_value_and_grad(state, batch, step_rng):
            """Scan loss+backward over pp_grad_groups batch groups,
            accumulating grads — one pipeline flush per group, so the
            backward holds one group's residuals at a time (see
            TrainConfig.pp_grad_groups)."""
            bsz = jax.tree.leaves(batch)[0].shape[0]
            if bsz % pp_groups:
                raise ValueError(
                    f"batch {bsz} not divisible by pp_grad_groups {pp_groups}"
                )
            gbatch = jax.tree.map(
                lambda a: a.reshape(pp_groups, a.shape[0] // pp_groups,
                                    *a.shape[1:]),
                batch,
            )

            def body(carry, inp):
                ms, acc_loss, acc_aux, acc_g = carry
                gidx, grp = inp

                def loss_wrap(params):
                    loss, aux, new_ms = loss_call(
                        params, ms,
                        grp, jax.random.fold_in(step_rng, gidx), True,
                    )
                    return loss, (aux, new_ms)

                (l, (aux, new_ms)), g = jax.value_and_grad(
                    loss_wrap, has_aux=True
                )(state.params)
                if "perplexity" in aux:
                    # accumulate mean MAIN-CE (log of per-group ppl), not
                    # mean-of-exps — exponentiated back after the scan.
                    # exp(total loss) would be wrong for objectives whose
                    # total carries aux terms (MTP, balance)
                    aux = dict(aux, perplexity=jnp.log(aux["perplexity"]))
                acc_g = jax.tree.map(lambda a, b: a + b / pp_groups, acc_g, g)
                acc_aux = jax.tree.map(
                    lambda a, b: a + b / pp_groups, acc_aux, aux
                )
                return (new_ms, acc_loss + l / pp_groups, acc_aux, acc_g), None

            aux_shape = jax.eval_shape(
                lambda p: loss_call(p, state.model_state,
                                    jax.tree.map(lambda a: a[0], gbatch),
                                    step_rng, True)[1],
                state.params,
            )
            carry0 = (
                state.model_state,
                jnp.zeros(()),
                jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), aux_shape),
                jax.tree.map(jnp.zeros_like, state.params),
            )
            (new_ms, loss, aux, grads), _ = jax.lax.scan(
                body, carry0, (jnp.arange(pp_groups), gbatch)
            )
            if "perplexity" in aux:
                # exp of the accumulated mean main-CE (see body)
                aux = dict(aux, perplexity=jnp.exp(aux["perplexity"]))
            return loss, aux, new_ms, grads

        if self.config.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"pp_schedule must be 'gpipe' or '1f1b', got "
                f"{self.config.pp_schedule!r}"
            )
        if (self.config.pp_schedule == "1f1b"
                and not self.config.pipeline_parallel):
            raise ValueError(
                "pp_schedule='1f1b' requires pipeline_parallel=True — "
                "without it the config would silently train on the plain "
                "data-parallel path"
            )
        pp_1f1b_vg = (
            self._pp_1f1b_vg_call()
            if self.config.pipeline_parallel
            and self.config.pp_schedule == "1f1b"
            else None
        )

        def train_step(state: TrainState, batch: dict):
            step_rng = jax.random.fold_in(state.rng, state.step)

            if pp_1f1b_vg is not None:
                loss, aux, new_ms, grads = pp_1f1b_vg(
                    state.params, state.model_state, batch, step_rng
                )
            elif pp_groups > 1:
                loss, aux, new_ms, grads = grouped_value_and_grad(
                    state, batch, step_rng
                )
            else:
                def loss_wrap(params):
                    loss, aux, new_ms = loss_call(
                        params, state.model_state, batch, step_rng, True
                    )
                    return loss, (aux, new_ms)

                (loss, (aux, new_ms)), grads = jax.value_and_grad(
                    loss_wrap, has_aux=True
                )(state.params)
            grad_norm = optax.global_norm(grads)
            new_state = state.apply_gradients(grads, new_ms)
            metrics = {
                "train_loss": loss,
                "grad_norm": grad_norm,
                "lr": self.schedule(state.step),
                **{f"train_{k}": v for k, v in aux.items()},
            }
            return new_state, metrics

        def eval_step(state: TrainState, batch: dict):
            loss, aux, _ = loss_call(
                state.params, state.model_state, batch, state.rng, False
            )
            return {"val_loss": loss, **{f"val_{k}": v for k, v in aux.items()}}

        if self._batch_shardings is None:
            raise RuntimeError(
                "batch shardings unknown: call init_state(example_batch) or "
                "fit() (which derives them from the first batch) before "
                "building steps"
            )
        data_sharding = self._batch_shardings
        self._train_step = jax.jit(
            train_step,
            in_shardings=(self._state_shardings, data_sharding),
            out_shardings=(self._state_shardings, replicated),
            donate_argnums=0,
        )
        self._eval_step = jax.jit(
            eval_step,
            in_shardings=(self._state_shardings, data_sharding),
            out_shardings=replicated,
        )

        if self.config.scan_steps > 1:
            def train_step_scan(state: TrainState, batches: dict):
                # batches: the per-step batch pytree with a stacked leading
                # K dim. Each scan iteration is bit-identical to one
                # _train_step call (same per-step rng fold on state.step);
                # returned metrics are the LAST step's — what a per-step
                # loop would log at the window boundary.
                new_state, ms = jax.lax.scan(train_step, state, batches)
                return new_state, jax.tree.map(lambda x: x[-1], ms)

            scan_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, P(None, *s.spec)),
                data_sharding,
            )
            self._train_step_scan = jax.jit(
                train_step_scan,
                in_shardings=(self._state_shardings, scan_shardings),
                out_shardings=(self._state_shardings, replicated),
                donate_argnums=0,
            )

    # ------------------------------------------------------------------ fit

    def fit(
        self,
        batch_iter: Iterator[dict],
        eval_iter_fn: Callable[[], Iterator[dict]] | None = None,
        writer: MetricsWriter | None = None,
        state: TrainState | None = None,
        callbacks: list[tuple[int, Callable]] | None = None,
    ) -> TrainState:
        """`callbacks`: [(every, fn(state, step))] — periodic hooks for
        qualitative eval (e.g. deepseekv3 cell 54's sample-and-save-text
        every 500 steps); exceptions propagate."""
        cfg = self.config
        # fit() already gates writes by log_every; the writer must not
        # re-filter or eval/final-step writes would be dropped
        writer = writer or ConsoleWriter()
        # flight recorder (TrainConfig.trace_path): spans for everything
        # the loop blocks on, exported in the finally below. step spans
        # fence each dispatch (see the config docstring), so goodput =
        # traced-step-time / wall is an honest utilization number.
        recorder = None
        step_span_total = 0.0
        t_fit0 = 0.0
        if cfg.trace_path:
            from solvingpapers_tpu.metrics.trace import FlightRecorder

            recorder = FlightRecorder()
            t_fit0 = recorder.clock()

        def _next(it):
            if recorder is None:
                return next(it)
            with recorder.span("data_wait", "train", "train"):
                return next(it)

        def _span(name, **kw):
            """Recorder span, or a no-op context when tracing is off —
            one `with` per instrumented section instead of a duplicated
            traced/untraced call at every site."""
            if recorder is None:
                return contextlib.nullcontext()
            return recorder.span(name, "train", "train", **kw)

        if state is None:
            first = _next(batch_iter)
            state = self.init_state(first)
        else:
            first = _next(batch_iter) if self._batch_shardings is None else None
            if first is not None:
                self._set_batch_shardings(first)
        if self._train_step is None:
            self._build_steps()

        if (cfg.xla_obs or cfg.mesh_obs) and self._registry is None:
            from solvingpapers_tpu.metrics.xla_obs import (
                CompileRegistry,
                HBMLedger,
                pytree_device_bytes,
            )

            # mesh_obs implies the compile registry (the collective
            # ledger reads compiled HLO) with per-program HLO parsing on
            self._registry = CompileRegistry(trace=recorder,
                                             collectives=cfg.mesh_obs)
            self._ledger = HBMLedger()
            # the lambdas close over the loop variable `state`, so the
            # gauges follow the live TrainState across step rebinding;
            # PER-DEVICE bytes (shard_shape), not global — capacity is a
            # per-chip number and fsdp/pipe-sharded pools must not book
            # their full global size against it
            self._ledger.register(
                "params", lambda: pytree_device_bytes(state.params)
            )
            self._ledger.register(
                "opt_state", lambda: pytree_device_bytes(state.opt_state)
            )
            self._ledger.temp_fn = self._registry.max_temp_bytes
        if cfg.mesh_obs and self._mesh_obs is None:
            from solvingpapers_tpu.metrics.mesh_obs import (
                MeshObservatory,
                PipelineScheduleInfo,
            )
            from solvingpapers_tpu.sharding import mesh_axis_sizes

            sched = None
            mcfg = getattr(self.model, "cfg", None)
            if cfg.pipeline_parallel and mcfg is not None:
                sched = PipelineScheduleInfo(
                    n_stages=mesh_axis_sizes(self.mesh).get("pipe", 1),
                    n_microbatches=getattr(mcfg, "n_microbatches", 1),
                    n_virtual=getattr(mcfg, "virtual_stages", 1),
                    schedule=cfg.pp_schedule,
                )
            self._mesh_obs = MeshObservatory(
                mesh=self.mesh, registry=self._registry, trace=recorder,
                schedule=sched,
            )
        # registry/observatory persist across fit() calls but the
        # recorder is per-run: re-attach so a resumed fit's compile and
        # mesh events land in ITS trace, not the first run's dead ring
        if self._registry is not None:
            self._registry.trace = recorder
        if self._mesh_obs is not None:
            self._mesh_obs.attach_trace(recorder)
        # observability modes fence every dispatch so step walls are
        # device-true; _obs_clock is the shared time base
        _fenced = recorder is not None or self._mesh_obs is not None
        _obs_clock = (
            recorder.clock if recorder is not None
            else self._mesh_obs.clock if self._mesh_obs is not None
            else None
        )
        # live status endpoint for the duration of fit(); last_row is
        # mutated at every log write so /metrics and /statusz always
        # serve the newest row without re-deriving device values
        last_row = {"step": int(jax.device_get(state.step)), "metrics": {}}
        if cfg.status_port is not None:
            from solvingpapers_tpu.metrics.http import StatusServer

            def _statusz() -> dict:
                d = {
                    "train": {"step": last_row["step"],
                              "steps_total": cfg.steps},
                    "metrics": last_row["metrics"],
                }
                if self._registry is not None:
                    d["compile"] = self._registry.snapshot()
                if self._ledger is not None:
                    d["mem"] = self._ledger.snapshot()
                if self._mesh_obs is not None:
                    d["mesh"] = self._mesh_obs.snapshot()
                return d

            def _metrics_fn() -> tuple[int, dict]:
                m = dict(last_row["metrics"])
                if self._registry is not None:
                    m.update(self._registry.gauges())
                    m.update(self._ledger.gauges())
                if self._mesh_obs is not None:
                    m.update(self._mesh_obs.gauges())
                return last_row["step"], m

            self._status = StatusServer(
                _statusz, _metrics_fn,
                host=cfg.status_host, port=cfg.status_port,
            )

        ckpt = None
        start_step = int(jax.device_get(state.step))
        if cfg.checkpoint_dir and cfg.ckpt_every > 0:
            ckpt = CheckpointManager(cfg.checkpoint_dir, cfg.keep_n, cfg.ckpt_every,
                                     async_saves=cfg.async_checkpointing)
            restored = ckpt.restore_latest(_pure_state(state))
            if restored is not None:
                pure, start_step = restored
                state = _apply_pure(state, pure)

        # preemption handling: SIGTERM/SIGINT request a final checkpoint at
        # the next step boundary (the auto-resume path restores it — the
        # workflow the reference performs by hand after Kaggle preemptions)
        preempted = {"flag": False}
        old_handlers = {}
        if ckpt is not None:
            import signal

            def _on_signal(signum, frame):
                preempted["flag"] = True

            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    old_handlers[sig] = signal.signal(sig, _on_signal)
                except ValueError:  # non-main thread
                    break

        profiling = False
        nan_debug_prev = None
        if cfg.debug_nans:
            nan_debug_prev = jax.config.jax_debug_nans
            jax.config.update("jax_debug_nans", True)
        t_prev = time.perf_counter()
        last_log_step = start_step
        scan_k = max(cfg.scan_steps, 1)
        if scan_k > 1:
            cadences = [("log_every", cfg.log_every),
                        ("eval_every", cfg.eval_every),
                        ("ckpt_every", cfg.ckpt_every)]
            cadences += [
                (f"callbacks[{i}].every", every)
                for i, (every, _) in enumerate(callbacks or [])
            ]
            for nm, ev in cadences:
                if ev > 0 and ev % scan_k:
                    raise ValueError(
                        f"{nm}={ev} must be a multiple of scan_steps="
                        f"{scan_k}: the host only sees window boundaries"
                    )
        profile_stopped = False
        tail_warmed = False
        excluded_steps = 0  # steps whose wall time was excluded since last log
        try:
            step = start_step
            while step < cfg.steps:
                # full scan windows on scan_k-aligned steps; single-step to
                # re-align (a checkpoint resume can start mid-window) and
                # through the ragged tail, so cfg.steps is hit exactly and
                # window ends stay multiples of scan_k (the cadence checks
                # depend on that)
                if step % scan_k or step + scan_k > cfg.steps:
                    kk = 1
                else:
                    kk = scan_k
                end = step + kk
                if preempted["flag"]:
                    ckpt.maybe_save(step, _pure_state(state), force=True)
                    writer.write(step, {"preempted": 1.0})
                    break
                # stop BEFORE the start check: when the profile window fits
                # inside one scan window, checking start first would open
                # and immediately close an empty trace in the same iteration
                if profiling and step - start_step >= cfg.profile_steps[1]:
                    jax.profiler.stop_trace()
                    profiling = False
                    profile_stopped = True
                if cfg.profile_dir and not profiling and not profile_stopped \
                        and step - start_step >= cfg.profile_steps[0]:
                    jax.profiler.start_trace(cfg.profile_dir)
                    profiling = True
                if kk == 1:
                    batch = first if (first is not None and step == start_step) \
                        else _next(batch_iter)
                    if first is not None and step == start_step:
                        first = None
                    exclude_compile = (
                        scan_k > 1 and not tail_warmed and step != start_step
                    )
                    if exclude_compile:
                        # first single-step call of a scan-windowed run (the
                        # ragged tail or a resume re-align): _train_step has
                        # not been traced yet, so fence and keep its compile
                        # out of the step timing, like eval/checkpoint
                        jax.device_get(metrics["train_loss"])
                        t_tail = time.perf_counter()
                    t_span = _obs_clock() if _fenced else 0.0
                    state, metrics = self._dispatch(
                        "train_step", self._train_step, state, batch
                    )
                    if _fenced:
                        jax.block_until_ready(metrics)
                        d_span = _obs_clock() - t_span
                        compiled = step == start_step
                        if recorder is not None:
                            recorder.complete("step", "train", "train",
                                              ts=t_span, dur=d_span, steps=1,
                                              compiled=int(compiled))
                        if not compiled:
                            # goodput's numerator counts TRAINING time;
                            # folding the first step's jit compile in
                            # would report ~1.0 on a run that spent most
                            # of its wall compiling (the wall stays in
                            # the denominator, so compile-dominated runs
                            # honestly read as low goodput)
                            step_span_total += d_span
                            if self._mesh_obs is not None:
                                self._mesh_obs.observe_step(t_span, d_span)
                    if exclude_compile:
                        jax.device_get(metrics["train_loss"])
                        t_prev += time.perf_counter() - t_tail
                        # the step's time is excluded, so drop it from the
                        # next log row's denominator too (else step_time /
                        # tokens_per_sec overstate by the excluded step)
                        excluded_steps += 1
                    tail_warmed = True
                else:
                    window = []
                    if first is not None and step == start_step:
                        window.append(first)
                        first = None
                    while len(window) < kk:
                        window.append(_next(batch_iter))
                    # device arrays (e.g. lm_batch_iterator's on-device
                    # crops) stack with jnp — np.stack would force K
                    # synchronous D2H pulls per window, catastrophic on
                    # high-latency transports; host arrays stack on host so
                    # the window ships as ONE transfer
                    batch = jax.tree.map(
                        lambda *xs: (jnp.stack(xs) if isinstance(xs[0], jax.Array)
                                     else np.stack(xs)),
                        *window,
                    )
                    t_span = _obs_clock() if _fenced else 0.0
                    state, metrics = self._dispatch(
                        "train_step_scan", self._train_step_scan, state, batch
                    )
                    if _fenced:
                        jax.block_until_ready(metrics)
                        d_span = _obs_clock() - t_span
                        compiled = step == start_step
                        if recorder is not None:
                            recorder.complete("step", "train", "train",
                                              ts=t_span, dur=d_span, steps=kk,
                                              compiled=int(compiled))
                        if not compiled:  # see the kk == 1 branch
                            step_span_total += d_span
                            if self._mesh_obs is not None:
                                self._mesh_obs.observe_step(
                                    t_span, d_span, steps=kk
                                )
                if step == start_step:
                    # fence the first step so compile time never pollutes
                    # step_time/tokens_per_sec/MFU metrics; the timed window
                    # therefore starts at the NEXT step
                    jax.device_get(metrics["train_loss"])
                    if self._mesh_obs is not None and cfg.pipeline_parallel:
                        # one-time stage probe for the bubble report,
                        # after the compile step (params live, jit warm)
                        # and before t_prev resets so its wall never
                        # leaks into step timing
                        self._probe_pipeline_stages(state, batch)
                    t_prev = time.perf_counter()
                    last_log_step = end

                run_eval = (
                    cfg.eval_every > 0 and eval_iter_fn
                    and end % cfg.eval_every == 0
                )
                run_cbs = callbacks and any(
                    every > 0 and end % every == 0 for every, _ in callbacks
                )
                if run_eval or run_cbs:
                    # fence queued async train steps BEFORE starting the
                    # excluded-time window: evaluate()/callbacks force them
                    # to completion via their data dependency on `state`,
                    # and without the fence that train time would be
                    # misattributed to eval and subtracted from the step
                    # timing (the source of impossible tokens/sec spikes on
                    # eval-aligned log rows)
                    jax.device_get(metrics["train_loss"])
                if run_eval:
                    t_eval = time.perf_counter()
                    with _span("eval", step=end):
                        val = self.evaluate(state, eval_iter_fn())
                    writer.write(end, {k: float(v) for k, v in val.items()})
                    t_prev += time.perf_counter() - t_eval  # keep eval out of step timing

                if run_cbs:
                    t_cb = time.perf_counter()
                    for every, fn in callbacks:
                        if every > 0 and end % every == 0:
                            with _span("callback", step=end):
                                fn(state, end)
                    t_prev += time.perf_counter() - t_cb

                if end % max(cfg.log_every, 1) == 0 or end == cfg.steps:
                    metrics = jax.device_get(metrics)  # blocks; also fences timing
                    if step == start_step:
                        # the compile step is excluded from the timed window;
                        # report its metrics without timing-derived fields
                        pass
                    else:
                        now = time.perf_counter()
                        dt = (now - t_prev) / max(
                            end - last_log_step - excluded_steps, 1
                        )
                        t_prev = now
                        last_log_step = end
                        excluded_steps = 0
                        metrics["step_time_s"] = dt
                        if cfg.tokens_per_step:
                            metrics["tokens_per_sec"] = cfg.tokens_per_step / dt
                            metrics["tokens"] = end * cfg.tokens_per_step
                            if cfg.flops_per_token:
                                from solvingpapers_tpu.metrics.mfu import chip_peak_flops

                                n_chips = self.mesh.devices.size
                                peak = chip_peak_flops() * n_chips
                                # NaN-safe: unknown chips have no peak
                                # table entry — omit the gauge rather
                                # than log a mis-scaled utilization
                                if math.isfinite(peak):
                                    metrics["mfu"] = (
                                        metrics["tokens_per_sec"]
                                        * cfg.flops_per_token / peak
                                    )
                    row = {k: float(v) for k, v in metrics.items()}
                    if self._registry is not None:
                        row.update(self._registry.gauges())
                        row.update(self._ledger.gauges())
                        self._ledger.check()
                    if self._mesh_obs is not None:
                        row.update(self._mesh_obs.gauges())
                    last_row["step"] = end
                    last_row["metrics"] = row
                    writer.write(end, row)

                if ckpt is not None and ckpt.save_every > 0 \
                        and end % ckpt.save_every == 0:
                    # keep the save (fence + D2H snapshot; the disk write is
                    # already async) out of step timing, like eval/callbacks
                    jax.device_get(metrics["train_loss"])
                    t_save = time.perf_counter()
                    with _span("checkpoint", step=end):
                        ckpt.maybe_save(end, _pure_state(state))
                    t_prev += time.perf_counter() - t_save
                step = end

            # unconditional: maybe_save dedupes existing steps, and a signal
            # landing during the final iteration must not lose the run
            if ckpt is not None:
                final_step = int(jax.device_get(state.step))
                ckpt.maybe_save(final_step, _pure_state(state), force=True)
        finally:
            if self._status is not None:
                self._status.close()
                self._status = None
            if profiling:
                jax.profiler.stop_trace()
            if nan_debug_prev is not None:
                jax.config.update("jax_debug_nans", nan_debug_prev)
            if ckpt is not None:
                ckpt.close()
            if old_handlers:
                import signal

                for sig, h in old_handlers.items():
                    signal.signal(sig, h)
            if recorder is not None:
                # goodput = fenced step time / fit wall: the fraction of
                # the run spent training vs data waits / eval / ckpt /
                # host bookkeeping. Export lives in the finally so a
                # crashed run still leaves its trace for the post-mortem.
                wall = recorder.clock() - t_fit0
                goodput = step_span_total / wall if wall > 0 else 0.0
                recorder.instant(
                    "goodput", "train", "train", goodput=round(goodput, 4),
                    step_s=round(step_span_total, 4), wall_s=round(wall, 4),
                )
                recorder.export_chrome(cfg.trace_path)
                writer.write(step, {"goodput": goodput})
        return state

    def _probe_pipeline_stages(self, state, batch) -> None:
        """One-time mesh-observatory stage probe (TrainConfig.mesh_obs +
        pipeline_parallel): run each stage_fn standalone on one
        microbatch-shaped activation, forward plus grad-of-recompute
        (the 1F1B unit-cost shape; a fair proxy for the GPipe backward
        too), and hand the per-stage seconds to the observatory — the
        bubble report then compares them against every later fenced step
        wall. Diagnosis must never kill training: any failure degrades
        to a warning and the report stays absent."""
        import warnings

        obs = self._mesh_obs
        mcfg = getattr(self.model, "cfg", None)
        probe_hook = getattr(self.model, "stage_probe_fn", None)
        params = state.params if isinstance(state.params, dict) else {}
        stages = params.get("stages")
        if obs is None or mcfg is None or stages is None:
            return
        if probe_hook is None:
            # explicit, not silent: the diagnosis needs a standalone
            # per-stage callable and this model does not provide one
            # (GPTPipe/LlamaPipe do; DSV3Pipe's stage_fn is entangled
            # with the routing-bias stack and axis_index)
            warnings.warn(
                f"mesh_obs: {type(self.model).__name__} has no "
                "stage_probe_fn — pipeline bubble diagnosis skipped "
                "(collective ledger and stage trace tracks still run)",
                stacklevel=2,
            )
            return
        try:
            from solvingpapers_tpu.metrics.mesh_obs import probe_stage_costs
            from solvingpapers_tpu.sharding import mesh_axis_sizes

            sizes = mesh_axis_sizes(self.mesh)
            x_leaf = batch["x"] if isinstance(batch, dict) \
                else jax.tree_util.tree_leaves(batch)[0]
            seq = int(x_leaf.shape[-1])
            n_micro = int(getattr(mcfg, "n_microbatches", 1))
            local_b = self.config.batch_size // max(
                sizes.get("data", 1) * sizes.get("fsdp", 1), 1
            )
            mb = max(local_b // n_micro, 1)
            x = jnp.zeros(
                (mb, seq, int(mcfg.dim)),
                getattr(mcfg, "compute_dtype", jnp.float32),
            )
            stage_s = probe_stage_costs(
                stages, x, probe_hook(mb, seq), train=True,
            )
            obs.set_stage_probe(stage_s, n_micro)
        except Exception as e:  # noqa: BLE001 — observability, not training
            warnings.warn(f"mesh_obs stage probe failed: {e}", stacklevel=2)

    def evaluate(self, state: TrainState, eval_iter: Iterator[dict]) -> dict:
        if self._eval_step is None:
            if self._batch_shardings is None:
                import itertools

                eval_iter = iter(eval_iter)
                first = next(eval_iter)
                self._set_batch_shardings(first)
                eval_iter = itertools.chain([first], eval_iter)
            self._build_steps()
        acc: dict[str, float] = {}
        n = 0
        for i, batch in enumerate(eval_iter):
            if i >= self.config.eval_batches:
                break
            m = jax.device_get(
                self._dispatch("eval_step", self._eval_step, state, batch)
            )
            for k, v in m.items():
                acc[k] = acc.get(k, 0.0) + float(v)
            n += 1
        return {k: v / max(n, 1) for k, v in acc.items()}


# ---------------------------------------------------------------- checkpoint IO


def _pure_state(state: TrainState) -> dict:
    """Strip static fields so Orbax only sees serializable arrays."""
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "rng": jax.random.key_data(state.rng),
        "model_state": state.model_state,
    }


def _apply_pure(state: TrainState, pure: dict) -> TrainState:
    return state.replace(
        step=pure["step"],
        params=pure["params"],
        opt_state=pure["opt_state"],
        # wrap with the template's impl (rbg key data is (4,) uint32,
        # threefry (2,)); the default impl would reject mismatched shapes
        rng=jax.random.wrap_key_data(
            pure["rng"], impl=jax.random.key_impl(state.rng)
        ),
        model_state=pure["model_state"],
    )
