"""Mesh-sharded flash attention.

A pallas_call is opaque to GSPMD: under pjit its operands get all-gathered
instead of partitioned. This wrapper runs the kernel inside `shard_map`
with batch sharded over (data, fsdp) and heads over the TP axis — attention
is embarrassingly parallel across both, so no collectives are needed inside
(context parallelism is sharding/ring_attention.py's job).

GQA constraint under TP: kv heads must divide evenly over the model axis
(each device needs its query heads' kv group locally).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from solvingpapers_tpu.kernels.flash_attention import flash_attention


def sharded_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: float | None = None,
    dropout_rate: float = 0.0,
    dropout_seed: jax.Array | int = 0,
    interpret: bool | None = None,
) -> jax.Array:
    """flash_attention with BSNH operands partitioned over `mesh`:
    batch over ('data','fsdp'), heads over 'model'. Seq stays unsharded
    (use ring_attention for context parallelism).

    MQA (n_kv == 1, e.g. absorbed-query MLA where k = v = latents) keeps
    its single kv head replicated over the model axis while q heads shard:
    the kernel's local q->kv head map (h * n_kv_local // n_heads_local)
    then resolves every local q head to kv head 0, which is correct."""
    b, n_heads, n_kv = q.shape[0], q.shape[2], k.shape[2]
    tp = mesh.shape.get("model", 1)
    dp = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
    if b % dp:
        raise ValueError(
            f"batch {b} must be divisible by the data x fsdp axes ({dp})"
        )
    if n_heads % tp or (n_kv % tp and n_kv != 1):
        raise ValueError(
            f"heads ({n_heads} q / {n_kv} kv) must divide the model axis "
            f"({tp}); only n_kv == 1 (MQA/MLA) may stay replicated"
        )

    spec = P(("data", "fsdp"), None, "model", None)
    kv_spec = spec if n_kv % tp == 0 else P(("data", "fsdp"), None, None, None)
    seed = jax.numpy.asarray(dropout_seed, jax.numpy.int32)

    def local(q, k, v, seed):
        # deterministic per-device stream: the kernel seeds its PRNG with
        # seed + block_uid (uid range ~ local_bn * n_qblocks * n_kblocks),
        # so small per-device offsets would just shift overlapping streams.
        # A Knuth multiplicative stride pushes devices far apart in seed
        # space (wraps mod 2^32 — collision needs a uid range beyond that).
        flat_idx = (
            jax.lax.axis_index("data") * mesh.shape.get("fsdp", 1)
            + jax.lax.axis_index("fsdp")
        ) * mesh.shape.get("model", 1) + jax.lax.axis_index("model")
        return flash_attention(
            q, k, v, causal=causal, scale=scale,
            dropout_rate=dropout_rate,
            dropout_seed=seed + flat_idx * jax.numpy.int32(-1640531527),
            interpret=interpret,
        )

    # check_vma=False: pallas_call's out_shape carries no varying-axes
    # metadata, which the vma checker (jax 0.9) rejects; the computation is
    # embarrassingly parallel over every sharded axis so the check adds
    # nothing here
    return jax.shard_map(
        local, mesh=mesh, in_specs=(spec, kv_spec, kv_spec, P()),
        out_specs=spec, check_vma=False,
    )(q, k, v, seed)
