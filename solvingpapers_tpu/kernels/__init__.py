"""Pallas TPU kernels (the framework's native-code surface).

The reference has zero custom kernels (SURVEY.md §0: no C++/CUDA at all);
these are new TPU-first implementations of the hot ops: blockwise flash
attention (causal + bidirectional, GQA) and MoE dispatch. Each kernel has a
pure-jnp reference in ops/ and interpret-mode equality tests.
"""

from solvingpapers_tpu.kernels.flash_attention import flash_attention
from solvingpapers_tpu.kernels.sharded_flash import sharded_flash_attention
