"""Blockwise flash attention for TPU (Pallas/Mosaic).

New TPU-native code — the reference computes dense (S, S) score matrices in
every notebook (e.g. gpt/gpt-jax.ipynb cell 9, LLaMA-jax.ipynb cell 24) and
has no custom kernels to port (SURVEY.md §0). This kernel family provides:

  * forward: online-softmax blockwise attention, causal or bidirectional,
    never materializing the (S, S) score matrix in HBM
  * GQA/MQA without materializing repeated KV heads (the kv block index map
    folds the q-head -> kv-head mapping, replacing ops.repeat_kv)
  * backward: custom VJP with separate dq and dk/dv kernels recomputing
    probabilities from the saved log-sum-exp (FlashAttention-2 style)
  * in-kernel attention-prob dropout: masks generated from
    (seed, block id) by the TPU PRNG and regenerated identically in the
    backward kernels — no (S, S) mask tensor ever exists (validated by the
    linearity identity in tests/test_flash_dropout_tpu.py; interpret-mode
    prng is a zero stub, so dropout tests are hardware-gated)

Numerics reference: ops.dot_product_attention (tests/test_flash_attention.py
asserts forward and gradient equality in interpret mode).

Layout: public API is BSNH (batch, seq, heads, head_dim) to match ops/;
kernels run on (batch*heads, seq, head_dim). The grid is 3-D — (batch*heads,
q-blocks, kv-blocks) with the kv axis 'arbitrary' (sequential) and the
online-softmax state carried in VMEM scratch — so VMEM holds only
O(block_q x block_k) tiles regardless of sequence length. (The earlier 2-D
formulation kept full-length K/V rows in VMEM and hit the 16 MB scoped-vmem
ceiling at seq 16k; this one trains 350M at 16k on a single v5e chip —
32k+ is HBM-bound there and is the job of context parallelism, see
BENCHMARKS.md.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BIG_NEG = -2.0**30
# 512 measured best on v5e for the 350M study at seq <= 4k
# (tools/scale_350m.py sweep: 128->35.9% MFU, 256->48.2%, 512->52.2%, 1024
# q-blocks regress); _pick_block still shrinks to fit shorter sequences.
DEFAULT_BLOCK = 512
# At LONG sequence the trade flips (tools/sweep_flash_bwd.py, v5e, 16k:
# 1024/1024 beats 512/512 by 2.1x fwd / 1.56x fwd+bwd on the MLA shape and
# 2.0x / 1.53x on GQA — more kv reuse per q tile, fewer grid steps), so
# callers that didn't override blocks get 1024 once the sequence clears
# this bound (VERDICT r4 ask 8: the 16k-MFU backward sweep).
LONG_SEQ = 8192
LONG_SEQ_BLOCK = 1024

_SEMANTICS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary")
)


def _dropout_keep(shape, seed_val, block_uid, rate):
    """Regenerable dropout keep-mask for one (q-block, k-block) score tile.

    Seeded by (seed, flat block id) so the forward and both backward kernels
    reproduce the identical mask regardless of their loop order. Returns a
    bool keep array; caller scales kept probs by 1/(1-rate).
    """
    pltpu.prng_seed(seed_val + block_uid)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    threshold = jnp.uint32(min(int((1.0 - rate) * 4294967296.0), 4294967295))
    return bits < threshold


def is_tpu_backend() -> bool:
    """True on real TPU hardware (incl. tunnelled platforms like 'axon'
    whose device_kind names a TPU) — where the Mosaic kernel and its
    hardware PRNG run; False on the CPU test platform / other backends."""
    dev = jax.devices()[0]
    return dev.platform == "tpu" or "TPU" in str(getattr(dev, "device_kind", ""))


def auto_block(seq: int, requested: int | None) -> int:
    """Resolve a caller's block request: None = seq-adaptive auto
    (LONG_SEQ_BLOCK past LONG_SEQ, DEFAULT_BLOCK below — the measured
    crossover, see the constants above); an explicit int is honored.
    Shared by flash_attention and the ring-flash per-chunk core so long
    CP shards get the long-sequence tile too."""
    if requested is not None:
        if requested <= 0:
            raise ValueError(f"block size must be positive, got {requested}")
        return requested
    return LONG_SEQ_BLOCK if seq >= LONG_SEQ else DEFAULT_BLOCK


def _pick_block(seq: int, requested: int) -> int:
    block = min(requested, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


def _pick_block_q(seq: int, requested: int) -> int:
    """Q-side block: the lse output's block is (1, 1, block_q), and Mosaic
    requires its last dim be 128-divisible OR equal to the array dim. Seqs
    with no >=128 power-of-2 divisor (e.g. a ragged 2016-token prefill
    chunk) run as ONE q block (equal-to-array is always legal); VMEM bounds
    that fallback, so past 4096 the caller must pad/truncate to a multiple
    of 128 instead."""
    block = _pick_block(seq, requested)
    if block % 128 and block != seq:
        if seq > 4096:
            raise ValueError(
                f"seq_q {seq} has no 128-divisible block and is too long "
                "for a single q block; pad or truncate the q sequence to a "
                "multiple of 128"
            )
        return seq
    return block


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying `like`'s varying-axes metadata, so the
    pallas_calls here are usable directly inside shard_map under the vma
    checker (jax 0.9) — e.g. as the per-chunk core of ring attention."""
    vma = getattr(jax.typeof(like), "vma", None)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _uid(i, j, kb, num_j, num_kb):
    """Flat (q-block, kv-block) id shared by fwd and both bwd kernels so
    dropout masks regenerate identically: (i*num_j + j)*num_kb + kb."""
    return (i * num_j + j) * num_kb + kb


def _live(jb, kb, block_q, block_k, offset, causal):
    """Whether q-block jb sees any of kv-block kb under the causal mask —
    one definition shared by fwd/dq/dkv so they can never disagree about
    which blocks contribute (the dropout-uid lesson, applied to liveness)."""
    if not causal:
        return True
    return kb * block_k <= (jb + 1) * block_q - 1 + offset


def _last_live_kb(jb, block_q, block_k, offset):
    """Largest kv block _live for q-block jb (the same diagonal as _live,
    solved for kb), floored at 0: with seq_q > seq_k the first q rows see
    no kv at all and an unfloored clamp would index before the array."""
    return jnp.maximum(((jb + 1) * block_q - 1 + offset) // block_k, 0)


def _first_live_jb(kb, block_q, block_k, offset):
    """Smallest q block _live for kv-block kb (_live solved for jb)."""
    return jnp.maximum(kb * block_k - offset, 0) // block_q


# --------------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, offset,
                dropout_rate, num_qb, num_kb):
    # q_ref: (1, block_q, D) resident across the kv sweep; k_ref/v_ref:
    # (1, block_k, D) for this kv step. `offset` end-aligns the causal mask
    # when seq_q != seq_k (ops.attention.causal_mask semantics: query i
    # attends to kv positions <= i + (seq_k - seq_q)).
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, BIG_NEG, m_scr.dtype)
        l_scr[...] = jnp.zeros(l_scr.shape, l_scr.dtype)
        acc_scr[...] = jnp.zeros(acc_scr.shape, acc_scr.dtype)

    live = _live(j, kb, block_q, block_k, offset, causal)

    @pl.when(live)
    def _step():
        q = q_ref[0, :, :].astype(jnp.float32) * scale
        k_blk = k_ref[0, :, :].astype(jnp.float32)
        v_blk = v_ref[0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows + offset, s, BIG_NEG)
        m_i, l_i, acc = m_scr[...], l_scr[...], acc_scr[...]
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1, keepdims=True))
        if causal and offset < 0:
            # seq_q > seq_k end-aligned causal only (e.g. a single-q-block
            # fallback): rows with r + offset < 0 see NO key in any block,
            # so m_new == BIG_NEG and exp(s - m_new) would be 1, crediting
            # unit mass to invisible keys. Zero masked entries so those rows
            # keep l == 0 and hit the empty-row guard at _finish. With
            # offset >= 0 every row is valid in kv block 0, after which
            # exp(BIG_NEG - m_new) underflows to 0 on its own — keep the
            # select off the seq_q == seq_k training hot path.
            p = jnp.where(s <= BIG_NEG * 0.5, 0.0, jnp.exp(s - m_new))
        else:
            p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_i - m_new)
        # l accumulates the UNdropped mass (the softmax denominator);
        # dropout applies to the normalized probs, i.e. to acc only
        l_new = alpha * l_i + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                p.shape, seed_ref[0], _uid(i, j, kb, num_qb, num_kb),
                dropout_rate,
            )
            p_use = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        else:
            p_use = p
        acc_scr[...] = acc * alpha + jax.lax.dot_general(
            p_use, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new
        l_scr[...] = l_new

    @pl.when(kb == num_kb - 1)
    def _finish():
        # Rows that saw no kv (causal with seq_q > seq_k under the
        # end-aligned mask) have l == 0: emit o = 0 instead of 0/0 = NaN so a
        # caller summing over all rows isn't gradient-poisoned, and lse = 0
        # (not m = BIG_NEG) so the backward's exp(s - lse) = exp(BIG_NEG)
        # underflows to 0 for those rows instead of exp(0) = 1.
        l_i = l_scr[...]
        empty = l_i <= 0.0
        safe_l = jnp.where(empty, 1.0, l_i)
        o_ref[0, :, :] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0, :] = jnp.where(
            empty, 0.0, m_scr[...] + jnp.log(safe_l)
        )[:, 0]


def _fwd(q3, k3, v3, seed, n_heads, n_kv, scale, causal, block_q, block_k,
         dropout_rate, interpret):
    """q3: (B*N, S, D); k3/v3: (B*Nkv, Skv, D). Returns (o, lse)."""
    bn, seq_q, d = q3.shape
    seq_k = k3.shape[1]
    group = n_heads // n_kv
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, offset=seq_k - seq_q,
        dropout_rate=dropout_rate, num_qb=num_qb, num_kb=num_kb,
    )
    offset = seq_k - seq_q

    def kv_index(i, j, kb):
        # flattened q index i = b*n_heads + h -> kv index b*n_kv + h//group,
        # which is exactly i // group since group | n_heads. For causal,
        # clamp dead past-diagonal steps to the last live kv block — the
        # block index then repeats, so Mosaic elides the DMA that pl.when
        # in the kernel would otherwise fetch-and-ignore (~2x bandwidth on
        # the causal sweep).
        if causal:
            kb = jnp.minimum(kb, _last_live_kb(j, block_q, block_k, offset))
        return (i // group, kb, 0)

    return pl.pallas_call(
        kernel,
        grid=(bn, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            _sds((bn, seq_q, d), q3.dtype, q3),
            _sds((bn, 1, seq_q), jnp.float32, q3),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(q3, k3, v3, seed)


# -------------------------------------------------------------------- backward


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                   dq_ref, dq_scr, *, scale, causal, offset, dropout_rate,
                   num_qb, num_kb):
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    i = pl.program_id(0)
    j = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, dq_scr.dtype)

    live = _live(j, kb, block_q, block_k, offset, causal)

    @pl.when(live)
    def _step():
        q = q_ref[0, :, :].astype(jnp.float32) * scale
        do = do_ref[0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        k_blk = k_ref[0, :, :].astype(jnp.float32)
        v_blk = v_ref[0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows + offset, s, BIG_NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                p.shape, seed_ref[0], _uid(i, j, kb, num_qb, num_kb),
                dropout_rate,
            )
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta)
        dq_scr[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(kb == num_kb - 1)
    def _finish():
        dq_ref[0, :, :] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, offset,
                    dropout_rate, num_qb, num_kb):
    # grid is (bn, kv-blocks, q-blocks): the q axis is the sequential carry
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    i = pl.program_id(0)
    kb = pl.program_id(1)
    jb = pl.program_id(2)

    @pl.when(jb == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, dk_scr.dtype)
        dv_scr[...] = jnp.zeros(dv_scr.shape, dv_scr.dtype)

    live = _live(jb, kb, block_q, block_k, offset, causal)

    @pl.when(live)
    def _step():
        q = q_ref[0, :, :].astype(jnp.float32) * scale
        do = do_ref[0, :, :].astype(jnp.float32)
        lse = lse_ref[0, 0, :][:, None]
        delta = delta_ref[0, 0, :][:, None]
        k_blk = k_ref[0, :, :].astype(jnp.float32)
        v_blk = v_ref[0, :, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            rows = jb * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows + offset, s, BIG_NEG)
        p = jnp.exp(s - lse)  # (bq, bk)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            keep = _dropout_keep(
                p.shape, seed_ref[0], _uid(i, jb, kb, num_qb, num_kb),
                dropout_rate,
            )
            p_v = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_v = p
        dv_scr[...] += jax.lax.dot_general(
            p_v, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        # q was pre-scaled, so ds^T @ q_scaled already carries softmax scale
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(jb == num_qb - 1)
    def _finish():
        dk_ref[0, :, :] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, :, :] = dv_scr[...].astype(dv_ref.dtype)


# ------------------------------------------------------------------ public API


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9)
)
def _flash(q3, k3, v3, seed, heads, scale, causal, blocks, dropout_rate,
           interpret):
    o, _ = _fwd(q3, k3, v3, seed, heads[0], heads[1], scale, causal,
                blocks[0], blocks[1], dropout_rate, interpret)
    return o


def _flash_fwd(q3, k3, v3, seed, heads, scale, causal, blocks, dropout_rate,
               interpret):
    o, lse = _fwd(q3, k3, v3, seed, heads[0], heads[1], scale, causal,
                  blocks[0], blocks[1], dropout_rate, interpret)
    return o, (q3, k3, v3, seed, o, lse)


def _flash_bwd(heads, scale, causal, blocks, dropout_rate, interpret, res, do):
    q3, k3, v3, seed, o, lse = res
    n_heads, n_kv = heads
    bn, seq_q, d = q3.shape
    seq_k = k3.shape[1]
    group = n_heads // n_kv

    if group > 1:  # materialize repeated kv for the backward pass
        bkv = k3.shape[0]
        rep = lambda x: jnp.repeat(  # noqa: E731
            x.reshape(bkv // n_kv, n_kv, seq_k, d), group, axis=1
        ).reshape(bn, seq_k, d)
        k3r, v3r = rep(k3), rep(v3)
    else:
        k3r, v3r = k3, v3

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[:, None, :]

    dq, dk_r, dv_r = _bwd_chunk(
        q3, k3r, v3r, do, lse, delta, seed, scale=scale, causal=causal,
        block_q=blocks[0], block_k=blocks[1], dropout_rate=dropout_rate,
        interpret=interpret,
    )

    if group > 1:  # reduce repeated-head grads back to kv heads
        b = bn // n_heads
        fold = lambda x: x.reshape(b, n_kv, group, seq_k, d).sum(axis=2).reshape(  # noqa: E731
            b * n_kv, seq_k, d
        )
        dk_r, dv_r = fold(dk_r), fold(dv_r)
    # seed is integer-typed: no cotangent
    return dq, dk_r.astype(k3.dtype), dv_r.astype(v3.dtype), None


def _bwd_chunk(q3, k3r, v3r, do, lse, delta, seed, *, scale, causal,
               block_q, block_k, dropout_rate, interpret):
    """dq/dk/dv pallas sweeps for one (q, kv) pair with kv already repeated
    to q heads. Shared by the full backward above and the ring-flash
    backward (sharding/ring_attention.py), which runs it once per rotating
    kv chunk with the GLOBAL lse/delta."""
    bn, seq_q, d = q3.shape
    seq_k = k3r.shape[1]
    num_qb = seq_q // block_q
    num_kb = seq_k // block_k
    offset = seq_k - seq_q

    def kv_index_rep(i, j, kb):
        # clamp dead causal steps to the last live kv block (repeated block
        # index -> Mosaic skips the DMA); kv here is pre-repeated per q-head
        if causal:
            kb = jnp.minimum(kb, _last_live_kb(j, block_q, block_k, offset))
        return (i, kb, 0)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          offset=offset, dropout_rate=dropout_rate,
                          num_qb=num_qb, num_kb=num_kb),
        grid=(bn, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), kv_index_rep),
            pl.BlockSpec((1, block_k, d), kv_index_rep),
            pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kb: (i, j, 0)),
        out_shape=_sds(q3.shape, q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(q3, k3r, v3r, do, lse, delta, seed)

    def q_index(i, kb, jb):
        # mirror clamp for the dkv sweep: q blocks before the diagonal are
        # dead — pin them to the first live q block so the DMA is elided
        if causal:
            jb = jnp.maximum(jb, _first_live_jb(kb, block_q, block_k, offset))
        return (i, jb, 0)

    def q_row_index(i, kb, jb):
        if causal:
            jb = jnp.maximum(jb, _first_live_jb(kb, block_q, block_k, offset))
        return (i, 0, jb)

    dk_r, dv_r = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          offset=offset, dropout_rate=dropout_rate,
                          num_qb=num_qb, num_kb=num_kb),
        grid=(bn, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), lambda i, kb, jb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, jb: (i, kb, 0)),
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, 1, block_q), q_row_index),
            pl.BlockSpec((1, 1, block_q), q_row_index),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, kb, jb: (i, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, kb, jb: (i, kb, 0)),
        ],
        out_shape=[
            _sds((bn, seq_k, d), k3r.dtype, k3r),
            _sds((bn, seq_k, d), v3r.dtype, k3r),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=interpret,
    )(q3, k3r, v3r, do, lse, delta, seed)

    return dq, dk_r, dv_r


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    dropout_rate: float = 0.0,
    dropout_seed: jax.Array | int = 0,
    interpret: bool | None = None,
) -> jax.Array:
    """Flash attention over BSNH tensors (drop-in for ops.dot_product_attention
    when there is no cache/explicit mask).

    q: (B, Sq, N, D); k, v: (B, Skv, Nkv, D) with N % Nkv == 0.
    dropout_rate > 0 applies attention-prob dropout INSIDE the kernel
    (masks regenerated from (dropout_seed, block id) in the backward — no
    (S, S) mask tensor ever exists); same Bernoulli semantics as the dense
    reference, different random stream.
    """
    b, seq_q, n_heads, d = q.shape
    seq_k, n_kv = k.shape[1], k.shape[2]
    if n_heads % n_kv:
        raise ValueError(f"q heads {n_heads} not a multiple of kv heads {n_kv}")
    if interpret is None:
        # interpret only on CPU (the test platform), so use_flash configs
        # are testable there; any other non-TPU backend still fails loudly
        # at Mosaic lowering rather than silently crawling through the
        # interpreter
        interpret = jax.devices()[0].platform == "cpu"
    if interpret and dropout_rate > 0.0:
        raise ValueError(
            "in-kernel dropout requires the hardware PRNG: interpret-mode "
            "pltpu.prng_random_bits is a zero stub, which would silently "
            "keep every element scaled by 1/(1-rate)"
        )
    if scale is None:
        scale = d**-0.5
    block_q = _pick_block_q(seq_q, auto_block(seq_q, block_q))
    block_k = _pick_block(seq_k, auto_block(seq_k, block_k))

    q3 = q.transpose(0, 2, 1, 3).reshape(b * n_heads, seq_q, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * n_kv, seq_k, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * n_kv, seq_k, d)
    seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    o3 = _flash(
        q3, k3, v3, seed, (n_heads, n_kv), float(scale), bool(causal),
        (block_q, block_k), float(dropout_rate), interpret,
    )
    return o3.reshape(b, n_heads, seq_q, d).transpose(0, 2, 1, 3)
