"""Blockwise flash attention for TPU (Pallas/Mosaic).

New TPU-native code — the reference computes dense (S, S) score matrices in
every notebook (e.g. gpt/gpt-jax.ipynb cell 9, LLaMA-jax.ipynb cell 24) and
has no custom kernels to port (SURVEY.md §0). This kernel family provides:

  * forward: online-softmax blockwise attention, causal or bidirectional,
    never materializing the (S, S) score matrix in HBM
  * GQA/MQA without materializing repeated KV heads (the kv block index map
    folds the q-head -> kv-head mapping, replacing ops.repeat_kv)
  * backward: custom VJP with separate dq and dk/dv kernels recomputing
    probabilities from the saved log-sum-exp (FlashAttention-2 style)
  * in-kernel attention-prob dropout: masks generated from
    (seed, block id) by the TPU PRNG and regenerated identically in the
    backward kernels — no (S, S) mask tensor ever exists (validated by the
    linearity identity in tests/test_flash_dropout_tpu.py; interpret-mode
    prng is a zero stub, so dropout tests are hardware-gated)

Numerics reference: ops.dot_product_attention (tests/test_flash_attention.py
asserts forward and gradient equality in interpret mode).

Layout: public API is BSNH (batch, seq, heads, head_dim) to match ops/;
kernels run on (batch*heads, seq, head_dim) with seq tiled by 128-aligned
blocks for the MXU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BIG_NEG = -2.0**30
# 512 measured best on v5e for the 350M study (tools/scale_350m.py sweep:
# 128->35.9% MFU, 256->48.2%, 512->52.2%, 1024 q-blocks regress); _pick_block
# still shrinks to fit shorter sequences.
DEFAULT_BLOCK = 512


def _dropout_keep(shape, seed_val, block_uid, rate):
    """Regenerable dropout keep-mask for one (q-block, k-block) score tile.

    Seeded by (seed, flat block id) so the forward and both backward kernels
    reproduce the identical mask regardless of their loop order. Returns a
    bool keep array; caller scales kept probs by 1/(1-rate).
    """
    pltpu.prng_seed(seed_val + block_uid)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    threshold = jnp.uint32(min(int((1.0 - rate) * 4294967296.0), 4294967295))
    return bits < threshold


def _pick_block(seq: int, requested: int) -> int:
    block = min(requested, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


# --------------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, seed_ref, o_ref, lse_ref, *, scale,
                causal, block_k, offset, dropout_rate, num_kb_total):
    # q_ref: (1, block_q, D); k_ref/v_ref: (1, S, D). `offset` end-aligns the
    # causal mask when seq_q != seq_k (ops.attention.causal_mask semantics:
    # query i attends to kv positions <= i + (seq_k - seq_q)).
    block_q = q_ref.shape[1]
    seq_k = k_ref.shape[1]
    d = q_ref.shape[2]
    j = pl.program_id(1)

    q = q_ref[0, :, :].astype(jnp.float32) * scale
    num_kb = seq_k // block_k
    if causal:
        hi = jnp.minimum(num_kb, pl.cdiv((j + 1) * block_q + offset, block_k))
    else:
        hi = num_kb
    # loop-invariant; also, pl.program_id inside a fori_loop body does not
    # lower in interpret mode
    prog_i = pl.program_id(0)
    num_j = pl.num_programs(1)

    def body(kb, carry):
        m_i, l_i, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows + offset, s, BIG_NEG)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_i - m_new)
        # l accumulates the UNdropped mass (the softmax denominator);
        # dropout applies to the normalized probs, i.e. to acc only
        l_new = alpha * l_i + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            uid = (prog_i * num_j + j) * num_kb_total + kb
            keep = _dropout_keep(p.shape, seed_ref[0], uid, dropout_rate)
            p_use = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        else:
            p_use = p
        acc = acc * alpha + jax.lax.dot_general(
            p_use, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), BIG_NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m_i, l_i, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))

    o_ref[0, :, :] = (acc / l_i).astype(o_ref.dtype)
    lse_ref[0, 0, :] = (m_i + jnp.log(l_i))[:, 0]


def _fwd(q3, k3, v3, seed, n_heads, n_kv, scale, causal, block_q, block_k,
         dropout_rate, interpret):
    """q3: (B*N, S, D); k3/v3: (B*Nkv, Skv, D). Returns (o, lse)."""
    bn, seq_q, d = q3.shape
    seq_k = k3.shape[1]
    group = n_heads // n_kv

    def kv_index(i, j):
        # flattened q index i = b*n_heads + h -> kv index b*n_kv + h//group,
        # which is exactly i // group since group divides n_heads
        return i // group

    grid = (bn, seq_q // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_k=block_k,
        offset=seq_k - seq_q, dropout_rate=dropout_rate,
        num_kb_total=seq_k // block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq_k, d), lambda i, j: (kv_index(i, j), 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda i, j: (kv_index(i, j), 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, seq_q, d), q3.dtype),
            jax.ShapeDtypeStruct((bn, 1, seq_q), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, seed)


# -------------------------------------------------------------------- backward


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                   dq_ref, *, scale, causal, block_k, offset, dropout_rate,
                   num_kb_total):
    block_q = q_ref.shape[1]
    seq_k = k_ref.shape[1]
    j = pl.program_id(1)

    q = q_ref[0, :, :].astype(jnp.float32) * scale
    do = do_ref[0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :][:, None]
    delta = delta_ref[0, 0, :][:, None]
    num_kb = seq_k // block_k
    hi = (
        jnp.minimum(num_kb, pl.cdiv((j + 1) * block_q + offset, block_k))
        if causal
        else num_kb
    )
    prog_i = pl.program_id(0)
    num_j = pl.num_programs(1)

    def body(kb, dq):
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            rows = j * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0
            )
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows + offset, s, BIG_NEG)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            uid = (prog_i * num_j + j) * num_kb_total + kb
            keep = _dropout_keep(p.shape, seed_ref[0], uid, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(
        0, hi, body, jnp.zeros((block_q, q_ref.shape[2]), jnp.float32)
    )
    dq_ref[0, :, :] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, seed_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, offset,
                    dropout_rate, num_kb_total):
    block_k = k_ref.shape[1]
    seq_q = q_ref.shape[1]
    kb = pl.program_id(1)
    d = q_ref.shape[2]

    k_blk = k_ref[0, :, :].astype(jnp.float32)
    v_blk = v_ref[0, :, :].astype(jnp.float32)
    num_qb = seq_q // block_q
    prog_i = pl.program_id(0)
    # first q block whose last row (jb+1)*bq - 1 + offset can reach col kb*bk
    lo = jnp.maximum(kb * block_k - offset, 0) // block_q if causal else 0

    def body(jb, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(jb * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(jb * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(jb * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(jb * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            rows = jb * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(cols <= rows + offset, s, BIG_NEG)
        p = jnp.exp(s - lse)  # (bq, bk)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if dropout_rate > 0.0:
            uid = (prog_i * num_qb + jb) * num_kb_total + kb
            keep = _dropout_keep(p.shape, seed_ref[0], uid, dropout_rate)
            p_v = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_v = p
        dv = dv + jax.lax.dot_general(
            p_v, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, num_qb, body, (dk0, dv0))
    # q was pre-scaled, so ds^T @ q_scaled already carries the softmax scale
    dk_ref[0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, :, :] = dv.astype(dv_ref.dtype)


# ------------------------------------------------------------------ public API


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9)
)
def _flash(q3, k3, v3, seed, heads, scale, causal, blocks, dropout_rate,
           interpret):
    o, _ = _fwd(q3, k3, v3, seed, heads[0], heads[1], scale, causal,
                blocks[0], blocks[1], dropout_rate, interpret)
    return o


def _flash_fwd(q3, k3, v3, seed, heads, scale, causal, blocks, dropout_rate,
               interpret):
    o, lse = _fwd(q3, k3, v3, seed, heads[0], heads[1], scale, causal,
                  blocks[0], blocks[1], dropout_rate, interpret)
    return o, (q3, k3, v3, seed, o, lse)


def _flash_bwd(heads, scale, causal, blocks, dropout_rate, interpret, res, do):
    q3, k3, v3, seed, o, lse = res
    n_heads, n_kv = heads
    block_q, block_k = blocks
    bn, seq_q, d = q3.shape
    seq_k = k3.shape[1]
    group = n_heads // n_kv

    if group > 1:  # materialize repeated kv for the backward pass
        bkv = k3.shape[0]
        rep = lambda x: jnp.repeat(  # noqa: E731
            x.reshape(bkv // n_kv, n_kv, seq_k, d), group, axis=1
        ).reshape(bn, seq_k, d)
        k3r, v3r = rep(k3), rep(v3)
    else:
        k3r, v3r = k3, v3

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[:, None, :]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, offset=seq_k - seq_q,
                          dropout_rate=dropout_rate,
                          num_kb_total=seq_k // block_k),
        grid=(bn, seq_q // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq_k, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, seq_k, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j: (i, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
        interpret=interpret,
    )(q3, k3r, v3r, do, lse, delta, seed)

    dk_r, dv_r = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, offset=seq_k - seq_q,
                          dropout_rate=dropout_rate,
                          num_kb_total=seq_k // block_k),
        grid=(bn, seq_k // block_k),
        in_specs=[
            pl.BlockSpec((1, seq_q, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, seq_q, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, seq_q), lambda i, j: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, seq_k, d), k3.dtype),
            jax.ShapeDtypeStruct((bn, seq_k, d), v3.dtype),
        ],
        interpret=interpret,
    )(q3, k3r, v3r, do, lse, delta, seed)

    if group > 1:  # reduce repeated-head grads back to kv heads
        b = bn // n_heads
        fold = lambda x: x.reshape(b, n_kv, group, seq_k, d).sum(axis=2).reshape(  # noqa: E731
            b * n_kv, seq_k, d
        )
        dk_r, dv_r = fold(dk_r), fold(dv_r)
    # seed is integer-typed: no cotangent
    return dq, dk_r.astype(k3.dtype), dv_r.astype(v3.dtype), None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    dropout_rate: float = 0.0,
    dropout_seed: jax.Array | int = 0,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over BSNH tensors (drop-in for ops.dot_product_attention
    when there is no cache/explicit mask).

    q: (B, Sq, N, D); k, v: (B, Skv, Nkv, D) with N % Nkv == 0.
    dropout_rate > 0 applies attention-prob dropout INSIDE the kernel
    (masks regenerated from (dropout_seed, block id) in the backward — no
    (S, S) mask tensor ever exists); same Bernoulli semantics as the dense
    reference, different random stream.
    """
    b, seq_q, n_heads, d = q.shape
    seq_k, n_kv = k.shape[1], k.shape[2]
    if n_heads % n_kv:
        raise ValueError(f"q heads {n_heads} not a multiple of kv heads {n_kv}")
    if interpret and dropout_rate > 0.0:
        raise ValueError(
            "in-kernel dropout requires the hardware PRNG: interpret-mode "
            "pltpu.prng_random_bits is a zero stub, which would silently "
            "keep every element scaled by 1/(1-rate)"
        )
    if scale is None:
        scale = d**-0.5
    block_q = _pick_block(seq_q, block_q)
    block_k = _pick_block(seq_k, block_k)

    q3 = q.transpose(0, 2, 1, 3).reshape(b * n_heads, seq_q, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * n_kv, seq_k, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * n_kv, seq_k, d)
    seed = jnp.asarray(dropout_seed, jnp.int32).reshape(1)
    o3 = _flash(
        q3, k3, v3, seed, (n_heads, n_kv), float(scale), bool(causal),
        (block_q, block_k), float(dropout_rate), interpret,
    )
    return o3.reshape(b, n_heads, seq_q, d).transpose(0, 2, 1, 3)
