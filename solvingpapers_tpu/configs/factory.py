"""Build model / data / trainer objects from a RunConfig."""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator, prefetch_batches
from solvingpapers_tpu.configs.registry import RunConfig


def build_model(cfg: RunConfig):
    fam = cfg.model_family
    if fam == "gpt":
        from solvingpapers_tpu.models.gpt import GPT

        return GPT(cfg.model)
    if fam == "llama3":
        from solvingpapers_tpu.models.llama3 import Llama

        return Llama(cfg.model)
    if fam == "gemma":
        from solvingpapers_tpu.models.gemma import Gemma

        return Gemma(cfg.model)
    if fam == "deepseekv3":
        from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3

        return DeepSeekV3(cfg.model)
    if fam == "gpt_pipe":
        from solvingpapers_tpu.models.gpt_pipe import GPTPipe

        return GPTPipe(cfg.model)
    if fam == "dsv3_pipe":
        from solvingpapers_tpu.models.deepseekv3_pipe import DSV3Pipe

        return DSV3Pipe(cfg.model)
    if fam == "llama3_pipe":
        from solvingpapers_tpu.models.llama3_pipe import LlamaPipe

        return LlamaPipe(cfg.model)
    if fam == "vit":
        from solvingpapers_tpu.models.vit import ViT

        return ViT(cfg.model)
    if fam == "alexnet":
        from solvingpapers_tpu.models.alexnet import AlexNet

        return AlexNet(cfg.model)
    if fam == "ae":
        from solvingpapers_tpu.models.autoencoder import AutoEncoder

        return AutoEncoder(cfg.model)
    if fam == "vae":
        from solvingpapers_tpu.models.autoencoder import VAE

        return VAE(cfg.model)
    if fam == "kd":
        from solvingpapers_tpu.models.kd import MLPClassifier

        return MLPClassifier(cfg.model)
    raise ValueError(f"unknown model family {cfg.model_family!r}")


def loss_fn_for(cfg: RunConfig):
    """Objective for a RunConfig's family (kd's teacher phase uses
    classification; its student phase is built in train.kd_pipeline)."""
    from solvingpapers_tpu.train import (
        classification_loss_fn,
        lm_loss_fn,
        reconstruction_loss_fn,
        vae_loss_fn,
    )
    from solvingpapers_tpu.train.objectives import dsv3_loss_fn

    return {
        "gpt": lm_loss_fn,
        "gpt_pipe": lm_loss_fn,
        "llama3": lm_loss_fn,
        "llama3_pipe": lm_loss_fn,
        "gemma": lm_loss_fn,
        "deepseekv3": dsv3_loss_fn,
        "dsv3_pipe": dsv3_loss_fn,
        "vit": classification_loss_fn,
        "alexnet": classification_loss_fn,
        "kd": classification_loss_fn,
        "ae": reconstruction_loss_fn,
        "vae": vae_loss_fn,
    }[cfg.model_family]


def rules_for(cfg: RunConfig):
    """Partition-rule table for a RunConfig — every Trainer construction
    site (train/eval/export/sample-restore) must agree on it, or restored
    states land in a layout that mismatches training."""
    from solvingpapers_tpu.sharding import LM_RULES, PP_RULES

    return PP_RULES if cfg.train.pipeline_parallel else LM_RULES


def init_fn_for(cfg: RunConfig):
    """Trainer init_fn override (None = default params-only init)."""
    if cfg.model_family in ("deepseekv3", "dsv3_pipe"):
        from solvingpapers_tpu.train.objectives import dsv3_init_fn

        return dsv3_init_fn
    return None


def build_image_run(cfg: RunConfig, mesh=None):
    """Returns (model, train_iter, eval_iter_fn, loss_fn) for image workloads."""
    from solvingpapers_tpu.data.images import image_batch_iterator, load_image_dataset

    d = cfg.data
    tx, ty, vx, vy = load_image_dataset(
        path=d.get("path"),
        n_train=d.get("n_train", 8192),
        n_test=d.get("n_test", 2048),
        side=d.get("side", 28),
        n_classes=d.get("n_classes", 10),
        seed=cfg.train.seed,
        source=d.get("source", "separable"),
        snr=d.get("snr", 2.8),
    )
    flatten = d.get("flatten", False)
    bsz = cfg.train.batch_size
    model = build_model(cfg)
    train_iter = image_batch_iterator(
        tx, ty, bsz, seed=cfg.train.seed, flatten=flatten, mesh=mesh
    )

    def eval_iter_fn():
        return image_batch_iterator(
            vx, vy, bsz, seed=10_000, flatten=flatten, mesh=mesh, loop=False
        )

    return model, train_iter, eval_iter_fn, loss_fn_for(cfg)


def build_char_lm_run(cfg: RunConfig, sharding=None):
    """Returns (run_cfg_with_vocab, model, tokenizer, train_iter, eval_iter_fn).

    data.kind 'char' builds a char vocab (gpt/gemma pipelines); 'bpe' trains
    a byte-level BPE on the corpus (the offline stand-in for the reference's
    tiktoken/HF GPT-2 tables — llama3 cell 6, deepseekv3 cell 6), or loads
    GPT-2-format tables from data['vocab_path']/data['merges_path'].
    """
    if cfg.data.get("kind") == "bpe":
        from solvingpapers_tpu.data.bpe import ByteBPETokenizer
        from solvingpapers_tpu.data.char import load_text, split_train_val

        # synthetic_chars: long-context configs need a corpus larger than
        # one block AFTER tokenization (BPE compresses ~4.5x — a 65k block
        # needs ~300k+ chars minimum; lm_batch_iterator raises otherwise)
        text = load_text(
            cfg.data.get("path"),
            synthetic_chars=cfg.data.get("synthetic_chars", 200_000),
        )
        if cfg.data.get("vocab_path") and cfg.data.get("merges_path"):
            tok = ByteBPETokenizer.from_files(
                cfg.data["vocab_path"], cfg.data["merges_path"]
            )
        else:
            tok = ByteBPETokenizer.train(
                text, cfg.data.get("bpe_vocab_size", 1024)
            )
        train_toks, val_toks = split_train_val(tok.encode(text))
    elif cfg.data.get("kind") == "tokens":
        # pre-tokenized stream (deepseekv3 cells 8-14: tokenize once, train
        # from saved tokens); model.vocab_size must match the tokenizer that
        # wrote the file; decode-side tokenizer is not reconstructable here
        from solvingpapers_tpu.data.char import split_train_val
        from solvingpapers_tpu.data.tokens import load_token_file

        toks = load_token_file(cfg.data["path"])

        class _IdTok:
            """Ids-only tokenizer: prompts are space-separated integer ids
            (the text tokenizer that wrote the file is not reconstructable)."""

            vocab_size = cfg.model.vocab_size

            def encode(self, s):
                try:
                    return np.asarray([int(t) for t in s.split()], np.int32)
                except ValueError:
                    raise RuntimeError(
                        "token-file runs carry no text tokenizer; prompts "
                        f"must be space-separated integer ids, got {s!r}"
                    ) from None

            def decode(self, ids):
                return " ".join(str(int(i)) for i in ids)

        from solvingpapers_tpu.data.tokens import token_file_max_id

        max_id = token_file_max_id(cfg.data["path"], toks)
        if max_id >= cfg.model.vocab_size:
            raise ValueError(
                f"token file {cfg.data['path']} holds id {max_id} but "
                f"model.vocab_size is {cfg.model.vocab_size}; XLA gathers "
                "clamp silently, so this must match the writing tokenizer"
            )
        tok = _IdTok()
        train_toks, val_toks = split_train_val(toks)
    elif cfg.data.get("source") == "markov":
        # entropy-calibrated corpus: val loss has an absolute target
        # (MarkovSource.entropy_rate_nats) that memorization cannot reach;
        # markov_text shares chain defaults with markov_entropy_nats so the
        # trained-on corpus and the gating floor come from the same chain
        from solvingpapers_tpu.data.char import CharTokenizer, split_train_val
        from solvingpapers_tpu.data.synthetic import markov_text

        text = markov_text(cfg.data)
        tok = CharTokenizer(text)
        train_toks, val_toks = split_train_val(tok.encode(text))
    else:
        tok, train_toks, val_toks = load_char_corpus(path=cfg.data.get("path"))
    block = cfg.data.get("block_size", 256)
    # the char vocab comes from the corpus; resize the model to match
    model_cfg = dataclasses.replace(cfg.model, vocab_size=max(tok.vocab_size, 2))
    cfg = dataclasses.replace(cfg, model=model_cfg)
    model = build_model(cfg)
    bsz = cfg.train.batch_size
    train_iter = lm_batch_iterator(train_toks, bsz, block, seed=cfg.train.seed, sharding=sharding)
    if isinstance(train_toks, np.memmap):
        # host-side gathers (native, GIL-releasing) overlap the device step;
        # in-memory corpora crop device-side so there is nothing to overlap
        train_iter = prefetch_batches(train_iter, depth=2)

    def eval_iter_fn() -> Iterator[dict]:
        return lm_batch_iterator(val_toks, bsz, block, seed=10_000, sharding=sharding)

    return cfg, model, tok, train_iter, eval_iter_fn
