"""Build model / data / trainer objects from a RunConfig."""

from __future__ import annotations

import dataclasses
from typing import Iterator

from solvingpapers_tpu.data import load_char_corpus
from solvingpapers_tpu.data.batches import lm_batch_iterator
from solvingpapers_tpu.configs.registry import RunConfig


def build_model(cfg: RunConfig):
    fam = cfg.model_family
    if fam == "gpt":
        from solvingpapers_tpu.models.gpt import GPT

        return GPT(cfg.model)
    if fam == "llama3":
        from solvingpapers_tpu.models.llama3 import Llama

        return Llama(cfg.model)
    if fam == "gemma":
        from solvingpapers_tpu.models.gemma import Gemma

        return Gemma(cfg.model)
    if fam == "deepseekv3":
        from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3

        return DeepSeekV3(cfg.model)
    if fam == "vit":
        from solvingpapers_tpu.models.vit import ViT

        return ViT(cfg.model)
    if fam == "alexnet":
        from solvingpapers_tpu.models.alexnet import AlexNet

        return AlexNet(cfg.model)
    if fam == "ae":
        from solvingpapers_tpu.models.autoencoder import AutoEncoder

        return AutoEncoder(cfg.model)
    if fam == "vae":
        from solvingpapers_tpu.models.autoencoder import VAE

        return VAE(cfg.model)
    raise ValueError(f"unknown model family {cfg.model_family!r}")


def build_char_lm_run(cfg: RunConfig, sharding=None):
    """Returns (run_cfg_with_vocab, model, tokenizer, train_iter, eval_iter_fn)."""
    tok, train_toks, val_toks = load_char_corpus(path=cfg.data.get("path"))
    block = cfg.data.get("block_size", 256)
    # the char vocab comes from the corpus; resize the model to match
    model_cfg = dataclasses.replace(cfg.model, vocab_size=max(tok.vocab_size, 2))
    cfg = dataclasses.replace(cfg, model=model_cfg)
    model = build_model(cfg)
    bsz = cfg.train.batch_size
    train_iter = lm_batch_iterator(train_toks, bsz, block, seed=cfg.train.seed, sharding=sharding)

    def eval_iter_fn() -> Iterator[dict]:
        return lm_batch_iterator(val_toks, bsz, block, seed=10_000, sharding=sharding)

    return cfg, model, tok, train_iter, eval_iter_fn
