"""Typed run configs (SURVEY.md §5 'config/flag system' rebuild).

One registry of named workloads replacing the reference's four ad-hoc
config styles; every notebook's train() cell is a named entry here,
launchable via `python -m solvingpapers_tpu.cli train --config=<name>`.
"""

from solvingpapers_tpu.configs.registry import RunConfig, get_config, list_configs, register
