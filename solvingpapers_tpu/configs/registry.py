"""Workload registry: name -> RunConfig (model + data + train settings)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from solvingpapers_tpu.train.engine import TrainConfig
from solvingpapers_tpu.train.optim import OptimizerConfig


@dataclasses.dataclass(frozen=True)
class RunConfig:
    name: str
    model_family: str  # gpt | llama3 | gemma | deepseekv3 | vit | alexnet | ae | vae | kd
    model: Any
    train: TrainConfig
    data: dict = dataclasses.field(default_factory=dict)
    notes: str = ""


_REGISTRY: dict[str, Callable[[], RunConfig]] = {}


def register(name: str):
    def deco(fn: Callable[[], RunConfig]):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_config(name: str, **overrides) -> RunConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown config {name!r}; available: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]()
    if overrides:
        train_overrides = {
            k: v for k, v in overrides.items()
            if k in {f.name for f in dataclasses.fields(TrainConfig)}
        }
        rest = {k: v for k, v in overrides.items() if k not in train_overrides}
        if train_overrides:
            train = dataclasses.replace(cfg.train, **train_overrides)
            # keep the LR schedule horizon aligned with an overridden step count
            if "steps" in train_overrides:
                train = dataclasses.replace(
                    train,
                    optimizer=dataclasses.replace(
                        train.optimizer, total_steps=train_overrides["steps"]
                    ),
                )
            cfg = dataclasses.replace(cfg, train=train)
        if rest:
            cfg = dataclasses.replace(cfg, **rest)
    return cfg


def list_configs() -> list[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------- workloads


@register("gpt_tiny")
def _gpt_tiny() -> RunConfig:
    """CPU-runnable smoke config (debugging / CI)."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_tiny",
        model_family="gpt",
        model=GPTConfig(vocab_size=64, block_size=64, dim=64, n_layers=2,
                        n_heads=2, dropout=0.0),
        train=TrainConfig(
            steps=100, batch_size=16, log_every=20, eval_every=50, eval_batches=5,
            optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10, total_steps=100),
            tokens_per_step=16 * 64,
        ),
        data={"kind": "char", "path": None, "block_size": 64},
        notes="smoke-test config, not a reference workload",
    )


@register("gpt_shakespeare")
def _gpt_shakespeare() -> RunConfig:
    """The reference's gpt/gpt-jax.ipynb cell 8 hyperparameters."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_shakespeare",
        model_family="gpt",
        model=GPTConfig(
            vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=1,
            dropout=0.1, dtype="bfloat16",
        ),
        train=TrainConfig(
            steps=1000,
            batch_size=128,
            log_every=50,
            eval_every=100,
            eval_batches=20,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=0, total_steps=1000,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=128 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="gpt/gpt-jax.ipynb cells 8-19; val loss 1.8871 @ step 1000 on T4",
    )


@register("llama3_shakespeare")
def _llama3_shakespeare() -> RunConfig:
    """The reference's llama3/LLaMA-jax.ipynb cell 9 hyperparameters.

    The notebook trains with hand-rolled SGD (cell 29) over 30 epochs x
    1000 steps (cell 31); optimizer name 'sgd' preserves that parity while
    `adamw` remains a config switch. The notebook tokenizes with tiktoken
    gpt2 BPE; this config defaults to the char pipeline (vocab resized by
    the factory) since the BPE merges table is not bundled offline.
    """
    from solvingpapers_tpu.models.llama3 import LlamaConfig

    return RunConfig(
        name="llama3_shakespeare",
        model_family="llama3",
        model=LlamaConfig(
            vocab_size=50257, max_seq_len=128, dim=256, n_layers=2, n_heads=4,
            n_kv_heads=2, hidden_dim=1024, dropout=0.0, dtype="bfloat16",
        ),
        train=TrainConfig(
            steps=30_000,  # 30 epochs x 1000 steps (cell 31)
            batch_size=16,
            log_every=100,
            eval_every=1000,
            eval_batches=20,
            optimizer=OptimizerConfig(
                name="sgd", max_lr=3e-4, warmup_steps=0, total_steps=30_000,
                grad_clip=0.0, weight_decay=0.0, min_lr_ratio=1.0,
            ),
            tokens_per_step=16 * 128,
        ),
        data={"kind": "char", "path": None, "block_size": 128},
        notes="LLaMA-jax.ipynb cells 9, 29-31; epoch-avg loss 8.10→5.47 over 30k steps",
    )
