"""Workload registry: name -> RunConfig (model + data + train settings)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from solvingpapers_tpu.sharding.mesh import MeshConfig
from solvingpapers_tpu.train.engine import TrainConfig
from solvingpapers_tpu.train.optim import OptimizerConfig


@dataclasses.dataclass(frozen=True)
class RunConfig:
    name: str
    model_family: str  # gpt | llama3 | gemma | deepseekv3 | vit | alexnet | ae | vae | kd
    model: Any
    train: TrainConfig
    data: dict = dataclasses.field(default_factory=dict)
    notes: str = ""


_REGISTRY: dict[str, Callable[[], RunConfig]] = {}


def register(name: str):
    def deco(fn: Callable[[], RunConfig]):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_config(name: str, **overrides) -> RunConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown config {name!r}; available: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]()
    if overrides:
        train_overrides = {
            k: v for k, v in overrides.items()
            if k in {f.name for f in dataclasses.fields(TrainConfig)}
        }
        rest = {k: v for k, v in overrides.items() if k not in train_overrides}
        if train_overrides:
            train = dataclasses.replace(cfg.train, **train_overrides)
            # keep the LR schedule horizon aligned with an overridden step count
            if "steps" in train_overrides:
                train = dataclasses.replace(
                    train,
                    optimizer=dataclasses.replace(
                        train.optimizer, total_steps=train_overrides["steps"]
                    ),
                )
            cfg = dataclasses.replace(cfg, train=train)
        if rest:
            cfg = dataclasses.replace(cfg, **rest)
    return cfg


def list_configs() -> list[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------- workloads


@register("gpt_tiny")
def _gpt_tiny() -> RunConfig:
    """CPU-runnable smoke config (debugging / CI)."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_tiny",
        model_family="gpt",
        model=GPTConfig(vocab_size=64, block_size=64, dim=64, n_layers=2,
                        n_heads=2, dropout=0.0),
        train=TrainConfig(
            steps=100, batch_size=16, log_every=20, eval_every=50, eval_batches=5,
            optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10, total_steps=100),
            tokens_per_step=16 * 64,
        ),
        data={"kind": "char", "path": None, "block_size": 64},
        notes="smoke-test config, not a reference workload",
    )


@register("gpt_tiny_long")
def _gpt_tiny_long() -> RunConfig:
    """gpt_tiny with a 256-position budget: the serving benches' long-
    stream smoke config (CPU-runnable; speculative decoding needs
    streams long enough for drafts to find history, which gpt_tiny's 64
    positions cannot hold). Train at the full block_size — the learned
    position table has no values beyond the trained length."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_tiny_long",
        model_family="gpt",
        model=GPTConfig(vocab_size=64, block_size=256, dim=64, n_layers=2,
                        n_heads=2, dropout=0.0),
        train=TrainConfig(
            steps=300, batch_size=16, log_every=50, eval_every=0,
            optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10,
                                      total_steps=300),
            tokens_per_step=16 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="smoke/bench config for long serve streams, not a "
              "reference workload",
    )


@register("gpt_shakespeare")
def _gpt_shakespeare() -> RunConfig:
    """The reference's gpt/gpt-jax.ipynb cell 8 hyperparameters."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_shakespeare",
        model_family="gpt",
        model=GPTConfig(
            vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=1,
            dropout=0.1, dtype="bfloat16",
        ),
        train=TrainConfig(
            steps=1000,
            batch_size=128,
            log_every=50,
            eval_every=100,
            eval_batches=20,
            # 10 on-device steps per dispatch (lax.scan window): amortizes
            # host dispatch latency, bit-identical to sequential stepping
            scan_steps=10,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=0, total_steps=1000,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=128 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="gpt/gpt-jax.ipynb cells 8-19; val loss 1.8871 @ step 1000 on T4",
    )


@register("llama3_shakespeare")
def _llama3_shakespeare() -> RunConfig:
    """The reference's llama3/LLaMA-jax.ipynb cell 9 hyperparameters.

    The notebook trains with hand-rolled SGD (cell 29) over 30 epochs x
    1000 steps (cell 31); optimizer name 'sgd' preserves that parity while
    `adamw` remains a config switch. The notebook tokenizes with tiktoken
    gpt2 BPE; this config defaults to the char pipeline (vocab resized by
    the factory) since the BPE merges table is not bundled offline.
    """
    from solvingpapers_tpu.models.llama3 import LlamaConfig

    return RunConfig(
        name="llama3_shakespeare",
        model_family="llama3",
        model=LlamaConfig(
            vocab_size=50257, max_seq_len=128, dim=256, n_layers=2, n_heads=4,
            n_kv_heads=2, hidden_dim=1024, dropout=0.0, dtype="bfloat16",
        ),
        train=TrainConfig(
            steps=30_000,  # 30 epochs x 1000 steps (cell 31)
            batch_size=16,
            log_every=100,
            eval_every=1000,
            eval_batches=20,
            optimizer=OptimizerConfig(
                name="sgd", max_lr=3e-4, warmup_steps=0, total_steps=30_000,
                grad_clip=0.0, weight_decay=0.0, min_lr_ratio=1.0,
            ),
            tokens_per_step=16 * 128,
        ),
        data={"kind": "char", "path": None, "block_size": 128},
        notes="LLaMA-jax.ipynb cells 9, 29-31; epoch-avg loss 8.10→5.47 over 30k steps",
    )


@register("dsv3_tinystories")
def _dsv3_tinystories() -> RunConfig:
    """deepseekv3/deepseekv3.ipynb cells 4, 42-44, 54: the reference flagship.

    196.08M params; 10k steps x 4,096 tok/step (bs 16 x block 256); AdamW
    6e-4 beta=(0.9,0.95) wd 0.1 clip 1.0, warmup 400 -> cosine to 0.1*max;
    final train loss 2.90068 / ppl 18.18644 on 2xT4 (readme tables).
    The notebook tokenizes TinyStories with GPT-2 BPE; offline default here
    is the char pipeline (factory resizes the vocab).
    """
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config

    return RunConfig(
        name="dsv3_tinystories",
        model_family="deepseekv3",
        # pe_scale=0.02: balances PE vs token signal (DeepSeekV3Config);
        # with the notebook's raw PE the routing gate specializes experts
        # by position — the drop_fraction 0.196 collapse in the round-2
        # artifacts/dsv3_run traces to it. capacity_factor 4 + the
        # sequence-wise balance term absorb the residual clustering skew of
        # the memorization corpus (r3 measured: drop 0.196 -> 0.072 from
        # pe_scale alone; the two knobs take it to ~0)
        model=DeepSeekV3Config(dtype="bfloat16", pe_scale=0.02,
                               capacity_factor=4.0,
                               balance_loss_weight=1e-2),
        train=TrainConfig(
            steps=10_000,
            batch_size=16,
            log_every=100,
            eval_every=500,
            eval_batches=20,
            ckpt_every=1000,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=6e-4, warmup_steps=400, total_steps=10_000,
                b1=0.9, b2=0.95, weight_decay=0.1, grad_clip=1.0, eps=1e-8,
            ),
            tokens_per_step=16 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="deepseekv3 readme: loss 2.90068 / ppl 18.18644 @ 10k steps",
    )


@register("gemma_char")
def _gemma_char() -> RunConfig:
    """gemma/gemma.ipynb hyperparameters (char Tiny-Shakespeare).

    Reference: dim 768, 12 layers, 4/2 heads, block 128, batch 64. The
    notebook's cell-1 beta/wd knobs are DEAD — cell 17 constructs plain
    torch AdamW(lr=2.5e-4), i.e. betas (0.9, 0.999), wd 0.01, constant LR,
    no clipping; those actually-used values are what this config encodes.
    Run stopped at step 3500 of 5000 (markdown cell 19).
    """
    from solvingpapers_tpu.models.gemma import GemmaConfig

    return RunConfig(
        name="gemma_char",
        model_family="gemma",
        model=GemmaConfig(dtype="bfloat16"),
        train=TrainConfig(
            steps=5000,
            batch_size=64,
            log_every=100,
            eval_every=500,
            eval_batches=20,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=2.5e-4, warmup_steps=0, total_steps=5000,
                b1=0.9, b2=0.999, weight_decay=0.01, grad_clip=0.0,
                min_lr_ratio=1.0,
            ),
            tokens_per_step=64 * 128,
        ),
        data={"kind": "char", "path": None, "block_size": 128},
        notes="gemma.ipynb cells 1, 17-18; 127.5M params, stopped at 3500 steps",
    )


# --------------------------------------------------- entropy-calibrated rows
# Quality-parity workloads on the order-2 Markov corpus (data/synthetic.py
# MarkovSource): the corpus' exact entropy rate (~2.362 nats for the pinned
# vocab=64/alpha=0.1/seed=1234 chain) is an ABSOLUTE val-loss target — the
# offline stand-in for the reference's real-data val numbers
# (gpt-jax.ipynb cell 18 val 1.8871; deepseekv3 readme loss 2.90068).
# tools/parity_suite.py reports val_loss - H per row and gates on it.

_MARKOV_DATA = {"kind": "char", "source": "markov", "block_size": 256,
                "n_chars": 4_000_000}


def _markov_train(steps: int, batch_size: int, block: int,
                  max_lr: float = 1e-3) -> TrainConfig:
    return TrainConfig(
        steps=steps, batch_size=batch_size, log_every=100,
        eval_every=max(steps // 4, 1), eval_batches=20,
        optimizer=OptimizerConfig(
            name="adamw", max_lr=max_lr, warmup_steps=min(100, steps // 10),
            total_steps=steps, weight_decay=0.01, grad_clip=1.0,
        ),
        tokens_per_step=batch_size * block,
    )


@register("gpt_markov")
def _gpt_markov() -> RunConfig:
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_markov",
        model_family="gpt",
        model=GPTConfig(vocab_size=64, block_size=256, dim=256, n_layers=4,
                        n_heads=4, dropout=0.0, dtype="bfloat16"),
        train=_markov_train(3000, 64, 256),
        data=dict(_MARKOV_DATA),
        notes="entropy-calibrated quality row; target val_loss -> H ~= 2.362",
    )


@register("llama3_markov")
def _llama3_markov() -> RunConfig:
    from solvingpapers_tpu.models.llama3 import LlamaConfig

    return RunConfig(
        name="llama3_markov",
        model_family="llama3",
        model=LlamaConfig(vocab_size=64, max_seq_len=256, dim=256, n_layers=3,
                          n_heads=4, n_kv_heads=2, dropout=0.0, dtype="bfloat16"),
        train=_markov_train(3000, 64, 256),
        data=dict(_MARKOV_DATA),
        notes="entropy-calibrated quality row; target val_loss -> H ~= 2.362",
    )


@register("gemma_markov")
def _gemma_markov() -> RunConfig:
    from solvingpapers_tpu.models.gemma import GemmaConfig

    return RunConfig(
        name="gemma_markov",
        model_family="gemma",
        model=GemmaConfig(vocab_size=64, max_seq_len=256, dim=256, n_layers=4,
                          n_heads=4, n_kv_heads=2, dropout=0.0, dtype="bfloat16"),
        train=_markov_train(3000, 64, 256),
        # capacity-matched corpus (VERDICT r4 ask 6 — the 0.139-nat outlier
        # diagnosed): the round-5 ablation (tools/gemma_markov_ablation.py,
        # 3000 steps each on the v5e) cleared the verdict's suspect list —
        # full-MHA 0.144 and SwiGLU-activation 0.132 sit AT the 0.139
        # baseline, so neither grouped-MQA nor GeGLU is the cause — while
        # 16M chars drops the gap to 0.044, best of the dense zoo. Gemma's
        # FFN carries ~2.25x llama3_markov's FFN params (4*dim GeGLU hidden
        # vs (2/3)*4*dim SwiGLU, 4 layers vs 3), so on the shared 4M-char
        # corpus it memorizes like dsv3 did in r4; the honest fix is the
        # same capacity-matched 16M-char source, not a schedule or
        # architecture change (supporting evidence: lr 5e-4 and 3-layer
        # variants land at 0.093/0.097 by REDUCING fit, not generalizing).
        data={**_MARKOV_DATA, "n_chars": 16_000_000},
        notes="entropy-calibrated quality row; target val_loss -> H ~= 2.362",
    )


@register("dsv3_markov")
def _dsv3_markov() -> RunConfig:
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config

    return RunConfig(
        name="dsv3_markov",
        model_family="deepseekv3",
        # pe_scale + rope_dim: see DeepSeekV3Config — position-critical
        # data is unlearnable (gap 1.80 nats) with the notebook's raw
        # sinusoidal PE and no relative-position channel
        model=DeepSeekV3Config(vocab_size=64, block_size=256, dim=256,
                               n_layers=4, n_heads=4, latent_dim=32,
                               rope_dim=32, pe_scale=0.02,
                               n_experts=8, top_experts=2, dropout=0.0,
                               attn_dropout=0.0, dtype="bfloat16"),
        train=_markov_train(3000, 64, 256),
        # capacity-matched corpus: the MoE carries ~5x the dense peers'
        # params (8 experts x SwiGLU per layer) and memorizes the shared
        # 4M-char corpus past ~2k steps (r4 measured gap 0.335 at 3000
        # steps there — the r3 1200-step pin was hiding this). The chain
        # is an unbounded synthetic source, so the honest fix is more
        # held-out-equivalent data, not a shorter schedule: at 16M chars
        # the same 3000-step run generalizes (gap 0.032, load entropy
        # 0.996, zero drops).
        data={**_MARKOV_DATA, "n_chars": 16_000_000},
        notes="entropy-calibrated quality row; target val_loss -> H ~= 2.362",
    )


@register("llama3_long")
def _llama3_long() -> RunConfig:
    """Long-context capability demo (nothing comparable in the reference —
    its max context is 256 tokens): llama with context_parallel=True for
    ring-attention training over a 'context' mesh axis. Driven end-to-end
    by the stock Trainer/CLI: the train step runs the whole loss inside
    shard_map with the sequence sharded (TrainConfig.context_parallel)."""
    from solvingpapers_tpu.models.llama3 import LlamaConfig

    return RunConfig(
        name="llama3_long",
        model_family="llama3",
        model=LlamaConfig(
            vocab_size=50257, max_seq_len=32_768, dim=1024, n_layers=16,
            n_heads=16, n_kv_heads=8, dropout=0.0, dtype="bfloat16",
            context_parallel=True, use_flash=True,
        ),
        train=TrainConfig(
            steps=10_000, batch_size=8, log_every=50, eval_every=500,
            eval_batches=8, ckpt_every=1000,
            mesh=MeshConfig(data=-1, context=4),
            context_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=3e-4, warmup_steps=200, total_steps=10_000,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=8 * 32_768,
        ),
        data={"kind": "bpe", "path": None, "block_size": 32_768,
              "bpe_vocab_size": 32_000, "synthetic_chars": 4_000_000},
        notes="beyond-reference long-context config; sequence sharded over "
              "the context axis, ring attention over ICI",
    )


@register("gpt_pp")
def _gpt_pp() -> RunConfig:
    """Pipeline-parallel GPT (SURVEY.md §2.3 PP row; nothing comparable in
    the reference): the reference GPT-jax architecture with its 8 decoder
    blocks split into 4 stages over the 'pipe' mesh axis, GPipe microbatch
    schedule inside shard_map, composed with data parallelism."""
    from solvingpapers_tpu.models.gpt_pipe import GPTPipeConfig

    return RunConfig(
        name="gpt_pp",
        model_family="gpt_pipe",
        model=GPTPipeConfig(
            vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=4,
            dtype="bfloat16", n_stages=4, n_microbatches=8,
            pipeline_parallel=True,
            # the reference GPT recipe's dropout (gpt-jax.ipynb cell 8)
            # trains under the schedule via per-(stage, microbatch, layer)
            # keys
            dropout=0.1,
        ),
        train=TrainConfig(
            steps=1000, batch_size=64, log_every=50, eval_every=200,
            eval_batches=10,
            mesh=MeshConfig(data=-1, pipe=4),
            pipeline_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=100, total_steps=1000,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=64 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="GPipe over 4 stages x data parallel; stage params stored "
              "sharded over 'pipe' (PP_RULES)",
    )


@register("gpt_pp_smoke")
def _gpt_pp_smoke() -> RunConfig:
    """CPU-mesh-sized gpt_pp (virtual 8-device mesh: data=2 x pipe=4)."""
    from solvingpapers_tpu.models.gpt_pipe import GPTPipeConfig

    return RunConfig(
        name="gpt_pp_smoke",
        model_family="gpt_pipe",
        model=GPTPipeConfig(
            vocab_size=256, block_size=64, dim=32, n_layers=4, n_heads=2,
            dtype="float32", n_stages=4, n_microbatches=4,
            pipeline_parallel=True,
            dropout=0.1,  # smoke the schedule-keyed dropout path too
        ),
        train=TrainConfig(
            steps=20, batch_size=8, log_every=5, eval_every=10,
            eval_batches=2,
            mesh=MeshConfig(data=-1, pipe=4),
            pipeline_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=5, total_steps=20,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=8 * 64,
        ),
        data={"kind": "char", "path": None, "block_size": 64},
        notes="gpt_pp at smoke scale for the virtual CPU mesh",
    )


@register("dsv3_pp")
def _dsv3_pp() -> RunConfig:
    """The flagship pipelined: DSV3Pipe (MLA + MoE staged over 'pipe' with
    shard-invariant routing-state updates) at the dsv3_tinystories scale,
    on a data x pipe mesh (8 real chips: data=2 x pipe=4). PP x FSDP (the
    embedding ZeRO-gathered in-step) is exercised by dsv3_pp_smoke's
    data=2 x fsdp=2 x pipe=2 mesh; add fsdp=2 here when chip count
    allows."""
    from solvingpapers_tpu.models.deepseekv3_pipe import DSV3PipeConfig

    return RunConfig(
        name="dsv3_pp",
        model_family="dsv3_pipe",
        model=DSV3PipeConfig(
            vocab_size=50257, block_size=256, dim=512, n_layers=8, n_heads=8,
            latent_dim=64, rope_dim=32, pe_scale=0.02, n_experts=8,
            top_experts=2, dtype="bfloat16", n_stages=4, n_microbatches=8,
            pipeline_parallel=True,
            # the reference recipe's dropout 0.1 (deepseekv3.ipynb cell 4)
            # now trains under the schedule (per-(stage, microbatch, layer)
            # mask keys)
            dropout=0.1, attn_dropout=0.1,
        ),
        train=TrainConfig(
            steps=10_000, batch_size=32, log_every=100, eval_every=500,
            eval_batches=8, ckpt_every=1000,
            mesh=MeshConfig(data=-1, pipe=4),
            pipeline_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=6e-4, warmup_steps=400,
                total_steps=10_000, b1=0.9, b2=0.95, weight_decay=0.1,
                grad_clip=1.0,
            ),
            tokens_per_step=32 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="flagship staged over the pipe axis; beyond-reference scale-out",
    )


@register("dsv3_pp_smoke")
def _dsv3_pp_smoke() -> RunConfig:
    """CPU-mesh-sized dsv3_pp (virtual 8-device mesh: data=2 x fsdp=2 x
    pipe=2 — exercises PP x FSDP with the MoE state recombination)."""
    from solvingpapers_tpu.models.deepseekv3_pipe import DSV3PipeConfig

    return RunConfig(
        name="dsv3_pp_smoke",
        model_family="dsv3_pipe",
        model=DSV3PipeConfig(
            vocab_size=256, block_size=64, dim=32, n_layers=4, n_heads=4,
            latent_dim=8, rope_dim=8, pe_scale=0.02, n_experts=4,
            top_experts=2, n_stages=2, n_microbatches=2,
            pipeline_parallel=True,
            # smoke the r4 paths: schedule-keyed dropout + replicated MTP
            dropout=0.1, attn_dropout=0.1, mtp_heads=1,
        ),
        train=TrainConfig(
            steps=20, batch_size=8, log_every=5, eval_every=10,
            eval_batches=2,
            mesh=MeshConfig(data=-1, fsdp=2, pipe=2),
            pipeline_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=5, total_steps=20,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=8 * 64,
        ),
        data={"kind": "char", "path": None, "block_size": 64},
        notes="dsv3_pp at smoke scale (PP x FSDP) for the virtual CPU mesh",
    )


@register("llama3_pp_smoke")
def _llama3_pp_smoke() -> RunConfig:
    """CPU-mesh-sized llama3 pipeline run (data=2 x pipe=4)."""
    from solvingpapers_tpu.models.llama3_pipe import LlamaPipeConfig

    return RunConfig(
        name="llama3_pp_smoke",
        model_family="llama3_pipe",
        model=LlamaPipeConfig(
            vocab_size=256, max_seq_len=64, dim=32, n_layers=4, n_heads=4,
            n_kv_heads=2, n_stages=4, n_microbatches=4,
            pipeline_parallel=True,
        ),
        train=TrainConfig(
            steps=20, batch_size=8, log_every=5, eval_every=10,
            eval_batches=2,
            mesh=MeshConfig(data=-1, pipe=4),
            pipeline_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=5, total_steps=20,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=8 * 64,
        ),
        data={"kind": "char", "path": None, "block_size": 64},
        notes="llama3 staged over the pipe axis at smoke scale",
    )


@register("llama3_long_smoke")
def _llama3_long_smoke() -> RunConfig:
    """CPU-mesh-sized llama3_long: the same context-parallel Trainer/CLI
    path (ring attention inside shard_map over data=2 x context=4) at toy
    dims, runnable on the virtual 8-device mesh in seconds. Release smoke
    test for the CP front door."""
    from solvingpapers_tpu.models.llama3 import LlamaConfig

    return RunConfig(
        name="llama3_long_smoke",
        model_family="llama3",
        model=LlamaConfig(
            vocab_size=256, max_seq_len=256, dim=64, n_layers=2,
            n_heads=4, n_kv_heads=2, dropout=0.0, dtype="float32",
            # flash on: the smoke exercises the same ring-flash core as
            # llama3_long (interpret-mode kernel on the CPU mesh)
            context_parallel=True, use_flash=True,
        ),
        train=TrainConfig(
            steps=20, batch_size=4, log_every=5, eval_every=10,
            eval_batches=2,
            mesh=MeshConfig(data=-1, context=4),
            context_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=5, total_steps=20,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=4 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="llama3_long at smoke scale for the virtual CPU mesh",
    )


@register("dsv3_long")
def _dsv3_long() -> RunConfig:
    """Long-context flagship demo (nothing comparable in the reference):
    DeepSeekV3 (MLA + MoE) at 16,384-token context on a single chip via
    flash-MLA (absorbed-query attention through the Pallas kernel; the
    dense einsum path cannot even compile at this length) + per-layer
    remat. Measured 433 ms/step / 38k tok/s on 1x v5e (BENCHMARKS.md)."""
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config

    return RunConfig(
        name="dsv3_long",
        model_family="deepseekv3",
        model=DeepSeekV3Config(
            vocab_size=50257, block_size=16_384, dtype="bfloat16",
            use_flash=True, remat=True, pe_scale=0.02, rope_dim=64,
        ),
        train=TrainConfig(
            steps=10_000, batch_size=1, log_every=50, eval_every=500,
            eval_batches=4, ckpt_every=1000,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=3e-4, warmup_steps=200, total_steps=10_000,
                b1=0.9, b2=0.95, weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=16_384,
        ),
        data={"kind": "bpe", "path": None, "block_size": 16_384,
              "bpe_vocab_size": 32_000, "synthetic_chars": 2_000_000},
        notes="beyond-reference: 64x the reference's maximum context for "
              "its own flagship architecture, one chip",
    )


@register("dsv3_mtp")
def _dsv3_mtp() -> RunConfig:
    """The flagship with multi-token prediction ENABLED (2 extra heads,
    loss weight 0.3). The reference builds the full MTP machinery but ships
    mtp_heads=0 (deepseekv3.ipynb cells 33, 46 — the else-branch runs);
    this config exercises the capability the notebook only gestures at."""
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config

    return RunConfig(
        name="dsv3_mtp",
        model_family="deepseekv3",
        model=DeepSeekV3Config(dtype="bfloat16", mtp_heads=2, pe_scale=0.02),
        train=TrainConfig(
            steps=10_000, batch_size=16, log_every=50, eval_every=500,
            eval_batches=8, ckpt_every=1000,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=6e-4, warmup_steps=400, total_steps=10_000,
                b1=0.9, b2=0.95, weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=16 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="deepseekv3 with mtp_heads=2 live (the reference's dormant "
              "branch); main CE + 0.3 x MTP loss",
    )


@register("dsv3_long_cp")
def _dsv3_long_cp() -> RunConfig:
    """The flagship at 65,536-token context via context parallelism: MLA
    rings over the latent stream across a 4-way 'context' axis (flash
    kernel per chunk), MoE routing state psum'd shard-invariant — 4x the
    single-chip dsv3_long ceiling, 256x the reference's maximum context.
    MTP (2 heads) composes: the i+k shift is a ppermute halo from the
    right neighbor (sharding.cp_halo_right), so long-context CP and the
    reference's MTP training feature are no longer mutually exclusive."""
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config

    return RunConfig(
        name="dsv3_long_cp",
        model_family="deepseekv3",
        model=DeepSeekV3Config(
            vocab_size=50257, block_size=65_536, dtype="bfloat16",
            use_flash=True, remat=True, context_parallel=True,
            dropout=0.0, attn_dropout=0.0, pe_scale=0.02, rope_dim=64,
            mtp_heads=2,
        ),
        train=TrainConfig(
            steps=10_000, batch_size=4, log_every=50, eval_every=500,
            eval_batches=4, ckpt_every=1000,
            mesh=MeshConfig(data=-1, context=4),
            context_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=3e-4, warmup_steps=200, total_steps=10_000,
                b1=0.9, b2=0.95, weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=4 * 65_536,
        ),
        data={"kind": "bpe", "path": None, "block_size": 65_536,
              "bpe_vocab_size": 32_000, "synthetic_chars": 8_000_000},
        notes="flagship long-context over the context axis (ring flash-MLA)",
    )


@register("dsv3_long_cp_smoke")
def _dsv3_long_cp_smoke() -> RunConfig:
    """CPU-mesh-sized dsv3_long_cp (virtual 8-device mesh: data=2 x
    context=4): same CP Trainer path — ring flash-MLA + psum'd MoE state —
    at toy dims."""
    from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config

    return RunConfig(
        name="dsv3_long_cp_smoke",
        model_family="deepseekv3",
        model=DeepSeekV3Config(
            vocab_size=256, block_size=256, dim=32, n_layers=2, n_heads=4,
            latent_dim=8, n_experts=4, top_experts=2, dropout=0.0,
            attn_dropout=0.0, use_flash=True, context_parallel=True,
            pe_scale=0.02, rope_dim=8,
        ),
        train=TrainConfig(
            steps=20, batch_size=4, log_every=5, eval_every=10,
            eval_batches=2,
            mesh=MeshConfig(data=-1, context=4),
            context_parallel=True,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=5, total_steps=20,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=4 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="dsv3_long_cp at smoke scale for the virtual CPU mesh",
    )


@register("vit_mnist")
def _vit_mnist() -> RunConfig:
    """vision transformer/ViT.ipynb cells 4-15: tiny ViT on MNIST-shaped data.

    Reference: 28x28 patch 7, dim 64, 4 heads, 4 blocks, MLP 2x, Adam 1e-3,
    batch 128, 5 epochs -> 97.25% test accuracy.
    """
    from solvingpapers_tpu.models.vit import ViTConfig

    return RunConfig(
        name="vit_mnist",
        model_family="vit",
        model=ViTConfig(),
        train=TrainConfig(
            steps=2000, batch_size=128, log_every=100, eval_every=500,
            eval_batches=16,
            optimizer=OptimizerConfig(
                name="adam", max_lr=1e-3, warmup_steps=0, total_steps=2000,
                min_lr_ratio=1.0, weight_decay=0.0, grad_clip=0.0,
            ),
        ),
        data={"kind": "images", "path": None, "side": 28, "n_classes": 10},
        notes="ViT.ipynb; MNIST via local npz path, else synthetic fallback",
    )


@register("vit_bayes")
def _vit_bayes() -> RunConfig:
    """vit_mnist on the Bayes-calibrated Gaussian image set
    (data/synthetic.GaussianImageSource): Bayes-optimal accuracy 0.8703 at
    snr 2.8 / 10 classes, computed exactly from the generative model — the
    vision analogue of the Markov corpus's entropy floor. val_accuracy has
    an absolute ceiling no model beats and a calibrated target a good one
    approaches; the separable set saturates at 1.0 and can't fail for the
    interesting reason (VERDICT r3)."""
    from solvingpapers_tpu.models.vit import ViTConfig

    return RunConfig(
        name="vit_bayes",
        model_family="vit",
        model=ViTConfig(),
        train=TrainConfig(
            # weight decay + cosine decay matter here: the Bayes rule is a
            # matched filter and unregularized nets overfit the per-pixel
            # noise (measured: wd 0.1 closes the val gap 0.085 -> 0.022 on
            # the MLP); 32k train samples bound the estimation error
            # eval_batches 64 (8192 samples): binomial eval noise at
            # p~0.84 is sigma~0.004, so the parity gate's 0.02 tolerance
            # sits 5 sigma out instead of 2.5 (VERDICT r4 ask 9 — the
            # steps stay pinned at 2000 so the row remains gate-comparable
            # across rounds; only the eval got less noisy)
            steps=2000, batch_size=128, log_every=100, eval_every=500,
            eval_batches=64,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=0, total_steps=2000,
                min_lr_ratio=0.1, weight_decay=0.1, grad_clip=1.0,
            ),
        ),
        data={"kind": "images", "path": None, "side": 28, "n_classes": 10,
              "source": "bayes", "snr": 2.8, "n_train": 32768},
        notes="ViT on the computable-Bayes Gaussian set (ceiling 0.8703)",
    )


@register("kd_bayes")
def _kd_bayes() -> RunConfig:
    """kd_mnist on the Bayes-calibrated Gaussian set (see vit_bayes): the
    distilled student's accuracy is measured against the computable 0.8703
    Bayes ceiling instead of a saturating 1.0."""
    from solvingpapers_tpu.models.kd import student_config

    return RunConfig(
        name="kd_bayes",
        model_family="kd",
        model=student_config(),
        train=TrainConfig(
            # see vit_bayes: wd + cosine + 32k samples keep the student at
            # the matched filter instead of the training noise;
            # eval_batches widened like vit_bayes (gate-noise margin)
            steps=4000, batch_size=64, log_every=200, eval_every=1000,
            eval_batches=64,
            optimizer=OptimizerConfig(name="adamw", max_lr=1e-3, warmup_steps=0,
                                      total_steps=4000, weight_decay=0.1,
                                      grad_clip=1.0, min_lr_ratio=0.1),
        ),
        data={"kind": "images", "path": None, "flatten": True,
              "teacher_steps": 1200, "temperature": 7.0, "alpha": 0.3,
              "source": "bayes", "snr": 2.8, "n_train": 32768},
        notes="KD on the computable-Bayes Gaussian set (ceiling 0.8703)",
    )


@register("alexnet_images")
def _alexnet_images() -> RunConfig:
    """alexnet/alexnet.py model (no train loop in reference); trained here
    with the shared engine on 224px 3-channel images."""
    from solvingpapers_tpu.models.alexnet import AlexNetConfig

    return RunConfig(
        name="alexnet_images",
        model_family="alexnet",
        model=AlexNetConfig(n_classes=10, in_channels=3),
        train=TrainConfig(
            steps=1000, batch_size=64, log_every=50, eval_every=250,
            eval_batches=8,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-4, warmup_steps=0,
                                      total_steps=1000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "side": 224, "n_classes": 10,
              "n_train": 2048, "n_test": 512},
        notes="alexnet.py:5-44 (classifier flatten size derived, not 256*5*5)",
    )


@register("ae_mnist")
def _ae_mnist() -> RunConfig:
    """autoencoder/autoencoder.ipynb: 784-256-32 AE, MSE+Adam(1e-3), 5 epochs."""
    from solvingpapers_tpu.models.autoencoder import AutoEncoderConfig

    return RunConfig(
        name="ae_mnist",
        model_family="ae",
        model=AutoEncoderConfig(),
        train=TrainConfig(
            steps=2000, batch_size=128, log_every=100, eval_every=500,
            eval_batches=16,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-3, warmup_steps=0,
                                      total_steps=2000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "flatten": True},
        notes="autoencoder.ipynb cells 4-9; reference MSE 0.012954 @ epoch 5",
    )


@register("vae_mnist")
def _vae_mnist() -> RunConfig:
    """autoencoder/variational autoencoder.ipynb: VAE(784,256,128), 10 epochs."""
    from solvingpapers_tpu.models.autoencoder import VAEConfig

    return RunConfig(
        name="vae_mnist",
        model_family="vae",
        model=VAEConfig(),
        train=TrainConfig(
            steps=4000, batch_size=128, log_every=100, eval_every=1000,
            eval_batches=16,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-3, warmup_steps=0,
                                      total_steps=4000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "flatten": True},
        notes="variational autoencoder.ipynb cells 5-8; summed ELBO 13881 @ ep10",
    )


@register("kd_mnist")
def _kd_mnist() -> RunConfig:
    """knowledge distillation/kd.py: teacher 3 epochs -> frozen -> student
    10 epochs with T=7, alpha=0.3 distillation; 97.50% student accuracy."""
    from solvingpapers_tpu.models.kd import student_config

    return RunConfig(
        name="kd_mnist",
        model_family="kd",
        model=student_config(),
        train=TrainConfig(
            steps=4000, batch_size=64, log_every=200, eval_every=1000,
            eval_batches=16,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-3, warmup_steps=0,
                                      total_steps=4000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "flatten": True,
              "teacher_steps": 1200, "temperature": 7.0, "alpha": 0.3},
        notes="kd.py:85-160; student target 97.50% (run screenshot)",
    )
