"""Workload registry: name -> RunConfig (model + data + train settings)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

from solvingpapers_tpu.train.engine import TrainConfig
from solvingpapers_tpu.train.optim import OptimizerConfig


@dataclasses.dataclass(frozen=True)
class RunConfig:
    name: str
    model_family: str  # gpt | llama3 | gemma | deepseekv3 | vit | alexnet | ae | vae | kd
    model: Any
    train: TrainConfig
    data: dict = dataclasses.field(default_factory=dict)
    notes: str = ""


_REGISTRY: dict[str, Callable[[], RunConfig]] = {}


def register(name: str):
    def deco(fn: Callable[[], RunConfig]):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_config(name: str, **overrides) -> RunConfig:
    if name not in _REGISTRY:
        raise KeyError(f"unknown config {name!r}; available: {sorted(_REGISTRY)}")
    cfg = _REGISTRY[name]()
    if overrides:
        train_overrides = {
            k: v for k, v in overrides.items()
            if k in {f.name for f in dataclasses.fields(TrainConfig)}
        }
        rest = {k: v for k, v in overrides.items() if k not in train_overrides}
        if train_overrides:
            train = dataclasses.replace(cfg.train, **train_overrides)
            # keep the LR schedule horizon aligned with an overridden step count
            if "steps" in train_overrides:
                train = dataclasses.replace(
                    train,
                    optimizer=dataclasses.replace(
                        train.optimizer, total_steps=train_overrides["steps"]
                    ),
                )
            cfg = dataclasses.replace(cfg, train=train)
        if rest:
            cfg = dataclasses.replace(cfg, **rest)
    return cfg


def list_configs() -> list[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------- workloads


@register("gpt_tiny")
def _gpt_tiny() -> RunConfig:
    """CPU-runnable smoke config (debugging / CI)."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_tiny",
        model_family="gpt",
        model=GPTConfig(vocab_size=64, block_size=64, dim=64, n_layers=2,
                        n_heads=2, dropout=0.0),
        train=TrainConfig(
            steps=100, batch_size=16, log_every=20, eval_every=50, eval_batches=5,
            optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10, total_steps=100),
            tokens_per_step=16 * 64,
        ),
        data={"kind": "char", "path": None, "block_size": 64},
        notes="smoke-test config, not a reference workload",
    )


@register("gpt_shakespeare")
def _gpt_shakespeare() -> RunConfig:
    """The reference's gpt/gpt-jax.ipynb cell 8 hyperparameters."""
    from solvingpapers_tpu.models.gpt import GPTConfig

    return RunConfig(
        name="gpt_shakespeare",
        model_family="gpt",
        model=GPTConfig(
            vocab_size=65, block_size=256, dim=256, n_layers=8, n_heads=1,
            dropout=0.1, dtype="bfloat16",
        ),
        train=TrainConfig(
            steps=1000,
            batch_size=128,
            log_every=50,
            eval_every=100,
            eval_batches=20,
            optimizer=OptimizerConfig(
                name="adamw", max_lr=1e-3, warmup_steps=0, total_steps=1000,
                weight_decay=0.1, grad_clip=1.0,
            ),
            tokens_per_step=128 * 256,
        ),
        data={"kind": "char", "path": None, "block_size": 256},
        notes="gpt/gpt-jax.ipynb cells 8-19; val loss 1.8871 @ step 1000 on T4",
    )


@register("llama3_shakespeare")
def _llama3_shakespeare() -> RunConfig:
    """The reference's llama3/LLaMA-jax.ipynb cell 9 hyperparameters.

    The notebook trains with hand-rolled SGD (cell 29) over 30 epochs x
    1000 steps (cell 31); optimizer name 'sgd' preserves that parity while
    `adamw` remains a config switch. The notebook tokenizes with tiktoken
    gpt2 BPE; this config defaults to the char pipeline (vocab resized by
    the factory) since the BPE merges table is not bundled offline.
    """
    from solvingpapers_tpu.models.llama3 import LlamaConfig

    return RunConfig(
        name="llama3_shakespeare",
        model_family="llama3",
        model=LlamaConfig(
            vocab_size=50257, max_seq_len=128, dim=256, n_layers=2, n_heads=4,
            n_kv_heads=2, hidden_dim=1024, dropout=0.0, dtype="bfloat16",
        ),
        train=TrainConfig(
            steps=30_000,  # 30 epochs x 1000 steps (cell 31)
            batch_size=16,
            log_every=100,
            eval_every=1000,
            eval_batches=20,
            optimizer=OptimizerConfig(
                name="sgd", max_lr=3e-4, warmup_steps=0, total_steps=30_000,
                grad_clip=0.0, weight_decay=0.0, min_lr_ratio=1.0,
            ),
            tokens_per_step=16 * 128,
        ),
        data={"kind": "char", "path": None, "block_size": 128},
        notes="LLaMA-jax.ipynb cells 9, 29-31; epoch-avg loss 8.10→5.47 over 30k steps",
    )


@register("vit_mnist")
def _vit_mnist() -> RunConfig:
    """vision transformer/ViT.ipynb cells 4-15: tiny ViT on MNIST-shaped data.

    Reference: 28x28 patch 7, dim 64, 4 heads, 4 blocks, MLP 2x, Adam 1e-3,
    batch 128, 5 epochs -> 97.25% test accuracy.
    """
    from solvingpapers_tpu.models.vit import ViTConfig

    return RunConfig(
        name="vit_mnist",
        model_family="vit",
        model=ViTConfig(),
        train=TrainConfig(
            steps=2000, batch_size=128, log_every=100, eval_every=500,
            eval_batches=16,
            optimizer=OptimizerConfig(
                name="adam", max_lr=1e-3, warmup_steps=0, total_steps=2000,
                min_lr_ratio=1.0, weight_decay=0.0, grad_clip=0.0,
            ),
        ),
        data={"kind": "images", "path": None, "side": 28, "n_classes": 10},
        notes="ViT.ipynb; MNIST via local npz path, else synthetic fallback",
    )


@register("alexnet_images")
def _alexnet_images() -> RunConfig:
    """alexnet/alexnet.py model (no train loop in reference); trained here
    with the shared engine on 224px 3-channel images."""
    from solvingpapers_tpu.models.alexnet import AlexNetConfig

    return RunConfig(
        name="alexnet_images",
        model_family="alexnet",
        model=AlexNetConfig(n_classes=10, in_channels=3),
        train=TrainConfig(
            steps=1000, batch_size=64, log_every=50, eval_every=250,
            eval_batches=8,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-4, warmup_steps=0,
                                      total_steps=1000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "side": 224, "n_classes": 10,
              "n_train": 2048, "n_test": 512},
        notes="alexnet.py:5-44 (classifier flatten size derived, not 256*5*5)",
    )


@register("ae_mnist")
def _ae_mnist() -> RunConfig:
    """autoencoder/autoencoder.ipynb: 784-256-32 AE, MSE+Adam(1e-3), 5 epochs."""
    from solvingpapers_tpu.models.autoencoder import AutoEncoderConfig

    return RunConfig(
        name="ae_mnist",
        model_family="ae",
        model=AutoEncoderConfig(),
        train=TrainConfig(
            steps=2000, batch_size=128, log_every=100, eval_every=500,
            eval_batches=16,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-3, warmup_steps=0,
                                      total_steps=2000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "flatten": True},
        notes="autoencoder.ipynb cells 4-9; reference MSE 0.012954 @ epoch 5",
    )


@register("vae_mnist")
def _vae_mnist() -> RunConfig:
    """autoencoder/variational autoencoder.ipynb: VAE(784,256,128), 10 epochs."""
    from solvingpapers_tpu.models.autoencoder import VAEConfig

    return RunConfig(
        name="vae_mnist",
        model_family="vae",
        model=VAEConfig(),
        train=TrainConfig(
            steps=4000, batch_size=128, log_every=100, eval_every=1000,
            eval_batches=16,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-3, warmup_steps=0,
                                      total_steps=4000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "flatten": True},
        notes="variational autoencoder.ipynb cells 5-8; summed ELBO 13881 @ ep10",
    )


@register("kd_mnist")
def _kd_mnist() -> RunConfig:
    """knowledge distillation/kd.py: teacher 3 epochs -> frozen -> student
    10 epochs with T=7, alpha=0.3 distillation; 97.50% student accuracy."""
    from solvingpapers_tpu.models.kd import student_config

    return RunConfig(
        name="kd_mnist",
        model_family="kd",
        model=student_config(),
        train=TrainConfig(
            steps=4000, batch_size=64, log_every=200, eval_every=1000,
            eval_batches=16,
            optimizer=OptimizerConfig(name="adam", max_lr=1e-3, warmup_steps=0,
                                      total_steps=4000, weight_decay=0.0,
                                      grad_clip=0.0, min_lr_ratio=1.0),
        ),
        data={"kind": "images", "path": None, "flatten": True,
              "teacher_steps": 1200, "temperature": 7.0, "alpha": 0.3},
        notes="kd.py:85-160; student target 97.50% (run screenshot)",
    )
