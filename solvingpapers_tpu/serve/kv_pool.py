"""KV cache pools for the serving engine: contiguous per-slot lanes
(`KVSlotPool`) and block-paged cache blocks (`PagedKVPool`).

Continuous batching needs slot-granular cache reuse: when one sequence
finishes, its cache storage must be handed to the next queued request
immediately, without waiting for the rest of the batch. `KVSlotPool`
applies that at lane granularity — one `max_seq` lane per slot, HBM
booked for the worst case. `PagedKVPool` (second half of this module)
is the full vLLM-PagedAttention layout: one physical pool of fixed-size
KV pages, per-slot page tables, and refcounted zero-copy prefix sharing
(`ServeConfig.paged`); the lane pool remains the default and the paired
baseline the bench measures the paged pool against.

The pool is carved out of the existing cache machinery unchanged: the
pooled pytrees come from ``model.init_caches(n_slots, max_len)``
(`infer/cache.py` KVCache / LatentCache — any family works), so the batch
dimension IS the slot dimension. Lane extraction/insertion are pytree
``dynamic_slice`` helpers meant to be traced inside the engine's jitted
programs (`serve/engine.py`); acquire/release bookkeeping is host-side.

Stale-data contract: a freed lane is NOT zeroed. Reuse is safe because
(a) prefill overwrites slots ``[0, P)`` of the lane before any attention
over it, and (b) decode masks with ``kv_index <= position`` (the cache
masking contract of `infer/cache.py`), so slots beyond the current length
never contribute — and every stale value is finite (written by a real
forward), so masked-softmax zeros annihilate it exactly.

Prefix reuse (`serve/prefix_cache.py`): `splice_prefix` copies a cached
batch-1 KV segment into a lane's leading slots before the suffix prefill
(copy-on-acquire — the lane owns its copy, so tree eviction can never
corrupt an in-flight stream), and `extract_prefix` snapshots a freshly
prefilled prompt span back out for the radix tree to keep.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from solvingpapers_tpu.ops.quant import (
    dequantize,
    dequantize_tree,
    quantize,
    quantize_tree,
    scale_shape,
)


# ======================================================================
# Quantized storage (`ServeConfig.kv_quant`, ops/quant.py)
# ======================================================================
#
# Both pools can hold their cache bytes as symmetric int8 with per-block
# absmax scales instead of the model's compute dtype: `QuantStore`
# replaces the plain cache pytree as the pool's device payload, and the
# jitted serving programs DEQUANTIZE ON READ (the gather/extract sites
# materialize the familiar compute-dtype lane view, so the models serve
# unmodified) and QUANTIZE ON WRITE (the store/scatter sites requantize
# exactly the blocks/pages the program wrote — untouched blocks are
# never re-read-modify-written, and within a touched block committed
# positions outside the written window re-encode from their own
# f32-dequantized codes rather than the lossy compute-dtype lane view,
# so old entries cannot drift step to step on any compute dtype; see
# ops/quant.py's fixed-point note).
#
# Exact traffic shares the same store: `exact` is a small sidecar lane
# pool in the ORIGINAL dtype ((kv_exact_lanes + 1) lanes; lane 0 is a
# trash lane, mirroring the paged pool's trash page). A slot serving a
# `SamplingParams.kv_exact` request carries a nonzero exact-lane index
# on the packed control rows: reads substitute its full-precision lane
# for the dequantized view (`jnp.where` per slot — one compiled program
# for mixed exact/quantized batches), writes land in BOTH (the int8
# shadow is harmless; the exact lane is authoritative), and quantized
# slots' exact-lane writes fall into trash lane 0. Exact streams are
# byte-identical to the unquantized engine's because the values the
# model ever reads for them are bit-equal.


@struct.dataclass
class QuantStore:
    """Quantized pool payload: int8 cache pytree + f32 scale sidecar
    (same tree structure, `ops.quant.scale_shape` leaves) + the optional
    exact-lane sidecar. `block`/`dtype` are static aux data (part of the
    jit signature): the time-block length scales tile and the compute
    dtype dequantized views materialize in."""

    q: object
    scale: object
    exact: object
    block: int = struct.field(pytree_node=False)
    dtype: object = struct.field(pytree_node=False)


@struct.dataclass
class QuantSegment:
    """Quantized prefix-cache segment (lane pools): the batch-1 int8 +
    scale slices `extract_prefix` snapshots and `splice_prefix` writes
    back. Cached prefixes stay quantized at rest — the radix tree's
    byte budget buys ~2x the cached tokens."""

    q: object
    scale: object
    block: int = struct.field(pytree_node=False)

    @property
    def length(self) -> int:
        return jax.tree_util.tree_leaves(self.q)[0].shape[1]

    @property
    def nbytes(self) -> int:
        return sum(
            leaf.size * leaf.dtype.itemsize
            for tree in (self.q, self.scale)
            for leaf in jax.tree_util.tree_leaves(tree)
        )

    def time_slice(self, start: int, end: int) -> "QuantSegment":
        """Token-axis sub-segment [start, end); bounds must be block
        multiples (they are page multiples, and the engine pins
        page % block == 0)."""
        if start % self.block or end % self.block:
            raise ValueError(
                f"quantized segment slice [{start}, {end}) is not "
                f"aligned to the quant block {self.block}"
            )
        b = self.block
        return QuantSegment(
            q=jax.tree_util.tree_map(lambda a: a[:, start:end], self.q),
            scale=jax.tree_util.tree_map(
                lambda a: a[:, start // b:end // b], self.scale
            ),
            block=b,
        )


def _leaf_dtype(caches):
    """The single compute dtype of a cache pytree (quantization keys its
    dequantized view on ONE static dtype; mixed-dtype caches would need
    a per-leaf aux tree nothing in the repo produces)."""
    dtypes = {leaf.dtype for leaf in jax.tree_util.tree_leaves(caches)}
    if len(dtypes) != 1:
        raise ValueError(
            f"kv_quant needs a single cache dtype, got {sorted(map(str, dtypes))}"
        )
    return dtypes.pop()


def make_quant_store(model, batch: int, time: int, block: int,
                     exact_lanes: int = 0,
                     exact_time: int | None = None) -> QuantStore:
    """Build a pool's quantized payload: int8 zeros + zero scales shaped
    like ``model.init_caches(batch, time)``, plus the exact-lane sidecar
    (``exact_lanes + 1`` full-precision lanes of `exact_time`; lane 0 is
    the trash lane). Zero scales dequantize to exact zeros, so a fresh
    quantized pool reads back bit-identical to a fresh plain one."""
    base = model.init_caches(batch, time)
    dtype = _leaf_dtype(base)
    q = jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.int8), base
    )
    scale = jax.tree_util.tree_map(
        lambda a: jnp.zeros(scale_shape(a.shape, block), jnp.float32), base
    )
    exact = None
    if exact_lanes > 0:
        exact = model.init_caches(exact_lanes + 1, exact_time or time)
    return QuantStore(q=q, scale=scale, exact=exact, block=block,
                      dtype=dtype)


def quant_pool_bytes(store: QuantStore) -> tuple[int, int, int, int]:
    """(payload+scale bytes, scale bytes, exact sidecar bytes, baseline
    bytes) — the analytic byte split the HBM ledger and the kv_quant
    gauges report. `baseline` is what the same pool would hold
    unquantized (int8 element count x the compute dtype's width)."""
    itemsize = np.dtype(store.dtype).itemsize
    q_bytes = sum(leaf.size for leaf in jax.tree_util.tree_leaves(store.q))
    s_bytes = sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(store.scale)
    )
    e_bytes = 0
    if store.exact is not None:
        e_bytes = sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(store.exact)
        )
    return q_bytes + s_bytes, s_bytes, e_bytes, q_bytes * itemsize


# --------------------------------------------------- traced read helpers


def _exact_select1(lane, store: QuantStore, eidx):
    """Batch-1 exact override: substitute the `eidx` exact lane when
    eidx > 0 (a kv_exact slot); eidx == 0 keeps the dequantized view."""
    if store.exact is None:
        return lane
    ex = extract_lane(store.exact, eidx)
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(eidx > 0, b, a), lane, ex
    )


def _exact_select(lanes, store: QuantStore, eidx_row):
    """Batched exact override for the (S, ...) lane view."""
    if store.exact is None:
        return lanes

    def sel(a, ex_pool):
        ex = ex_pool[eidx_row]
        mask = (eidx_row > 0).reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(mask, ex, a)

    return jax.tree_util.tree_map(sel, lanes, store.exact)


def quant_lane_view(store: QuantStore, slot, eidx):
    """Batch-1 compute-dtype lane view of a quantized LANE pool slot
    (traced) — `extract_lane` + dequantize + the exact override."""
    lane = dequantize_tree(
        extract_lane(store.q, slot), extract_lane(store.scale, slot),
        store.dtype,
    )
    return _exact_select1(lane, store, eidx)


def quant_lanes_view(store: QuantStore, eidx_row):
    """All-slot (S, max_len, ...) view of a quantized lane pool (traced)
    — what the decode programs carry through their scan."""
    lanes = dequantize_tree(store.q, store.scale, store.dtype)
    return _exact_select(lanes, store, eidx_row)


def quant_gather_lane(store: QuantStore, row, eidx):
    """Batch-1 lane view of a quantized PAGE pool: gather the int8
    pages and their per-page scale rows through the same page-table row,
    dequantize, apply the exact override (traced)."""

    def g(qleaf, sleaf):
        pages = qleaf[row].astype(jnp.float32)   # (PPL, page, ...)
        sc = sleaf[row][..., None]               # (PPL, 1[, H], 1)
        x = (pages * sc).astype(store.dtype)
        ppl, page = x.shape[:2]
        return x.reshape((1, ppl * page) + x.shape[2:])

    lane = jax.tree_util.tree_map(g, store.q, store.scale)
    return _exact_select1(lane, store, eidx)


def quant_gather_lanes(store: QuantStore, table, eidx_row):
    """(S, max_len, ...) view of a quantized page pool through the
    (S, pages_per_lane) page table (traced). The int8 gather moves half
    the bytes of the plain pool's — the paged full-lane-gather tax
    shrinks with the payload."""

    def g(qleaf, sleaf):
        pages = qleaf[table].astype(jnp.float32)  # (S, PPL, page, ...)
        sc = sleaf[table][..., None]              # (S, PPL, 1[, H], 1)
        x = (pages * sc).astype(store.dtype)
        s, ppl, page = x.shape[:3]
        return x.reshape((s, ppl * page) + x.shape[3:])

    lanes = jax.tree_util.tree_map(g, store.q, store.scale)
    return _exact_select(lanes, store, eidx_row)


# -------------------------------------------------- traced write helpers


def quant_store_lane(store: QuantStore, lane, slot, eidx,
                     t0: int, t1: int, hi=None) -> QuantStore:
    """Quantize-on-write for a batch-1 lane (the prefill store site):
    requantize ONLY the written span [t0, t1) (static; `t0` block-aligned
    — prefix-hit starts are page multiples and page % block == 0, `t1`
    rounds up to the block) into the slot's int8 + scale rows, and mirror
    the full-precision lane into the exact sidecar at `eidx` (trash lane
    0 for quantized slots). Blocks below t0 hold spliced prefix data the
    prefill never touched — not rewriting them is what keeps the
    quantized prefix cache's contents stable under reuse. `hi` (traced)
    is the end of the REAL tokens: prompts are right-padded, and a
    padding activation sharing the tail block would otherwise inflate
    its absmax and coarsen the last committed tokens' codes — positions
    past `hi` are zeroed before quantizing instead (they sit beyond
    `attend_len`, are never attended, and decode overwrites them; zeros
    can never widen a scale)."""
    b = store.block
    t_max = jax.tree_util.tree_leaves(store.q)[0].shape[1]
    if t0 % b:
        raise ValueError(f"write start {t0} is not a multiple of the "
                         f"quant block {b}")
    t1 = min(-(-t1 // b) * b, t_max)
    span = jax.tree_util.tree_map(lambda a: a[:, t0:t1], lane)
    if hi is not None:
        tcol = jnp.arange(t0, t1)

        def _zero_pads(a):
            m = (tcol < hi).reshape((1, t1 - t0) + (1,) * (a.ndim - 2))
            return jnp.where(m, a, jnp.zeros_like(a))

        span = jax.tree_util.tree_map(_zero_pads, span)
    q_span, s_span = quantize_tree(span, b)
    q = jax.tree_util.tree_map(
        lambda a, s: jax.lax.dynamic_update_slice(
            a, s, (slot, t0) + (0,) * (a.ndim - 2)),
        store.q, q_span,
    )
    scale = jax.tree_util.tree_map(
        lambda a, s: jax.lax.dynamic_update_slice(
            a, s, (slot, t0 // b) + (0,) * (a.ndim - 2)),
        store.scale, s_span,
    )
    exact = store.exact
    if exact is not None:
        exact = store_lane(exact, lane, eidx)
    return store.replace(q=q, scale=scale, exact=exact)


def quant_store_written(store: QuantStore, lanes, pos0, span: int,
                        eidx_row, hi=None,
                        tail_garbage: bool = False) -> QuantStore:
    """Quantize-on-write for the decode programs' (S, max_len, ...) lane
    view: each slot wrote positions ``[pos0[s], pos0[s] + span)`` (span
    static — `decode_block`, or rounds x chunk for speculation), which
    touches a static number of quant blocks per slot; requantize exactly
    those blocks and leave the rest of the pool's payload byte-identical
    (clipped duplicate windows rewrite the same block with the same
    content — idempotent). Within a rewritten block, only positions
    inside the written window take the compute-dtype lane view; the rest
    re-encode from their OWN f32-dequantized codes. That merge matters
    twice over: (1) on bf16 pools the lane view is a lossy cast, and
    requantizing committed entries through it would walk their codes
    step to step (ops/quant.py's fixed-point note only holds in f32);
    (2) when the caller knows the lane past a per-slot `hi` holds
    REJECTED-draft garbage (`tail_garbage=True`, the speculative
    write-back — `hi` is the device-committed end, default pos0 + span),
    excluding it keeps a garbage outlier from inflating the block absmax
    and permanently coarsening the committed entries that share the
    block. On f32 pools with a trustworthy tail the merge reproduces
    the lane bit-for-bit, so it is skipped at trace time (dtype and
    `tail_garbage` are static) and the plain-decode f32 write site keeps
    its pre-merge cost. The exact sidecar takes each slot's full lane at
    its `eidx` (duplicate trash-lane writes are garbage-on-garbage)."""
    b = store.block
    t_max = jax.tree_util.tree_leaves(store.q)[0].shape[1]
    nb = t_max // b
    n_slots = pos0.shape[0]
    rows = jnp.arange(n_slots)
    q_tree, s_tree = store.q, store.scale
    end = (pos0 + span) if hi is None else hi
    merge = tail_garbage or jnp.dtype(store.dtype) != jnp.float32
    for w in range((span - 1) // b + 2):
        bidx = jnp.clip((pos0 + w * b) // b, 0, nb - 1)  # (S,)
        tcol = bidx[:, None] * b + jnp.arange(b)[None, :]  # (S, b)

        def one(qleaf, sleaf, lane_leaf, bidx=bidx, tcol=tcol):
            vals = jax.vmap(
                lambda lane, i: jax.lax.dynamic_slice_in_dim(
                    lane, i * b, b, axis=0)
            )(lane_leaf, bidx)                       # (S, b, ...)
            if merge:
                old_q = jax.vmap(
                    lambda qrow, i: jax.lax.dynamic_slice_in_dim(
                        qrow, i * b, b, axis=0)
                )(qleaf, bidx)                       # (S, b, ...) int8
                old = dequantize(old_q, sleaf[rows, bidx][:, None],
                                 jnp.float32)
                wr = ((tcol >= pos0[:, None])
                      & (tcol < end[:, None]))
                wr = wr.reshape(wr.shape + (1,) * (vals.ndim - 2))
                vals = jnp.where(wr, vals.astype(jnp.float32), old)
            qv, sv = quantize(vals, b)               # scale (S, 1[, H])
            qleaf = qleaf.at[rows[:, None], tcol].set(qv)
            sleaf = sleaf.at[rows, bidx].set(
                jnp.squeeze(sv, axis=1))
            return qleaf, sleaf

        pairs = [one(ql, sl, ll) for ql, sl, ll in zip(
            jax.tree_util.tree_leaves(q_tree),
            jax.tree_util.tree_leaves(s_tree),
            jax.tree_util.tree_leaves(lanes))]
        treedef = jax.tree_util.tree_structure(q_tree)
        q_tree = jax.tree_util.tree_unflatten(
            treedef, [q for q, _ in pairs])
        s_tree = jax.tree_util.tree_unflatten(
            treedef, [s for _, s in pairs])
    return quant_store_exact_lanes(
        store.replace(q=q_tree, scale=s_tree), lanes, eidx_row)


def quant_scatter_lane_pages(store: QuantStore, lane, row,
                             start_page: int, eidx, hi=None) -> QuantStore:
    """`scatter_lane_pages` for a quantized page pool (the paged prefill
    write site): quantize the batch-1 lane's pages [start_page:] —
    one absmax scale row per (page, head) — and scatter payload + scales
    to the physical ids; mirror the lane into the exact sidecar. `hi`
    (traced) zeroes right-padding positions before quantizing, exactly
    as `quant_store_lane` documents — a pad activation must not widen
    the scale of the page holding the last real tokens."""
    ids = row[start_page:]

    def sc(qleaf, sleaf, lane_leaf):
        page = qleaf.shape[1]
        ppl = row.shape[0]
        pages = lane_leaf.reshape((ppl, page) + lane_leaf.shape[2:])
        pages = pages[start_page:]
        if hi is not None:
            tcol = (start_page * page
                    + jnp.arange((ppl - start_page) * page)).reshape(
                        (ppl - start_page, page))
            m = (tcol < hi).reshape(tcol.shape + (1,) * (pages.ndim - 2))
            pages = jnp.where(m, pages, jnp.zeros_like(pages))
        qv, sv = quantize(pages, page)
        return qleaf.at[ids].set(qv), sleaf.at[ids].set(sv)

    pairs = [sc(ql, sl, ll) for ql, sl, ll in zip(
        jax.tree_util.tree_leaves(store.q),
        jax.tree_util.tree_leaves(store.scale),
        jax.tree_util.tree_leaves(lane))]
    treedef = jax.tree_util.tree_structure(store.q)
    q = jax.tree_util.tree_unflatten(treedef, [a for a, _ in pairs])
    scale = jax.tree_util.tree_unflatten(treedef, [b for _, b in pairs])
    exact = store.exact
    if exact is not None:
        exact = store_lane(exact, lane, eidx)
    return store.replace(q=q, scale=scale, exact=exact)


def quant_scatter_written_pages(store: QuantStore, lanes, table,
                                pos, lo=None, hi=None,
                                tail_garbage: bool = False) -> QuantStore:
    """`scatter_written_pages` for a quantized page pool: gather each
    slot's written page out of the compute-dtype lane view, quantize it
    (fresh per-(page, head) scales), scatter payload + scale rows to the
    physical ids. `lo`/`hi` (per-slot logical positions, hi exclusive)
    bound the window the program actually wrote: positions outside it
    re-encode from their OWN f32-dequantized physical codes — needed on
    lossy compute dtypes (the bf16 drift `quant_store_written`
    documents) and, with `tail_garbage=True` (the speculative
    write-back), on EVERY dtype: there the lane past `hi` holds
    rejected-draft values whose outliers would otherwise inflate the
    page absmax and permanently coarsen the committed entries sharing
    the page. An f32 pool with a trustworthy tail skips the merge at
    trace time (the lane view is bit-for-bit the dequantized codes).
    Exact lanes are written separately, once per program
    (`quant_store_exact_lanes`) — this runs in a loop over page
    windows."""
    ppl = table.shape[1]
    merge = (lo is not None
             and (tail_garbage or jnp.dtype(store.dtype) != jnp.float32))

    def sc(qleaf, sleaf, lane_leaf):
        page = qleaf.shape[1]
        pg = jnp.clip(pos.astype(jnp.int32) // page, 0, ppl - 1)
        ids = jnp.take_along_axis(table, pg[:, None], axis=1)[:, 0]
        pages = jax.vmap(
            lambda lane, i: jax.lax.dynamic_slice_in_dim(
                lane, i * page, page, axis=0
            )
        )(lane_leaf, pg)
        if merge:
            tcol = pg[:, None] * page + jnp.arange(page)[None, :]
            old = dequantize(qleaf[ids], sleaf[ids], jnp.float32)
            wr = (tcol >= lo[:, None]) & (tcol < hi[:, None])
            wr = wr.reshape(wr.shape + (1,) * (pages.ndim - 2))
            pages = jnp.where(wr, pages.astype(jnp.float32), old)
        qv, sv = quantize(pages, page)  # (S, page, ...), (S, 1[, H])
        return qleaf.at[ids].set(qv), sleaf.at[ids].set(sv)

    pairs = [sc(ql, sl, ll) for ql, sl, ll in zip(
        jax.tree_util.tree_leaves(store.q),
        jax.tree_util.tree_leaves(store.scale),
        jax.tree_util.tree_leaves(lanes))]
    treedef = jax.tree_util.tree_structure(store.q)
    q = jax.tree_util.tree_unflatten(treedef, [a for a, _ in pairs])
    scale = jax.tree_util.tree_unflatten(treedef, [b for _, b in pairs])
    return store.replace(q=q, scale=scale)


def quant_scatter_window_pages(store: QuantStore, lanes, table, start,
                               last, span: int) -> QuantStore:
    """`scatter_window_pages` for a quantized page pool — the
    speculative decode write-back (same clamped page walk, quantized
    payload). [start, last] is the device-committed window: those
    positions take the lane's draws; committed pages below `start` keep
    their own codes, and the stale tail past `last` keeps old codes
    instead of rejected draws on EVERY dtype (`tail_garbage` — a
    rejected outlier would otherwise coarsen the whole page's scale;
    the tail itself stays overwrite-before-attend garbage either
    way)."""
    page = jax.tree_util.tree_leaves(store.q)[0].shape[1]
    limit = table.shape[1] * page - 1
    last = jnp.maximum(last, start)
    for w in range((span - 1) // page + 2):
        pos_w = jnp.clip(jnp.minimum(start + w * page, last), 0, limit)
        store = quant_scatter_written_pages(store, lanes, table, pos_w,
                                            lo=start, hi=last + 1,
                                            tail_garbage=True)
    return store


def quant_store_exact_lanes(store: QuantStore, lanes,
                            eidx_row) -> QuantStore:
    """Write every slot's full-precision lane view into its exact lane
    (paged decode/spec programs; trash-lane duplicates are benign)."""
    if store.exact is None:
        return store
    exact = jax.tree_util.tree_map(
        lambda ex, ln: ex.at[eidx_row].set(ln.astype(ex.dtype)),
        store.exact, lanes,
    )
    return store.replace(exact=exact)


def _require_same_dtype(pool_leaf, seg_leaf, op: str) -> None:
    """Lane/segment writes never cast: a silent `astype` would down-cast
    an fp32 segment into a bf16 pool (or vice versa) and quietly change
    every stream decoded over it. Trace-time error instead — the caller
    casts explicitly if a conversion is really intended."""
    if seg_leaf.dtype != pool_leaf.dtype:
        raise TypeError(
            f"{op}: segment dtype {seg_leaf.dtype} != pool dtype "
            f"{pool_leaf.dtype}; implicit casts are not performed (a "
            "silent astype would corrupt precision) — cast explicitly "
            "before the write"
        )


def extract_lane(caches, slot):
    """Slice slot `slot`'s batch-1 lane out of pooled caches (traced)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=0), caches
    )


def store_lane(caches, lane, slot):
    """Write a batch-1 lane back into the pooled caches at `slot` (traced).
    Dtypes must match exactly — see `_require_same_dtype`."""

    def upd(a, lane_leaf):
        _require_same_dtype(a, lane_leaf, "store_lane")
        return jax.lax.dynamic_update_slice_in_dim(a, lane_leaf, slot, axis=0)

    return jax.tree_util.tree_map(upd, caches, lane)


@functools.partial(jax.jit, donate_argnames=("caches",))
def _splice_program(caches, segment, ctl):
    """Copy-on-acquire: write a batch-1 prefix `segment` (time length L,
    static per compiled program) into lane `ctl[0]` at time offset
    `ctl[1]`. One fused program — every layer's `dynamic_update_slice`
    lands in a single dispatch, and donation reuses the pool's buffers.
    Program inventory is bounded because segment lengths are multiples of
    the prefix cache's page size."""
    slot, offset = ctl[0], ctl[1]

    def upd(a, s):
        _require_same_dtype(a, s, "splice_prefix")
        starts = (slot, offset) + (0,) * (a.ndim - 2)
        return jax.lax.dynamic_update_slice(a, s, starts)

    return jax.tree_util.tree_map(upd, caches, segment)


@functools.partial(jax.jit, static_argnames=("length",))
def _extract_program(caches, ctl, length):
    """Snapshot lane `ctl[0]`'s time span [ctl[1], ctl[1]+length) as a
    batch-1 segment pytree (a COPY — the lane can be overwritten or
    released without invalidating it)."""
    slot, offset = ctl[0], ctl[1]

    def ext(a):
        starts = (slot, offset) + (0,) * (a.ndim - 2)
        sizes = (1, length) + a.shape[2:]
        return jax.lax.dynamic_slice(a, starts, sizes)

    return jax.tree_util.tree_map(ext, caches)


def _zero_batch_entry(a, idx):
    """Zero batch entry `idx` of one cache leaf (traced `idx` — one
    compiled scrub program per tree structure, not per slot)."""
    return jax.lax.dynamic_update_slice_in_dim(
        a, jnp.zeros((1,) + a.shape[1:], a.dtype), idx, axis=0
    )


@functools.partial(jax.jit, donate_argnames=("caches",))
def scrub_lane_program(caches, slot, eidx):
    """Quarantine decontamination (lane pools): zero slot `slot`'s lane
    — and, on a quantized pool, its scale rows plus exact sidecar lane
    `eidx` (0 = the trash lane, harmless to clear). The stale-data
    contract above ("masked-softmax zeros annihilate stale values")
    only holds for FINITE stale values: ``0 * NaN`` is NaN, so a lane a
    NaN/Inf-poisoned forward wrote into would contaminate the next
    stream admitted into it through the masked attention tail. Compiled
    only when a quarantine actually fires — a fault-free engine never
    traces it."""
    if isinstance(caches, QuantStore):
        exact = caches.exact
        if exact is not None:
            exact = jax.tree_util.tree_map(
                lambda a: _zero_batch_entry(a, eidx), exact
            )
        return caches.replace(
            q=jax.tree_util.tree_map(
                lambda a: _zero_batch_entry(a, slot), caches.q),
            scale=jax.tree_util.tree_map(
                lambda a: _zero_batch_entry(a, slot), caches.scale),
            exact=exact,
        )
    return jax.tree_util.tree_map(
        lambda a: _zero_batch_entry(a, slot), caches
    )


@functools.partial(jax.jit, donate_argnames=("phys",))
def scrub_pages_program(phys, row, eidx):
    """Quarantine decontamination (paged pools): zero the physical pages
    listed in `row` — a fixed-length id vector holding the quarantined
    slot's exclusively-owned pages padded with the trash page, which is
    therefore ALWAYS scrubbed too (the poisoned slot's masked overshoot
    writes land there, and a non-finite trash page would leak into every
    slot's masked gather tail). Duplicate ids are idempotent zero
    writes. Shared (refcount > 1) pages are excluded by the caller: they
    hold prompt-prefix KV written strictly before the poisoned step and
    other holders still read them."""
    if isinstance(phys, QuantStore):
        exact = phys.exact
        if exact is not None:
            exact = jax.tree_util.tree_map(
                lambda a: _zero_batch_entry(a, eidx), exact
            )
        return phys.replace(
            q=jax.tree_util.tree_map(lambda a: a.at[row].set(0), phys.q),
            scale=jax.tree_util.tree_map(
                lambda a: a.at[row].set(0), phys.scale),
            exact=exact,
        )
    return jax.tree_util.tree_map(lambda a: a.at[row].set(0), phys)


@functools.partial(jax.jit, donate_argnames=("caches",))
def _quant_splice_program(caches, segment, ctl):
    """Quantized splice: the segment's int8 payload lands at
    ``(ctl[0], ctl[1])`` and its scale rows at ``offset // block`` —
    cached prefixes stay quantized end to end (no dequant/requant on the
    reuse path, so the spliced bytes are bitwise the producer's)."""
    slot, offset = ctl[0], ctl[1]
    b = caches.block

    def upd(a, s, off):
        _require_same_dtype(a, s, "splice_prefix")
        starts = (slot, off) + (0,) * (a.ndim - 2)
        return jax.lax.dynamic_update_slice(a, s, starts)

    return caches.replace(
        q=jax.tree_util.tree_map(
            lambda a, s: upd(a, s, offset), caches.q, segment.q),
        scale=jax.tree_util.tree_map(
            lambda a, s: upd(a, s, offset // b), caches.scale,
            segment.scale),
    )


@functools.partial(jax.jit, static_argnames=("length",))
def _quant_extract_program(caches, ctl, length):
    """Quantized snapshot: slice lane `ctl[0]`'s int8 span plus the
    matching scale rows into an independent `QuantSegment` — the
    prefix-cache insert path at HALF the copy (and tree budget) bytes."""
    slot, offset = ctl[0], ctl[1]
    b = caches.block

    def ext(a, off, ln):
        starts = (slot, off) + (0,) * (a.ndim - 2)
        sizes = (1, ln) + a.shape[2:]
        return jax.lax.dynamic_slice(a, starts, sizes)

    return QuantSegment(
        q=jax.tree_util.tree_map(
            lambda a: ext(a, offset, length), caches.q),
        scale=jax.tree_util.tree_map(
            lambda a: ext(a, offset // b, length // b), caches.scale),
        block=b,
    )


class _SlotBook:
    """Shared slot bookkeeping for both pool layouts: a LIFO free list
    (the freshest slot is reused while its buffers / table row are
    warm) plus an O(1) membership mask — the double-release guard must
    never scan the list on the hot release path. Subclasses call
    `_init_slots` at construction and compose `_guard_release` /
    `_finish_release` around their own teardown."""

    def _init_slots(self, n_slots: int) -> None:
        if n_slots < 1:
            raise ValueError(f"need at least one slot, got {n_slots}")
        self.n_slots = n_slots
        self.positions = np.zeros(n_slots, np.int32)
        self._free = list(range(n_slots - 1, -1, -1))
        self._free_mask = np.ones(n_slots, bool)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.n_active / self.n_slots

    def acquire(self) -> int | None:
        """Claim a free slot (or None when all are taken)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self._free_mask[slot] = False
        self.positions[slot] = 0
        return slot

    def _guard_release(self, slot: int) -> None:
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        if self._free_mask[slot]:
            raise ValueError(f"slot {slot} is already free (double release)")

    def _finish_release(self, slot: int) -> None:
        self.positions[slot] = 0
        self._free.append(slot)
        self._free_mask[slot] = True


class KVSlotPool(_SlotBook):
    """`n_slots` cache lanes + free-list bookkeeping.

    `caches` is the pooled pytree (list of per-layer caches, batch dim =
    slot); the engine reassigns it after every jitted step (functional
    updates, donated buffers). `positions[slot]` is the pool's public
    per-lane fill level — how many cache slots hold real KV entries:
    prompt plus every emitted token except the newest (a sampled token's
    KV is only written when it is fed back on the next step) — for
    introspection and capacity accounting. It is deliberately distinct
    from the engine's private device-carry mirror, which also counts the
    discarded overshoot of full-block decode steps. Freed lanes reset
    to 0.
    """

    def __init__(self, model, n_slots: int, max_len: int,
                 quant: str | None = None, quant_block: int = 16,
                 exact_lanes: int = 0):
        self._init_slots(n_slots)
        self.max_len = max_len
        self.quant = quant
        self.quant_block = quant_block
        self.exact_lanes = exact_lanes if quant else 0
        if quant:
            if max_len % quant_block:
                raise ValueError(
                    f"max_len {max_len} is not a multiple of the quant "
                    f"block {quant_block} — scale rows must tile the lane"
                )
            self.caches = make_quant_store(
                model, n_slots, max_len, quant_block,
                exact_lanes=exact_lanes,
            )
        else:
            self.caches = model.init_caches(n_slots, max_len)
        # optional metrics.xla_obs.CompileRegistry (set by the engine
        # when the observatory is on): splice/extract program calls are
        # routed through it so their compilations and run seconds are
        # accounted like the engine's own programs; None = direct jit
        self.registry = None

    @property
    def nbytes(self) -> int:
        """Device bytes the pooled cache pytree holds (all lanes; for a
        quantized pool: int8 payload + scale sidecar + exact lanes) —
        the HBM ledger's kv_pool gauge."""
        from solvingpapers_tpu.metrics.xla_obs import pytree_bytes

        return pytree_bytes(self.caches)

    @property
    def token_capacity(self) -> int:
        """Cache slots the pool books (the kv_bytes_per_token gauge's
        denominator): every lane's full length."""
        return self.n_slots * self.max_len

    def release(self, slot: int) -> None:
        """Return a lane to the pool; it is immediately reusable."""
        self._guard_release(slot)
        self._finish_release(slot)

    # --------------------------------------------------- prefix segments

    def _check_quant_span(self, offset: int, length: int, op: str) -> None:
        b = self.quant_block
        if offset % b or length % b:
            raise ValueError(
                f"{op} span [{offset}, {offset + length}) is not aligned "
                f"to the quant block {b} — quantized segments carry "
                "whole scale rows (prefix pages must be block multiples)"
            )

    def splice_prefix(self, slot: int, segment, offset: int = 0) -> None:
        """Copy-on-acquire: splice a cached batch-1 prefix `segment` into
        lane `slot` at time offset `offset` (one fused jitted program; the
        lane owns the copy, so the source node may be evicted freely
        afterwards). Must run before the suffix prefill that continues at
        `offset + segment length`."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        if self.quant:
            if not isinstance(segment, QuantSegment):
                raise TypeError(
                    "a quantized pool splices QuantSegment payloads "
                    f"(int8 + scales), got {type(segment).__name__} — "
                    "the prefix cache and the pool must agree on kv_quant"
                )
            length = segment.length
        else:
            length = jax.tree_util.tree_leaves(segment)[0].shape[1]
        if offset < 0 or offset + length > self.max_len:
            raise ValueError(
                f"segment span [{offset}, {offset + length}) exceeds the "
                f"lane capacity {self.max_len}"
            )
        if self.quant:
            self._check_quant_span(offset, length, "splice_prefix")
        prog = _quant_splice_program if self.quant else _splice_program
        ctl = jnp.asarray([slot, offset], jnp.int32)
        if self.registry is not None:
            # segment layout is fixed per model (one pool, one model), so
            # the static time length is the whole varying signature
            self.caches = self.registry.call(
                "splice_program", (length,), prog,
                (self.caches, segment, ctl),
            )
        else:
            self.caches = prog(self.caches, segment, ctl)

    def extract_prefix(self, slot: int, offset: int, length: int):
        """Snapshot lane `slot`'s KV span [offset, offset+length) as an
        independent batch-1 segment (the prefix cache's insert path)."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        if offset < 0 or length < 1 or offset + length > self.max_len:
            raise ValueError(
                f"extract span [{offset}, {offset + length}) exceeds the "
                f"lane capacity {self.max_len}"
            )
        if self.quant:
            self._check_quant_span(offset, length, "extract_prefix")
        prog = _quant_extract_program if self.quant else _extract_program
        ctl = jnp.asarray([slot, offset], jnp.int32)
        if self.registry is not None:
            return self.registry.call(
                "extract_program", (length,), prog,
                (self.caches, ctl, length), static_argnums=(2,),
            )
        return prog(self.caches, ctl, length)


# ======================================================================
# Paged pool: block-paged cache lanes + refcounted zero-copy sharing
# ======================================================================
#
# The lane pool above books `max_len` cache slots per engine slot — HBM
# reserved for the worst case, slot count coupled to max_seq, and every
# prefix hit paying a device copy (splice). `PagedKVPool` is the vLLM
# PagedAttention layout instead: ONE physical pool of fixed-size KV
# blocks ("pages"), carved from `model.init_caches(n_pages, page_size)`
# so the batch dimension IS the page id, plus a host-side per-slot page
# table mapping logical page index -> physical page id. The jitted
# prefill/decode programs translate logical->physical with a gather
# (`gather_lanes`) that materializes the familiar (S, max_len, ...)
# lane view, run the models UNMODIFIED on it, and scatter only the
# written page(s) back — so all four decoder families serve paged with
# zero model changes, and the page table rides the engine's existing
# packed control-array transfer.
#
# Sharing: the radix prefix cache holds PHYSICAL PAGE IDS with
# refcounts instead of snapshot copies (the SGLang RadixAttention
# move). A prefix hit is a host-side page-table append + incref — zero
# device copies — and inserting a freshly prefilled prompt is an incref
# of the slot's own fully-filled pages. This is sound because cached
# pages are never rewritten by their producer: the engine only caches
# prompt positions [0, aligned) with aligned <= len(prompt)-1
# page-aligned, and the owning slot's future writes land at positions
# >= len(prompt), i.e. in pages strictly AFTER every cached one; decode
# scatters exactly the one page containing the written position, and
# prefill scatters only pages >= the (page-aligned) match length. So a
# shared page is immutable for as long as anything references it — no
# copy-on-write machinery needed.
#
# Page 0 is a reserved TRASH page, never allocated: page-table entries
# beyond a slot's allocation (and every entry of an idle slot) point at
# it, so gathers always read valid (finite, masked-away) memory and
# masked dummy writes / discarded overshoot land harmlessly there. The
# stale-data contract is the lane pool's, per page: freed pages are not
# zeroed, reuse is safe because prefill/decode overwrite before any
# attention and position masking annihilates slack beyond the fill.


def gather_lanes(phys, table):
    """Logical lane view of the physical pool (traced): `table` is the
    (S, pages_per_lane) page-table block; returns the (S, max_len, ...)
    pytree the lane-pool programs operate on. One gather per leaf — the
    logical->physical translation the paged programs do up front."""

    def g(leaf):
        pages = leaf[table]  # (S, PPL, page, ...)
        s, ppl, page = pages.shape[:3]
        return pages.reshape((s, ppl * page) + leaf.shape[2:])

    return jax.tree_util.tree_map(g, phys)


def gather_lane(phys, row):
    """Batch-1 lane view for one slot: `row` is its (pages_per_lane,)
    page-table row (traced)."""

    def g(leaf):
        pages = leaf[row]  # (PPL, page, ...)
        ppl, page = pages.shape[:2]
        return pages.reshape((1, ppl * page) + leaf.shape[2:])

    return jax.tree_util.tree_map(g, phys)


def scatter_lane_pages(phys, lane, row, start_page: int):
    """Write a batch-1 lane's pages [start_page:] back to the pool at
    `row[start_page:]` (traced; `start_page` static). The pages BELOW
    `start_page` are deliberately untouched — on a prefix hit they are
    shared, refcounted pages the prefill never wrote, and not rewriting
    them is what makes the hit zero-copy. Unallocated tail entries point
    at the trash page, so their (unchanged, garbage) lane pages land
    there; duplicate trash indices are benign (.at[].set last-writer)."""
    ppl = row.shape[0]
    ids = row[start_page:]

    def sc(p_leaf, lane_leaf):
        page = p_leaf.shape[1]
        pages = lane_leaf.reshape((ppl, page) + lane_leaf.shape[2:])
        return p_leaf.at[ids].set(pages[start_page:])

    return jax.tree_util.tree_map(sc, phys, lane)


def scatter_written_pages(phys, lanes, table, pos):
    """Per-slot single-page write-back for one decode step (traced):
    slot s wrote exactly one token at position `pos[s]`, so exactly one
    page — index pos[s] // page — of its gathered lane changed. Gather
    that page per slot and scatter the batch to the physical ids. Active
    slots' write pages are exclusively owned (see the module comment:
    shared pages always precede the write frontier), so the batched
    scatter indices never collide except on the trash page, where
    garbage overwriting garbage is fine."""
    ppl = table.shape[1]

    def sc(p_leaf, lane_leaf):
        page = p_leaf.shape[1]
        pg = jnp.clip(pos.astype(jnp.int32) // page, 0, ppl - 1)
        ids = jnp.take_along_axis(table, pg[:, None], axis=1)[:, 0]
        pages = jax.vmap(
            lambda lane, i: jax.lax.dynamic_slice_in_dim(
                lane, i * page, page, axis=0
            )
        )(lane_leaf, pg)
        return p_leaf.at[ids].set(pages)

    return jax.tree_util.tree_map(sc, phys, lanes)


def pad_time(tree, extra: int):
    """Append `extra` zeroed slots along the TIME axis (axis 1) of every
    cache leaf (traced). The speculative decode programs (serve/engine.py)
    pad each lane with ``spec_k + 1`` scratch slots before their
    draft-verify rounds: a chunk write at time offset p spans
    ``[p, p + k]``, and XLA's `dynamic_update_slice` CLAMPS an
    out-of-range start — which would SHIFT the whole chunk left and
    silently overwrite committed KV. With the scratch tail, every chunk
    whose start is inside the real lane fits, and overshoot (post-EOS /
    post-budget rounds, frozen at the lane end) lands in slack that
    `strip_time` drops before the lanes go back to the pool."""
    return jax.tree_util.tree_map(
        lambda a: jnp.concatenate(
            [a, jnp.zeros((a.shape[0], extra) + a.shape[2:], a.dtype)],
            axis=1,
        ),
        tree,
    )


def strip_time(tree, extra: int):
    """Drop the trailing `extra` time slots `pad_time` appended (traced)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.slice_in_dim(a, 0, a.shape[1] - extra, axis=1),
        tree,
    )


def scatter_window_pages(phys, lanes, table, start, last, span: int):
    """Scatter each slot's written page window back to the pool (traced):
    slot s wrote positions ``[start[s], last[s]]`` of its gathered lane
    view — the speculative decode block's ACCEPTED window (``last`` is
    the final committed position; rejected-draft garbage beyond it never
    reaches the physical pool, so a paged spec engine's pool holds only
    committed KV). `span` is the static per-slot window bound in tokens
    (rounds x chunk width for the spec block); the page walk advances in
    page-size steps clamped to ``last``, so trailing windows re-write the
    last committed page with its own final content — idempotent. Slots
    with nothing committed (``last < start``, inactive lanes) clamp to
    `start`, whose table entry rests at the trash page."""
    page = jax.tree_util.tree_leaves(phys)[0].shape[1]
    limit = table.shape[1] * page - 1
    last = jnp.maximum(last, start)
    for w in range((span - 1) // page + 2):
        pos_w = jnp.clip(jnp.minimum(start + w * page, last), 0, limit)
        phys = scatter_written_pages(phys, lanes, table, pos_w)
    return phys


TRASH_PAGE = 0  # physical page 0: reserved write sink, never allocated


class PagedKVPool(_SlotBook):
    """Block-paged KV pool: `page_budget` allocatable fixed-size pages +
    per-slot page tables + refcounts (host-side bookkeeping; the traced
    side is the gather/scatter helpers above).

    `phys` is the physical pytree — `model.init_caches(page_budget + 1,
    page_size)`, batch dim = page id, page 0 the trash page — and is
    NEVER reallocated: `nbytes` is constant for the pool's lifetime,
    which is the point (HBM booked once, up front, independent of slot
    count and max_seq). `table` is the (n_slots, pages_per_lane) int32
    page-table mirror shipped to the device inside the engine's packed
    control arrays; entries [0, n_alloc[slot]) are live (refcounted),
    the rest rest at the trash page.

    Refcount protocol: an owned page (fresh `ensure` allocation) starts
    at 1; every additional holder — a slot appending shared prefix pages
    (`append_shared`) or the radix tree taking a reference
    (`share_range`) — increfs; `release`/`decref` decrement and a page
    returns to the free list at zero. The tree and the slots are
    symmetric holders: either can outlive the other.

    `positions[slot]` keeps the lane pool's fill-level semantics (prompt
    + emitted - newest), for introspection and the fragmentation gauge.
    """

    def __init__(self, model, n_slots: int, max_len: int, page_size: int,
                 page_budget: int | None = None, quant: str | None = None,
                 exact_lanes: int = 0):
        self._init_slots(n_slots)
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if max_len % page_size:
            raise ValueError(
                f"max_len {max_len} is not a multiple of page_size "
                f"{page_size} — page tables need whole pages per lane"
            )
        self.max_len = max_len
        self.page_size = page_size
        self.pages_per_lane = max_len // page_size
        self.quant = quant
        self.quant_block = page_size  # one scale row per (page, head)
        self.exact_lanes = exact_lanes if quant else 0
        if page_budget is None:
            # lane-pool-equivalent capacity: every slot can hold a full
            # lane at once (callers shrink it to trade worst-case room
            # for more slots — that is the capacity win)
            page_budget = n_slots * self.pages_per_lane
        if page_budget < self.pages_per_lane:
            raise ValueError(
                f"page_budget {page_budget} cannot cover even one full "
                f"lane ({self.pages_per_lane} pages) — a single max-length "
                "request could never be scheduled"
            )
        self.page_budget = page_budget
        self.n_pages = page_budget + 1  # + the trash page
        if quant:
            # exact lanes are LANE-shaped (max_len): a kv_exact stream
            # never allocates pages at all — its table rests at trash
            # and its KV lives wholly in the full-precision sidecar
            self.phys = make_quant_store(
                model, self.n_pages, page_size, page_size,
                exact_lanes=exact_lanes, exact_time=max_len,
            )
        else:
            self.phys = model.init_caches(self.n_pages, page_size)
        self.table = np.full((n_slots, self.pages_per_lane), TRASH_PAGE,
                             np.int32)
        self.n_alloc = np.zeros(n_slots, np.int32)
        self.refcount = np.zeros(self.n_pages, np.int32)
        self.refcount[TRASH_PAGE] = 1  # permanently held, never freed
        # LIFO free list: recently-freed pages are reused warm
        self._free_pages = list(range(self.n_pages - 1, TRASH_PAGE, -1))

    # ------------------------------------------------------------ gauges

    @property
    def nbytes(self) -> int:
        """Device bytes of the physical pool — CONSTANT by construction
        (the pool never grows or shrinks); the HBM ledger's kv_pool
        gauge."""
        from solvingpapers_tpu.metrics.xla_obs import pytree_bytes

        return pytree_bytes(self.phys)

    @property
    def page_nbytes(self) -> int:
        """Device bytes one page holds across every cache leaf (for a
        quantized pool: int8 payload + its scale rows, excluding the
        exact-lane sidecar, which no page reference pins) — what a
        radix-tree page reference costs in the prefix cache's budget."""
        if self.quant:
            pool_bytes, _, _, _ = quant_pool_bytes(self.phys)
            return pool_bytes // self.n_pages
        return self.nbytes // self.n_pages

    @property
    def token_capacity(self) -> int:
        """Allocatable cache slots (the kv_bytes_per_token gauge's
        denominator): every budgeted page, trash excluded."""
        return self.page_budget * self.page_size

    @property
    def pages_free(self) -> int:
        return len(self._free_pages)

    @property
    def pages_active(self) -> int:
        return self.page_budget - len(self._free_pages)

    @property
    def fragmentation(self) -> float:
        """Internal fragmentation: the fraction of slot-allocated page
        capacity not (yet) holding live KV — decode reservations and
        trailing-page slack. 0.0 with nothing allocated; paged pools
        have no EXTERNAL fragmentation (any free page serves any slot),
        which is the property the gauge exists to contrast with the
        lane pool's whole-lane booking."""
        alloc_tokens = int(self.n_alloc.sum()) * self.page_size
        if alloc_tokens == 0:
            return 0.0
        used = int(np.minimum(self.positions,
                              self.n_alloc * self.page_size).sum())
        return 1.0 - used / alloc_tokens

    # ------------------------------------------------------------- slots
    #
    # acquire() is the shared _SlotBook one; pages are NOT reserved at
    # acquire — `append_shared`/`ensure` populate the table as the
    # request's footprint becomes known.

    def release(self, slot: int) -> None:
        """Free a slot: decref every table entry it holds (owned pages
        free immediately; shared ones survive under their other
        holders), park the row at the trash page."""
        self._guard_release(slot)
        n = int(self.n_alloc[slot])
        self.decref(self.table[slot, :n].tolist())
        self.table[slot, :n] = TRASH_PAGE
        self.n_alloc[slot] = 0
        self._finish_release(slot)

    # ------------------------------------------------------------- pages

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to cover token positions [0, n_tokens)."""
        return -(-n_tokens // self.page_size)

    def ensure(self, slot: int, n_tokens: int) -> bool:
        """Grow `slot`'s table to cover positions [0, n_tokens) with
        freshly-owned pages. False when the free list runs dry — the
        allocation KEEPS what it got (the pages stay booked to the slot;
        the caller reclaims — prefix-tree eviction, then preemption —
        and retries). Shared prefix pages must already be appended:
        `ensure` only ever extends the tail."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        target = self.pages_for(min(n_tokens, self.max_len))
        if target > self.pages_per_lane:
            raise ValueError(
                f"coverage of {n_tokens} tokens exceeds the lane capacity "
                f"{self.max_len}"
            )
        while int(self.n_alloc[slot]) < target:
            if not self._free_pages:
                return False
            pid = self._free_pages.pop()
            self.refcount[pid] = 1
            self.table[slot, self.n_alloc[slot]] = pid
            self.n_alloc[slot] += 1
        return True

    def append_shared(self, slot: int, page_ids) -> None:
        """Zero-copy prefix hit: extend `slot`'s page table with already-
        populated shared pages (incref'd — the radix tree keeps its own
        references). Must precede any `ensure` for the slot: shared
        prefix pages are logically the lane's leading pages."""
        if not page_ids:
            return
        n = int(self.n_alloc[slot])
        if n + len(page_ids) > self.pages_per_lane:
            raise ValueError(
                f"shared append of {len(page_ids)} pages at table offset "
                f"{n} exceeds the lane capacity {self.pages_per_lane}"
            )
        for pid in page_ids:
            if not TRASH_PAGE < pid < self.n_pages:
                raise ValueError(f"page id {pid} out of range")
        self.incref(page_ids)
        self.table[slot, n:n + len(page_ids)] = page_ids
        self.n_alloc[slot] += len(page_ids)

    def share_range(self, slot: int, offset: int, length: int) -> list[int]:
        """Take references on the pages covering `slot`'s token span
        [offset, offset + length) — the prefix cache's insert path
        (page-aligned span; the lane-pool `extract_prefix` analogue,
        minus the device copy). The returned ids are INCREF'D: the
        caller owns one reference per page and must `decref` to drop
        them (the radix tree does, on eviction)."""
        if offset % self.page_size or length % self.page_size:
            raise ValueError(
                f"share span [{offset}, {offset + length}) is not "
                f"page-aligned (page_size {self.page_size})"
            )
        if length < 1:
            raise ValueError(f"length must be >= 1, got {length}")
        first = offset // self.page_size
        last = (offset + length) // self.page_size
        if last > int(self.n_alloc[slot]):
            raise ValueError(
                f"share span [{offset}, {offset + length}) exceeds slot "
                f"{slot}'s allocated coverage "
                f"{int(self.n_alloc[slot]) * self.page_size}"
            )
        ids = self.table[slot, first:last].tolist()
        self.incref(ids)
        return ids

    def incref(self, page_ids) -> None:
        """Take one reference per id (the single bump path —
        `append_shared`/`share_range` route through it). Per-element on
        purpose: a numpy fancy-index `+= 1` silently under-counts
        duplicate ids."""
        for pid in page_ids:
            if self.refcount[pid] < 1:
                raise ValueError(f"page {pid} is free — cannot incref")
        for pid in page_ids:
            self.refcount[pid] += 1

    def decref(self, page_ids) -> None:
        """Drop one reference per id; pages hitting zero return to the
        free list (LIFO). Over-release raises — a negative refcount
        means a page was freed while someone still held it, the exact
        bug the counts exist to make loud."""
        for pid in page_ids:
            if pid == TRASH_PAGE:
                raise ValueError("the trash page is never released")
            if self.refcount[pid] < 1:
                raise ValueError(
                    f"page {pid} over-released (refcount already 0)"
                )
            self.refcount[pid] -= 1
            if self.refcount[pid] == 0:
                self._free_pages.append(pid)
