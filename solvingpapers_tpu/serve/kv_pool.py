"""Fixed-size pool of per-slot KV/latent cache lanes.

Continuous batching needs slot-granular cache reuse: when one sequence
finishes, its cache storage must be handed to the next queued request
immediately, without waiting for the rest of the batch (the vLLM
PagedAttention insight, applied at lane granularity — one lane per slot
rather than paged blocks, because the repo's caches are preallocated
static-shape pytrees and XLA wants the batch dimension fixed).

The pool is carved out of the existing cache machinery unchanged: the
pooled pytrees come from ``model.init_caches(n_slots, max_len)``
(`infer/cache.py` KVCache / LatentCache — any family works), so the batch
dimension IS the slot dimension. Lane extraction/insertion are pytree
``dynamic_slice`` helpers meant to be traced inside the engine's jitted
programs (`serve/engine.py`); acquire/release bookkeeping is host-side.

Stale-data contract: a freed lane is NOT zeroed. Reuse is safe because
(a) prefill overwrites slots ``[0, P)`` of the lane before any attention
over it, and (b) decode masks with ``kv_index <= position`` (the cache
masking contract of `infer/cache.py`), so slots beyond the current length
never contribute — and every stale value is finite (written by a real
forward), so masked-softmax zeros annihilate it exactly.
"""

from __future__ import annotations

import jax
import numpy as np


def extract_lane(caches, slot):
    """Slice slot `slot`'s batch-1 lane out of pooled caches (traced)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=0), caches
    )


def store_lane(caches, lane, slot):
    """Write a batch-1 lane back into the pooled caches at `slot` (traced)."""
    return jax.tree_util.tree_map(
        lambda a, l: jax.lax.dynamic_update_slice_in_dim(
            a, l.astype(a.dtype), slot, axis=0
        ),
        caches,
        lane,
    )


class KVSlotPool:
    """`n_slots` cache lanes + free-list bookkeeping.

    `caches` is the pooled pytree (list of per-layer caches, batch dim =
    slot); the engine reassigns it after every jitted step (functional
    updates, donated buffers). `positions[slot]` is the pool's public
    per-lane fill level — how many cache slots hold real KV entries:
    prompt plus every emitted token except the newest (a sampled token's
    KV is only written when it is fed back on the next step) — for
    introspection and capacity accounting. It is deliberately distinct
    from the engine's private device-carry mirror, which also counts the
    discarded overshoot of full-block decode steps. Freed lanes reset
    to 0.
    """

    def __init__(self, model, n_slots: int, max_len: int):
        if n_slots < 1:
            raise ValueError(f"need at least one slot, got {n_slots}")
        self.n_slots = n_slots
        self.max_len = max_len
        self.caches = model.init_caches(n_slots, max_len)
        self.positions = np.zeros(n_slots, np.int32)
        # LIFO free list, seeded so acquire() hands out slot 0 first —
        # recently-freed lanes are reused while their buffers are warm
        self._free = list(range(n_slots - 1, -1, -1))

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.n_active / self.n_slots

    def acquire(self) -> int | None:
        """Claim a free lane (or None when the pool is exhausted)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self.positions[slot] = 0
        return slot

    def release(self, slot: int) -> None:
        """Return a lane to the pool; it is immediately reusable."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double release)")
        self.positions[slot] = 0
        self._free.append(slot)
