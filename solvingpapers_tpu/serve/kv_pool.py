"""Fixed-size pool of per-slot KV/latent cache lanes.

Continuous batching needs slot-granular cache reuse: when one sequence
finishes, its cache storage must be handed to the next queued request
immediately, without waiting for the rest of the batch (the vLLM
PagedAttention insight, applied at lane granularity — one lane per slot
rather than paged blocks, because the repo's caches are preallocated
static-shape pytrees and XLA wants the batch dimension fixed).

The pool is carved out of the existing cache machinery unchanged: the
pooled pytrees come from ``model.init_caches(n_slots, max_len)``
(`infer/cache.py` KVCache / LatentCache — any family works), so the batch
dimension IS the slot dimension. Lane extraction/insertion are pytree
``dynamic_slice`` helpers meant to be traced inside the engine's jitted
programs (`serve/engine.py`); acquire/release bookkeeping is host-side.

Stale-data contract: a freed lane is NOT zeroed. Reuse is safe because
(a) prefill overwrites slots ``[0, P)`` of the lane before any attention
over it, and (b) decode masks with ``kv_index <= position`` (the cache
masking contract of `infer/cache.py`), so slots beyond the current length
never contribute — and every stale value is finite (written by a real
forward), so masked-softmax zeros annihilate it exactly.

Prefix reuse (`serve/prefix_cache.py`): `splice_prefix` copies a cached
batch-1 KV segment into a lane's leading slots before the suffix prefill
(copy-on-acquire — the lane owns its copy, so tree eviction can never
corrupt an in-flight stream), and `extract_prefix` snapshots a freshly
prefilled prompt span back out for the radix tree to keep.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _require_same_dtype(pool_leaf, seg_leaf, op: str) -> None:
    """Lane/segment writes never cast: a silent `astype` would down-cast
    an fp32 segment into a bf16 pool (or vice versa) and quietly change
    every stream decoded over it. Trace-time error instead — the caller
    casts explicitly if a conversion is really intended."""
    if seg_leaf.dtype != pool_leaf.dtype:
        raise TypeError(
            f"{op}: segment dtype {seg_leaf.dtype} != pool dtype "
            f"{pool_leaf.dtype}; implicit casts are not performed (a "
            "silent astype would corrupt precision) — cast explicitly "
            "before the write"
        )


def extract_lane(caches, slot):
    """Slice slot `slot`'s batch-1 lane out of pooled caches (traced)."""
    return jax.tree_util.tree_map(
        lambda a: jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=0), caches
    )


def store_lane(caches, lane, slot):
    """Write a batch-1 lane back into the pooled caches at `slot` (traced).
    Dtypes must match exactly — see `_require_same_dtype`."""

    def upd(a, lane_leaf):
        _require_same_dtype(a, lane_leaf, "store_lane")
        return jax.lax.dynamic_update_slice_in_dim(a, lane_leaf, slot, axis=0)

    return jax.tree_util.tree_map(upd, caches, lane)


@functools.partial(jax.jit, donate_argnames=("caches",))
def _splice_program(caches, segment, ctl):
    """Copy-on-acquire: write a batch-1 prefix `segment` (time length L,
    static per compiled program) into lane `ctl[0]` at time offset
    `ctl[1]`. One fused program — every layer's `dynamic_update_slice`
    lands in a single dispatch, and donation reuses the pool's buffers.
    Program inventory is bounded because segment lengths are multiples of
    the prefix cache's page size."""
    slot, offset = ctl[0], ctl[1]

    def upd(a, s):
        _require_same_dtype(a, s, "splice_prefix")
        starts = (slot, offset) + (0,) * (a.ndim - 2)
        return jax.lax.dynamic_update_slice(a, s, starts)

    return jax.tree_util.tree_map(upd, caches, segment)


@functools.partial(jax.jit, static_argnames=("length",))
def _extract_program(caches, ctl, length):
    """Snapshot lane `ctl[0]`'s time span [ctl[1], ctl[1]+length) as a
    batch-1 segment pytree (a COPY — the lane can be overwritten or
    released without invalidating it)."""
    slot, offset = ctl[0], ctl[1]

    def ext(a):
        starts = (slot, offset) + (0,) * (a.ndim - 2)
        sizes = (1, length) + a.shape[2:]
        return jax.lax.dynamic_slice(a, starts, sizes)

    return jax.tree_util.tree_map(ext, caches)


class KVSlotPool:
    """`n_slots` cache lanes + free-list bookkeeping.

    `caches` is the pooled pytree (list of per-layer caches, batch dim =
    slot); the engine reassigns it after every jitted step (functional
    updates, donated buffers). `positions[slot]` is the pool's public
    per-lane fill level — how many cache slots hold real KV entries:
    prompt plus every emitted token except the newest (a sampled token's
    KV is only written when it is fed back on the next step) — for
    introspection and capacity accounting. It is deliberately distinct
    from the engine's private device-carry mirror, which also counts the
    discarded overshoot of full-block decode steps. Freed lanes reset
    to 0.
    """

    def __init__(self, model, n_slots: int, max_len: int):
        if n_slots < 1:
            raise ValueError(f"need at least one slot, got {n_slots}")
        self.n_slots = n_slots
        self.max_len = max_len
        self.caches = model.init_caches(n_slots, max_len)
        self.positions = np.zeros(n_slots, np.int32)
        # LIFO free list, seeded so acquire() hands out slot 0 first —
        # recently-freed lanes are reused while their buffers are warm
        self._free = list(range(n_slots - 1, -1, -1))
        # optional metrics.xla_obs.CompileRegistry (set by the engine
        # when the observatory is on): splice/extract program calls are
        # routed through it so their compilations and run seconds are
        # accounted like the engine's own programs; None = direct jit
        self.registry = None

    @property
    def nbytes(self) -> int:
        """Device bytes the pooled cache pytree holds (all lanes) — the
        HBM ledger's kv_pool gauge."""
        from solvingpapers_tpu.metrics.xla_obs import pytree_bytes

        return pytree_bytes(self.caches)

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.n_active / self.n_slots

    def acquire(self) -> int | None:
        """Claim a free lane (or None when the pool is exhausted)."""
        if not self._free:
            return None
        slot = self._free.pop()
        self.positions[slot] = 0
        return slot

    def release(self, slot: int) -> None:
        """Return a lane to the pool; it is immediately reusable."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double release)")
        self.positions[slot] = 0
        self._free.append(slot)

    # --------------------------------------------------- prefix segments

    def splice_prefix(self, slot: int, segment, offset: int = 0) -> None:
        """Copy-on-acquire: splice a cached batch-1 prefix `segment` into
        lane `slot` at time offset `offset` (one fused jitted program; the
        lane owns the copy, so the source node may be evicted freely
        afterwards). Must run before the suffix prefill that continues at
        `offset + segment length`."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        length = jax.tree_util.tree_leaves(segment)[0].shape[1]
        if offset < 0 or offset + length > self.max_len:
            raise ValueError(
                f"segment span [{offset}, {offset + length}) exceeds the "
                f"lane capacity {self.max_len}"
            )
        ctl = jnp.asarray([slot, offset], jnp.int32)
        if self.registry is not None:
            # segment layout is fixed per model (one pool, one model), so
            # the static time length is the whole varying signature
            self.caches = self.registry.call(
                "splice_program", (length,), _splice_program,
                (self.caches, segment, ctl),
            )
        else:
            self.caches = _splice_program(self.caches, segment, ctl)

    def extract_prefix(self, slot: int, offset: int, length: int):
        """Snapshot lane `slot`'s KV span [offset, offset+length) as an
        independent batch-1 segment (the prefix cache's insert path)."""
        if not 0 <= slot < self.n_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.n_slots})")
        if offset < 0 or length < 1 or offset + length > self.max_len:
            raise ValueError(
                f"extract span [{offset}, {offset + length}) exceeds the "
                f"lane capacity {self.max_len}"
            )
        ctl = jnp.asarray([slot, offset], jnp.int32)
        if self.registry is not None:
            return self.registry.call(
                "extract_program", (length,), _extract_program,
                (self.caches, ctl, length), static_argnums=(2,),
            )
        return _extract_program(self.caches, ctl, length)
