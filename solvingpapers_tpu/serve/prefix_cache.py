"""Host-side radix tree over token-id prefixes with device-resident KV
segments — cross-request prefix reuse for the serving engine.

Real serving traffic shares long prompt prefixes (system prompts, few-shot
templates, multi-turn chat); re-running prefill from token 0 for every
request recomputes identical KV. This module keeps a radix tree keyed by
token ids whose nodes own batch-1 KV/latent segments (the same pytree
layout `model.init_caches(1, seg_len)` would produce, sliced along the
time axis) so a new request's matched prefix can be SPLICED into its lane
(`KVSlotPool.splice_prefix`) instead of prefilled — the RadixAttention
idea (SGLang), adapted to the repo's lane-granular pool.

Exactness: cached K/V for a token at absolute position p depends only on
the token ids at positions <= p (causal attention + RoPE/learned tables
keyed by absolute position), so splicing a segment produced by an earlier
request with an identical prefix is bitwise the same computation the lane
would have run itself. The engine never caches a prompt's final token
(the suffix prefill must produce at least one logits row to sample from).

Page granularity: all edges and match lengths are multiples of `page`.
This bounds the jitted splice/extract program inventory (segment time
lengths are page multiples <= max_len) and keeps node splits aligned so a
split never has to cut a device segment at an arbitrary offset mid-walk.

Memory: with the lane pool, segments are COPIES (snapshotted out of a
lane after prefill by `extract_fn`), accounted against `max_bytes`; LRU
leaves are evicted once the budget is exceeded. `refcount` pins a
matched path while its splice is in flight — a pinned node (or any
ancestor of one; `_split` preserves the invariant) is never evicted, so
eviction under pressure cannot corrupt an active lane's stream. Lanes
own their spliced copy, so once the splice returns the pins can drop and
later evictions are irrelevant to in-flight requests.

Paged pools (`PrefixCache(pool=PagedKVPool)`): nodes hold PHYSICAL PAGE
IDS with refcounts instead of device copies — the RadixAttention sharing
model in full. `extract_fn` then returns incref'd page ids
(`PagedKVPool.share_range` — a host-side refcount bump, zero device
copies), a hit appends those ids to the acquiring slot's page table
(`append_shared`, zero copies again), splits are list splits, and
eviction decrefs (the page frees only when no slot still references it
— the tree and the slots are symmetric holders, so eviction can NEVER
corrupt an in-flight stream by construction, not just by pinning).
`max_bytes` then bounds the tree's page-reference footprint — how much
of the fixed physical pool the tree may keep pinned away from the
allocator — rather than extra HBM.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from solvingpapers_tpu.serve.kv_pool import QuantSegment


def segment_bytes(segment) -> int:
    """Device bytes held by a batch-1 segment pytree (a quantized
    segment counts its int8 payload + scale rows — roughly half a
    bf16 segment's budget charge for the same tokens)."""
    if isinstance(segment, QuantSegment):
        return segment.nbytes
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(segment)
    )


def segment_length(segment) -> int:
    """Time-axis length of a batch-1 segment pytree (axis 1 by the
    KVCache/LatentCache layout contract)."""
    if isinstance(segment, QuantSegment):
        return segment.length
    return jax.tree_util.tree_leaves(segment)[0].shape[1]


def slice_segment(segment, start: int, end: int):
    """Time-axis sub-segment [start, end) — static bounds, eager ops.
    Quantized segments slice payload and scale rows together (bounds
    are page multiples, pages are quant-block multiples)."""
    if isinstance(segment, QuantSegment):
        return segment.time_slice(start, end)
    return jax.tree_util.tree_map(lambda a: a[:, start:end], segment)


class _Segment:
    """Lane-pool node payload: an OWNED batch-1 device segment (a copy
    snapshotted out of a lane). Released by garbage collection — nothing
    else references the buffers."""

    __slots__ = ("segment",)

    def __init__(self, segment):
        self.segment = segment

    @property
    def nbytes(self) -> int:
        return segment_bytes(self.segment)

    def split(self, k: int):
        """(upper payload of tokens [0, k), lower of [k, n)) — device
        slices; both halves are independent copies of their spans."""
        n = segment_length(self.segment)
        return (_Segment(slice_segment(self.segment, 0, k)),
                _Segment(slice_segment(self.segment, k, n)))

    def release(self) -> None:
        pass  # dropping the reference frees the device buffers


class _PageRun:
    """Paged-pool node payload: a run of REFERENCED physical page ids
    (one tree-held refcount each, taken by `PagedKVPool.share_range`).
    Zero device bytes of its own — `nbytes` is the pool bytes the run
    keeps pinned, which is what the LRU budget must account."""

    __slots__ = ("pages", "pool")

    def __init__(self, pages: list, pool):
        self.pages = list(pages)
        self.pool = pool

    @property
    def nbytes(self) -> int:
        return len(self.pages) * self.pool.page_nbytes

    def split(self, k: int):
        """List split at the page boundary — no device work, no refcount
        change: the run's references are distributed, not duplicated
        (each half releases only its own ids)."""
        kp = k // self.pool.page_size
        return (_PageRun(self.pages[:kp], self.pool),
                _PageRun(self.pages[kp:], self.pool))

    def release(self) -> None:
        self.pool.decref(self.pages)
        self.pages = []


class _Node:
    """One radix edge: `tokens` (page-multiple id array) + the payload
    holding their KV (`_Segment` copy or `_PageRun` references), rooted
    at absolute prefix offset = sum of ancestor edge lengths.

    `children` is keyed by the child edge's FIRST PAGE (`tokens[:page]`
    as bytes), not its first token: matches only ever advance in whole
    pages, so the next page is the exact lookup unit — and siblings that
    diverge mid-page (different pages, same first token) can coexist,
    which single-token keys would force into collision."""

    __slots__ = ("tokens", "payload", "children", "parent", "refcount",
                 "stamp", "nbytes")

    def __init__(self, tokens: np.ndarray, payload, parent: "_Node | None"):
        self.tokens = tokens
        self.payload = payload
        self.children: dict[bytes, _Node] = {}
        self.parent = parent
        self.refcount = 0
        self.stamp = 0
        self.nbytes = 0 if payload is None else payload.nbytes

    @property
    def length(self) -> int:
        return int(self.tokens.size)

    @property
    def segment(self) -> object:
        """The device segment (lane-pool payloads) — what the engine
        splices; kept as the node's public face for that path."""
        return self.payload.segment

    @property
    def pages(self) -> list:
        """The physical page ids (paged-pool payloads) — what the engine
        appends to a hitting slot's page table."""
        return self.payload.pages


@dataclasses.dataclass
class PrefixMatch:
    """Result of `PrefixCache.match`: the root->leaf node path covering
    `length` tokens (edge lengths sum to `length`). Pin protects the path
    from EVICTION only; `nodes`' identities/segments are only valid until
    the next match/insert (either can split an edge and re-slice its
    segment) — splice immediately after matching, as the engine does."""

    nodes: list
    length: int


class PrefixCache:
    """Radix tree + LRU byte-budget eviction + refcount pinning.

    `pool=None` (lane pools): nodes own segment copies and `extract_fn`
    returns batch-1 segment pytrees. With a `PagedKVPool` bound, nodes
    hold refcounted page-id runs and `extract_fn` must return incref'd
    page ids (the engine binds `pool.share_range`); `page` must then
    equal the pool's `page_size` so tree edges and physical pages stay
    aligned (splits never have to cut a page)."""

    def __init__(self, page: int = 16, max_bytes: int = 64 << 20,
                 trace=None, pool=None):
        if page < 1:
            raise ValueError(f"page must be >= 1, got {page}")
        if max_bytes < 0:
            raise ValueError(f"max_bytes must be >= 0, got {max_bytes}")
        if pool is not None and pool.page_size != page:
            raise ValueError(
                f"tree page {page} != pool page_size {pool.page_size}: "
                "page-id sharing needs tree edges aligned to physical "
                "pages"
            )
        self.page = page
        self.max_bytes = max_bytes
        self.pool = pool
        # optional metrics.trace.FlightRecorder (the engine's); hooks are
        # single `is not None` branches when tracing is off
        self.trace = trace
        self.root = _Node(np.zeros(0, np.int32), None, None)
        self.evictions = 0
        self.bytes_held = 0
        self._clock = 0

    # ------------------------------------------------------------ queries

    @property
    def n_nodes(self) -> int:
        return sum(1 for _ in self._walk()) - 1  # exclude root

    def stats(self) -> dict:
        """Host-cheap snapshot for /statusz and the HBM ledger: tree
        shape + byte accounting, no device reads."""
        return {
            "nodes": self.n_nodes,
            "bytes_held": self.bytes_held,
            "max_bytes": self.max_bytes,
            "evictions": self.evictions,
            "page": self.page,
        }

    def _walk(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _key(self, tokens: np.ndarray, i: int = 0) -> bytes:
        return tokens[i:i + self.page].tobytes()

    def _common(self, edge: np.ndarray, tokens: np.ndarray, i: int) -> int:
        n = min(edge.size, tokens.size - i)
        neq = np.flatnonzero(edge[:n] != tokens[i:i + n])
        return n if neq.size == 0 else int(neq[0])

    def peek(self, tokens) -> int:
        """Read-only match length (page-aligned); no LRU touch, no splits.
        What the prefix-aware scheduler calls per queued request."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        node, i = self.root, 0
        while tokens.size - i >= self.page:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            common = self._common(child.tokens, tokens, i)
            if common == child.tokens.size:
                i += common
                node = child
                continue
            i += common // self.page * self.page
            break
        return i

    def match(self, tokens, _trace: bool = True) -> PrefixMatch:
        """Longest page-aligned cached prefix of `tokens`.

        Touches the matched path's LRU stamps and splits a partially
        matched edge at the page-aligned common length, so every returned
        node is usable whole — splice `match.nodes` in order at offsets
        accumulating each node's `length`.
        """
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        node, i, path = self.root, 0, []
        while tokens.size - i >= self.page:
            child = node.children.get(self._key(tokens, i))
            if child is None:
                break
            common = self._common(child.tokens, tokens, i)
            if common == child.tokens.size:
                path.append(child)
                i += common
                node = child
                continue
            aligned = common // self.page * self.page
            if aligned > 0:
                path.append(self._split(child, aligned))
                i += aligned
            break
        stamp = self._tick()
        for nd in path:
            nd.stamp = stamp
        if self.trace is not None and _trace:
            # _trace=False on insert()'s internal re-match, so the trace's
            # lookup stream counts only real admission-time lookups
            self.trace.instant(
                "prefix_lookup", "prefix", "prefix",
                matched=i, pages=i // self.page,
                hit=int(i > 0), prompt_len=int(tokens.size),
            )
        return PrefixMatch(nodes=path, length=i)

    # ---------------------------------------------------------- mutation

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _split(self, node: _Node, k: int) -> _Node:
        """Split `node`'s edge at page-aligned k: a new upper node takes
        tokens[:k]; `node` (keeping its children, segment tail, and its
        own refcount) becomes the lower part. Returns the upper node.
        The upper needs no refcount of its own: eviction only ever takes
        CHILDLESS leaves, and the pinned lower is its child — so a pinned
        path stays eviction-safe across splits without the upper carrying
        a count that no `unpin` would ever drop."""
        assert 0 < k < node.tokens.size and k % self.page == 0
        old_bytes = node.nbytes
        up_payload, lo_payload = node.payload.split(k)
        upper = _Node(node.tokens[:k].copy(), up_payload, node.parent)
        upper.stamp = node.stamp
        node.parent.children[self._key(upper.tokens)] = upper
        node.payload = lo_payload
        node.tokens = node.tokens[k:].copy()
        node.nbytes = lo_payload.nbytes
        node.parent = upper
        upper.children[self._key(node.tokens)] = node
        self.bytes_held += upper.nbytes + node.nbytes - old_bytes
        return upper

    def pin(self, match: PrefixMatch) -> None:
        """Protect every node on the matched path from eviction until
        `unpin` — call immediately after `match`, before any other tree
        mutation can restructure the path."""
        for node in match.nodes:
            node.refcount += 1

    def unpin(self, match: PrefixMatch) -> None:
        for node in match.nodes:
            node.refcount -= 1

    def insert(self, tokens, extract_fn) -> int:
        """Cache `tokens` (length must be a page multiple); the portion
        not already in the tree is captured via ``extract_fn(offset,
        length)`` (offset/length in token positions within the prompt).
        With a lane pool that returns a snapshot segment (the engine
        binds `KVSlotPool.extract_prefix` — a device copy); with a paged
        pool it returns incref'd page ids (`PagedKVPool.share_range` —
        zero device work; only a trailing partial page would ever need a
        copy, and the engine never inserts one: insert lengths are page
        multiples by contract). Returns the number of NEW tokens cached.
        May evict LRU leaves to respect `max_bytes`.
        """
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size % self.page:
            raise ValueError(
                f"insert length {tokens.size} is not a multiple of the "
                f"page size {self.page}"
            )
        if tokens.size == 0:
            return 0
        m = self.match(tokens, _trace=False)
        rem = tokens[m.length:]
        if rem.size == 0:
            return 0
        parent = m.nodes[-1] if m.nodes else self.root
        # page-keyed children make a collision structurally impossible: an
        # existing child with rem's first page would have matched (and the
        # match advanced past it). Defensive first-come-wins regardless —
        # overwriting would orphan a subtree and leak its byte accounting.
        if self._key(rem) in parent.children:
            return 0
        raw = extract_fn(m.length, int(rem.size))
        payload = (_PageRun(raw, self.pool) if self.pool is not None
                   else _Segment(raw))
        node = _Node(rem.copy(), payload, parent)
        node.stamp = self._tick()
        parent.children[self._key(rem)] = node
        self.bytes_held += node.nbytes
        if self.trace is not None:
            self.trace.instant(
                "prefix_snapshot", "prefix", "prefix",
                new_tokens=int(rem.size), pages=int(rem.size) // self.page,
                nbytes=node.nbytes, held=self.bytes_held,
            )
        self._evict_to_budget()
        return int(rem.size)

    def evict_one(self) -> bool:
        """Evict the LRU unpinned childless leaf unconditionally (the
        paged engine's page-pressure reclaim: shedding tree references
        is always preferable to preempting a live request). False when
        everything left is pinned or interior — the tree cannot help."""
        victim = None
        for node in self._walk():
            if node is self.root or node.children or node.refcount > 0:
                continue
            if victim is None or node.stamp < victim.stamp:
                victim = node
        if victim is None:
            return False
        del victim.parent.children[self._key(victim.tokens)]
        self.bytes_held -= victim.nbytes
        self.evictions += 1
        freed = victim.nbytes
        victim.payload.release()
        if self.trace is not None:
            self.trace.instant(
                "prefix_evict", "prefix", "prefix",
                tokens=victim.length, freed=freed,
                held=self.bytes_held,
            )
        return True

    def _evict_to_budget(self) -> None:
        """Drop LRU unpinned leaves until under budget. Interior nodes
        become evictable once their children go; pinned nodes never do."""
        while self.bytes_held > self.max_bytes:
            if not self.evict_one():
                return  # everything left is pinned or interior
