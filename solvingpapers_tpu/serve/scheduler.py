"""Request lifecycle + FIFO admission/interleaving policy (Orca-style
iteration-level scheduling).

A `Request` is the unit of work the engine tracks from submit to finish;
the `FIFOScheduler` decides, once per engine iteration, which waiting
requests get prefilled into free slots. Policy knobs:

* admission control — the waiting queue is bounded (`max_waiting`);
  submissions beyond it are rejected up front instead of growing an
  unbounded backlog (the engine surfaces this as `state == "rejected"`).
* decode priority (default) — while any slot is decoding, at most
  `max_prefills_per_step` waiting requests are admitted per iteration, so
  a burst of arrivals cannot stall in-flight token streams behind a wall
  of prefills. With the pool idle, prefill fills every free slot at once.
* waiting budget — a request queued for more than `max_wait_steps`
  engine iterations overrides decode priority: the scheduler admits up to
  all free slots that iteration, bounding starvation under sustained
  decode load.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque

import numpy as np

from solvingpapers_tpu.serve import metrics as smetrics

_ids = itertools.count()

WAITING = "waiting"
ACTIVE = "active"
FINISHED = "finished"
REJECTED = "rejected"


@dataclasses.dataclass
class Request:
    """One generation request and its evolving state.

    `tokens` is the output stream: generated ids appended as the engine
    produces them, ending with the request's `eos_id` when it stopped on
    EOS (`finish_reason == "eos"`) or after `max_new_tokens` ids
    (`finish_reason == "length"`).
    """

    prompt: np.ndarray
    max_new_tokens: int
    eos_id: int | None
    id: int = dataclasses.field(default_factory=lambda: next(_ids))
    state: str = WAITING
    tokens: list[int] = dataclasses.field(default_factory=list)
    finish_reason: str | None = None
    slot: int | None = None
    waited_steps: int = 0
    # late-bound so every engine timestamp shares one clock domain with
    # serve.metrics.now (patchable in tests/simulation)
    submit_time: float = dataclasses.field(
        default_factory=lambda: smetrics.now()
    )
    admit_time: float | None = None
    first_token_time: float | None = None
    finish_time: float | None = None

    @property
    def done(self) -> bool:
        return self.state == FINISHED

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)


class FIFOScheduler:
    """Bounded FIFO queue with decode-priority prefill interleaving."""

    def __init__(
        self,
        max_waiting: int = 256,
        decode_priority: bool = True,
        max_prefills_per_step: int = 1,
        max_wait_steps: int = 64,
    ):
        self.max_waiting = max_waiting
        self.decode_priority = decode_priority
        self.max_prefills_per_step = max(1, max_prefills_per_step)
        self.max_wait_steps = max_wait_steps
        self.queue: deque[Request] = deque()

    def __len__(self) -> int:
        return len(self.queue)

    def submit(self, req: Request) -> bool:
        """Enqueue, or reject when the waiting queue is at capacity."""
        if len(self.queue) >= self.max_waiting:
            req.state = REJECTED
            return False
        self.queue.append(req)
        return True

    def pick(self, n_free: int, n_active: int) -> list[Request]:
        """Pop the requests to prefill this iteration (FIFO order)."""
        if not self.queue or n_free == 0:
            return []
        budget = n_free
        if (
            self.decode_priority
            and n_active > 0
            and self.queue[0].waited_steps <= self.max_wait_steps
        ):
            budget = self.max_prefills_per_step
        picked = []
        while self.queue and len(picked) < min(budget, n_free):
            picked.append(self.queue.popleft())
        return picked

    def tick(self) -> None:
        """One engine iteration elapsed for everything still queued."""
        for req in self.queue:
            req.waited_steps += 1
