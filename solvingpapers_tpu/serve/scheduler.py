"""Request lifecycle + FIFO admission/interleaving policy (Orca-style
iteration-level scheduling).

A `Request` is the unit of work the engine tracks from submit to finish;
the `FIFOScheduler` decides, once per engine iteration, which waiting
requests get prefilled into free slots. Policy knobs:

* admission control — the waiting queue is bounded (`max_waiting`);
  submissions beyond it are rejected up front instead of growing an
  unbounded backlog (the engine surfaces this as `state == "rejected"`).
* decode priority (default) — while any slot is decoding, at most
  `max_prefills_per_step` waiting requests are admitted per iteration, so
  a burst of arrivals cannot stall in-flight token streams behind a wall
  of prefills. With the pool idle, prefill fills every free slot at once.
* waiting budget — a request queued for more than `max_wait_steps`
  engine iterations overrides decode priority: the scheduler admits up to
  all free slots that iteration, bounding starvation under sustained
  decode load.
* prefix awareness (`prefer_cached`, off by default) — with a
  `prefix_lookup` bound (the engine wires `ServeEngine._match_len`), the
  scheduler looks up each waiting request's cached-prefix match length
  and admits shortest-uncovered-suffix first (cheapest prefills =
  fastest TTFT under load, and hot prefixes stay hot). Requests past the
  wait budget still go first, in FIFO order — the anti-starvation
  guarantee is unchanged.
* page-budget gate (`can_admit`, bound by the paged engine) — with a
  `PagedKVPool`, a free SLOT is no longer a sufficient admission
  condition: the request also needs free PAGES for its prompt plus a
  decode reservation. `pick` stops at the first candidate the gate
  rejects (head-of-line blocking is deliberate: admitting a shorter
  request past a page-starved head would starve long prompts forever),
  and `requeue_front` returns a preempted request to the head of the
  queue so its recompute runs as soon as pages free up.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque

import numpy as np

from solvingpapers_tpu.serve import metrics as smetrics
from solvingpapers_tpu.serve.sampling import GREEDY, SamplingParams

_ids = itertools.count()

WAITING = "waiting"
ACTIVE = "active"
FINISHED = "finished"
REJECTED = "rejected"


@dataclasses.dataclass(eq=False)
class Request:
    """One generation request and its evolving state. Identity semantics
    (eq=False): a request is the one object the engine threads from
    submit to finish — the generated `==` would compare numpy prompts
    elementwise and raise on mixed lengths (e.g. inside deque.remove).

    `tokens` is the output stream: generated ids appended as the engine
    produces them. `finish_reason` says why it ended:

        eos        the request's `eos_id` was emitted (kept in the stream)
        length     `max_new_tokens` (or `params.max_tokens`) ids emitted
        stop       a `params.stop_token_ids` id or `params.stop` string
                   matched (the matching token is kept in the stream)
        cancelled  `engine.cancel(request)` — a waiting request finishes
                   immediately, an active one at the next block boundary
        timeout    the request's deadline passed (waiting requests are
                   purged from the queue; active ones freed at the next
                   block boundary, the expired block's tokens discarded)

    `params` is the request's `SamplingParams`; `logprobs` streams the
    chosen-token logprob per generated token when `params.logprobs`.
    """

    prompt: np.ndarray
    max_new_tokens: int
    eos_id: int | None
    params: SamplingParams = GREEDY
    id: int = dataclasses.field(default_factory=lambda: next(_ids))
    state: str = WAITING
    tokens: list[int] = dataclasses.field(default_factory=list)
    logprobs: list[float] = dataclasses.field(default_factory=list)
    finish_reason: str | None = None
    slot: int | None = None
    # accumulated wait, in BLOCK-EQUIVALENTS of delivered tokens (see
    # tick): a plain engine iteration ages it by 1; a speculative step
    # that committed w blocks' worth of tokens ages it by w
    waited_steps: float = 0.0
    # absolute deadline on the engine clock (serve.metrics.now), or None
    deadline: float | None = None
    # set by engine.cancel on an ACTIVE request; the lane is freed (and
    # the request finished "cancelled") at the next block boundary
    cancelled: bool = False
    # why a REJECTED request bounced beyond a full queue: "unhealthy"
    # (the engine is draining after persistent failures) or
    # "shed:<class>" (the degradation ladder load-shed its SLO class) —
    # the HTTP front door words its 503 envelope from this
    reject_reason: str | None = None
    # grammar-constrained decoding (serve/grammar.py JsonStepper or any
    # object with allowed(budget)/advance(tok)/done): the engine packs
    # its allowed-token list into the jitted programs' allow-mask and
    # finishes the stream ("stop") when the grammar accepts. One stepper
    # per request — it is stateful and advances with the stream.
    grammar: object | None = None
    # streaming hook: called on the ENGINE thread as
    # ``stream_cb(request, n_new_tokens, finished)`` after every token
    # append and once at finish (n_new may be 0 for a cancel/timeout
    # boundary). Must be cheap and non-blocking — the HTTP front door
    # (serve/api.py) pushes a count into a bounded per-connection queue
    # and does all I/O on its own handler thread.
    stream_cb: object | None = None
    # memoized cached-prefix match length for prefix-aware scheduling:
    # computed once at first pick() (a per-request tree walk per iteration
    # would burden the dispatch-bound host loop). Slightly stale by design
    # — it only orders admission; the engine re-matches at admit time.
    prefix_hint: int | None = None
    # client-facing trace identity (the HTTP front door's X-Request-Id,
    # honored or minted): joins the wire request to this engine object
    # in traces and the GET /v1/requests/<id> debug timeline
    trace_id: str | None = None
    # per-request speculative-decoding facts (serve/spec.py): drafts this
    # request's slot proposed / survived verification — the acceptance
    # fact its debug timeline carries (engine-wide rates hide per-request
    # adversarial streams)
    spec_proposed: int = 0
    spec_accepted: int = 0
    # peak pages the request's slot held (paged pool; 0 on lane pools) —
    # stamped at finish/preempt boundaries, the page-usage fact of the
    # debug timeline
    pages_held: int = 0
    # SLO verdict (serve/slo.py SloTracker.observe): class / attained /
    # violated metrics / latencies, set at finish when SLO accounting is
    # configured
    slo_result: dict | None = None
    # late-bound so every engine timestamp shares one clock domain with
    # serve.metrics.now (patchable in tests/simulation)
    submit_time: float = dataclasses.field(
        default_factory=lambda: smetrics.now()
    )
    admit_time: float | None = None
    first_token_time: float | None = None
    finish_time: float | None = None

    @property
    def done(self) -> bool:
        return self.state == FINISHED

    @property
    def remaining(self) -> int:
        return self.max_new_tokens - len(self.tokens)


class FIFOScheduler:
    """Bounded FIFO queue with decode-priority prefill interleaving."""

    def __init__(
        self,
        max_waiting: int = 256,
        decode_priority: bool = True,
        max_prefills_per_step: int = 1,
        max_wait_steps: int = 64,
        prefer_cached: bool = False,
        prefix_lookup=None,
        can_admit=None,
        trace=None,
    ):
        self.max_waiting = max_waiting
        self.decode_priority = decode_priority
        self.max_prefills_per_step = max(1, max_prefills_per_step)
        self.max_wait_steps = max_wait_steps
        self.prefer_cached = prefer_cached
        # prompt (np.ndarray) -> cached-prefix match length; read-only
        self.prefix_lookup = prefix_lookup
        # Request -> bool capacity gate beyond free slots (the paged
        # engine's page-budget check); None = slots are the only gate
        self.can_admit = can_admit
        # optional metrics.trace.FlightRecorder (the engine's); every
        # hook below is one `is not None` branch when tracing is off
        self.trace = trace
        self.queue: deque[Request] = deque()

    def __len__(self) -> int:
        return len(self.queue)

    @property
    def capacity_left(self) -> int:
        """Waiting-queue room before `submit` starts rejecting — the
        HTTP front door's cheap backpressure probe (serve/api.py sizes
        its 503 Retry-After hint from queue pressure without burning a
        submission on a request it knows will bounce)."""
        return max(0, self.max_waiting - len(self.queue))

    def submit(self, req: Request) -> bool:
        """Enqueue, or reject when the waiting queue is at capacity."""
        if len(self.queue) >= self.max_waiting:
            req.state = REJECTED
            return False
        self.queue.append(req)
        return True

    def pick(self, n_free: int, n_active: int) -> list[Request]:
        """Pop the requests to prefill this iteration.

        FIFO order by default; with `prefer_cached` and a bound
        `prefix_lookup`, requests within the wait budget are ordered by
        shortest uncovered suffix (ties stay FIFO), while overdue
        requests keep strict FIFO priority ahead of everything.
        """
        if not self.queue or n_free == 0:
            return []
        budget = n_free
        if self.decode_priority and n_active > 0:
            head = self.queue[0]
            if head.waited_steps <= self.max_wait_steps:
                budget = self.max_prefills_per_step
            elif self.trace is not None:
                # anti-starvation override fired: the head waited past the
                # budget, so prefill takes every free slot despite active
                # decodes — the event that explains ITL spikes in a trace
                self.trace.instant(
                    "wait_budget_override", "sched", "queue", req=head.id,
                    waited_steps=head.waited_steps, queued=len(self.queue),
                )
        k = min(budget, n_free, len(self.queue))
        if not (self.prefer_cached and self.prefix_lookup is not None):
            picked = []
            while len(picked) < k and self.queue:
                if (self.can_admit is not None
                        and not self.can_admit(self.queue[0])):
                    break  # page-starved head blocks: strict FIFO
                picked.append(self.queue.popleft())
            return picked
        overdue = [r for r in self.queue
                   if r.waited_steps > self.max_wait_steps]
        fresh = [r for r in self.queue
                 if r.waited_steps <= self.max_wait_steps]
        for r in fresh:
            if r.prefix_hint is None:
                r.prefix_hint = self.prefix_lookup(r.prompt)
        fresh.sort(key=lambda r: r.prompt.size - r.prefix_hint)
        picked = []
        for r in overdue + fresh:
            if len(picked) >= k:
                break
            if self.can_admit is not None and not self.can_admit(r):
                break  # same head-of-line discipline in preference order
            picked.append(r)
        taken = {id(r) for r in picked}
        self.queue = deque(r for r in self.queue if id(r) not in taken)
        return picked

    def requeue_front(self, req: Request) -> None:
        """Return a PREEMPTED request to the head of the queue (the paged
        engine's page-exhaustion path): it was already admitted once, so
        it bypasses the `max_waiting` bound and keeps its accumulated
        `waited_steps` (the anti-starvation clock must not reset — the
        preemption already cost it its slot)."""
        req.state = WAITING
        self.queue.appendleft(req)

    def remove(self, req: Request) -> bool:
        """Drop a waiting request from the queue (identity match — the
        engine's cancel/deadline paths); False if it was not queued."""
        try:
            self.queue.remove(req)
            return True
        except ValueError:
            return False

    def tick(self, weight: float = 1.0) -> None:
        """One engine iteration elapsed for everything still queued.

        `weight` is the iteration's age in BLOCK-EQUIVALENTS of delivered
        tokens (the engine passes ``max per-slot delivered tokens /
        decode_block``, floored at 1). Plain decode blocks deliver at
        most one block per slot per iteration, so their weight is exactly
        1 and the historical steps == iterations semantics is unchanged.
        A SPECULATIVE step can deliver several blocks' worth of tokens in
        one iteration; without the weight, a high-acceptance batch would
        age the waiting queue one tick per many-block steps — the
        anti-starvation budget would be worth MORE delivered work the
        better speculation goes, starving the queue head exactly when the
        engine is at its fastest (regression-pinned in
        tests/test_spec.py)."""
        if weight < 1.0:
            weight = 1.0
        for req in self.queue:
            req.waited_steps += weight
