"""Continuous-batching serving engine: the long-lived mixed prefill/decode
step over a slot pool.

`infer.decode.generate` is one static batch to completion — a new request
waits for the whole previous batch. `ServeEngine` instead advances a pool
of S independent slots one iteration at a time (Orca-style iteration-level
scheduling): each `step()` admits waiting requests into free lanes
(chunked prefill, same end-aligned attend_len contract as `generate`),
then advances every active slot by a block of single-token steps, emitting
per-request token streams as they materialize. A slot freed by an
early-EOS sequence is re-acquired by the next queued request immediately
— the batch never drains.

Static shapes throughout (XLA requirement): the batch dimension of every
jitted program is the slot count, inactive slots run masked dummy steps
(their writes land in lane slot 0, overwritten by the next prefill;
masked-softmax zeros annihilate stale finite values exactly — see
`serve/kv_pool.py`). Per-slot positions are made possible by `vmap`ping a
batch-1 single-token apply over the slot axis: the models' cached
attention writes at ``positions[0, 0]`` (one scalar per call), and under
vmap that scalar is per-slot — so every decoder family (gpt, llama3,
gemma, deepseekv3) serves unmodified.

Compiled-program inventory (bounded by construction): ONE decode program
(every block runs the full `decode_block`; a slot that hits EOS or its
budget mid-block keeps stepping and the host discards its overshoot —
the wasted writes stay inside that slot's own lane, which the next
prefill overwrites), one prefill program per prompt bucket (prompts pad
right to a multiple of ``bucket``; the pad region is causally invisible
to real tokens and its cache slots are overwritten by the decode stream
before ever being attended).

Cross-request prefix reuse (`serve/prefix_cache.py`, opt-in via
`ServeConfig.prefix_cache` — see its docstring for the cost model):
admission first splices the longest cached page-aligned prompt prefix
into the freed lane
(copy-on-acquire — one fused dynamic_update_slice program per segment)
and prefills only the uncovered suffix from position `matched`, then
snapshots the prompt's prefix back into the radix tree. Cached KV at
position p depends only on tokens <= p, so greedy streams are token-exact
with the cache on or off.

Greedy streams are token-exact vs per-request one-shot `generate`
(tests/test_serve.py, tests/test_prefix_cache.py); stochastic samplers
draw from a different rng chain than `generate` and match only in
distribution.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from solvingpapers_tpu import ops
from solvingpapers_tpu.serve import metrics as smetrics
from solvingpapers_tpu.serve.kv_pool import KVSlotPool, extract_lane, store_lane
from solvingpapers_tpu.serve.metrics import ServeMetrics
from solvingpapers_tpu.serve.prefix_cache import PrefixCache
from solvingpapers_tpu.serve.scheduler import (
    ACTIVE,
    FINISHED,
    FIFOScheduler,
    Request,
)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape/policy knobs.

    `decode_block` amortizes host dispatch: each decode program advances
    all slots `block` tokens in one `lax.scan` before the host looks at
    the stream again (termination granularity = one block; EOS discovered
    mid-block discards the padded tail, matching `generate`'s
    pad-with-EOS semantics). `bucket` quantizes prefill lengths so the
    number of compiled prefill programs stays bounded — use a multiple of
    128 for `use_flash` models (the Pallas q-block constraint).

    Prefix cache (`serve/prefix_cache.py`): with `prefix_cache` on, each
    admitted request splices its longest cached page-aligned prompt
    prefix into the lane and prefills only the uncovered suffix (start
    position = matched length; the suffix pads to `bucket` as before, so
    compiled prefill programs stay bounded by (page multiples x
    buckets)). `prefix_cache_bytes` caps the HBM the radix tree may hold
    (LRU leaf eviction; refcounted nodes are never evicted);
    `prefix_page` is the match/segment granularity. `prefix_sched` makes
    the scheduler prefer waiting requests with the shortest uncovered
    suffix (the existing anti-starvation wait budget still overrides).
    Greedy streams are token-exact with the cache on or off. Opt-in:
    every admission pays a match + snapshot copy and the tree holds up
    to `prefix_cache_bytes` of HBM, which is pure overhead on traffic
    with no shared prefixes (~10% req/s on the Poisson bench) — turn it
    on when prompts share stems (system prompts, few-shot, multi-turn).
    """

    n_slots: int = 8
    max_len: int = 512
    decode_block: int = 8
    bucket: int = 64
    prefill_chunk: int | None = None
    max_waiting: int = 256
    decode_priority: bool = True
    max_prefills_per_step: int = 1
    max_wait_steps: int = 64
    eos_id: int | None = None  # default per-request EOS (None = run to budget)
    seed: int = 0
    prefix_cache: bool = False
    prefix_page: int = 16
    prefix_cache_bytes: int = 64 << 20
    prefix_sched: bool = False


_UNSET = object()


@functools.partial(
    jax.jit,
    static_argnames=("model", "sampler", "padded", "chunk", "start"),
    donate_argnames=("caches",),
)
def _prefill_program(model, sampler, padded, chunk, start, variables, caches,
                     prompt, ctl, rng):
    """Prefill one request into lane `ctl[0]` and sample its first token.

    `prompt` is (padded,) right-padded; `ctl = [slot, length, step]` is
    the host's packed control word (one transfer instead of three — the
    host loop's dispatch overhead is the serving bottleneck on small
    models, see tools/bench_serve.py), where `length` is the real token
    count, so one compiled program serves every prompt in the bucket.
    `rng` is the engine's base key, decorrelated per call by folding in
    the step counter. Chunks mirror `generate`'s static-bound python
    loop; the logits row for the LAST REAL token is gathered from
    whichever chunk contains it (padding makes that not-necessarily-the-
    last chunk).

    `start` (static) is the prefix-cache match length: `prompt` is the
    UNCOVERED SUFFIX, cache slots [0, start) already hold the spliced
    prefix KV, and positions/attend_len shift by `start` — the same
    end-aligned contract, so chunk i attends causally over every written
    slot [0, start + end_i). `start=0` is a full prefill. Static because
    `attend_len` drives a static slice; start values are page multiples,
    keeping the compiled inventory bounded.
    """
    slot, length = ctl[0], ctl[1]
    rng = jax.random.fold_in(rng, ctl[2])
    lane = extract_lane(caches, slot)
    toks = prompt[None, :]
    step = chunk or padded
    last = None
    for cs in range(0, padded, step):
        ce = min(cs + step, padded)
        tok_chunk = jax.lax.slice_in_dim(toks, cs, ce, axis=1)
        positions = jnp.broadcast_to(
            jnp.arange(start + cs, start + ce), (1, ce - cs)
        )
        logits, lane = model.apply(
            variables, tok_chunk, positions=positions, caches=lane,
            deterministic=True, attend_len=start + ce,
        )
        idx = jnp.clip(length - 1 - cs, 0, ce - cs - 1)
        row = jax.lax.dynamic_index_in_dim(logits[0], idx, axis=0,
                                           keepdims=False)
        sel = (length - 1 >= cs) & (length - 1 < ce)
        last = row if last is None else jnp.where(sel, row, last)
    first = sampler(last[None], rng)[0].astype(jnp.int32)
    return store_lane(caches, lane, slot), first


@functools.partial(
    jax.jit,
    static_argnames=("model", "sampler", "block"),
    donate_argnames=("caches",),
)
def _decode_program(model, sampler, block, variables, caches, state, rng):
    """Advance every slot `block` tokens; inactive slots run masked.

    `state` is the host's packed (5, n_slots) int32 control block —
    rows [toks, pos, active, eos, step] — so each call costs ONE
    host->device transfer; the host keeps a numpy mirror of toks/pos and
    only the emitted stream `out` comes back. `rng` is the engine's base
    key (a constant buffer), decorrelated per block by folding in the
    step counter riding row 4.

    The per-slot apply is a batch-1 single-token forward vmapped over the
    slot axis — per-slot positions and per-slot cache writes fall out of
    the models' ``positions[0, 0]`` write contract under vmap. EOS
    padding is sticky by induction (an emitted EOS forces every later
    emission to EOS), mirroring `generate`'s done-flag semantics.
    """
    toks, pos = state[0], state[1]
    active, eos = state[2].astype(bool), state[3]
    rng = jax.random.fold_in(rng, state[4, 0])

    def one(tok, p, slot_caches):
        lane = jax.tree_util.tree_map(lambda a: a[None], slot_caches)
        logits, lane = model.apply(
            variables, tok[None, None], positions=jnp.reshape(p, (1, 1)),
            caches=lane, deterministic=True,
        )
        return logits[0, 0], jax.tree_util.tree_map(
            lambda a: jnp.squeeze(a, axis=0), lane
        )

    def step(carry, sub):
        toks, pos, caches = carry
        logits, caches = jax.vmap(one)(toks, pos, caches)
        nxt = sampler(logits, sub).astype(toks.dtype)
        hit_eos = (eos >= 0) & (toks == eos)
        nxt = jnp.where(hit_eos, eos.astype(toks.dtype), nxt)
        nxt = jnp.where(active, nxt, toks)
        pos = jnp.where(active, pos + 1, pos)
        return (nxt, pos, caches), nxt

    (toks, pos, caches), out = jax.lax.scan(
        step, (toks, pos, caches), jax.random.split(rng, block)
    )
    return caches, out


class ServeEngine:
    """Long-lived continuous-batching engine over one decoder model.

    >>> eng = ServeEngine(model, params, ServeConfig(n_slots=4))
    >>> reqs = [eng.submit(p, max_new_tokens=64) for p in prompts]
    >>> eng.run()              # drain: step() until queue + slots empty
    >>> reqs[0].tokens         # per-request generated ids

    `submit` is non-blocking (admission control may mark the request
    ``rejected``); `step()` is one scheduler iteration and may be driven
    by an external loop that interleaves new submissions — that is the
    point of continuous batching.
    """

    def __init__(
        self,
        model,
        params,
        config: ServeConfig | None = None,
        *,
        sampler=ops.sample_greedy,
        extra_variables: dict | None = None,
        metrics_window: int = 4096,
    ):
        cfg = config or ServeConfig()
        limit = getattr(model, "max_positions", None)
        if limit is not None and cfg.max_len > limit:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's max positions "
                f"{limit}"
            )
        self.model = model
        self.config = cfg
        self.sampler = sampler
        self.variables = {"params": params, **(extra_variables or {})}
        if cfg.prefix_sched and not cfg.prefix_cache:
            raise ValueError(
                "prefix_sched orders admission by cached-prefix match "
                "length, which needs prefix_cache=True — without the radix "
                "tree the knob would silently degrade to plain FIFO"
            )
        self.pool = KVSlotPool(model, cfg.n_slots, cfg.max_len)
        self.prefix_cache = (
            PrefixCache(page=cfg.prefix_page, max_bytes=cfg.prefix_cache_bytes)
            if cfg.prefix_cache else None
        )
        self.scheduler = FIFOScheduler(
            max_waiting=cfg.max_waiting,
            decode_priority=cfg.decode_priority,
            max_prefills_per_step=cfg.max_prefills_per_step,
            max_wait_steps=cfg.max_wait_steps,
            prefer_cached=cfg.prefix_sched,
            prefix_lookup=self._match_len if self.prefix_cache else None,
        )
        self.metrics = ServeMetrics(window=metrics_window)
        self._slot_req: list[Request | None] = [None] * cfg.n_slots
        # host-side numpy mirrors of per-slot decode state: shipped to the
        # device as ONE packed array per jitted call — eager .at[].set
        # bookkeeping was half the drain time on small models
        self._toks = np.zeros(cfg.n_slots, np.int32)
        self._pos = np.zeros(cfg.n_slots, np.int32)
        self._rng = jax.random.key(cfg.seed)  # base key; folded per call
        self._rng_step = 0
        self._last_emit = np.zeros(cfg.n_slots)  # per-slot last emit time

    # ------------------------------------------------------------- submit

    def submit(
        self,
        prompt,
        max_new_tokens: int = 64,
        eos_id=_UNSET,
    ) -> Request:
        """Enqueue one request; returns its live handle immediately."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = prompt.size + max_new_tokens
        limit = getattr(self.model, "max_positions", None)
        cap = min(self.config.max_len, limit or self.config.max_len)
        if total > cap:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds the engine capacity {cap} "
                "(min of ServeConfig.max_len and the model's max positions)"
            )
        req = Request(
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=self.config.eos_id if eos_id is _UNSET else eos_id,
        )
        if not self.scheduler.submit(req):
            self.metrics.record_reject()
        return req

    # --------------------------------------------------------------- step

    def has_work(self) -> bool:
        return bool(len(self.scheduler)) or self.pool.n_active > 0

    def step(self) -> list[Request]:
        """One engine iteration: admit + prefill, then one decode block.

        Returns the requests that FINISHED this iteration.
        """
        finished: list[Request] = []
        for req in self.scheduler.pick(self.pool.n_free, self.pool.n_active):
            if self._admit(req):
                finished.append(req)  # prefill-only finish (eos/budget 1)
        if self.pool.n_active > 0:
            finished.extend(self._decode_block())
        self.scheduler.tick()
        self.metrics.record_step(self.pool.occupancy)
        return finished

    def run(self, max_steps: int | None = None) -> None:
        """Drive step() until queue and slots drain (or `max_steps`)."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return

    # ------------------------------------------------------------ private

    def _bucketed(self, length: int, start: int = 0) -> int:
        b = self.config.bucket
        padded = -(-length // b) * b
        limit = getattr(self.model, "max_positions", None)
        cap = min(self.config.max_len, limit or self.config.max_len) - start
        return max(length, min(padded, cap))

    def _match_len(self, prompt: np.ndarray) -> int:
        """Cached page-aligned prefix length for `prompt` (read-only; the
        scheduler's admission lookup). Capped at len-1: the suffix prefill
        must produce at least one logits row to sample from."""
        if self.prefix_cache is None or prompt.size < 2:
            return 0
        return self.prefix_cache.peek(prompt[: prompt.size - 1])

    def _admit(self, req: Request) -> bool:
        """Prefill `req` into a free lane; True if it finished already.

        With the prefix cache on: splice the longest cached page-aligned
        prompt prefix into the lane (copy-on-acquire), prefill only the
        uncovered suffix from position `matched`, then snapshot the
        prompt's page-aligned prefix back into the tree so later requests
        reuse it.
        """
        slot = self.pool.acquire()
        assert slot is not None, "scheduler admitted beyond free slots"
        now = smetrics.now()
        req.state = ACTIVE
        req.slot = slot
        req.admit_time = now
        self.metrics.record_admit(req, now)

        length = int(req.prompt.size)
        matched = 0
        if self.prefix_cache is not None and length > 1:
            match = self.prefix_cache.match(req.prompt[: length - 1])
            matched = match.length
            self.metrics.record_prefix_lookup(matched)
            if matched:
                # pin across the splice. In today's single-threaded engine
                # nothing can evict between match and splice (eviction only
                # runs inside insert, below) — the pin is the invariant a
                # future async/threaded admission path must keep, kept live
                # here so the refcount machinery stays exercised.
                self.prefix_cache.pin(match)
                offset = 0
                for node in match.nodes:
                    self.pool.splice_prefix(slot, node.segment, offset)
                    offset += node.length
                self.prefix_cache.unpin(match)

        suffix = length - matched
        padded = self._bucketed(suffix, start=matched)
        chunk = self.config.prefill_chunk
        if chunk is None and padded > 4096:
            chunk = 2048  # same auto-chunk threshold as infer.decode.generate
        if chunk is not None and chunk >= padded:
            chunk = None
        prompt_padded = np.zeros(padded, np.int32)
        prompt_padded[:suffix] = req.prompt[matched:]
        ctl = np.asarray([slot, suffix, self._rng_step], np.int32)
        self._rng_step += 1
        self.pool.caches, first = _prefill_program(
            self.model, self.sampler, padded, chunk, matched, self.variables,
            self.pool.caches, jnp.asarray(prompt_padded), jnp.asarray(ctl),
            self._rng,
        )
        first = int(first)
        if self.prefix_cache is not None:
            # snapshot while the lane's [0, length) span is pristine (an
            # active lane's decode writes land at positions >= length, and
            # dummy writes only hit FREED lanes' slot 0)
            page = self.prefix_cache.page
            aligned = (length - 1) // page * page
            # aligned == matched on a full hit: nothing new to cache, and
            # insert's internal re-match would re-walk the whole prefix on
            # the dispatch-bound host hot path for nothing
            if aligned > matched:
                self.prefix_cache.insert(
                    req.prompt[:aligned],
                    lambda off, n: self.pool.extract_prefix(slot, off, n),
                )
            self.metrics.record_prefix_state(
                self.prefix_cache.bytes_held, self.prefix_cache.evictions
            )
        now = smetrics.now()
        req.first_token_time = now
        req.tokens.append(first)
        self.metrics.record_first_token(req, now, prefilled=suffix)
        self._last_emit[slot] = now
        self.pool.positions[slot] = length
        self._toks[slot] = first
        self._pos[slot] = length
        self._slot_req[slot] = req
        if req.eos_id is not None and first == req.eos_id:
            reason = "eos"
        elif req.remaining == 0:
            reason = "length"
        else:
            return False
        self._finish(req, reason, now)
        return True

    def _decode_block(self) -> list[Request]:
        cfg = self.config
        block = cfg.decode_block
        state = np.zeros((5, cfg.n_slots), np.int32)
        state[0] = self._toks
        state[1] = self._pos
        state[3] = -1
        for slot, r in enumerate(self._slot_req):
            if r is not None:
                state[2, slot] = 1
                if r.eos_id is not None:
                    state[3, slot] = r.eos_id
        state[4] = self._rng_step
        self._rng_step += 1
        self.pool.caches, out = _decode_program(
            self.model, self.sampler, block, self.variables,
            self.pool.caches, jnp.asarray(state), self._rng,
        )
        out = np.asarray(out)  # (block, n_slots); overshoot truncated below
        now = smetrics.now()
        finished: list[Request] = []
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            appended = 0
            reason = None
            for t in out[:, slot]:
                req.tokens.append(int(t))
                appended += 1
                if req.eos_id is not None and int(t) == req.eos_id:
                    reason = "eos"  # tail of the block is EOS padding
                    break
                if req.remaining == 0:
                    reason = "length"
                    break
            self.metrics.record_tokens(
                req, appended, now - self._last_emit[slot], now
            )
            self._last_emit[slot] = now
            self.pool.positions[slot] += appended
            if reason is not None:
                self._finish(req, reason, now)
                finished.append(req)
            else:
                # mirror the device carry: the slot ran the full block
                self._toks[slot] = out[-1, slot]
                self._pos[slot] += block
        return finished

    def _finish(self, req: Request, reason: str, now: float) -> None:
        req.state = FINISHED
        req.finish_reason = reason
        req.finish_time = now
        self.metrics.record_finish(req, now)
        slot = req.slot
        self._slot_req[slot] = None
        # park the idle lane at position 0: its masked dummy writes land
        # in slot 0, which the next prefill overwrites first
        self._toks[slot] = 0
        self._pos[slot] = 0
        self.pool.release(slot)
